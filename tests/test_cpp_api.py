"""C++ application API (native/include/tpurpc/client.{h,hpp}).

The reference's L7 includes a full C++ app surface (src/cpp/ +
include/grpcpp/, SURVEY.md §1); tpurpc's native equivalent is a blocking
C/C++ client over the native framing. This test compiles the example app
with g++ and runs it against a live Python server — once over a TCP
listener and once over a ring-platform listener (whose accept path
protocol-sniffs the framing preface), proving a native app needs no Python
anywhere in its process.
"""

import os
import shutil
import subprocess
import threading

import pytest

import tpurpc.rpc as rpc

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN = os.path.join(ROOT, "native", "build", "cpp_client_example")


def _build_cpp(out_bin, example, native_src, headers):
    """Compile one example+runtime pair, skipping when the binary is newer
    than every source/header it depends on."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    os.makedirs(os.path.dirname(out_bin), exist_ok=True)
    native_srcs = ([native_src] if isinstance(native_src, str)
                   else list(native_src))
    srcs = [os.path.join(ROOT, "examples", example)] + [
        os.path.join(ROOT, "native", "src", ns) for ns in native_srcs]
    deps = srcs + [os.path.join(ROOT, "native", "src", h) for h in
                   ("framing_common.h", "ring_transport.h", "tpr_obs.h")] + [
        os.path.join(ROOT, "native", "include", "tpurpc", h) for h in headers]
    if (os.path.exists(out_bin)
            and all(os.path.getmtime(out_bin) > os.path.getmtime(d)
                    for d in deps)):
        return
    subprocess.run(
        [gxx, "-std=c++17", "-O2", *srcs,
         "-I", os.path.join(ROOT, "native", "include"),
         "-lpthread", "-lrt", "-o", out_bin],
        check=True, timeout=180, capture_output=True)


def _build_example():
    _build_cpp(BIN, "cpp_client.cc", ["tpurpc_client.cc", "tpr_rdv.cc", "tpr_obs.cc", "ring.cc"],
               ["client.h", "client.hpp"])


def _server():
    srv = rpc.Server(max_workers=4)
    srv.add_method(
        "/demo.Greeter/SayHello",
        rpc.unary_unary_rpc_method_handler(
            lambda req, ctx: b"Hello, " + bytes(req) + b"!"))
    srv.add_method(
        "/demo.Greeter/Echo",
        rpc.unary_unary_rpc_method_handler(lambda req, ctx: bytes(req)))

    def chat(req_iter, ctx):
        for m in req_iter:
            yield b"echo:" + bytes(m)

    srv.add_method("/demo.Greeter/Chat",
                   rpc.stream_stream_rpc_method_handler(chat))
    return srv


def _run_example(port: int) -> str:
    proc = subprocess.run([BIN, str(port)], capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    return proc.stdout


def _check(out: str):
    assert "unary=Hello, cpp!" in out
    assert "missing_status=12" in out          # UNIMPLEMENTED
    assert out.count("stream=echo:m") == 3
    assert "stream_status=0 got=3" in out
    assert "big_ok=1" in out and "match=1" in out
    assert "ping_us=" in out


def test_cpp_client_against_tcp_server(monkeypatch):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    _build_example()
    srv = _server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        _check(_run_example(port))
    finally:
        srv.stop(grace=0)


def test_cpp_client_against_ring_platform_server(monkeypatch):
    """Ring-platform listeners sniff the preface: a plain-TCP native-framing
    client coexists with ring-bootstrap clients on one port."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    _build_example()
    srv = _server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        _check(_run_example(port))
    finally:
        srv.stop(grace=0)


def test_cpp_send_lease_ring(monkeypatch):
    """Zero-copy send lease E2E (round 5): a C client serializes payloads
    DIRECTLY into the transport ring (tpr_call_send_reserve/commit — the
    reference's SendZerocopy shape) and a live Python server verifies
    every byte. Covers wrapped spans (odd sizes walk the tail over the
    ring edge), interleaving with classic sends on the same stream, and
    the misuse guards (double reserve / stray commit return -1)."""
    import numpy as np

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    lease_bin = os.path.join(ROOT, "native", "build", "cpp_send_lease")
    _build_cpp(lease_bin, "cpp_send_lease.cc",
               ["tpurpc_client.cc", "tpr_rdv.cc", "tpr_obs.cc", "ring.cc"], ["client.h"])

    def check(req_iter, ctx):
        for m in req_iter:
            arr = np.frombuffer(bytes(m), np.uint8)
            yield f"{arr.size}:{int(arr.sum(dtype=np.uint64))}".encode()

    srv = rpc.Server(max_workers=4)
    srv.add_method("/lease.S/Check",
                   rpc.stream_stream_rpc_method_handler(check))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        out = subprocess.run([lease_bin, str(port)], capture_output=True,
                             text=True, timeout=120)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "LEASE-OK" in out.stdout and "wrapped=" in out.stdout
    finally:
        srv.stop(grace=0)


def test_cpp_client_deadline(monkeypatch):
    """A stalled server method must produce DEADLINE_EXCEEDED client-side."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    _build_example()
    srv = rpc.Server(max_workers=2)
    release = threading.Event()

    def stall(req, ctx):
        release.wait(timeout=30)
        return b"late"

    srv.add_method("/demo.Greeter/SayHello",
                   rpc.unary_unary_rpc_method_handler(stall))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        src = f"""
#include <cstdio>
#include "tpurpc/client.hpp"
int main() {{
  tpurpc::Channel ch("127.0.0.1", {port});
  auto [st, body] = ch.UnaryCall("/demo.Greeter/SayHello", "x", 500);
  printf("code=%d\\n", st.code);
  return st.code == TPR_DEADLINE_EXCEEDED ? 0 : 1;
}}
"""
        tmp_src = os.path.join(ROOT, "native", "build", "deadline_test.cc")
        tmp_bin = os.path.join(ROOT, "native", "build", "deadline_test")
        with open(tmp_src, "w") as f:
            f.write(src)
        subprocess.run(
            ["g++", "-std=c++17", "-O0", tmp_src,
             os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
             os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
             os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
             os.path.join(ROOT, "native", "src", "ring.cc"),
             "-I", os.path.join(ROOT, "native", "include"),
             "-lpthread", "-lrt", "-o", tmp_bin],
            check=True, timeout=180, capture_output=True)
        proc = subprocess.run([tmp_bin], capture_output=True, text=True,
                              timeout=60)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
    finally:
        release.set()
        srv.stop(grace=0)


def test_cpp_client_inline_read_ring(monkeypatch):
    """TPURPC_NATIVE_INLINE_READ=1: no reader thread — callers pump the
    ring themselves (the reference's pollset_work discipline). The full
    example battery must behave identically; measured win:
    5.4us p50 streaming vs 7.2 with the reader thread (micro_native)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    _build_example()
    srv = _server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
                   TPURPC_NATIVE_INLINE_READ="1")
        proc = subprocess.run([BIN, str(port)], capture_output=True,
                              text=True, timeout=120, env=env)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        _check(proc.stdout)
    finally:
        srv.stop(grace=0)


def test_cpp_inline_read_deadline_and_threads(monkeypatch):
    """Inline mode corner cases: a deadline against a silent server must
    fire at a frame boundary (the pumping thread abandons the header
    wait), and two app threads sharing one inline channel must hand the
    pump off correctly under concurrent calls."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    srv = rpc.Server(max_workers=4)
    release = threading.Event()
    srv.add_method("/demo.Greeter/Hang", rpc.unary_unary_rpc_method_handler(
        lambda r, c: release.wait(30) or b"late"))
    srv.add_method("/demo.Greeter/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    src = r"""
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include "tpurpc/client.h"
int main(int argc, char **argv) {
  tpr_channel *ch = tpr_channel_create("127.0.0.1", atoi(argv[1]), 5000);
  if (!ch) return 2;
  // 1. deadline with the pump blocked on a silent server
  uint8_t *resp; size_t rlen; char det[256];
  int st = tpr_unary_call(ch, "/demo.Greeter/Hang", nullptr, 0,
                          &resp, &rlen, det, sizeof det, 400);
  printf("deadline_status=%d\n", st);
  // 2. two threads, concurrent unary calls on ONE inline channel
  int bad = 0;
  auto worker = [&](int base) {
    for (int i = 0; i < 200; i++) {
      std::string req = "t" + std::to_string(base + i);
      uint8_t *r2; size_t l2;
      int s2 = tpr_unary_call(ch, "/demo.Greeter/Echo",
                              (const uint8_t *)req.data(), req.size(),
                              &r2, &l2, nullptr, 0, 10000);
      if (s2 != TPR_OK || l2 != req.size() ||
          memcmp(r2, req.data(), l2) != 0) { bad++; }
      if (s2 == TPR_OK) tpr_buf_free(r2);
    }
  };
  std::thread a(worker, 0), b(worker, 1000);
  a.join(); b.join();
  printf("threads_bad=%d\n", bad);
  tpr_channel_destroy(ch);
  return (st == TPR_DEADLINE_EXCEEDED && bad == 0) ? 0 : 1;
}
"""
    tmp_src = os.path.join(ROOT, "native", "build", "inline_test.cc")
    tmp_bin = os.path.join(ROOT, "native", "build", "inline_test")
    with open(tmp_src, "w") as f:
        f.write(src)
    try:
        subprocess.run(
            ["g++", "-std=c++17", "-O2", tmp_src,
             os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
             os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
             os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
             os.path.join(ROOT, "native", "src", "ring.cc"),
             "-I", os.path.join(ROOT, "native", "include"),
             "-lpthread", "-lrt", "-o", tmp_bin],
            check=True, timeout=180, capture_output=True)
        env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
                   TPURPC_NATIVE_INLINE_READ="1")
        proc = subprocess.run([tmp_bin, str(port)], capture_output=True,
                              text=True, timeout=120, env=env)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "deadline_status=4" in proc.stdout
        assert "threads_bad=0" in proc.stdout
    finally:
        release.set()
        srv.stop(grace=0)


def test_native_server_compression_degrades_to_identity(monkeypatch):
    """A Python channel with framing compression on, against the NATIVE C++
    server: the native loop links no decompressor and rejects the stream
    UNIMPLEMENTED before any handler runs. The channel treats that as
    compression negotiation (gRPC's grpc-accept-encoding equivalent):
    degrade to identity, transparently replay the unary call — so the
    drop-in caller sees SUCCESS, not a transport quirk."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        with rpc.Channel(f"127.0.0.1:{port}", compression="gzip") as ch:
            import tpurpc.rpc.frame as fr
            assert ch._compress_flag == fr.FLAG_COMPRESSED
            # First call probes, hits UNIMPLEMENTED, degrades, replays:
            assert ch.unary_unary("/demo.Greeter/Echo")(
                b"x" * 256, timeout=15) == b"x" * 256
            assert ch._compress_flag == 0  # identity from here on
            assert ch.unary_unary("/demo.Greeter/Echo")(b"ok",
                                                        timeout=15) == b"ok"
    finally:
        proc.kill()
        proc.wait()


# -- completion-queue async client -------------------------------------------

ASYNC_BIN = os.path.join(ROOT, "native", "build", "cpp_async_example")


def _build_async_example():
    _build_cpp(ASYNC_BIN, "cpp_async_client.cc",
               ["tpurpc_client.cc", "tpr_rdv.cc", "tpr_obs.cc", "ring.cc"], ["client.h"])


def _async_server():
    srv = _server()
    hang = threading.Event()

    def hang_handler(req, ctx):
        hang.wait(timeout=30)
        return b"late"

    srv.add_method("/demo.Greeter/Hang",
                   rpc.unary_unary_rpc_method_handler(hang_handler))
    return srv, hang


def _check_async(out: str):
    assert "async_unary done=64 matched=64" in out
    assert "big_async_ok=1" in out  # >1MiB request takes the fragmenting path
    assert "stream_status=0 got=3" in out
    assert "deadline_status=4" in out  # DEADLINE_EXCEEDED from the cq puller
    assert "shutdown_rc=-1" in out


def test_cpp_async_client_tcp(monkeypatch):
    """The CQ async shape (grpc CompletionQueue::Next): 64 pipelined unary
    calls on one channel, tagged streaming recvs, cq-enforced deadline."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    _build_async_example()
    srv, hang = _async_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        proc = subprocess.run([ASYNC_BIN, str(port)], capture_output=True,
                              text=True, timeout=120)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        _check_async(proc.stdout)
    finally:
        hang.set()
        srv.stop(grace=0)


def test_cpp_async_client_ring(monkeypatch):
    """Same battery with the byte pipe swapped to the shm ring by env —
    the CQ surface is transport-agnostic like the blocking one."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    _build_async_example()
    srv, hang = _async_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP")
        proc = subprocess.run([ASYNC_BIN, str(port)], capture_output=True,
                              text=True, timeout=120, env=env)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        _check_async(proc.stdout)
    finally:
        hang.set()
        srv.stop(grace=0)


def test_cpp_async_parked_puller_deadline(monkeypatch):
    """A puller already parked in tpr_cq_next (no queued events, no timed
    calls) must be woken by a later deadlined call's registration and
    enforce its expiry — regression for the missing notify on insert."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    srv, hang = _async_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        src = f"""
#include <cstdio>
#include <thread>
#include <chrono>
#include "tpurpc/client.h"
int main() {{
  tpr_channel *ch = tpr_channel_create("127.0.0.1", {port}, 5000);
  if (!ch) return 2;
  tpr_cq *cq = tpr_cq_create();
  int dl_status = -1;
  std::thread puller([&] {{
    tpr_event ev;
    // parks in cv.wait (no timeout, nothing queued, no timed calls yet)
    if (tpr_cq_next(cq, &ev, 0) == 1 && ev.type == TPR_EV_FINISH) {{
      dl_status = ev.status;
      if (ev.data) tpr_buf_free(ev.data);
    }}
  }});
  std::this_thread::sleep_for(std::chrono::milliseconds(300));  // park it
  tpr_call *c = tpr_unary_call_cq(ch, "/demo.Greeter/Hang", nullptr, 0,
                                  400, cq, (void *)1);
  puller.join();  // hangs forever if the insert doesn't notify
  if (c) tpr_call_destroy(c);
  printf("dl=%d\\n", dl_status);
  tpr_cq_shutdown(cq);
  tpr_cq_destroy(cq);
  tpr_channel_destroy(ch);
  return dl_status == TPR_DEADLINE_EXCEEDED ? 0 : 1;
}}
"""
        tmp_src = os.path.join(ROOT, "native", "build", "parked_puller.cc")
        tmp_bin = os.path.join(ROOT, "native", "build", "parked_puller")
        with open(tmp_src, "w") as f:
            f.write(src)
        subprocess.run(
            ["g++", "-std=c++17", "-O0", tmp_src,
             os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
             os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
             os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
             os.path.join(ROOT, "native", "src", "ring.cc"),
             "-I", os.path.join(ROOT, "native", "include"),
             "-lpthread", "-lrt", "-o", tmp_bin],
            check=True, timeout=180, capture_output=True)
        proc = subprocess.run([tmp_bin], capture_output=True, text=True,
                              timeout=60)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
    finally:
        hang.set()
        srv.stop(grace=0)


# -- native C++ SERVER -------------------------------------------------------

SRV_BIN = os.path.join(ROOT, "native", "build", "cpp_server_example")


def _build_server_example():
    _build_cpp(SRV_BIN, "cpp_server.cc", ["tpurpc_server.cc", "tpr_rdv.cc", "tpr_obs.cc", "ring.cc"],
               ["server.h", "server.hpp"])


def test_python_client_against_cpp_server():
    """The native C++ server serves Python tpurpc channels: unary, bidi
    streaming, large fragmented messages, unknown-method status."""
    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        from tpurpc.rpc.status import RpcError, StatusCode

        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            hello = ch.unary_unary("/demo.Greeter/SayHello")
            assert hello(b"py", timeout=10) == b"Hello, py!"

            # bidi
            chat = ch.stream_stream("/demo.Greeter/Chat")
            got = [bytes(m) for m in
                   chat(iter([b"a", b"b", b"c"]), timeout=10)]
            assert got == [b"echo:a", b"echo:b", b"echo:c"]

            # large message across the 1MiB frame bound, echoed back
            big = b"B" * (3 << 20)
            echo = ch.unary_unary("/demo.Greeter/Echo")
            assert echo(big, timeout=30) == big

            # unknown method -> UNIMPLEMENTED
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/no.Such/Method")(b"", timeout=10)
            assert ei.value.code() == StatusCode.UNIMPLEMENTED

            # concurrent clients on separate connections
            import threading

            results = []

            def worker(i):
                with rpc.Channel(f"127.0.0.1:{port}") as ch2:
                    r = ch2.unary_unary("/demo.Greeter/SayHello")(
                        str(i).encode(), timeout=10)
                    results.append(bytes(r))

            ths = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            [t.start() for t in ths]
            [t.join() for t in ths]
            assert sorted(results) == sorted(
                b"Hello, %d!" % i for i in range(4))
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_cpp_client_against_cpp_server():
    """Full native loop: C++ client -> C++ server, zero Python in either
    process."""
    _build_example()
    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = proc.stdout.readline().split()[1]
        out = subprocess.run([BIN, port], capture_output=True, text=True,
                             timeout=120)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "unary=Hello, cpp!" in out.stdout
        assert "stream_status=0 got=3" in out.stdout
        assert "big_ok=1" in out.stdout and "match=1" in out.stdout
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_python_multiplexed_streams_on_cpp_server():
    """Python channels multiplex concurrent calls on ONE connection; the
    native server must demux per-stream (not drop other-sid frames)."""
    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            hello = ch.unary_unary("/demo.Greeter/SayHello")
            results = []
            errs = []

            def worker(i):
                try:
                    results.append(bytes(hello(str(i).encode(), timeout=15)))
                except Exception as exc:
                    errs.append(exc)

            ths = [threading.Thread(target=worker, args=(i,))
                   for i in range(6)]
            [t.start() for t in ths]
            [t.join(timeout=30) for t in ths]
            assert not errs, errs
            assert sorted(results) == sorted(
                b"Hello, %d!" % i for i in range(6))
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_cpp_loop_under_asan():
    """The full native client→server loop compiled with ASan+UBSan: catches
    use-after-free / data races in the call-lifetime machinery (the
    cancel/deadline RST path pins call objects; this is its tripwire)."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    bd = os.path.join(ROOT, "native", "build")
    os.makedirs(bd, exist_ok=True)
    asan_srv = os.path.join(bd, "asan_server")
    asan_cli = os.path.join(bd, "asan_client")
    flags = ["-std=c++17", "-O1", "-g", "-fsanitize=address,undefined",
             "-I", os.path.join(ROOT, "native", "include"), "-lpthread", "-lrt"]
    subprocess.run([gxx, os.path.join(ROOT, "examples", "cpp_server.cc"),
                    os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
                    os.path.join(ROOT, "native", "src", "ring.cc"),
                    *flags, "-o", asan_srv],
                   check=True, timeout=180, capture_output=True)
    subprocess.run([gxx, os.path.join(ROOT, "examples", "cpp_client.cc"),
                    os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
                    os.path.join(ROOT, "native", "src", "ring.cc"),
                    *flags, "-o", asan_cli],
                   check=True, timeout=180, capture_output=True)
    asan_async = os.path.join(bd, "asan_async_client")
    subprocess.run([gxx, os.path.join(ROOT, "examples", "cpp_async_client.cc"),
                    os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
                    os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
                    os.path.join(ROOT, "native", "src", "ring.cc"),
                    *flags, "-o", asan_async],
                   check=True, timeout=180, capture_output=True)
    proc = subprocess.Popen([asan_srv], stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = proc.stdout.readline().split()[1]
        # plain TCP, then the ring data plane with the inline-read pump
        # (inline needs the ring; the server sniffs TRB1 per connection)
        for env_extra in ({"GRPC_PLATFORM_TYPE": "TCP"},
                          {"GRPC_PLATFORM_TYPE": "RDMA_BP",
                           "TPURPC_NATIVE_INLINE_READ": "1"}):
            env = dict(os.environ, **env_extra)
            out = subprocess.run([asan_cli, port], capture_output=True,
                                 text=True, timeout=120, env=env)
            assert out.returncode == 0, (out.stdout, out.stderr)
            assert "ERROR" not in out.stderr, out.stderr
            assert "runtime error" not in out.stderr, out.stderr
        # CQ async machinery under ASan (pin/destroy lifecycle tripwire).
        # The example's Hang-method deadline phase gets UNIMPLEMENTED here
        # (this server has no Hang) — lifecycle still fully exercised, so
        # only sanitizer findings fail the test, not the exit code.
        out = subprocess.run([asan_async, port], capture_output=True,
                             text=True, timeout=120,
                             env=dict(os.environ, GRPC_PLATFORM_TYPE="TCP"))
        assert "ERROR" not in out.stderr, out.stderr
        assert "runtime error" not in out.stderr, out.stderr  # UBSan recoverable
        # every phase except the deadline one must still pass outright
        assert "async_unary done=64 matched=64" in out.stdout, out.stdout
        assert "big_async_ok=1" in out.stdout, out.stdout
        assert "stream_status=0 got=3" in out.stdout, out.stdout
        assert "shutdown_rc=-1" in out.stdout, out.stdout
    finally:
        proc.stdin.close()
        proc.wait(timeout=15)
        srv_err = proc.stderr.read()
        assert "ERROR" not in srv_err, srv_err
        assert "runtime error" not in srv_err, srv_err  # UBSan recoverable


def test_bulk_lease_loop_under_asan():
    """Round-5 native machinery under ASan+UBSan: the zero-copy send lease
    (reserve/commit into the peer ring) and the wait_event one-poller
    rewrite, driven by the send_ab A/B loop (client+server in one
    process: poller threads, handler drain, credit waits, bulk rings)."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    bd = os.path.join(ROOT, "native", "build")
    os.makedirs(bd, exist_ok=True)
    asan_ab = os.path.join(bd, "asan_send_ab")
    subprocess.run(
        [gxx, os.path.join(ROOT, "native", "bench", "send_ab.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
         os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
         os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
         os.path.join(ROOT, "native", "src", "ring.cc"),
         "-std=c++17", "-O1", "-g", "-fsanitize=address,undefined",
         "-I", os.path.join(ROOT, "native", "include"), "-lpthread", "-lrt",
         "-o", asan_ab],
        check=True, timeout=240, capture_output=True)
    out = subprocess.run(
        [asan_ab, "0.4"], capture_output=True, text=True, timeout=300,
        env=dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
                 GRPC_RDMA_RING_BUFFER_SIZE_KB="1024"))
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "ERROR" not in out.stderr, out.stderr
    assert "runtime error" not in out.stderr, out.stderr
    import re as _re

    # at least the 16KB and 128KB lease cells must have RUN (the 1MB one
    # legitimately SKIPs: it exceeds this test's 1MB ring's max payload)
    assert len(_re.findall(r"mode=lease size=\d+ msgs=\d+ [\d.]+ GB/s",
                           out.stdout)) >= 2, out.stdout


_CB_SERVER_SRC = r"""
// callback (reactor) API server: handlers run inline on the reader thread
#include <cstdio>
#include <cstring>
#include <string>
#include "tpurpc/server.h"

static int echo_cb(tpr_server_call *call, const uint8_t *d, size_t n, void *) {
  tpr_srv_send(call, d, n);
  return 0;
}
static int limit_cb(tpr_server_call *call, const uint8_t *d, size_t n, void *ud) {
  // ends the call with RESOURCE_EXHAUSTED(8) on a message saying "stop"
  (void)ud;
  if (n == 4 && memcmp(d, "stop", 4) == 0) {
    tpr_srv_set_details(call, "limit reached");
    return 8;
  }
  tpr_srv_send(call, d, n);
  return 0;
}
int main() {
  tpr_server *s = tpr_server_create(0);
  tpr_server_register_callback(s, "/cb.S/Echo", echo_cb, nullptr);
  tpr_server_register_callback(s, "/cb.S/Limited", limit_cb, nullptr);
  tpr_server_start(s);
  printf("PORT %d\n", tpr_server_port(s));
  fflush(stdout);
  getchar();  // run until stdin closes
  tpr_server_destroy(s);
  return 0;
}
"""


def test_python_client_against_cpp_callback_server(tmp_path):
    """The callback (reactor) server API — handlers inline on the reader
    thread (ref src/cpp/server/server_callback.cc shape): unary, streaming
    ping-pong, mid-stream nonzero status, and multiplexed calls."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    src = tmp_path / "cb_server.cc"
    src.write_text(_CB_SERVER_SRC)
    binp = tmp_path / "cb_server"
    subprocess.run(
        [gxx, "-std=c++17", "-O1", str(src),
         os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
         os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
         os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
         os.path.join(ROOT, "native", "src", "ring.cc"),
         "-I", os.path.join(ROOT, "native", "include"),
         "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=180, capture_output=True)
    proc = subprocess.Popen([str(binp)], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        from tpurpc.rpc.status import RpcError, StatusCode

        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            # unary through the reactor path
            echo = ch.unary_unary("/cb.S/Echo")
            assert echo(b"hi", timeout=10) == b"hi"
            # streaming ping-pong
            chat = ch.stream_stream("/cb.S/Echo")
            got = [bytes(m) for m in chat(iter([b"a", b"b", b"c"]),
                                          timeout=10)]
            assert got == [b"a", b"b", b"c"]
            # mid-stream nonzero status ends the call with that code
            lim = ch.stream_stream("/cb.S/Limited")
            call = lim(iter([b"one", b"stop", b"never-sent"]), timeout=10)
            seen = []
            with pytest.raises(RpcError) as ei:
                for m in call:
                    seen.append(bytes(m))
            assert seen == [b"one"]
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
            assert "limit reached" in ei.value.details()
            # reactor calls multiplex on one connection like any other
            mc = ch.unary_unary("/cb.S/Echo")
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(4) as ex:
                outs = list(ex.map(
                    lambda i: bytes(mc(b"m%d" % i, timeout=10)), range(8)))
            assert outs == [b"m%d" % i for i in range(8)]
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_micro_native_bench_smoke(tmp_path):
    """The native micro-bench (the reference's examples/cpp/micro-bench
    analog) builds and produces sane numbers in both modes."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    binp = tmp_path / "micro_native"
    subprocess.run(
        [gxx, "-std=c++17", "-O2",
         os.path.join(ROOT, "native", "bench", "micro_native.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
         os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
         os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
         os.path.join(ROOT, "native", "src", "ring.cc"),
         "-I", os.path.join(ROOT, "native", "include"),
         "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=180, capture_output=True)
    import json as _json

    for streaming in (0, 1):
        out = subprocess.run([str(binp), "64", "1", "1", str(streaming)],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        rec = _json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["rpcs"] > 100
        assert rec["rtt_us_p50"] > 0

    # CQ-pipelined mode (outstanding=8): all slots drain cleanly
    out = subprocess.run([str(binp), "64", "1", "1", "0", "1", "8"],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    rec = _json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["outstanding"] == 8
    assert rec["rpcs"] > 100


# -- C++ apps on the RING transport (VERDICT r2 next#8) ----------------------

def test_cpp_client_rides_ring_data_plane(monkeypatch):
    """GRPC_PLATFORM_TYPE=RDMA_BP in the C++ client's env makes it bootstrap
    the shm ring over the socket and run ALL frames through one-sided ring
    writes — app code unchanged (the reference's defining property,
    endpoint.cc:33-54, now for native apps)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    _build_example()
    srv = _server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
                   GRPC_RDMA_RING_BUFFER_SIZE_KB="1024")
        proc = subprocess.run([BIN, str(port)], capture_output=True,
                              text=True, timeout=120, env=env)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        _check(proc.stdout)
    finally:
        srv.stop(grace=0)


def test_python_client_against_cpp_ring_server(monkeypatch):
    """Reverse direction: the Python channel ring-bootstraps against a pure
    C++ server whose listener protocol-sniffs TRB1."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    monkeypatch.setenv("GRPC_RDMA_RING_BUFFER_SIZE_KB", "1024")
    _build_server_example()
    env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="1024")
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True, env=env)
    try:
        port = int(proc.stdout.readline().split()[1])
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            hello = ch.unary_unary("/demo.Greeter/SayHello")
            assert hello(b"ring", timeout=20) == b"Hello, ring!"
            # big payload: wrap-split + partial sends + credit returns
            big = b"R" * (3 << 20)
            echo = ch.unary_unary("/demo.Greeter/Echo")
            assert echo(big, timeout=60) == big
            # streaming across the ring
            chat = ch.stream_stream("/demo.Greeter/Chat")
            got = [bytes(m) for m in chat(iter([b"a", b"b"]), timeout=20)]
            assert got == [b"echo:a", b"echo:b"]
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_cpp_ring_micro_smoke(tmp_path):
    """C++ client <-> C++ server entirely over the ring transport."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    binp = tmp_path / "micro_ring"
    subprocess.run(
        [gxx, "-std=c++17", "-O2",
         os.path.join(ROOT, "native", "bench", "micro_native.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
         os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
         os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
         os.path.join(ROOT, "native", "src", "ring.cc"),
         "-I", os.path.join(ROOT, "native", "include"),
         "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=180, capture_output=True)
    import json as _json

    env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="1024")
    out = subprocess.run([str(binp), "4096", "1", "1", "1"],
                         capture_output=True, text=True, timeout=60, env=env)
    assert out.returncode == 0, out.stderr
    rec = _json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["rpcs"] > 100


def test_native_ring_beats_tcp_small_rpc(tmp_path):
    """The repo's central perf claim, CI-enforced on the NATIVE loop (it
    holds even single-core: data crosses shm, only 1-byte notify tokens
    cross the kernel — bench/results/micro_native_1core.log measured
    87K vs 53K RPC/s). Asserted with margin: ring must not LOSE to TCP."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    binp = tmp_path / "micro_rvt"
    subprocess.run(
        [gxx, "-std=c++17", "-O2",
         os.path.join(ROOT, "native", "bench", "micro_native.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_client.cc"),
         os.path.join(ROOT, "native", "src", "tpurpc_server.cc"),
         os.path.join(ROOT, "native", "src", "tpr_rdv.cc"),
         os.path.join(ROOT, "native", "src", "tpr_obs.cc"),
         os.path.join(ROOT, "native", "src", "ring.cc"),
         "-I", os.path.join(ROOT, "native", "include"),
         "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=300, capture_output=True)
    import json as _json

    def rate(env_extra):
        env = dict(os.environ, **env_extra)
        best = 0.0
        for _ in range(2):  # best of 2 absorbs scheduler noise
            out = subprocess.run([str(binp), "64", "2", "1", "1"],
                                 capture_output=True, text=True, timeout=60,
                                 env=env)
            assert out.returncode == 0, out.stderr
            rec = _json.loads(out.stdout.strip().splitlines()[-1])
            best = max(best, rec["rate_rps"])
        return best

    import sys as _sys

    tcp = rate({"GRPC_PLATFORM_TYPE": "TCP"})
    ring = rate({"GRPC_PLATFORM_TYPE": "RDMA_BP",
                 "GRPC_RDMA_RING_BUFFER_SIZE_KB": "1024"})
    _sys.stderr.write(f"ring={ring:.0f} tcp={tcp:.0f} RPC/s\n")
    assert ring > tcp * 0.9  # ring must at least match TCP (wins by ~1.6x
    # unloaded; 0.9 margin absorbs CI noise without masking a regression)


def test_native_server_survives_garbage_connections():
    """Junk at the native server's protocol sniff (random bytes, truncated
    TRB1, oversized frame headers) costs only its own connection; the
    server keeps serving real clients."""
    import socket
    import struct

    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        payloads = [
            os.urandom(64),
            b"TRB",                                   # truncated ring magic
            b"TRB1" + os.urandom(32),                  # bogus bootstrap blob
            b"TPURPC\x01\x00" + os.urandom(64),        # junk after preface
            b"TPURPC\x01\x00" + struct.pack(           # oversized frame
                "<BBII", 2, 0, 1, 0xFFFFFFF0),
        ]
        for _ in range(4):
            for junk in payloads:
                s = socket.create_connection(("127.0.0.1", port), timeout=10)
                try:
                    s.sendall(junk)
                except OSError:
                    pass
                finally:
                    s.close()
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/demo.Greeter/Echo")(b"alive",
                                                        timeout=20) == b"alive"
        assert proc.poll() is None  # the server process itself survived
    finally:
        proc.kill()
        proc.wait()
