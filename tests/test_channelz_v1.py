"""grpc.channelz.v1 wire service: stock grpcio client, hand-decoded protos
(the grpc_channelz package isn't in this image; these are the bytes the
grpcdebug tool sends)."""

import grpc
import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.channelz_v1 import SERVICE, enable_channelz
from tpurpc.wire.protowire import fields, ld, vf

_ID = lambda b: b


def _submsgs(raw, field_no):
    return [bytes(v) for f, _w, v in fields(bytes(raw)) if f == field_no]


def _field(raw, field_no, default=None):
    for f, _w, v in fields(bytes(raw)):
        if f == field_no:
            return v
    return default


@pytest.fixture()
def served():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/z.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r), inline=True))
    enable_channelz(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield srv, port
    srv.stop(grace=0)


def test_get_servers_stock_grpcio(served):
    srv, port = served
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.unary_unary(f"/{SERVICE}/GetServers", _ID, _ID)
        resp = mc(b"")  # defaults: start 0
    servers = _submsgs(resp, 1)
    assert servers, "no servers listed"
    assert _field(resp, 2) == 1  # end = true
    # our server is among them: its ref has an id, its listen socket is
    # named after the port (socket ids come from the entity-id space)
    found = False
    for s in servers:
        ref = _field(s, 1)
        for sock in _submsgs(s, 3):
            if _field(sock, 2) == f"listen:{port}".encode():
                found = True
                assert _field(sock, 1, 0) > 0
        assert ref is not None and _field(ref, 1, 0) > 0
    assert found, f"listen socket {port} not reported"


def test_channel_counters_and_get_channel(served):
    _, port = served
    with rpc.insecure_channel(f"127.0.0.1:{port}") as tch:
        echo = tch.unary_unary("/z.S/Echo")
        for _ in range(3):
            assert echo(b"x", timeout=10) == b"x"
        cid = tch._channelz_id
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary(f"/{SERVICE}/GetChannel", _ID, _ID)
            resp = mc(vf(1, cid))
        channel = _field(resp, 1)
        data = _field(channel, 2)
        assert _field(data, 4) >= 3      # calls_started
        assert _field(data, 5) >= 3      # calls_succeeded
        state = _field(data, 1)
        assert _field(state, 1) == 3     # READY (channelz.proto)
        # NOT_FOUND for a bogus id
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary(f"/{SERVICE}/GetChannel", _ID, _ID)
            with pytest.raises(grpc.RpcError) as ei:
                mc(vf(1, 999999))
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_server_call_counters_move(served):
    srv, port = served
    with rpc.insecure_channel(f"127.0.0.1:{port}") as tch:
        assert tch.unary_unary("/z.S/Echo")(b"y", timeout=10) == b"y"
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.unary_unary(f"/{SERVICE}/GetServer", _ID, _ID)
        resp = mc(vf(1, srv._channelz_id))
    data = _field(_field(resp, 1), 2)
    assert _field(data, 2, 0) >= 1  # calls_started (incl. this RPC family)


def test_pagination(served):
    _, port = served
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.unary_unary(f"/{SERVICE}/GetTopChannels", _ID, _ID)
        # max_results=1: first page may not be the end (suite leaves live
        # channels around); walking with start_channel_id terminates
        start, seen, pages = 0, 0, 0
        while True:
            resp = mc(vf(1, start) + vf(2, 1))
            chans = _submsgs(resp, 1)
            seen += len(chans)
            pages += 1
            if _field(resp, 2) == 1 or not chans:
                break
            ref = _field(chans[-1], 1)
            start = _field(ref, 1) + 1
            assert pages < 1000
    assert seen >= 1


def test_get_server_sockets_and_get_socket(served):
    srv, port = served
    with rpc.insecure_channel(f"127.0.0.1:{port}") as tch:
        tch.unary_unary("/z.S/Echo")(b"s", timeout=10)  # a live native conn
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary(f"/{SERVICE}/GetServerSockets", _ID, _ID)
            resp = mc(vf(1, srv._channelz_id))
            assert _field(resp, 2) == 1  # end
            refs = _submsgs(resp, 1)
            assert refs, "no live connection sockets listed"
            sid = _field(refs[0], 1)
            gs = ch.unary_unary(f"/{SERVICE}/GetSocket", _ID, _ID)
            sock = _field(gs(vf(1, sid)), 1)
            data = _field(sock, 2)
            assert _field(data, 1, 0) >= 1  # streams_started
            assert _field(sock, 4) is not None  # remote TcpIpAddress
            with pytest.raises(grpc.RpcError) as ei:
                mc(vf(1, 999999))
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND
            with pytest.raises(grpc.RpcError) as ei:
                gs(vf(1, 999999))
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_deadline_expired_call_counts_as_failed(served):
    import time as _t

    srv, port = served

    def slow(req, ctx):
        _t.sleep(1.0)
        return b"late"

    srv.add_method("/z.S/Slow", rpc.unary_unary_rpc_method_handler(slow))
    with rpc.insecure_channel(f"127.0.0.1:{port}") as tch:
        with pytest.raises(rpc.RpcError):
            tch.unary_unary("/z.S/Slow")(b"", timeout=0.2)
        c = tch.call_counters
        deadline = _t.monotonic() + 5
        while c.failed < 1 and _t.monotonic() < deadline:
            _t.sleep(0.02)
        assert c.started == 1 and c.failed == 1  # reconciled


def test_get_socket_resolves_listen_socket_ids(served):
    """The listen SocketRef ids GetServer advertises must resolve via
    GetSocket (review finding: they 404'd)."""
    srv, port = served
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        resp = ch.unary_unary(f"/{SERVICE}/GetServer", _ID, _ID)(
            vf(1, srv._channelz_id))
        server_msg = _field(resp, 1)
        listen_refs = _submsgs(server_msg, 3)
        assert listen_refs
        sid = _field(listen_refs[0], 1)
        sock = _field(ch.unary_unary(f"/{SERVICE}/GetSocket", _ID, _ID)(
            vf(1, sid)), 1)
        ref = _field(sock, 1)
        assert _field(ref, 2) == f"listen:{port}".encode()
