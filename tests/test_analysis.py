"""tpurpc.analysis: lint fixtures, lock-order detector, ring model checker.

Three layers (ISSUE 2):
* AST lint — positive/negative fixtures per rule, and the repo-wide gate
  (the tree must be clean, with zero copy-suppressions in hot modules).
* CheckedLock — a seeded lock-order cycle the detector must flag, the
  self-deadlock trap, cv-wait-while-holding, and blocking-call notes.
* ringcheck — the exhaustive suites must pass on the real protocol and
  reject every seeded mutant.

Plus regression tests for the concurrency fixes this subsystem surfaced
(poller start/stop, channelz counter snapshots, xds subscription handoff).
"""

import threading

import pytest

from tpurpc.analysis import lint, locks, ringcheck
from tpurpc.analysis.lint import lint_source


def _rules(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# lint: lease pairing
# ---------------------------------------------------------------------------

LEASE_OK = '''
def write_lease(lib, call, segs):
    if lib.tpr_call_send_reserve2(call) != 0:
        return False
    try:
        fill(segs)
    except BaseException:
        lib.tpr_call_send_abort(call)
        raise
    if lib.tpr_call_send_commit(call) != 0:
        raise RuntimeError("send failed")
    return True
'''

LEASE_NO_COMMIT = '''
def write_lease(lib, call, segs):
    lib.tpr_call_send_reserve2(call)
    try:
        fill(segs)
    except BaseException:
        lib.tpr_call_send_abort(call)
        raise
'''

LEASE_NO_ABORT = '''
def write_lease(lib, call, segs):
    lib.tpr_call_send_reserve2(call)
    fill(segs)
    lib.tpr_call_send_commit(call)
'''

LEASE_ABORT_NOT_EXCEPTIONAL = '''
def write_lease(lib, call, segs):
    lib.tpr_call_send_reserve2(call)
    if not fill(segs):
        lib.tpr_call_send_abort(call)
        return False
    lib.tpr_call_send_commit(call)
    return True
'''

LEASE_UNCOVERED_FILL = '''
def write_lease(lib, call, segs):
    lib.tpr_call_send_reserve2(call)
    fill(segs)  # raises -> lease leaks: not inside the try
    try:
        fill(segs)
    except BaseException:
        lib.tpr_call_send_abort(call)
        raise
    lib.tpr_call_send_commit(call)
'''


def test_lease_pairing_positive():
    assert lint_source(LEASE_OK, "fixture.py") == []


def test_lease_missing_commit_flagged():
    vs = lint_source(LEASE_NO_COMMIT, "fixture.py")
    assert _rules(vs) == ["lease"] and "never commits" in vs[0].message


def test_lease_missing_abort_flagged():
    vs = lint_source(LEASE_NO_ABORT, "fixture.py")
    assert _rules(vs) == ["lease"] and "exception path" in vs[0].message


def test_lease_abort_outside_handler_flagged():
    vs = lint_source(LEASE_ABORT_NOT_EXCEPTIONAL, "fixture.py")
    assert _rules(vs) == ["lease"]


def test_lease_uncovered_fill_flagged():
    vs = lint_source(LEASE_UNCOVERED_FILL, "fixture.py")
    assert any("not covered" in v.message for v in vs)


def test_lease_suppression():
    src = LEASE_NO_COMMIT.replace(
        "lib.tpr_call_send_reserve2(call)",
        "lib.tpr_call_send_reserve2(call)  # tpr: allow(lease)")
    assert lint_source(src, "fixture.py") == []


# ---------------------------------------------------------------------------
# lint: hot-path no-copy
# ---------------------------------------------------------------------------

def test_copy_join_flagged_in_hot_module():
    src = 'def f(parts):\n    return b"".join(parts)\n'
    vs = lint_source(src, "fixture.py", hot_copy=True)
    assert _rules(vs) == ["copy"]
    # the same source outside a hot module passes
    assert lint_source(src, "fixture.py", hot_copy=False) == []


def test_copy_from_buffer_copy_flagged():
    src = "def f(ctypes, v):\n    return (ctypes.c_uint8 * 4).from_buffer_copy(v)\n"
    assert _rules(lint_source(src, "fixture.py", hot_copy=True)) == ["copy"]


def test_copy_slice_to_bytes_flagged():
    src = "def f(buf, n):\n    return bytes(buf[:n])\n"
    assert _rules(lint_source(src, "fixture.py", hot_copy=True)) == ["copy"]


def test_copy_tobytes_escape_hatch_allowed():
    src = ("def f(buf, n):\n"
           "    mv = memoryview(buf)\n"
           "    return mv[:n].tobytes()\n")
    assert lint_source(src, "fixture.py", hot_copy=True) == []


def test_copy_suppression_comment():
    src = 'def f(parts):\n    return b"".join(parts)  # tpr: allow(copy)\n'
    assert lint_source(src, "fixture.py", hot_copy=True) == []


def test_hot_modules_carry_no_copy_suppressions():
    """Acceptance: the data-plane modules are clean WITHOUT suppressions."""
    import os

    root = os.path.dirname(lint.tree_root())
    for suffix in lint.HOT_COPY_MODULES:
        path = os.path.join(root, suffix)
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        assert "allow(copy" not in src, f"{suffix} suppresses the copy rule"
        assert lint_source(src, path) == []


# ---------------------------------------------------------------------------
# lint: lock map
# ---------------------------------------------------------------------------

LOCKMAP_OK = '''
class Pool:
    _GUARDED_BY = {"items": "_lock", "count": "_lock"}

    def __init__(self):
        self.items = []   # __init__ exempt: construction happens-before
        self.count = 0

    def add(self, x):
        with self._lock:
            self.items.append(x)
            self.count += 1
'''

LOCKMAP_BAD = '''
class Pool:
    _GUARDED_BY = {"items": "_lock"}

    def add(self, x):
        self.items.append(x)

    def reset(self):
        self.items[:] = []
'''


def test_lockmap_positive():
    assert lint_source(LOCKMAP_OK, "fixture.py") == []


def test_lockmap_unlocked_mutations_flagged():
    vs = lint_source(LOCKMAP_BAD, "fixture.py")
    assert _rules(vs) == ["lock"] and len(vs) == 2  # append + slice-assign


def test_lockmap_wrong_lock_flagged():
    src = LOCKMAP_OK.replace('with self._lock:', 'with self._other:')
    vs = lint_source(src, "fixture.py")
    assert _rules(vs) == ["lock"]


# ---------------------------------------------------------------------------
# lint: monotonic clocks
# ---------------------------------------------------------------------------

def test_wallclock_flagged_and_suppressable():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert _rules(lint_source(src, "fixture.py")) == ["wallclock"]
    ok = src.replace("time.time()", "time.time()  # tpr: allow(wallclock)")
    assert lint_source(ok, "fixture.py") == []
    mono = src.replace("time.time()", "time.monotonic()")
    assert lint_source(mono, "fixture.py") == []


# ---------------------------------------------------------------------------
# lint: guarded logging on hot-path modules (ISSUE 4)
# ---------------------------------------------------------------------------

LOG_SRC = '''
from tpurpc.utils.trace import log_debug, log_info, log_error, trace_ring

def hot(msg):
    log_debug("got %r", msg)            # unguarded: formatting always runs
    log_info("state %s", msg)           # unguarded
    log_error("broken: %s", msg)        # error paths are cold: exempt
    if trace_ring:
        log_debug("guarded %r", msg)    # behind the flag: fine
    if trace_ring.enabled:
        log_info("also guarded %s", msg)
    trace_ring.log("flag-local %r", msg)  # TraceFlag.log checks enabled
'''


def test_log_rule_flags_unguarded_hot_logging():
    vs = lint_source(LOG_SRC, "tpurpc/core/ring.py")
    assert _rules(vs) == ["log"]
    assert len(vs) == 2  # the two unguarded log_debug/log_info calls
    assert {v.line for v in vs} == {5, 6}


def test_log_rule_scoped_to_hot_modules():
    # the same source off the hot-path module set is fine
    assert lint_source(LOG_SRC, "tpurpc/rpc/server.py") == []
    assert lint_source(LOG_SRC, "fixture.py") == []
    # ...and every declared hot module enforces it
    for mod in ("tpurpc/core/pair.py", "tpurpc/core/poller.py",
                "tpurpc/wire/grpc_h2.py"):
        assert _rules(lint_source(LOG_SRC, mod)) == ["log"]


def test_log_rule_suppression_comment():
    ok = LOG_SRC.replace('log_debug("got %r", msg)',
                         'log_debug("got %r", msg)  # tpr: allow(log)')
    ok = ok.replace('log_info("state %s", msg)',
                    'log_info("state %s", msg)  # tpr: allow(log)')
    assert lint_source(ok, "tpurpc/core/ring.py") == []


def test_log_rule_hot_modules_are_clean():
    import tpurpc.core.pair
    import tpurpc.core.poller
    import tpurpc.core.ring
    import tpurpc.wire.grpc_h2

    for mod in (tpurpc.core.ring, tpurpc.core.pair, tpurpc.core.poller,
                tpurpc.wire.grpc_h2):
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert [v for v in vs if v.rule == "log"] == []


# ---------------------------------------------------------------------------
# lint: no blocking calls on the inline dispatch path (ISSUE 3)
# ---------------------------------------------------------------------------

BLOCK_SRC = '''
import time

class _ServerConnection:
    def _run_handler_inner(self, handler, st, ctx, path):
        time.sleep(0.1)
        item = st.requests.get()
        self._lock.acquire()
        st._credits.wait()
        self._thread.join()

    def off_path_helper(self):
        time.sleep(1)          # not an inline-dispatch function: allowed
'''

BLOCK_BOUNDED = '''
class _ServerStream:
    def next_request(self, timeout=None):
        item = self.requests.get(timeout=timeout)
        self._credits.acquire(timeout=0.25)
        self._credits.acquire(blocking=False)
        self._done.wait(timeout=1.0)
        self._thread.join(5)
        return item
'''


def test_block_rule_flags_unbounded_calls_on_dispatch_path():
    vs = lint_source(BLOCK_SRC, "tpurpc/rpc/server.py")
    assert _rules(vs) == ["block"]
    # sleep, bare .get(), bare .acquire(), bare .wait(), bare .join() —
    # and ONLY inside the configured inline-path functions
    assert len(vs) == 5
    assert all("_run_handler_inner" in v.message for v in vs)


def test_block_rule_bounded_waits_pass():
    assert lint_source(BLOCK_BOUNDED, "tpurpc/rpc/server.py") == []


def test_block_rule_scoped_to_inline_dispatch_module():
    # the same source outside rpc/server.py is not on the dispatch path
    assert lint_source(BLOCK_SRC, "tpurpc/rpc/channel.py") == []
    assert lint_source(BLOCK_SRC, "fixture.py") == []


def test_block_rule_suppression_comment():
    src = BLOCK_BOUNDED.replace(
        "item = self.requests.get(timeout=timeout)",
        "item = self.requests.get()  # tpr: allow(block)")
    assert lint_source(src, "tpurpc/rpc/server.py") == []
    # without the annotation the same line is a finding
    bare = BLOCK_BOUNDED.replace(
        "item = self.requests.get(timeout=timeout)",
        "item = self.requests.get()")
    assert _rules(lint_source(bare, "tpurpc/rpc/server.py")) == ["block"]


def test_block_rule_real_server_module_is_clean():
    import importlib

    server_mod = importlib.import_module("tpurpc.rpc.server")
    path = server_mod.__file__
    with open(path, "r", encoding="utf-8") as f:
        vs = lint_source(f.read(), path)
    assert [v for v in vs if v.rule == "block"] == []


# ---------------------------------------------------------------------------
# the repo-wide gate
# ---------------------------------------------------------------------------

def test_tree_is_lint_clean():
    violations = lint.lint_tree()
    assert violations == [], "\n".join(map(str, violations))


# ---------------------------------------------------------------------------
# runtime lock-order detector
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _clean_lock_state():
    locks.reset_lock_state()
    yield
    locks.reset_lock_state()


def test_checked_lock_passthrough_semantics():
    lk = locks.CheckedLock("t.lk")
    with lk:
        assert lk.locked()
    assert not lk.locked()
    assert lk.acquire(blocking=False)
    lk.release()


def test_lock_order_cycle_reported():
    a = locks.CheckedLock("t.A")
    b = locks.CheckedLock("t.B")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()
    v = locks.lock_violations()
    assert any("lock-order cycle" in m and "t.A" in m and "t.B" in m
               for m in v), v


def test_lock_order_cycle_by_name_across_instances():
    """Lockdep-style: two INSTANCES of the same named lock form one graph
    node, so the cycle is caught without the same objects ever deadlocking."""
    a1, a2 = locks.CheckedLock("t.A"), locks.CheckedLock("t.A")
    b = locks.CheckedLock("t.B")
    with a1:
        with b:
            pass
    with b:
        with a2:
            pass
    assert any("lock-order cycle" in m for m in locks.lock_violations())


def test_no_cycle_no_violation():
    a = locks.CheckedLock("t.A")
    b = locks.CheckedLock("t.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert locks.lock_violations() == []


def test_self_deadlock_trapped():
    lk = locks.CheckedLock("t.self")
    with lk:
        with pytest.raises(RuntimeError, match="re-acquire"):
            lk.acquire()
    assert any("self-deadlock" in m for m in locks.lock_violations())


def test_cv_wait_while_holding_other_lock_flagged():
    other = locks.CheckedLock("t.other")
    cv = locks.checked_condition("t.cv")
    with other:
        with cv:
            cv.wait(timeout=0.01)
    assert any("cv-wait" in m and "t.other" in m
               for m in locks.lock_violations())


def test_cv_wait_alone_is_clean():
    cv = locks.checked_condition("t.cv")
    with cv:
        cv.wait(timeout=0.01)
    assert locks.lock_violations() == []


def test_note_blocking_flags_held_locks(monkeypatch):
    monkeypatch.setattr(locks, "ENABLED", True)
    lk = locks.CheckedLock("t.held")
    locks.note_blocking("socket recv")  # nothing held: no violation
    assert locks.lock_violations() == []
    with lk:
        locks.note_blocking("socket recv")
    assert any("held across blocking call" in m
               for m in locks.lock_violations())


def test_factories_are_zero_overhead_when_disabled(monkeypatch):
    monkeypatch.setattr(locks, "ENABLED", False)
    assert type(locks.make_lock("x")) is type(threading.Lock())
    assert isinstance(locks.make_condition("x"), threading.Condition)
    monkeypatch.setattr(locks, "ENABLED", True)
    assert isinstance(locks.make_lock("x"), locks.CheckedLock)
    assert isinstance(locks.make_condition("x"), locks.CheckedCondition)


# ---------------------------------------------------------------------------
# ring model checker
# ---------------------------------------------------------------------------

def test_ring_protocol_exhaustive_ok():
    for res in ringcheck.default_suite():
        assert res.ok, repr(res)
        assert res.states > 0


def test_ring_capacity4_exhausts_with_wrap():
    # 3 messages x span 3 through a 4-word ring: every offset wraps twice
    res = ringcheck.check_ring(4, [1, 1, 1])
    assert res.ok and res.states > 0


def test_batched_write_many_protocol_ok():
    res = ringcheck.check_ring(8, [1, 1, 1], batched=True)
    assert res.ok, repr(res)


@pytest.mark.parametrize("mutant", ringcheck.MUTANTS)
def test_every_seeded_mutant_is_killed(mutant):
    kills = ringcheck.mutant_kill_suite()
    assert kills[mutant], f"mutant {mutant} survived the checker"


def test_publish_before_write_is_torn_read():
    res = ringcheck.check_ring(8, [1, 1], mutant="publish_before_write")
    assert not res.ok and res.violation.kind == "torn"
    assert res.violation.trace  # a concrete interleaving is reported


def test_ignore_credits_is_overwrite():
    res = ringcheck.check_ring(4, [1, 1, 1], mutant="ignore_credits")
    assert not res.ok and res.violation.kind in ("overwrite", "torn")


def test_cli_default_gate_exits_zero():
    from tpurpc.analysis.__main__ import main

    assert main([]) == 0


# ---------------------------------------------------------------------------
# regressions for the fixes the new passes surfaced
# ---------------------------------------------------------------------------

def test_poller_concurrent_start_stop_regression():
    """start() used to flip _running outside the cv lock; racing starts or a
    start/stop overlap could wedge the scan threads."""
    from tpurpc.core.poller import Poller

    p = Poller(thread_num=2)
    threads = [threading.Thread(target=p.start) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert p._running and len(p._threads) == 2
    p.stop()
    assert not p._running and p._threads == []


def test_channelz_counter_snapshot_regression():
    """as_dict() used to read the counters unlocked — a snapshot could pair
    a call count with the previous call's timestamp."""
    from tpurpc.rpc.channelz import CallCounters

    c = CallCounters()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            c.on_start()
            c.on_finish(True)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(200):
            snap = c.as_dict()
            if snap["calls_started"]:
                assert snap["last_call_started"] > 0.0
            assert snap["calls_succeeded"] <= snap["calls_started"]
    finally:
        stop.set()
        t.join()


def test_xds_subscription_swap_under_load_regression():
    """The v3 reader thread now compares AND swaps `subscribed` inside the
    servicer lock; set_endpoints churn concurrent with subscription reads
    must never tear (the round-5 xds.py:161 bug class)."""
    from tpurpc.rpc.xds import XdsServicer

    s = XdsServicer()
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            s.set_endpoints("svc", [f"h{i}:1"])
            i += 1

    t = threading.Thread(target=churn)
    t.start()
    try:
        for _ in range(300):
            eps = s.get_endpoints("svc")
            assert len(eps) <= 1
    finally:
        stop.set()
        t.join()


def test_lockmap_declarations_hold_on_declaring_modules():
    """The regression guard for the declared lock maps: the modules that
    declare _GUARDED_BY must stay clean under the lock-map pass."""
    import tpurpc.core.poller as poller_mod
    import tpurpc.rpc.channelz as channelz_mod
    import tpurpc.rpc.xds as xds_mod

    for mod in (poller_mod, channelz_mod, xds_mod):
        path = mod.__file__
        with open(path, "r", encoding="utf-8") as f:
            vs = [v for v in lint_source(f.read(), path) if v.rule == "lock"]
        assert vs == [], vs


# ---------------------------------------------------------------------------
# lint: shard confinement (tpurpc-manycore, ISSUE 7)
# ---------------------------------------------------------------------------

SHARD_OK = '''
class Sub:
    _GUARDED_BY = {"out": "done"}

class Merger:
    _MERGE_BOUNDARY = ("_merge_loop", "_resolve")

    def _merge_loop(self):
        sub = self.ring.take()
        self._resolve(sub)

    def _resolve(self, sub):
        sub.out = 1        # cross-shard write INSIDE the boundary: legal
'''

SHARD_CROSS_MUTATION = '''
class Sub:
    _GUARDED_BY = {"out": "done"}

class Merger:
    _MERGE_BOUNDARY = ("_merge_loop",)

    def _merge_loop(self):
        pass

    def helper(self, sub):
        sub.out = 1        # cross-shard write OUTSIDE the boundary
'''

SHARD_MUTATOR_CALL = '''
class Shard:
    _GUARDED_BY = {"_queue": "_lock"}

class Merger:
    _MERGE_BOUNDARY = ("_merge_loop",)

    def _merge_loop(self):
        pass

    def steal(self, other):
        other._queue.append(1)   # reaching into another shard's queue
'''

SHARD_SELF_OK = '''
class Shard:
    _GUARDED_BY = {"_queue": "_lock"}
    _MERGE_BOUNDARY = ("_merge_loop",)

    def _merge_loop(self):
        pass

    def local(self):
        with self._lock:
            self._queue.append(1)   # shard-LOCAL mutation: the lock map rules
'''

SHARD_NOT_ARMED = '''
class Shard:
    _GUARDED_BY = {"_queue": "_lock"}

def elsewhere(other):
    other._queue.append(1)   # no _MERGE_BOUNDARY in module: rule silent
'''


def test_shard_rule_boundary_mutation_passes():
    assert "shard" not in _rules(lint_source(SHARD_OK, "x.py"))


def test_shard_rule_flags_cross_shard_mutation():
    v = [x for x in lint_source(SHARD_CROSS_MUTATION, "x.py")
         if x.rule == "shard"]
    assert len(v) == 1 and "Sub.out" in v[0].message


def test_shard_rule_flags_mutator_calls():
    v = [x for x in lint_source(SHARD_MUTATOR_CALL, "x.py")
         if x.rule == "shard"]
    assert len(v) == 1 and "Shard._queue" in v[0].message


def test_shard_rule_self_mutation_is_lock_maps_job():
    assert "shard" not in _rules(lint_source(SHARD_SELF_OK, "x.py"))


def test_shard_rule_only_armed_with_merge_boundary():
    assert "shard" not in _rules(lint_source(SHARD_NOT_ARMED, "x.py"))


def test_shard_rule_suppression_comment():
    src = SHARD_CROSS_MUTATION.replace(
        "sub.out = 1 ", "sub.out = 1  # tpr: allow(shard)")
    assert "shard" not in _rules(lint_source(src, "x.py"))


def test_shard_rule_jaxshim_service_is_clean():
    """The real merge module must satisfy its own declared boundary."""
    import os

    import tpurpc

    path = os.path.join(os.path.dirname(tpurpc.__file__), "jaxshim",
                        "service.py")
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    assert "_MERGE_BOUNDARY" in src  # the rule is ARMED there
    assert "shard" not in _rules(lint_source(src, path))


# ---------------------------------------------------------------------------
# ringcheck: MPMC handoff model (tpurpc-manycore, ISSUE 7)
# ---------------------------------------------------------------------------

def test_handoff_protocol_exhaustive_ok():
    res = ringcheck.check_handoff(n_producers=2, items_per_producer=2,
                                  capacity=2, words=2)
    assert res.ok, res


def test_handoff_three_producers_ok():
    res = ringcheck.check_handoff(n_producers=3, items_per_producer=1,
                                  capacity=2, words=2)
    assert res.ok, res


@pytest.mark.parametrize("mutant", ringcheck.HANDOFF_MUTANTS)
def test_every_handoff_mutant_is_killed(mutant):
    kills = ringcheck.handoff_mutant_kill_suite()
    assert kills[mutant], f"handoff mutant {mutant} survived"


def test_handoff_read_uncommitted_is_torn():
    res = ringcheck.check_handoff(n_producers=2, items_per_producer=2,
                                  capacity=2, words=2,
                                  mutant="handoff_read_uncommitted")
    assert not res.ok and res.violation.kind == "torn"


def test_handoff_runtime_matches_model_shape():
    """The runtime HandoffRing implements the modeled protocol: claim via
    one atomic ticket, commit stamp after payload, ticket-order consume,
    lap-free stamp — spot-check the stamps through one lap."""
    from tpurpc.core.handoff import HandoffRing

    ring = HandoffRing(capacity=2)
    assert ring._seq == [0, 1]         # lap-0 free stamps
    assert ring.publish("a")
    assert ring._seq[0] == 1           # commit stamp t+1
    assert ring.take() == "a"
    assert ring._seq[0] == 2           # freed for lap 1 (h + capacity)
    assert ring.publish("b") and ring.publish("c")
    assert ring.take() == "b" and ring.take() == "c"
    ring.close()


# ---------------------------------------------------------------------------
# lint: static stage/hop registrations + pure-int hop accounting (ISSUE 8)
# ---------------------------------------------------------------------------

STAGE_OK = '''
from tpurpc.obs import lens as _lens
from tpurpc.obs import profiler as _profiler

_LENS_WIRE_BYTES, _LENS_WIRE_NS, _LENS_WIRE_COPY = _lens.hop_counters("wire")

_LENS_STAGES = {"write": "wire", "read": "wire"}
_profiler.register_stages(__file__, _LENS_STAGES)
_profiler.register_stages("socketserver.py", {"serve_forever": "idle"})


def site(n, t0, t1):
    dt = t1 - t0
    _LENS_WIRE_NS.inc(dt)
    _LENS_WIRE_BYTES.inc(n)
'''


def test_stage_rule_static_registrations_pass():
    assert lint_source(STAGE_OK, "fixture.py") == []


def test_stage_rule_flags_registration_inside_function():
    src = STAGE_OK + '''

def late(profiler):
    profiler.register_stages(__file__, _LENS_STAGES)
'''
    vs = lint_source(src, "fixture.py")
    assert _rules(vs) == ["stage"] and "module-level" in vs[0].message


def test_stage_rule_flags_dynamic_strings():
    src = '''
from tpurpc.obs import profiler as _profiler

name = "ring" + "-write"
_profiler.register_stages(__file__, {"writev": name})
'''
    vs = lint_source(src, "fixture.py")
    assert _rules(vs) == ["stage"] and "static" in vs[0].message


def test_stage_rule_flags_non_constant_mapping_name():
    src = '''
from tpurpc.obs import profiler as _profiler


def build():
    return {"writev": "ring-write"}


_MAPPING = build()
_profiler.register_stages(__file__, _MAPPING)
'''
    assert _rules(lint_source(src, "fixture.py")) == ["stage"]


def test_stage_rule_flags_dynamic_hop_name():
    src = '''
from tpurpc.obs import lens as _lens

hop = "wire"
_LENS_X_B, _LENS_X_NS, _LENS_X_C = _lens.hop_counters(hop)
'''
    vs = lint_source(src, "fixture.py")
    assert _rules(vs) == ["stage"] and "string-literal" in vs[0].message


def test_stage_rule_flags_hop_binding_inside_function():
    src = '''
from tpurpc.obs import lens as _lens


def bind():
    return _lens.hop_counters("wire")
'''
    assert _rules(lint_source(src, "fixture.py")) == ["stage"]


def test_stage_rule_flags_calls_in_hop_accounting():
    src = STAGE_OK + '''

def bad_site(views):
    _LENS_WIRE_BYTES.inc(sum(len(v) for v in views))
'''
    vs = lint_source(src, "fixture.py")
    assert _rules(vs) == ["stage"]
    assert "precompute the int" in vs[0].message


def test_stage_rule_flags_str_constant_in_hop_accounting():
    src = STAGE_OK + '''

def bad_site2():
    _LENS_WIRE_NS.inc("12")
'''
    assert _rules(lint_source(src, "fixture.py")) == ["stage"]


def test_stage_rule_ignores_non_lens_counters():
    src = '''
def site(c, n):
    c.inc(len(n))          # a plain counter: not hop accounting
    _OTHER.inc(str(n))     # not a _LENS_ binding either
'''
    assert lint_source(src, "fixture.py") == []


def test_stage_rule_suppression_comment():
    src = STAGE_OK + '''

def deliberate(views):
    _LENS_WIRE_BYTES.inc(len(views))  # tpr: allow(stage)
'''
    assert lint_source(src, "fixture.py") == []


def test_stage_rule_instrumented_modules_are_clean():
    """The real hop-accounting/marker modules hold the contract."""
    import tpurpc.core.endpoint
    import tpurpc.core.pair
    import tpurpc.core.ring
    import tpurpc.jaxshim.codec
    import tpurpc.obs.profiler
    import tpurpc.tpu.endpoint
    import tpurpc.tpu.hbm_ring

    for mod in (tpurpc.core.ring, tpurpc.core.pair, tpurpc.core.endpoint,
                tpurpc.jaxshim.codec, tpurpc.tpu.hbm_ring,
                tpurpc.tpu.endpoint, tpurpc.obs.profiler):
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert [v for v in vs if v.rule == "stage"] == [], mod.__name__


# ---------------------------------------------------------------------------
# lint: rendezvous claim pairing (tpurpc-express, ISSUE 9)
# ---------------------------------------------------------------------------

RDV_OK = '''
def send_big(self, stream_id, flags, segs, total):
    claim = self.rdv_claim(stream_id, total, 1)
    if claim is None:
        return False
    try:
        self._rdv_write(claim, segs, total)
    except BaseException:
        self.rdv_release(claim)
        raise
    self.rdv_complete(claim, stream_id, flags, total)
    return True
'''

RDV_NO_COMPLETE = '''
def send_big(self, stream_id, total):
    claim = self.rdv_claim(stream_id, total, 1)
    self._rdv_write(claim, [], total)
'''

RDV_NO_RELEASE = '''
def send_big(self, stream_id, flags, segs, total):
    claim = self.rdv_claim(stream_id, total, 1)
    self._rdv_write(claim, segs, total)
    self.rdv_complete(claim, stream_id, flags, total)
'''

RDV_RELEASE_NOT_EXCEPTIONAL = '''
def send_big(self, stream_id, flags, segs, total):
    claim = self.rdv_claim(stream_id, total, 1)
    if bad(claim):
        self.rdv_release(claim)
        return False
    self._rdv_write(claim, segs, total)
    self.rdv_complete(claim, stream_id, flags, total)
'''


def test_rdv_pairing_positive():
    assert lint_source(RDV_OK, "fixture.py") == []


def test_rdv_missing_complete_flagged():
    vs = lint_source(RDV_NO_COMPLETE, "fixture.py")
    assert _rules(vs) == ["rdv"] and "never" in vs[0].message


def test_rdv_missing_release_flagged():
    vs = lint_source(RDV_NO_RELEASE, "fixture.py")
    assert _rules(vs) == ["rdv"] and "exception path" in vs[0].message


def test_rdv_release_outside_handler_flagged():
    # a release on a NON-exception branch does not cover the raise-between-
    # claim-and-complete window
    vs = lint_source(RDV_RELEASE_NOT_EXCEPTIONAL, "fixture.py")
    assert _rules(vs) == ["rdv"]


def test_rdv_finally_release_passes():
    src = RDV_NO_RELEASE.replace(
        "    self._rdv_write(claim, segs, total)\n",
        "    try:\n"
        "        self._rdv_write(claim, segs, total)\n"
        "    finally:\n"
        "        self.rdv_release(claim)\n")
    assert lint_source(src, "fixture.py") == []


def test_rdv_suppression():
    src = RDV_NO_COMPLETE.replace(
        "self.rdv_claim(stream_id, total, 1)",
        "self.rdv_claim(stream_id, total, 1)  # tpr: allow(rdv)")
    assert lint_source(src, "fixture.py") == []


def test_rdv_rendezvous_module_is_clean():
    """The real sender (core/rendezvous.py) holds the claim-pairing and
    flight-encoder contracts it exports."""
    import tpurpc.core.rendezvous as rdv_mod

    with open(rdv_mod.__file__, "r", encoding="utf-8") as f:
        vs = lint_source(f.read(), rdv_mod.__file__)
    assert [v for v in vs if v.rule in ("rdv", "flight")] == []


# ---------------------------------------------------------------------------
# ringcheck: rendezvous offer/claim/write/complete model (tpurpc-express)
# ---------------------------------------------------------------------------

def test_rendezvous_model_clean_configs():
    from tpurpc.analysis import ringcheck

    for cfg in (dict(messages=2, words=2, standing=True),
                dict(messages=2, words=2, standing=False),
                dict(messages=3, words=2, standing=True)):
        res = ringcheck.check_rendezvous(**cfg)
        assert res.ok, res


def test_rendezvous_model_peer_death_releases_claims():
    """Sender death explored at EVERY protocol point: the receiver's close
    must release the claimed landing region (the leaked-claim violation
    fires otherwise — proven by the mutant-free death configs passing and
    by hand-wiring a close-less variant being impossible without editing
    the model)."""
    from tpurpc.analysis import ringcheck

    for standing in (True, False):
        res = ringcheck.check_rendezvous(messages=2, words=2,
                                         standing=standing,
                                         with_death=True)
        assert res.ok, res


def test_rendezvous_mutants_killed():
    from tpurpc.analysis import ringcheck

    verdicts = ringcheck.rendezvous_mutant_kill_suite()
    assert verdicts == {"write_before_claim": True,
                       "complete_before_write": True}


def test_rendezvous_mutants_ride_default_kill_suite():
    """The CLI gate (python -m tpurpc.analysis) must exercise the
    rendezvous mutants alongside the ring + handoff ones."""
    from tpurpc.analysis import ringcheck

    verdicts = ringcheck.mutant_kill_suite()
    for mutant in ringcheck.RDV_MUTANTS:
        assert verdicts.get(mutant) is True, verdicts
    assert all(verdicts.values()), verdicts


def test_rendezvous_model_rides_default_suite():
    from tpurpc.analysis import ringcheck

    results = ringcheck.default_suite()
    rdv = [r for r in results if r.config.startswith("rendezvous")]
    assert len(rdv) >= 4 and all(r.ok for r in rdv)


# ---------------------------------------------------------------------------
# tpurpc-cadence (ISSUE 10): the decode step loop under the analysis gate
# ---------------------------------------------------------------------------

SERVING_BLOCK_SRC = '''
import time

class DecodeScheduler:
    def _step_loop(self):
        time.sleep(0.01)               # unbounded nap on the step loop
        self._lock.acquire()           # timeout-less lock

    def _boundary(self):
        self._kick.wait()              # timeout-less park

    def _run_step(self):
        out = self._inflight.get()     # timeout-less queue get

    def _off_loop_helper(self):
        time.sleep(1)                  # not a step-loop function: allowed
'''

SERVING_BLOCK_BOUNDED = '''
class DecodeScheduler:
    def _boundary(self):
        self._kick.wait(timeout=self.idle_wait_s)   # bounded slice: fine

    def _run_step(self):
        ok = self._lock.acquire(timeout=0.5)        # bounded: fine
'''


def test_serving_step_loop_under_block_rule():
    vs = lint_source(SERVING_BLOCK_SRC, "tpurpc/serving/scheduler.py")
    assert _rules(vs) == ["block"] and len(vs) == 4
    assert {v.line for v in vs} == {6, 7, 10, 13}


def test_serving_block_rule_bounded_waits_pass():
    assert lint_source(SERVING_BLOCK_BOUNDED,
                       "tpurpc/serving/scheduler.py") == []


def test_serving_block_rule_scoped_to_scheduler_module():
    # the same source elsewhere in the serving package is not on the path
    assert lint_source(SERVING_BLOCK_SRC, "tpurpc/serving/api.py") == []


def test_serving_block_rule_suppression_comment():
    ok = SERVING_BLOCK_SRC
    for needle in ('time.sleep(0.01)               # unbounded nap on the step loop',
                   'self._lock.acquire()           # timeout-less lock',
                   'self._kick.wait()              # timeout-less park',
                   'out = self._inflight.get()     # timeout-less queue get'):
        ok = ok.replace(needle, needle.split("#")[0].rstrip()
                        + "  # tpr: allow(block)")
    assert lint_source(ok, "tpurpc/serving/scheduler.py") == []


SERVING_FLIGHT_SRC = '''
from tpurpc.obs import flight as _flight

class DecodeScheduler:
    def _run_step(self):
        _flight.emit(_flight.GEN_STEP_BEGIN, self._tag,
                     len(self._running), 0)      # Call in an emit arg
        _flight.emit(_flight.GEN_SHED, self._tag, 0, "batch")  # str const

    def _ok_site(self):
        nb = 4
        _flight.emit(_flight.GEN_STEP_END, self._tag, nb, 0)  # pure ints
'''


def test_serving_flight_rule_enforced():
    vs = lint_source(SERVING_FLIGHT_SRC, "tpurpc/serving/scheduler.py")
    assert _rules(vs) == ["flight"] and len(vs) == 2
    assert {v.line for v in vs} == {6, 8}


def test_serving_flight_rule_scoped():
    # serving/api.py is transport glue, not an emission site — exempt
    assert lint_source(SERVING_FLIGHT_SRC, "tpurpc/serving/api.py") == []


def test_serving_scheduler_module_is_clean():
    import tpurpc.serving.scheduler as sched_mod

    with open(sched_mod.__file__, "r", encoding="utf-8") as f:
        vs = lint_source(f.read(), sched_mod.__file__)
    assert vs == []


# ---------------------------------------------------------------------------
# tpurpc-keystone (ISSUE 11): the kv block-alloc pairing rule
# ---------------------------------------------------------------------------

KV_OK = '''
def prefill_row(self, seq, prompt):
    kv, hit = self.mgr.alloc_for_prompt(seq, prompt)
    try:
        self.model.fold(prompt, kv)
    except BaseException:
        self.mgr.free_blocks(kv)
        raise
    return kv
'''

KV_NO_RELEASE = '''
def prefill_row(self, seq, prompt):
    kv, hit = self.mgr.alloc_for_prompt(seq, prompt)
    self.model.fold(prompt, kv)
    return kv
'''

KV_SWAP_COVERS = '''
def preempt(self, seq):
    blocks = self.mgr.alloc_blocks(seq, 2)
    try:
        fill(blocks)
    finally:
        self.mgr.swap_out(seq)
'''

KV_QUARANTINE_COVERS = '''
def receive(self, seq, n):
    blocks = self.mgr.alloc_blocks(seq, n)
    try:
        land(blocks)
    except Exception:
        self.mgr.quarantine(blocks)
        raise
'''


def test_kv_pairing_positive():
    assert lint_source(KV_OK, "fixture.py") == []


def test_kv_missing_release_flagged():
    vs = lint_source(KV_NO_RELEASE, "fixture.py")
    assert _rules(vs) == ["kv"] and "exception path" in vs[0].message


def test_kv_swap_out_counts_as_release():
    assert lint_source(KV_SWAP_COVERS, "fixture.py") == []


def test_kv_quarantine_counts_as_release():
    assert lint_source(KV_QUARANTINE_COVERS, "fixture.py") == []


def test_kv_suppression():
    src = KV_NO_RELEASE.replace(
        "self.mgr.alloc_for_prompt(seq, prompt)",
        "self.mgr.alloc_for_prompt(seq, prompt)  # tpr: allow(kv)")
    assert lint_source(src, "fixture.py") == []


def test_kv_modules_are_clean():
    """The real KV plane holds the pairing + flight-encoder contracts it
    exports (serving/kv.py and serving/disagg.py are both on the flight
    hot-module list)."""
    import tpurpc.serving.disagg as disagg_mod
    import tpurpc.serving.kv as kv_mod

    for mod in (kv_mod, disagg_mod):
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert [v for v in vs
                if v.rule in ("kv", "flight", "lock")] == [], mod.__name__


# ---------------------------------------------------------------------------
# ringcheck: the kv block-table handoff model (tpurpc-keystone)
# ---------------------------------------------------------------------------

def test_kv_handoff_model_clean_configs():
    from tpurpc.analysis import ringcheck

    for cfg in (dict(blocks=2), dict(blocks=3),
                dict(blocks=2, with_death=True),
                dict(blocks=3, with_death=True)):
        res = ringcheck.check_kv_handoff(**cfg)
        assert res.ok, res


def test_kv_handoff_reuse_before_quarantine_killed():
    """The ISSUE 11 seeded mutant: a dest that returns a reaped handoff's
    blocks to the free list lets a straggling one-sided write land in
    re-leased memory — the model must catch exactly that."""
    from tpurpc.analysis import ringcheck

    res = ringcheck.check_kv_handoff(blocks=2, with_death=True,
                                     mutant="kv_reuse_before_quarantine")
    assert not res.ok
    assert res.violation.kind == "stale-write"


def test_kv_handoff_free_before_complete_killed():
    from tpurpc.analysis import ringcheck

    res = ringcheck.check_kv_handoff(blocks=2,
                                     mutant="kv_free_before_complete")
    assert not res.ok
    assert res.violation.kind == "torn"


def test_kv_handoff_mutants_ride_default_kill_suite():
    from tpurpc.analysis import ringcheck

    verdicts = ringcheck.mutant_kill_suite()
    for mutant in ringcheck.KV_MUTANTS:
        assert verdicts.get(mutant) is True, verdicts
    assert all(verdicts.values()), verdicts


def test_kv_handoff_model_rides_default_suite():
    from tpurpc.analysis import ringcheck

    results = ringcheck.default_suite()
    kv = [r for r in results if r.config.startswith("kv_handoff")]
    assert len(kv) >= 4 and all(r.ok for r in kv)


# ---------------------------------------------------------------------------
# lint: rawlock (tpurpc-proof, ISSUE 12 — factory-made locks only, in
# modules that already import the factory)
# ---------------------------------------------------------------------------

RAWLOCK_BAD = '''
import threading

from tpurpc.analysis.locks import make_lock


class Pool:
    def __init__(self):
        self._lock = make_lock("Pool._lock")
        self._aux = threading.Lock()
        self._cv = threading.Condition(self._aux)
'''

RAWLOCK_UNARMED = '''
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
'''

RAWLOCK_SUPPRESSED = '''
import threading

from tpurpc.analysis.locks import make_condition


class Pool:
    def __init__(self):
        self._cv = make_condition("Pool._cv")
        self._raw = threading.Lock()  # tpr: allow(rawlock)
'''


def test_rawlock_flags_raw_primitives_next_to_the_factory():
    vs = [v for v in lint_source(RAWLOCK_BAD, "x.py")
          if v.rule == "rawlock"]
    assert len(vs) == 2  # the Lock and the Condition


def test_rawlock_unarmed_without_factory_import():
    assert [v for v in lint_source(RAWLOCK_UNARMED, "x.py")
            if v.rule == "rawlock"] == []


def test_rawlock_suppression_comment():
    assert [v for v in lint_source(RAWLOCK_SUPPRESSED, "x.py")
            if v.rule == "rawlock"] == []


def test_rawlock_factory_importing_modules_are_clean():
    """The satellite fix itself: the decode scheduler and the rendezvous
    plane route every lock through the factory now — TPURPC_DEBUG_LOCKS
    and the schedule explorer finally cover them."""
    import importlib

    for name in ("tpurpc.serving.scheduler", "tpurpc.core.rendezvous",
                 "tpurpc.rpc.shard", "tpurpc.rpc.channel"):
        mod = importlib.import_module(name)
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert [v for v in vs if v.rule == "rawlock"] == [], name


def test_scheduler_and_rendezvous_locks_are_factory_made(monkeypatch):
    """Runtime proof of the blind-spot fix: constructing the live classes
    under the exploration factory hook yields hooked primitives."""
    from tpurpc.analysis import locks as locks_mod

    seen = []

    def hook(kind, name, lock):
        seen.append((kind, name))
        return None  # decline: normal primitives, we only observe

    locks_mod.set_factory_hook(hook)
    try:
        import numpy as np

        from tpurpc.core.rendezvous import LandingPool
        from tpurpc.serving.scheduler import DecodeScheduler

        class _M:
            def prefill(self, prompts):
                return ([np.zeros(1)] * len(prompts),
                        [1] * len(prompts))

            def step(self, states, tokens):
                return states, [int(t) + 1 for t in tokens]

        s = DecodeScheduler(_M(), name="rawlock-probe")
        s.close(timeout=2)
        pool = LandingPool("local", budget=1 << 20)
        pool.trim()
    finally:
        locks_mod.set_factory_hook(None)
    names = {n for _k, n in seen}
    assert "DecodeScheduler._lock" in names
    assert "DecodeScheduler._kick" in names
    assert "LandingPool._lock" in names


# ---------------------------------------------------------------------------
# the suppression audit (tpurpc-proof, ISSUE 12)
# ---------------------------------------------------------------------------

SUPPRESS_LIVE = '''
import time


def stamp():
    return time.time()  # tpr: allow(wallclock)
'''

SUPPRESS_STALE = '''
import time


def stamp():
    return time.monotonic()  # tpr: allow(wallclock)
'''

SUPPRESS_UNKNOWN = '''
X = 1  # tpr: allow(wallcheck)
'''

SUPPRESS_DOC_MENTION = '''
def f():
    """Docs may quote the grammar: ``# tpr: allow(wallclock)``."""
    return 1
'''


def test_audit_accepts_live_suppression():
    assert lint.audit_suppressions_source(SUPPRESS_LIVE, "x.py") == []


def test_audit_flags_stale_suppression():
    vs = lint.audit_suppressions_source(SUPPRESS_STALE, "x.py")
    assert len(vs) == 1 and vs[0].rule == "suppress"
    assert "stale" in vs[0].message


def test_audit_flags_unknown_rule_name():
    vs = lint.audit_suppressions_source(SUPPRESS_UNKNOWN, "x.py")
    assert len(vs) == 1 and "unknown rule" in vs[0].message


def test_audit_ignores_docstring_mentions():
    assert lint.audit_suppressions_source(SUPPRESS_DOC_MENTION,
                                          "x.py") == []


def test_audit_does_not_disturb_normal_linting():
    """The audit's suppression-void pass must not leak: a normal lint of
    a suppressed violation still honors the suppression afterwards."""
    lint.audit_suppressions_source(SUPPRESS_STALE, "x.py")
    assert lint_source(SUPPRESS_LIVE, "x.py") == []


def test_tree_suppressions_are_all_live():
    """Every `# tpr: allow(...)` in the tree earns its keep — the ~37
    accreted suppressions were audited and the stale ones deleted
    (ISSUE 12 satellite); new dead ones are gate failures."""
    violations = lint.audit_suppressions_tree()
    assert violations == [], "\n".join(map(str, violations))


# ---------------------------------------------------------------------------
# tpurpc-argus (ISSUE 14): the flight rule extends to the obs modules
# ---------------------------------------------------------------------------

ARGUS_FLIGHT_SRC = '''
from tpurpc.obs import flight as _flight

class SloEvaluator:
    def _transition(self, obj, track, burn):
        _flight.emit(_flight.SLO_FIRING, obj.tag,
                     int(burn * 100), 0)         # Call in an emit arg
        _flight.emit(_flight.SLO_RESOLVED, obj.tag, 0, "latency")  # str

    def _ok_site(self, obj):
        burn_pct = 240
        _flight.emit(_flight.SLO_FIRING, obj.tag, 2, burn_pct)  # pure ints
'''


@pytest.mark.parametrize("mod", ["tsdb", "slo", "bundle", "collector"])
def test_argus_flight_rule_enforced_per_module(mod):
    vs = lint_source(ARGUS_FLIGHT_SRC, f"tpurpc/obs/{mod}.py")
    assert _rules(vs) == ["flight"] and len(vs) == 2
    assert {v.line for v in vs} == {6, 8}


def test_argus_flight_rule_scoped():
    # the registry itself is not an emission module — exempt
    assert lint_source(ARGUS_FLIGHT_SRC, "tpurpc/obs/metrics.py") == []


ARGUS_FLIGHT_SUPPRESSED = '''
from tpurpc.obs import flight as _flight

class SloEvaluator:
    def _transition(self, obj, burn):
        _flight.emit(_flight.SLO_FIRING, obj.tag, int(burn), 0)  # tpr: allow(flight)
'''


def test_argus_flight_rule_suppression():
    assert lint_source(ARGUS_FLIGHT_SUPPRESSED, "tpurpc/obs/slo.py") == []


def test_argus_modules_are_clean():
    """The real tsdb sample path / slo evaluator / bundle / collector hold
    the pure-int flight contract (and every other rule) they export."""
    import tpurpc.obs.bundle as bundle_mod
    import tpurpc.obs.collector as collector_mod
    import tpurpc.obs.slo as slo_mod
    import tpurpc.obs.tsdb as tsdb_mod

    for mod in (tsdb_mod, slo_mod, bundle_mod, collector_mod):
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert vs == [], (mod.__name__, list(map(str, vs)))


# ---------------------------------------------------------------------------
# lint: cross-process sends route through the transport seam (ISSUE 17)
# ---------------------------------------------------------------------------

XPROC_BAD_RAW = '''
class Pair:
    def hot_notify(self, token):
        self._notify_raw(token)          # around the seam: flagged

    def _send_frame(self, payload):
        r = _transport.dispatch("frame", self, self._send_frame_raw, payload)
        if r is NotImplemented:
            return self._send_frame_raw(payload)  # seam fallback: fine
        return r

    def _send_frame_raw(self, payload):
        return self.sock.sendall(payload)         # raw impl: fine
'''

XPROC_BAD_RING = '''
class CtrlPlane:
    def post_fast(self, op, payload):
        tx = self.tx
        return tx.post(op, 0, payload, 0)  # peer-ring store, no seam
'''


def test_xproc_flags_raw_send_around_the_seam():
    vs = [v for v in lint_source(XPROC_BAD_RAW, "tpurpc/core/pair.py")
          if v.rule == "xproc"]
    assert len(vs) == 1 and vs[0].line == 4, list(map(str, vs))


def test_xproc_seam_wrapper_and_raw_impl_are_exempt():
    ok = XPROC_BAD_RAW.replace("self._notify_raw(token)",
                               '_transport.dispatch("frame", self, '
                               "self._notify_raw, token)")
    assert [v for v in lint_source(ok, "tpurpc/core/pair.py")
            if v.rule == "xproc"] == []


def test_xproc_flags_direct_peer_ring_post():
    vs = [v for v in lint_source(XPROC_BAD_RING, "tpurpc/core/ctrlring.py")
          if v.rule == "xproc"]
    assert len(vs) == 1 and "tx.post" in vs[0].message


def test_xproc_scoped_to_cross_process_modules():
    # the same source off the cross-process module set is fine
    assert lint_source(XPROC_BAD_RAW, "tpurpc/obs/flight.py") == []
    assert lint_source(XPROC_BAD_RAW, "fixture.py") == []
    # ...and every declared cross-process module enforces it
    for mod in ("tpurpc/core/pair.py", "tpurpc/core/rendezvous.py",
                "tpurpc/core/ctrlring.py", "tpurpc/serving/disagg.py"):
        assert [v.rule for v in lint_source(XPROC_BAD_RAW, mod)
                if v.rule == "xproc"] == ["xproc"]


def test_xproc_receive_side_raw_is_not_a_send():
    src = '''
class Pair:
    def drain_notifications(self):
        return self._drain_raw()   # local read of our own socket: fine
'''
    assert lint_source(src, "tpurpc/core/pair.py") == []


def test_xproc_suppression_comment():
    ok = XPROC_BAD_RAW.replace(
        "self._notify_raw(token)          # around the seam: flagged",
        "self._notify_raw(token)  # tpr: allow(xproc)")
    assert [v for v in lint_source(ok, "tpurpc/core/pair.py")
            if v.rule == "xproc"] == []


def test_xproc_modules_are_clean():
    """The real cross-process modules route every wire effect through the
    seam — the property that makes simnet's exploration exhaustive over
    their sends."""
    import tpurpc.core.ctrlring as ctrlring_mod
    import tpurpc.core.pair as pair_mod
    import tpurpc.core.rendezvous as rendezvous_mod
    import tpurpc.serving.disagg as disagg_mod

    for mod in (pair_mod, rendezvous_mod, ctrlring_mod, disagg_mod):
        with open(mod.__file__, "r", encoding="utf-8") as f:
            vs = lint_source(f.read(), mod.__file__)
        assert [v for v in vs if v.rule == "xproc"] == [], (
            mod.__name__, list(map(str, vs)))


# ---------------------------------------------------------------------------
# lint: tpr-obs — the C emission macro's discipline (tpurpc-xray, ISSUE 19)
# ---------------------------------------------------------------------------

from tpurpc.analysis.lint import lint_native_source, lint_native_tree

TPROBS_OK = '''
void Link::rdv_release(const std::shared_ptr<Claim> &c) {
  TPR_OBS(tpr_obs::kEvRdvRelease, otag_rdv_, c->lease_id, 0);
  TPR_OBS(tpr_obs::kEvCtrlStallBegin, otag_ctrl_,
          tx_.seq - head, 0);
}
'''

TPROBS_DYNAMIC_CODE = '''
void f(uint16_t code) {
  TPR_OBS(code, otag_rdv_, 1, 0);
}
'''

TPROBS_TAG_FOR = '''
void f() {
  TPR_OBS(tpr_obs::kEvRdvOffer, tpr_obs::tag_for("nrdv:x"), req, total);
}
'''

TPROBS_STRING_ARG = '''
void f() {
  TPR_OBS(tpr_obs::kEvRdvOffer, otag_rdv_, 'x', 0);
}
'''

TPROBS_CALL_ARG = '''
void f() {
  TPR_OBS(tpr_obs::kEvRdvOffer, otag_rdv_, payload.size(), 0);
}
'''

TPROBS_RAW_EMIT = '''
void f() {
  tpr_obs::emit(tpr_obs::kEvRdvOffer, otag_rdv_, 1, 0);
}
'''


def _nrules(vs):
    return sorted(v.rule for v in vs)


def test_tprobs_clean_site_passes():
    assert lint_native_source(TPROBS_OK, "native/src/tpr_rdv.cc") == []


def test_tprobs_dynamic_event_code_flagged():
    vs = lint_native_source(TPROBS_DYNAMIC_CODE, "native/src/tpr_rdv.cc")
    assert _nrules(vs) == ["tpr-obs"] and "kEv*" in vs[0].message


def test_tprobs_tag_for_in_args_flagged():
    vs = lint_native_source(TPROBS_TAG_FOR, "native/src/tpr_rdv.cc")
    assert any("interns per event" in v.message for v in vs)


def test_tprobs_string_literal_flagged():
    vs = lint_native_source(TPROBS_STRING_ARG, "native/src/tpr_rdv.cc")
    assert any("string/char literal" in v.message for v in vs)


def test_tprobs_per_event_call_flagged():
    vs = lint_native_source(TPROBS_CALL_ARG, "native/src/tpr_rdv.cc")
    assert _nrules(vs) == ["tpr-obs"] and "per event" in vs[0].message


def test_tprobs_raw_emit_outside_plane_flagged():
    vs = lint_native_source(TPROBS_RAW_EMIT, "native/src/tpr_rdv.cc")
    assert _nrules(vs) == ["tpr-obs"] and "enabled() guard" in vs[0].message


def test_tprobs_raw_emit_inside_plane_exempt():
    assert lint_native_source(TPROBS_RAW_EMIT, "native/src/tpr_obs.cc") == []


def test_tprobs_macro_definition_exempt():
    src = "#define TPR_OBS(code, tag, a1, a2) tpr_obs::emit(code, tag)\n"
    assert lint_native_source(src, "native/src/tpr_obs.h") == []


def test_tprobs_suppression_comment():
    ok = TPROBS_CALL_ARG.replace(
        "payload.size(), 0);",
        "payload.size(), 0);  // tpr: allow(tpr-obs)")
    assert lint_native_source(ok, "native/src/tpr_rdv.cc") == []


def test_tprobs_native_tree_is_clean():
    """Every real TPR_OBS site in native/src keeps the static-tag pure-int
    discipline — the same bar the `flight` rule holds the Python plane to."""
    vs = lint_native_tree()
    assert vs == [], list(map(str, vs))


# -- diag: evidence rules are read-only (tpurpc-oracle, ISSUE 20) ------------

DIAG_MUTATING = '''
def _collect_widget(planes):
    flight.emit(LEASE_RESERVE, tag, 1)
    return [("flight", "x", 1)]

def _score_widget(facts, planes):
    c.inc()
    return 0.5
'''

DIAG_CLEAN = '''
def _collect_widget(planes):
    ev = planes.flight_events()
    wins = planes.windows()
    seen = set()           # builtin set() is not the mutator set()
    return [("flight", e["event"], e["a1"]) for e in ev if ev]

def helper_outside_rule():
    flight.emit(1, 2, 3)   # not a _collect_*/_score_* function
'''


def test_diag_mutating_collect_and_score_flagged():
    vs = [v for v in lint_source(DIAG_MUTATING, "tpurpc/obs/diagnose.py")
          if v.rule == "diag"]
    assert len(vs) == 2
    assert "read-only" in vs[0].message and "emit()" in vs[0].message
    assert "inc()" in vs[1].message


def test_diag_clean_rule_and_non_rule_function_pass():
    assert [v for v in lint_source(DIAG_CLEAN, "tpurpc/obs/diagnose.py")
            if v.rule == "diag"] == []


def test_diag_scoped_to_diagnose_module():
    assert [v for v in lint_source(DIAG_MUTATING, "tpurpc/obs/other.py")
            if v.rule == "diag"] == []


def test_diag_suppression_comment():
    ok = DIAG_MUTATING.replace(
        "flight.emit(LEASE_RESERVE, tag, 1)",
        "flight.emit(LEASE_RESERVE, tag, 1)  # tpr: allow(diag)")
    vs = [v for v in lint_source(ok, "tpurpc/obs/diagnose.py")
          if v.rule == "diag"]
    assert len(vs) == 1 and "inc()" in vs[0].message


def test_diagnose_module_is_diag_flight_and_block_clean():
    """The real engine holds its own bar: read-only evidence rules,
    pure-int flight discipline, and no unbounded blocking on the
    dispatch-path functions."""
    import tpurpc.obs.diagnose as dz
    path = dz.__file__
    with open(path, "r", encoding="utf-8") as f:
        vs = lint_source(f.read(), path)
    assert vs == [], list(map(str, vs))
