"""REAL multi-host execution of the parallel stack — two jax processes
(separate interpreters, gloo cross-process collectives) join a
coordinator via tpurpc's bring-up seam and run pjit programs over the
GLOBAL mesh.

This is the multi-process analog of the reference's MPI-launched
multi-node benchmarks (SURVEY.md §2.8): process bring-up by env
(TPURPC_COORDINATOR/NUM_PROCESSES/PROCESS_ID — the launcher-agnostic
family), then the same mesh programs used single-host run globally with
dp crossing "DCN" (here: localhost gloo) and tp staying "on-slice".
No TPU pod needed: each process pins JAX_PLATFORMS=cpu with 4 virtual
devices, giving an 8-device global mesh across 2 hosts.
"""

import os
import socket
import subprocess
import sys


ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import os, sys
import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.environ["TPURPC_ROOT"])

from tpurpc.parallel.distributed import (global_mesh, initialize_cluster,
                                         process_count)

pid = initialize_cluster()  # coordinator/count/id all from TPURPC_* env
assert process_count() == 2, process_count()

import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import NamedSharding, PartitionSpec as P

assert len(jax.devices()) == 8, len(jax.devices())   # global view
assert len(jax.local_devices()) == 4                 # per-host view

# the seam's 5-axis factoring covers the global device count
_gm, sizes = global_mesh()
assert int(np.prod(list(sizes.values()))) == 8

# Explicit 2x4 mesh for the collective checks: dp CROSSES the hosts
# (jax.devices() lists process 0's devices first), tp stays host-local —
# the scaling-book placement the module docstring prescribes.
from jax.sharding import Mesh
mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))

# -- 1. cross-host reduction: host-local rows -> global array -> jit sum --
local = np.arange(4.0) + 4 * pid          # host0: 0..3, host1: 4..7
garr = multihost_utils.host_local_array_to_global_array(
    local, mesh, P("dp"))
assert garr.shape == (8,)                 # concatenated across hosts
total = float(jax.jit(jnp.sum)(garr))
assert total == 28.0, total               # sum(0..7): crossed the hosts

# -- 2. pjit matmul over the global mesh, dp-sharded batch ----------------
# Both hosts derive the same full inputs from one seed; each feeds only
# its local shard; the sharded result must equal the dense product.
rng = np.random.default_rng(7)
X = rng.standard_normal((8, 16)).astype(np.float32)
W = rng.standard_normal((16, 4)).astype(np.float32)
Xg = multihost_utils.host_local_array_to_global_array(
    X[pid * 4:(pid + 1) * 4], mesh, P("dp"))
Wg = multihost_utils.host_local_array_to_global_array(W, mesh, P())

@jax.jit
def mm(x, w):
    return x @ w

Yg = mm(Xg, Wg)
Yl = multihost_utils.global_array_to_host_local_array(Yg, mesh, P("dp"))
np.testing.assert_allclose(np.asarray(Yl), X[pid * 4:(pid + 1) * 4] @ W,
                           rtol=1e-5)

# -- 3. psum across the dp axis inside shard_map (explicit collective) ----
from jax.experimental.shard_map import shard_map

@jax.jit
def allred(x):
    return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                     in_specs=P("dp"), out_specs=P())(x)

red = np.asarray(allred(garr))
# dp shards [0..3] and [4..7] summed elementwise across the two hosts
np.testing.assert_allclose(red, [4.0, 6.0, 8.0, 10.0], rtol=1e-6)
print(f"WORKER_OK {pid}", flush=True)
'''


def _free_port_coord() -> str:
    """Kernel-assigned free port for the coordinator. bind-then-close is
    a TOCTOU (jax needs a literal address, it can't bind :0 itself), but
    ephemeral ports aren't rehanded out while recently closed, so the
    realistic collision window is negligible."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"127.0.0.1:{s.getsockname()[1]}"


def test_two_process_global_mesh_collectives(tmp_path):
    coord = _free_port_coord()
    wf = tmp_path / "worker.py"
    wf.write_text(WORKER)
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   TPURPC_ROOT=ROOT,
                   TPURPC_COORDINATOR=coord,
                   TPURPC_NUM_PROCESSES="2",
                   TPURPC_PROCESS_ID=str(pid))
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never tunnel-hostage
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(wf)], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"WORKER_OK {pid}" in out


SERVE_WORKER = r'''
import os, sys
import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.environ["TPURPC_ROOT"])

from tpurpc.parallel.distributed import initialize_cluster

pid = initialize_cluster()

import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
rng = np.random.default_rng(21)
W = rng.standard_normal((16, 4)).astype(np.float32)
Wg = multihost_utils.host_local_array_to_global_array(W, mesh, P())
N_REQS = int(os.environ["TPURPC_TEST_REQS"])

mm = jax.jit(lambda x, w: x @ w,
             out_shardings=NamedSharding(mesh, P()))

def step(x_np):
    """SPMD step every host runs: broadcast the batch host0 received over
    RPC, shard it dp across BOTH hosts, matmul, gather replicated."""
    x = multihost_utils.broadcast_one_to_all(x_np)
    xl = np.asarray(x).reshape(8, 16)[pid * 4:(pid + 1) * 4]
    xg = multihost_utils.host_local_array_to_global_array(xl, mesh, P("dp"))
    return np.asarray(mm(xg, Wg))

if pid == 0:
    # host 0 fronts the cluster: tensor RPC in, global-mesh compute, reply
    from tpurpc.jaxshim import add_tensor_method
    from tpurpc.rpc.server import Server

    srv = Server(max_workers=2)

    def infer(tree):
        return {"y": step(np.asarray(tree["x"]))}

    add_tensor_method(srv, "Infer", infer)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    print(f"PORT {port}", flush=True)
    # serve until the test signals the client finished (a request-count
    # wrapper would race the reply); worker 1 loops the fixed count
    import time
    sentinel = os.environ["TPURPC_TEST_DONE"]
    while not os.path.exists(sentinel):
        time.sleep(0.1)
    srv.stop(grace=5)
else:
    for _ in range(N_REQS):
        step(np.zeros((8, 16), np.float32))  # value ignored: broadcast
print(f"SERVE_OK {pid}", flush=True)
'''

CLIENT = r'''
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.environ["TPURPC_ROOT"])
from tpurpc.jaxshim.codec import tree_deserializer, tree_serializer
from tpurpc.rpc.channel import Channel

port = int(sys.argv[1])
n = int(sys.argv[2])
rng = np.random.default_rng(21)
W = rng.standard_normal((16, 4)).astype(np.float32)
with Channel(f"127.0.0.1:{port}") as ch:
    infer = ch.unary_unary("/tpurpc.Tensor/Infer",
                           request_serializer=tree_serializer,
                           response_deserializer=tree_deserializer)
    xr = np.random.default_rng(5)
    for i in range(n):
        X = xr.standard_normal((8, 16)).astype(np.float32)
        out = infer({"x": X}, timeout=120)
        np.testing.assert_allclose(out["y"], X @ W, rtol=1e-4)
print("CLIENT_OK", flush=True)
'''


def test_rpc_fanin_to_global_mesh_serving(tmp_path):
    """The multi-host serving topology end to end: a client's tensor RPC
    lands on host 0, the batch is broadcast and dp-sharded over a 2-host
    global mesh, and the replicated result is returned over the RPC —
    the sharded_inference example made REALLY multi-host."""
    coord = _free_port_coord()
    wf = tmp_path / "serve_worker.py"
    wf.write_text(SERVE_WORKER)
    cf = tmp_path / "client.py"
    cf.write_text(CLIENT)
    done = tmp_path / "done.sentinel"
    n_reqs = 3
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   TPURPC_ROOT=ROOT,
                   TPURPC_COORDINATOR=coord,
                   TPURPC_NUM_PROCESSES="2",
                   TPURPC_PROCESS_ID=str(pid),
                   TPURPC_TEST_REQS=str(n_reqs),
                   TPURPC_TEST_DONE=str(done))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(wf)], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env))
    client = None
    try:
        port = None
        for line in procs[0].stdout:
            if line.startswith("PORT "):
                port = int(line.split()[1])
                break
        assert port, "host 0 never printed its port"
        cenv = dict(os.environ, TPURPC_ROOT=ROOT)
        cenv.pop("PALLAS_AXON_POOL_IPS", None)
        cenv.pop("XLA_FLAGS", None)
        client = subprocess.run(
            [sys.executable, str(cf), str(port), str(n_reqs)],
            capture_output=True, text=True, timeout=240, env=cenv)
        assert client.returncode == 0, client.stdout + client.stderr
        assert "CLIENT_OK" in client.stdout
        done.write_text("done")
        for p in procs:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            p.kill()
