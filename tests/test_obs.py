"""tpurpc-scope (ISSUE 4): metrics registry, span timelines, trace-context
propagation on both planes, the scrape endpoint, and the trace-env grammar.

The acceptance test is :func:`test_depth4_pipeline_trace_python_plane`: a
depth-4 pipelined TensorClient request against serve_jax produces a single
trace_id whose exported span tree shows client-send, wire, batch-wait,
infer, and respond spans in order, while the Prometheus endpoint on the
SAME serving port exposes ring/batcher/pipeline series that channelz
mirrors.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from tpurpc.obs import metrics, tracing
from tpurpc.utils import stats, trace

NATIVE_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "libtpurpc.so")


@pytest.fixture
def forced_tracing():
    tracing.reset()
    tracing.force(True)
    yield
    tracing.force(None)
    tracing.reset()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_basics():
    reg = metrics.Registry()
    c = reg.counter("c")
    c.inc()
    c.inc(4)
    assert c.snapshot() == 5
    g = reg.gauge("g")
    g.set(3.5)
    g.inc()
    g.dec(0.5)
    assert g.snapshot() == 4.0
    assert reg.counter("c") is c  # same name, same object
    with pytest.raises(TypeError):
        reg.gauge("c")  # kind conflict is an error, not a shadow


def test_size_histogram_exact_percentiles():
    reg = metrics.Registry()
    h = reg.histogram("h")
    for v in (1, 1, 1, 2, 8):
        h.record(v)
    s = h.snapshot()
    assert s["count"] == 5 and s["p50"] == 1 and s["max"] == 8
    assert s["p99"] == 8


def test_latency_histogram_interpolates_within_bucket():
    reg = metrics.Registry()
    h = reg.histogram("lat", kind="latency")
    for v in range(1000, 2000):
        h.record(v)
    # uniform [1000, 2000): true p50 ~1500. The log2 bucket holding it is
    # [1024, 2048) — a bucket-upper-bound answer would say 2048.
    assert 1300 <= h.percentile(0.5) <= 1700
    assert h.percentile(0.99) <= 2000  # clamped to the observed max


def test_fleet_gauge_drops_dead_objects():
    reg = metrics.Registry()

    class Obj:
        depth = 7

    f = reg.fleet("live_depth", lambda o: o.depth)
    a, b = Obj(), Obj()
    f.track(a)
    f.track(b)
    assert f.collect() == (14.0, 2)
    del b
    import gc

    gc.collect()
    assert f.collect() == (7.0, 1)


def test_registry_reset_keeps_fleet_membership():
    reg = metrics.Registry()
    reg.counter("x").inc(9)

    class Obj:
        pass

    f = reg.fleet("objs")
    f.track(Obj.__call__ if False else Obj())  # noqa — tracked instance dies
    o = Obj()
    f.track(o)
    reg.reset()
    assert reg.counter("x").snapshot() == 0
    assert f.collect()[1] >= 1  # membership survived the reset


# ---------------------------------------------------------------------------
# utils/stats façade folds into the registry (no parallel bookkeeping)
# ---------------------------------------------------------------------------

def test_stats_facade_is_registry_backed():
    stats.counter_inc("obs_test_counter", 3)
    assert metrics.counter("obs_test_counter").snapshot() >= 3
    h = stats.batch_hist("obs_test_hist")
    assert h is metrics.histogram("obs_test_hist")
    h.record(4)
    assert stats.batch_snapshot()["obs_test_hist"]["count"] >= 1
    assert isinstance(h, stats.BatchHist)  # PR 1 alias still holds


def test_copy_ledger_backed_by_registry():
    before = metrics.counter("copyledger_host_copy").snapshot()
    stats.ledger.add("host_copy", 64)
    assert metrics.counter("copyledger_host_copy").snapshot() == before + 64
    assert stats.ledger.host_copy == before + 64
    with pytest.raises(ValueError):
        stats.ledger.add("bogus", 1)


def test_stats_hist_percentile_interpolated():
    # the satellite fix: p50 of a known distribution must not snap to the
    # power-of-two bucket upper bound (2048 for uniform [1000, 2000))
    h = stats._Hist()
    for v in range(1000, 2000):
        h.record(v)
    p50 = h.percentile(0.5)
    assert 1300 <= p50 <= 1700, p50
    assert h.percentile(0.99) <= 2000


# ---------------------------------------------------------------------------
# trace-env grammar (satellite): -name negation, all, list_tracers,
# TPURPC_TRACE overriding GRPC_TRACE
# ---------------------------------------------------------------------------

@pytest.fixture
def clean_trace_env(monkeypatch):
    for var in ("TPURPC_TRACE", "GRPC_TRACE"):
        monkeypatch.delenv(var, raising=False)
    yield monkeypatch
    for var in ("TPURPC_TRACE", "GRPC_TRACE"):
        monkeypatch.delenv(var, raising=False)
    trace.reapply_env()


def test_trace_all_with_negation(clean_trace_env):
    clean_trace_env.setenv("TPURPC_TRACE", "all,-ring")
    trace.reapply_env()
    flags = trace.list_tracers()
    assert flags["endpoint"] and flags["http2"] and not flags["ring"]


def test_tpurpc_trace_overrides_grpc_trace(clean_trace_env):
    clean_trace_env.setenv("GRPC_TRACE", "ring")
    clean_trace_env.setenv("TPURPC_TRACE", "endpoint")
    trace.reapply_env()
    flags = trace.list_tracers()
    assert flags["endpoint"] and not flags["ring"]
    # GRPC_TRACE alone still works (reference debugging habits carry over)
    clean_trace_env.delenv("TPURPC_TRACE")
    trace.reapply_env()
    flags = trace.list_tracers()
    assert flags["ring"] and not flags["endpoint"]


def test_list_tracers_token_prints_registry_once(clean_trace_env, capfd):
    clean_trace_env.setenv("TPURPC_TRACE", "list_tracers,ring")
    trace.reapply_env()
    assert bool(trace.trace_ring)  # first USE flushes the listing
    err = capfd.readouterr().err
    assert "available tracers:" in err
    assert "ring: on" in err and "endpoint: off" in err
    bool(trace.trace_ring)  # one-shot: no second print
    assert "available tracers:" not in capfd.readouterr().err


# ---------------------------------------------------------------------------
# tracing core
# ---------------------------------------------------------------------------

def test_context_encode_decode_roundtrip():
    ctx = tracing.TraceContext(0xDEADBEEF12345678, 42, True)
    got = tracing.TraceContext.decode(ctx.encode())
    assert (got.trace_id, got.span_id, got.sampled) == (
        ctx.trace_id, ctx.span_id, True)
    off = tracing.TraceContext(1, 2, False)
    assert not tracing.TraceContext.decode(off.encode()).sampled
    assert tracing.TraceContext.decode("garbage") is None
    assert tracing.TraceContext.decode(b"") is None


def test_disabled_tracing_is_inert():
    # fully off = sampling at 0 AND tail capture off (tail defaults ON
    # since ISSUE 5 — sample-rate 0 alone still hands out provisional
    # contexts so pathological calls keep their span trees)
    tracing.force(None)
    tracing.configure(0.0)
    tracing.tail(False)
    try:
        assert not tracing.ACTIVE
        assert not tracing.LIVE
        assert tracing.maybe_sample() is None
        assert tracing.current() is None
        with tracing.span("nope") as sp:
            assert sp is None
    finally:
        tracing.tail(None)


def test_sample_zero_yields_provisional_context():
    """The blackbox contract: TPURPC_TRACE_SAMPLE=0 still hands every call
    a provisional context whose spans only surface on commit."""
    tracing.reset()
    tracing.force(None)
    tracing.configure(0.0)
    assert not tracing.ACTIVE and tracing.LIVE
    ctx = tracing.maybe_sample()
    assert ctx is not None and ctx.provisional and ctx.sampled
    with tracing.use(ctx):
        with tracing.span("hidden"):
            pass
    assert tracing.spans(ctx.trace_id) == []  # buffered, not committed
    assert tracing.tail_pending(ctx.trace_id) == 1
    tracing.tail_commit(ctx.trace_id)
    assert [s["name"] for s in tracing.spans(ctx.trace_id)] == ["hidden"]
    tracing.reset()


def test_span_record_and_tree(forced_tracing):
    ctx = tracing.maybe_sample()
    with tracing.use(ctx):
        with tracing.span("outer"):
            tracing.record("manual", ctx, 123, 456, note="x")
    flat = tracing.spans(ctx.trace_id)
    assert {s["name"] for s in flat} == {"outer", "manual"}
    tree = tracing.span_tree(f"{ctx.trace_id:016x}")
    assert tree["trace_id"] == f"{ctx.trace_id:016x}"
    assert {n["name"] for n in tree["spans"]} == {"outer", "manual"}
    chrome = tracing.chrome_trace(ctx.trace_id)
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    # perfetto named lanes (ISSUE 5 satellite): process_name + one
    # thread_name metadata event per recording thread ride along
    metas = [e for e in chrome["traceEvents"] if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in metas}
    ev = {e["name"]: e for e in xs}
    assert ev["manual"]["args"]["note"] == "x"
    assert ev["manual"]["dur"] == 456 / 1e3


# ---------------------------------------------------------------------------
# the acceptance path: depth-4 pipelined tensor serving, Python plane
# ---------------------------------------------------------------------------

def test_depth4_pipeline_trace_python_plane(forced_tracing):
    import jax

    from tpurpc.jaxshim import TensorClient, serve_jax
    from tpurpc.rpc.channel import Channel

    srv, port, batcher = serve_jax(jax.jit(lambda t: {"y": t["x"] * 2}),
                                   batching=True, max_batch=4,
                                   max_delay_s=0.01)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch, depth=4)
            futs = [cli.call_async("Call",
                                   {"x": np.full((1, 3), i, np.float32)},
                                   timeout=60)
                    for i in range(8)]
            for i, f in enumerate(futs):
                out = f.result(60)
                assert np.asarray(out["y"]).ravel()[0] == 2 * i

            # -- span timeline: one trace_id per request, 5 spans in order
            # The server-side "respond" span closes when the gathered
            # writev RETURNS — on loopback the client's future can resolve
            # a hair earlier, so poll briefly instead of racing the server
            # thread's span append (observed under full-suite CPU load).
            import time as _time

            deadline = _time.monotonic() + 5
            while True:
                by_trace = {}
                for s in tracing.spans():
                    by_trace.setdefault(s["trace_id"], []).append(s)
                complete = [tid for tid, ss in by_trace.items()
                            if {"client-send", "wire", "batch-wait", "infer",
                                "respond"} <= {s["name"] for s in ss}]
                if len(complete) >= 8 or _time.monotonic() >= deadline:
                    break
                _time.sleep(0.02)
            assert len(complete) >= 8, (
                {tid: sorted({s['name'] for s in ss})
                 for tid, ss in by_trace.items()})
            ss = by_trace[complete[0]]
            t0 = {s["name"]: s["t0_ns"] for s in ss}
            assert (t0["client-send"] <= t0["wire"] <= t0["batch-wait"]
                    <= t0["infer"] <= t0["respond"]), t0
            # the tree export carries the same spans
            tree = tracing.span_tree(complete[0])

            def names(nodes):
                out = set()
                for n in nodes:
                    out.add(n["name"])
                    out |= names(n["children"])
                return out

            assert {"client-send", "wire", "batch-wait", "infer",
                    "respond"} <= names(tree["spans"])

            # -- the introspection plane on the SAME serving port
            txt = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            for series in ("tpurpc_fanin_batch_count",
                           "tpurpc_batcher_rows",
                           "tpurpc_pipeline_call_us_count",
                           "tpurpc_ring_msgs_read",
                           "tpurpc_srv_call_us_count",
                           "tpurpc_channelz_calls"):
                assert series in txt, f"{series} missing from scrape"

            # -- channelz mirrors what the scrape says
            from tpurpc.rpc import channelz

            started = sum(
                float(line.rsplit(" ", 1)[1])
                for line in txt.splitlines()
                if line.startswith("tpurpc_channelz_calls")
                and 'entity="server"' in line and 'kind="started"' in line)
            infos = [channelz.server_info(s)
                     for _id, s in channelz.live_servers()]
            assert sum(i.get("calls_started", 0) for i in infos) >= started
            assert started >= 8
    finally:
        srv.stop(grace=0)
        batcher.close()


# ---------------------------------------------------------------------------
# native plane: depth-4 propagation through tpr_call_start metadata
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not os.path.exists(NATIVE_LIB),
                    reason="native lib not built")
def test_depth4_native_plane_trace_propagation(forced_tracing):
    import tpurpc.rpc as rpc
    from tpurpc.rpc.native_client import NativeChannel

    def whoami(req, ctx):
        cur = tracing.current()
        return cur.encode().encode() if cur is not None else b"none"

    srv = rpc.Server(max_workers=8)
    srv.add_method("/obs/WhoAmI", rpc.unary_unary_rpc_method_handler(whoami))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with NativeChannel("127.0.0.1", port) as ch:
            ctxs = [tracing.TraceContext(0x1000 + i, i + 1) for i in range(4)]
            calls = [ch.start_call(
                "/obs/WhoAmI", timeout=30,
                metadata=[(tracing.HEADER, c.encode())]) for c in ctxs]
            for nc in calls:  # depth-4: all four streams in flight at once
                nc.write(b"hi")
                nc.writes_done()
            for nc, ctx in zip(calls, ctxs):
                body = nc.read()
                assert body is not None
                got = tracing.TraceContext.decode(bytes(body))
                assert got is not None, bytes(body)
                assert got.trace_id == ctx.trace_id, (
                    f"{got.trace_id:x} != {ctx.trace_id:x}")
                assert nc.read() is None
                code, _ = nc.finish()
                nc.close()
                assert code is rpc.StatusCode.OK
            # the server-side spans carry the propagated trace ids — via
            # the native trampoline's "handler" span when the connection
            # was adopted onto the C plane, or the Python plane's
            # "dispatch"/"respond" spans otherwise; propagation must hold
            # either way (the body echo above already proved current()).
            srv_traces = {s["trace_id"] for s in tracing.spans()
                          if s["name"] in ("handler", "dispatch", "respond")}
            assert {f"{c.trace_id:016x}" for c in ctxs} <= srv_traces
    finally:
        srv.stop(grace=0)


@pytest.mark.skipif(not os.path.exists(NATIVE_LIB),
                    reason="native lib not built")
def test_native_dataplane_trace_extraction(forced_tracing, monkeypatch):
    """Ring platform: the server ADOPTS the connection onto the C plane, so
    the trace context must survive tpr_call_start → tpr_srv_metadata_get →
    the default trampoline's ambient install ("handler" span)."""
    import tpurpc.rpc as rpc
    from tpurpc.rpc.native_client import NativeChannel
    from tpurpc.rpc.native_server import adoption_eligible
    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    config_mod.set_config(None)
    try:
        def whoami(req, ctx):
            cur = tracing.current()
            return cur.encode().encode() if cur is not None else b"none"

        srv = rpc.Server(max_workers=4)
        srv.add_method("/obs/WhoAmI",
                       rpc.unary_unary_rpc_method_handler(whoami))
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        assert adoption_eligible(srv)
        try:
            with NativeChannel("127.0.0.1", port) as ch:
                ctx = tracing.TraceContext(0xFACE, 7)
                nc = ch.start_call("/obs/WhoAmI", timeout=30,
                                   metadata=[(tracing.HEADER, ctx.encode())])
                nc.write(b"q")
                nc.writes_done()
                body = nc.read()
                got = tracing.TraceContext.decode(bytes(body))
                assert got is not None and got.trace_id == ctx.trace_id
                assert nc.read() is None
                nc.finish()
                nc.close()
            assert f"{ctx.trace_id:016x}" in {
                s["trace_id"] for s in tracing.spans()
                if s["name"] == "handler"}
        finally:
            srv.stop(grace=0)
    finally:
        config_mod.set_config(None)


# ---------------------------------------------------------------------------
# scrape endpoint plumbing
# ---------------------------------------------------------------------------

def test_scrape_routes_on_serving_port():
    import tpurpc.rpc as rpc

    srv = rpc.Server(max_workers=2)
    srv.add_method("/obs/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        base = f"http://127.0.0.1:{port}"
        assert urllib.request.urlopen(
            f"{base}/healthz", timeout=10).read() == b"ok\n"
        hz = json.loads(urllib.request.urlopen(
            f"{base}/channelz", timeout=10).read())
        assert "servers" in hz and "channels" in hz
        tr = json.loads(urllib.request.urlopen(
            f"{base}/traces", timeout=10).read())
        assert "traceEvents" in tr
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert exc.value.code == 404
        # RPC traffic still works on the same port after the scrapes
        from tpurpc.rpc.channel import Channel

        with Channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/obs/Echo")(b"x", timeout=10) == b"x"
    finally:
        srv.stop(grace=0)


def test_standalone_http_server():
    from tpurpc.obs import scrape

    srv, port = scrape.start_http_server()
    try:
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "tpurpc_" in txt
    finally:
        srv.shutdown()


def test_prometheus_render_parses():
    from tpurpc.obs import scrape
    from tpurpc.tools.top import parse_prometheus

    metrics.counter("render_probe").inc(3)
    metrics.histogram("render_hist").record(5)
    parsed = parse_prometheus(scrape.render_prometheus())
    assert parsed[("tpurpc_render_probe", "")] == 3
    assert parsed[("tpurpc_render_hist", 'quantile="0.5"')] == 5
    assert parsed[("tpurpc_render_hist_count", "")] >= 1
