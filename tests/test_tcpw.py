"""tcp_window: the cross-host one-sided ring domain over sockets.

The second real implementation of the MemoryDomain seam (VERDICT r2 next#5):
the identical pair/ring/credit protocol that runs over /dev/shm runs across
process (and host) boundaries over an ordered record socket — the role the
reference's RDMA WRITE fabric plays (``pair.cc:587-622``). No shared memory
exists between the peers in any test here.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

import tpurpc.core.pair as P
from tpurpc.core.pair import Pair, PairState, create_loopback_pair
from tpurpc.core.poller import wait_readable
from tpurpc.core.tcpw import TcpWindowDomain, _PeerLink, _RecordServer


def test_tcpw_same_process_roundtrip():
    a, b = create_loopback_pair(ring_size=4096, domain=TcpWindowDomain())
    try:
        a.send([b"over the record socket"])
        assert wait_readable(b, timeout=10, discipline="event")
        assert b.recv() == b"over the record socket"
        # and the reverse direction
        b.send([b"back"])
        assert wait_readable(a, timeout=10, discipline="event")
        assert a.recv() == b"back"
    finally:
        a.destroy()
        b.destroy()


def test_tcpw_large_messages_wrap_and_credits():
    """Messages larger than the ring force wrap-split writes, partial sends,
    and credit returns — all riding the record stream's ordering."""
    a, b = create_loopback_pair(ring_size=4096, domain=TcpWindowDomain())
    try:
        payload = bytes(range(256)) * 64  # 16 KiB through a 4 KiB ring
        done = threading.Event()

        def pump():
            # partial sends are the contract (rdma_flush loop analog):
            # resume as credits arrive over the record stream
            sent = 0
            while sent < len(payload):
                n = a.send([payload], sent)
                sent += n
                if n == 0:
                    time.sleep(0.002)  # credits in flight
            done.set()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        got = b""
        deadline = time.monotonic() + 20
        while len(got) < len(payload) and time.monotonic() < deadline:
            if wait_readable(b, timeout=5, discipline="event"):
                got += b.recv()
        assert got == payload
        assert done.wait(5)
    finally:
        a.destroy()
        b.destroy()


def test_tcpw_stale_write_discarded():
    """A write racing region teardown is dropped (deregistered-MR analog),
    never applied to freed memory and never a crash."""
    dom = TcpWindowDomain()
    region = dom.alloc(1024)
    win = dom.open_window(region.handle, 1024)
    win.write(0, b"live")
    deadline = time.monotonic() + 5
    while bytes(region.buf[:4]) != b"live" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bytes(region.buf[:4]) == b"live"
    region.close()  # unregisters the key
    win.write(0, b"dead")  # must be discarded server-side
    time.sleep(0.2)
    win.close()


def test_tcpw_out_of_bounds_write_discarded():
    dom = TcpWindowDomain()
    region = dom.alloc(64)
    win = dom.open_window(region.handle, 64)
    win.write(60, b"0123456789")  # runs past the region: dropped whole
    win.write(0, b"ok")
    deadline = time.monotonic() + 5
    while bytes(region.buf[:2]) != b"ok" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bytes(region.buf[:2]) == b"ok"
    assert bytes(region.buf[60:]) == b"\0\0\0\0"
    win.close()
    region.close()


def test_tcpw_windows_share_one_ordered_link():
    """All windows to one peer process share a single connection — the RC-QP
    total-order property the ring protocol's publication invariant needs
    (data write then credit write must never be observed reordered)."""
    dom = TcpWindowDomain()
    r1, r2 = dom.alloc(128), dom.alloc(128)
    w1 = dom.open_window(r1.handle, 128)
    w2 = dom.open_window(r2.handle, 128)
    host_port = r1.handle.rsplit(":", 2)[0][5:], None
    with _PeerLink._links_lock:
        assert len([k for k in _PeerLink._links]) >= 1
        # both windows resolved to the same (host, port) → same link
        server = _RecordServer.get()
        link_keys = {k for k in _PeerLink._links if k[1] == server.port}
        assert len(link_keys) == 1
    for i in range(50):  # interleave; ordering is per-link FIFO
        w1.write(0, bytes([i]))
        w2.write(0, bytes([i]))
    deadline = time.monotonic() + 5
    while (region_bytes := (r1.buf[0], r2.buf[0])) != (49, 49) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert region_bytes == (49, 49)
    for x in (w1, w2, r1, r2):
        x.close()


def test_tcpw_cross_process_echo():
    """Two processes, no shared memory: rings live in each process's private
    heap; every one-sided write crosses a real socket."""
    parent_sock, child_sock = socket.socketpair()
    pid = os.fork()
    if pid == 0:
        status = 1
        try:
            parent_sock.close()
            pair = Pair(TcpWindowDomain(), ring_size=8192)
            pair.init()
            pair.connect_over_socket(child_sock)
            echoed = 0
            while echoed < 3:
                if wait_readable(pair, timeout=10, discipline="event"):
                    data = pair.recv()
                    if data:
                        pair.send([b"echo:", data])
                        echoed += 1
                    elif pair.get_status() is not PairState.CONNECTED:
                        break
            pair.destroy()
            status = 0
        finally:
            os._exit(status)
    child_sock.close()
    pair = Pair(TcpWindowDomain(), ring_size=8192)
    pair.init()
    pair.connect_over_socket(parent_sock)
    try:
        for i in range(3):
            msg = f"msg-{i}".encode() * (i + 1)
            pair.send([msg])
            got = b""
            deadline = time.monotonic() + 10
            while len(got) < len(msg) + 5 and time.monotonic() < deadline:
                if wait_readable(pair, timeout=5, discipline="event"):
                    got += pair.recv()
            assert got == b"echo:" + msg
        pair.disconnect()
    finally:
        pair.destroy()
        _, code = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(code) == 0


def test_tcpw_domain_mismatch_rejected():
    """A tcp_window peer meeting an shm peer fails loudly at bootstrap
    (the reference asserts tag/ring-size match the same way)."""
    a = Pair(TcpWindowDomain(), ring_size=4096)
    b = Pair(P.ShmDomain(), ring_size=4096)
    a.init()
    b.init()
    sa, sb = socket.socketpair()
    errs = []

    def side(pair, sock):
        try:
            pair.connect_over_socket(sock)
        except ValueError as exc:
            errs.append(str(exc))

    t = threading.Thread(target=side, args=(b, sb), daemon=True)
    t.start()
    side(a, sa)
    t.join(10)
    a.destroy()
    b.destroy()
    assert any("domain mismatch" in e for e in errs)


def _run_cross_process(server_src: str, client_src: str, env: dict,
                       client_timeout: float = 120) -> None:
    """Spawn the server script, read its port with a bounded wait, run the
    client script against it, kill the server. One copy of the hazards:
    readline can't hang the suite (selector-bounded), a bad first line
    kills the child BEFORE draining stderr (so the read sees EOF), and the
    child is killed in finally."""
    import selectors

    srv = subprocess.Popen([sys.executable, "-c", server_src],
                           stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                           text=True, env=env)
    try:
        sel = selectors.DefaultSelector()
        sel.register(srv.stdout, selectors.EVENT_READ)
        if not sel.select(timeout=120):
            srv.kill()
            raise AssertionError("server never printed its port: "
                                 + srv.stderr.read()[:2000])
        port = srv.stdout.readline().strip()
        if not port.isdigit():
            srv.kill()
            raise AssertionError(f"bad port line {port!r}: "
                                 + srv.stderr.read()[:2000])
        cli = subprocess.run([sys.executable, "-c", client_src, port],
                             capture_output=True, text=True, env=env,
                             timeout=client_timeout)
        assert cli.returncode == 0, cli.stderr
        assert "CLIENT_OK" in cli.stdout
    finally:
        srv.kill()
        srv.wait()


_RPC_SERVER = r"""
import sys
import tpurpc.rpc as rpc

srv = rpc.Server(max_workers=4)
srv.add_method("/x.S/Echo", rpc.unary_unary_rpc_method_handler(
    lambda req, ctx: bytes(req) + b"/tcpw"))
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
print(port, flush=True)
srv.wait_for_termination(timeout=120)
"""

_RPC_CLIENT = r"""
import sys
import tpurpc.rpc as rpc
from tpurpc.utils.config import get_config

assert get_config().ring_domain == "tcp_window", get_config().ring_domain
with rpc.insecure_channel(f"127.0.0.1:{sys.argv[1]}") as ch:
    echo = ch.unary_unary("/x.S/Echo")
    for i in range(5):
        assert echo(b"m%d" % i, timeout=30) == b"m%d/tcpw" % i
    # big payload: exercises chunking + credits across the record stream
    big = bytes(range(256)) * 4096  # 1 MiB
    assert echo(big, timeout=60) == big + b"/tcpw"
print("CLIENT_OK", flush=True)
"""


def test_tcpw_full_rpc_cross_process():
    """The capability the reference ships: unmodified RPC apps, fast pipe
    between (here: processes standing in for) hosts — selected purely by env
    (GRPC_PLATFORM_TYPE=RDMA_BP + TPURPC_RING_DOMAIN=tcp_window)."""
    env = dict(os.environ,
               GRPC_PLATFORM_TYPE="RDMA_BP",
               TPURPC_RING_DOMAIN="tcp_window",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="256")
    _run_cross_process(_RPC_SERVER, _RPC_CLIENT, env)


def test_tcpw_qps_scenario():
    """The qps driver/worker rig (test/cpp/qps clone) runs its measured
    traffic over the tcp_window ring platform — the reference's distributed
    perf rig shape on the cross-host fabric (VERDICT r2 #5 'done' bar)."""
    code = (
        "import json\n"
        "from tpurpc.bench import qps\n"
        "from tpurpc.utils.config import get_config\n"
        "assert get_config().ring_domain == 'tcp_window'\n"
        "agg = qps.run_localhost(n_clients=2, req_size=64, duration=1.5,"
        " concurrency=1)\n"
        "print(json.dumps({'rpcs': agg['rpcs'], 'rate': agg['rate_rps']}))\n"
    )
    env = dict(os.environ,
               GRPC_PLATFORM_TYPE="RDMA_BP",
               TPURPC_RING_DOMAIN="tcp_window",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="256")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env=env, timeout=180)
    assert out.returncode == 0, out.stderr
    stats = __import__("json").loads(out.stdout.strip().splitlines()[-1])
    assert stats["rpcs"] > 20 and stats["rate"] > 0


_TPU_TCPW_SERVER = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import tpurpc.rpc as rpc
from tpurpc.jaxshim import add_tensor_method
from tpurpc.utils.config import get_config

assert get_config().ring_domain == "tcp_window", get_config().ring_domain
seen = {}

def fn(tree):
    import jax
    seen["ok"] = isinstance(tree["x"], jax.Array)
    return {"y": np.asarray(tree["x"]) * 3, "ring": np.int64(seen["ok"])}

srv = rpc.Server(max_workers=4)
add_tensor_method(srv, "Call", fn, device=True)
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
print(port, flush=True)
srv.wait_for_termination(timeout=120)  # orphan self-reaps if pytest dies
"""

_TPU_TCPW_CLIENT = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from tpurpc.jaxshim import TensorClient
from tpurpc.rpc.channel import Channel
from tpurpc.utils.config import get_config

assert get_config().ring_domain == "tcp_window"
x = np.arange(2048, dtype=np.float32).reshape(64, 32)
with Channel(f"127.0.0.1:{sys.argv[1]}") as ch:
    out = TensorClient(ch).call("Call", {"x": x}, timeout=60)
np.testing.assert_array_equal(np.asarray(out["y"]), x * 3)
assert int(np.asarray(out["ring"]).ravel()[0]) == 1  # device-ring-backed
print("CLIENT_OK", flush=True)
"""


def test_tpu_platform_over_tcpw_cross_process():
    """The north-star topology composed: GRPC_PLATFORM_TYPE=TPU (payloads
    land in the receiver's DEVICE ring, handler gets lease-backed
    jax.Arrays) x TPURPC_RING_DOMAIN=tcp_window (the one-sided ring carried
    between PROCESSES standing in for hosts). Tensor bytes from another
    process land in the device ring purely by env selection."""
    env = dict(os.environ,
               GRPC_PLATFORM_TYPE="TPU",
               TPURPC_RING_DOMAIN="tcp_window",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="1024",
               JAX_PLATFORMS="cpu")  # conftest already stripped the tunnel var
    _run_cross_process(_TPU_TCPW_SERVER, _TPU_TCPW_CLIENT, env,
                       client_timeout=240)


def test_forged_records_cannot_land_bytes():
    """VERDICT r3 #8: write authorization is possession of the per-region
    HMAC secret (delivered only via the handle, i.e. the bootstrap channel)
    — an attacker who knows everything ON THE WIRE short of the secret
    (host, port, hello, region key, record format) cannot land a byte."""

    from tpurpc.core import tcpw as T

    dom = TcpWindowDomain()
    region = dom.alloc(256)
    # the 16B region key is the wire-visible identifier; the secret is not
    _, _, key_hex, _secret_hex = region.handle[5:].rsplit(":", 3)
    key = bytes.fromhex(key_hex)
    server = _RecordServer.get()

    def forge(records, hello=T._HELLO):
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        try:
            try:
                s.sendall(hello)
                for rec in records:
                    s.sendall(rec)
            except (BrokenPipeError, ConnectionResetError):
                return b""  # server dropped us mid-send: same verdict
            # server closes on verification failure; a clean read of 0
            # bytes = dropped connection (it never writes back otherwise)
            s.settimeout(5)
            try:
                return s.recv(1)
            except socket.timeout:
                return b"open"
            except ConnectionResetError:
                return b""  # dropped with unread bytes pending: RST
        finally:
            s.close()

    payload = b"A" * 32
    hdr = T._REC.pack(key, 0, len(payload))

    # (1) garbage MAC: dropped, nothing lands
    assert forge([hdr + b"\x00" * T._MAC_LEN + payload]) == b""
    # (2) MAC computed with the WRONG secret: dropped, nothing lands
    bad = T._record_mac(b"x" * 32, hdr, payload)
    assert forge([hdr + bad + payload]) == b""
    # (3) pure garbage stream: dropped at the hello
    assert forge([b"\xde\xad" * 40], hello=b"XXXX") == b""
    # (4) oversized length field (payload > region): skimmed through a
    # bounded scratch — no region-sized allocation, nothing lands, and a
    # single offense keeps the connection (legit teardown races look the
    # same) rather than dropping it
    big_hdr = T._REC.pack(key, 0, 1024)
    assert forge([big_hdr + b"\x00" * T._MAC_LEN + b"B" * 1024]) == b"open"
    # (5) unknown-key flood: the per-connection unverifiable budget runs
    # out (it only replenishes on VERIFIED records, which a forger can't
    # produce) and the connection is dropped — no infinite free probing
    flood = []
    for i in range(1100):
        fh = T._REC.pack(os.urandom(16), 0, 4)
        flood.append(fh + b"\x00" * T._MAC_LEN + b"XXXX")
    assert forge(flood) == b""
    time.sleep(0.1)
    assert bytes(region.buf) == b"\0" * 256, "forged bytes landed!"

    # (4) the LEGITIMATE path (handle carries the secret) still works
    win = dom.open_window(region.handle, 256)
    win.write(0, b"legit")
    deadline = time.monotonic() + 5
    while bytes(region.buf[:5]) != b"legit" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bytes(region.buf[:5]) == b"legit"
    win.close()
    region.close()
