"""Test configuration: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; shardings are validated the way the driver's
``dryrun_multichip`` does — over ``xla_force_host_platform_device_count`` CPU devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Subprocesses spawned by tests must never touch the TPU tunnel either:
# the axon sitecustomize registers its PJRT plugin whenever this var is
# set, and a black-holing tunnel then hangs ANY jax-importing child at
# first use (observed mid round-3: jnp.zeros blocking >200s). Popping it
# here sanitizes the env every test child inherits.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (TPU tunnel image) force-registers jax_platforms
# "axon,cpu" regardless of env; pin the jax config back to pure CPU so the
# suite is hermetic and never blocks on the single shared TPU chip.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'); covered by "
        "the analysis gate or a dedicated stage instead")


@pytest.fixture(autouse=True)
def _reset_config_singleton():
    """Each test sees a fresh Config.from_env() so monkeypatched env vars apply;
    poller/pool singletons die with the test that used them."""
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    yield
    from tpurpc.core.poller import PairPool, Poller

    Poller.reset()
    PairPool.reset()
    config_mod.set_config(None)


#: shared skip marker for suites that need the native core built
#: (tests/test_native_client.py, test_native_server.py, test_aio.py,
#: test_scalability.py import it instead of hand-rolling the path check)
requires_native_lib = pytest.mark.skipif(
    not os.path.exists(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native", "build", "libtpurpc.so")),
    reason="native lib not built")
