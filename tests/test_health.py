"""grpc.health.v1 service: native channels, status lifecycle, Watch streams,
and wire compatibility with a stock grpcio client over the h2 path."""

import threading
import time

import grpc
import pytest

import tpurpc.rpc as tps
from tpurpc.rpc import health
from tpurpc.rpc.status import RpcError, StatusCode


def _rig():
    srv = tps.Server(max_workers=4)
    servicer = health.add_health_servicer(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, servicer, port


def test_check_overall_and_named_service():
    srv, servicer, port = _rig()
    try:
        servicer.set("demo.Svc", health.ServingStatus.SERVING)
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            check = ch.unary_unary(f"/{health.SERVICE_NAME}/Check")
            assert health.decode_response(
                check(health.encode_request(""), timeout=10)) \
                is health.ServingStatus.SERVING
            assert health.decode_response(
                check(health.encode_request("demo.Svc"), timeout=10)) \
                is health.ServingStatus.SERVING
            servicer.set("demo.Svc", health.ServingStatus.NOT_SERVING)
            assert health.decode_response(
                check(health.encode_request("demo.Svc"), timeout=10)) \
                is health.ServingStatus.NOT_SERVING
            with pytest.raises(RpcError) as ei:
                check(health.encode_request("no.such.Svc"), timeout=10)
            assert ei.value.code() is StatusCode.NOT_FOUND
    finally:
        srv.stop(grace=0)


def test_watch_streams_status_transitions():
    srv, servicer, port = _rig()
    try:
        servicer.set("w.Svc", health.ServingStatus.SERVING)
        seen = []
        done = threading.Event()

        def watch():
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                stream = ch.unary_stream(f"/{health.SERVICE_NAME}/Watch")(
                    health.encode_request("w.Svc"), timeout=30)
                for msg in stream:
                    seen.append(health.decode_response(msg))
                    if len(seen) == 3:
                        done.set()
                        return

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while len(seen) < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        servicer.set("w.Svc", health.ServingStatus.NOT_SERVING)
        while len(seen) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        servicer.set("w.Svc", health.ServingStatus.SERVING)
        assert done.wait(timeout=10), seen
        assert seen == [health.ServingStatus.SERVING,
                        health.ServingStatus.NOT_SERVING,
                        health.ServingStatus.SERVING]
    finally:
        srv.stop(grace=0)


def test_watch_unknown_service_reports_service_unknown():
    srv, _, port = _rig()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            stream = iter(ch.unary_stream(f"/{health.SERVICE_NAME}/Watch")(
                health.encode_request("never.registered"), timeout=10))
            assert health.decode_response(next(stream)) \
                is health.ServingStatus.SERVICE_UNKNOWN
    finally:
        srv.stop(grace=0)


def test_stock_grpcio_health_check_wire_compat():
    """A stock grpcio client speaking the health proto (raw encoding — the
    installed grpcio ships no grpc_health package here) over the h2 path."""
    srv, servicer, port = _rig()
    try:
        servicer.set("h2.Svc", health.ServingStatus.SERVING)
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary(f"/{health.SERVICE_NAME}/Check",
                                lambda x: x, lambda x: x)
            raw = mc(health.encode_request("h2.Svc"), timeout=10)
            assert health.decode_response(raw) is health.ServingStatus.SERVING
            with pytest.raises(grpc.RpcError) as ei:
                mc(health.encode_request("missing"), timeout=10)
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        srv.stop(grace=0)


def test_proto_roundtrip_and_unknown_fields():
    assert health.decode_request(health.encode_request("a.b.C")) == "a.b.C"
    assert health.decode_request(b"") == ""
    for st in health.ServingStatus:
        assert health.decode_response(health.encode_response(st)) is st
    # unknown fields are skipped, not fatal (forward compat)
    extra = health.encode_request("svc") + b"\x10\x05"  # field 2 varint
    assert health.decode_request(extra) == "svc"


def test_drain_reports_draining_not_serving():
    """tpurpc-fleet (ISSUE 6): during Server.drain() the health service
    answers NOT_SERVING (overall and named services) and /healthz reports
    'draining' with a 200 — healthy-but-leaving, distinct from the
    watchdog's degraded 503."""
    from tpurpc.obs import scrape, watchdog

    srv, servicer, port = _rig()
    servicer.set("drain.Svc", health.ServingStatus.SERVING)
    try:
        watchdog.get().reset()  # no stale degraded state from other tests
        status, _ctype, body = scrape._route("/healthz")
        assert (status, body) == (200, b"ok\n")
        assert srv.drain(linger=1.0) is True  # no streams: clean drain
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            check = ch.unary_unary(f"/{health.SERVICE_NAME}/Check",
                                   tpurpc_native=False)
            for svc in ("", "drain.Svc"):
                got = health.decode_response(
                    check(health.encode_request(svc), timeout=10))
                assert got is health.ServingStatus.NOT_SERVING, svc
        status, _ctype, body = scrape._route("/healthz")
        assert status == 200, "draining is NOT a failure state"
        assert body == b"draining\n"
    finally:
        srv.stop(grace=0)
    # /healthz recovers once the drained server object is gone (channelz
    # holds it weakly; winding-down connections pin it briefly after stop)
    import gc

    del srv
    deadline = time.monotonic() + 10
    body = b""
    while time.monotonic() < deadline:
        gc.collect()
        _status, _ctype, body = scrape._route("/healthz")
        if body == b"ok\n":
            break
        time.sleep(0.1)
    assert body == b"ok\n"


def test_watch_sees_drain_transition():
    """A health Watch stream open across Server.drain() observes the
    SERVING → NOT_SERVING transition (set_all bumps one epoch) before the
    drained connection winds down."""
    srv, servicer, port = _rig()
    servicer.set("wd.Svc", health.ServingStatus.SERVING)
    seen = []
    try:
        def watch():
            try:
                with tps.Channel(f"127.0.0.1:{port}") as ch:
                    stream = ch.unary_stream(
                        f"/{health.SERVICE_NAME}/Watch", tpurpc_native=False)(
                        health.encode_request("wd.Svc"), timeout=30)
                    for msg in stream:
                        seen.append(health.decode_response(msg))
                        if seen[-1] is health.ServingStatus.NOT_SERVING:
                            return
            except RpcError:
                pass  # the draining server may close after delivery

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while not seen and time.monotonic() < deadline:
            time.sleep(0.05)
        assert seen and seen[0] is health.ServingStatus.SERVING
        srv.drain(linger=5.0)
        t.join(timeout=10)
        assert health.ServingStatus.NOT_SERVING in seen, seen
    finally:
        srv.stop(grace=0)


def test_malformed_request_maps_to_invalid_argument():
    srv, _, port = _rig()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            check = ch.unary_unary(f"/{health.SERVICE_NAME}/Check")
            with pytest.raises(RpcError) as ei:
                check(b"\x0a\x80", timeout=10)  # truncated length varint
            assert ei.value.code() is StatusCode.INVALID_ARGUMENT
    finally:
        srv.stop(grace=0)
