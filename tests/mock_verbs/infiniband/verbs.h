// MOCK <infiniband/verbs.h> — CI's compile-and-behavior proof for the
// verbs domain skeleton (native/src/verbs_domain.cc) on hosts with no IB
// hardware or headers. Implements exactly the subset the skeleton uses,
// in-process: ibv_reg_mr tracks regions in a global registry keyed by
// rkey; IBV_WR_RDMA_WRITE validates {rkey, bounds} and memcpys into the
// target region (the NIC's placement write, minus the NIC); every
// signaled write completes immediately on the CQ. QP state transitions
// are recorded and order-checked (RESET->INIT->RTR->RTS), so the
// skeleton's bring-up sequence is verified, not just compiled.
//
// THIS IS A TEST DOUBLE. It lives under tests/ and is only reachable via
// -Itests/mock_verbs -DTPR_TEST_MOCK_VERBS; production builds pick up the
// real libibverbs header or compile the unavailable stubs.
#ifndef TPURPC_TESTS_MOCK_VERBS_H
#define TPURPC_TESTS_MOCK_VERBS_H

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <vector>

enum ibv_qp_type { IBV_QPT_RC = 2 };
enum ibv_qp_state {
  IBV_QPS_RESET,
  IBV_QPS_INIT,
  IBV_QPS_RTR,
  IBV_QPS_RTS,
  IBV_QPS_ERR
};
enum ibv_mtu { IBV_MTU_1024 = 3 };
enum ibv_wr_opcode { IBV_WR_RDMA_WRITE = 0 };
enum ibv_wc_status { IBV_WC_SUCCESS = 0, IBV_WC_REM_ACCESS_ERR = 10 };
enum {
  IBV_ACCESS_LOCAL_WRITE = 1,
  IBV_ACCESS_REMOTE_WRITE = 2,
  IBV_SEND_SIGNALED = 2,
  IBV_QP_STATE = 1 << 0,
  IBV_QP_PKEY_INDEX = 1 << 1,
  IBV_QP_PORT = 1 << 2,
  IBV_QP_ACCESS_FLAGS = 1 << 3,
  IBV_QP_AV = 1 << 4,
  IBV_QP_PATH_MTU = 1 << 5,
  IBV_QP_DEST_QPN = 1 << 6,
  IBV_QP_RQ_PSN = 1 << 7,
  IBV_QP_MAX_DEST_RD_ATOMIC = 1 << 8,
  IBV_QP_MIN_RNR_TIMER = 1 << 9,
  IBV_QP_SQ_PSN = 1 << 10,
  IBV_QP_TIMEOUT = 1 << 11,
  IBV_QP_RETRY_CNT = 1 << 12,
  IBV_QP_RNR_RETRY = 1 << 13,
  IBV_QP_MAX_QP_RD_ATOMIC = 1 << 14
};

struct ibv_device {
  const char *name;
};
struct ibv_context {
  ibv_device *device;
};
struct ibv_pd {
  ibv_context *context;
};
struct ibv_wc {
  uint64_t wr_id;
  int status;
};
struct ibv_cq {
  std::mutex mu;
  std::queue<ibv_wc> completions;
};
struct ibv_mr {
  ibv_pd *pd;
  void *addr;
  size_t length;
  uint32_t lkey, rkey;
};
union ibv_gid {
  uint8_t raw[16];
};
struct ibv_port_attr {
  uint16_t lid;
};
struct ibv_global_route {
  ibv_gid dgid;
  uint8_t hop_limit;
};
struct ibv_ah_attr {
  ibv_global_route grh;
  uint16_t dlid;
  uint8_t sl, src_path_bits, is_global, port_num;
};
struct ibv_qp_cap {
  uint32_t max_send_wr, max_recv_wr, max_send_sge, max_recv_sge;
};
struct ibv_qp_init_attr {
  void *qp_context;
  ibv_cq *send_cq, *recv_cq;
  void *srq;
  ibv_qp_cap cap;
  int qp_type;
  int sq_sig_all;
};
struct ibv_qp_attr {
  int qp_state;
  int path_mtu;
  uint32_t dest_qp_num, rq_psn, sq_psn;
  uint8_t max_dest_rd_atomic, min_rnr_timer, max_rd_atomic;
  uint8_t timeout, retry_cnt, rnr_retry;
  uint16_t pkey_index;
  uint8_t port_num;
  int qp_access_flags;
  ibv_ah_attr ah_attr;
};
struct ibv_qp {
  ibv_pd *pd;
  ibv_cq *send_cq;
  uint32_t qp_num;
  int state;
  uint32_t dest_qp_num;
};
struct ibv_sge {
  uint64_t addr;
  uint32_t length, lkey;
};
struct ibv_send_wr {
  uint64_t wr_id;
  ibv_send_wr *next;
  ibv_sge *sg_list;
  int num_sge;
  int opcode;
  int send_flags;
  struct {
    struct {
      uint64_t remote_addr;
      uint32_t rkey;
    } rdma;
  } wr;
};

// ---- in-process fabric state ------------------------------------------------

struct tpr_mock_fabric {
  std::mutex mu;
  std::map<uint32_t, ibv_mr *> mrs_by_rkey;  // the "NIC's" MR table
  uint32_t next_key = 0x1000;
  uint32_t next_qpn = 0x100;
  static tpr_mock_fabric &get() {
    static tpr_mock_fabric f;
    return f;
  }
};

// ---- API subset -------------------------------------------------------------

static inline ibv_device **ibv_get_device_list(int *n) {
  static ibv_device dev = {"mock0"};
  static ibv_device *list[2] = {&dev, nullptr};
  if (n) *n = 1;
  return list;
}
static inline void ibv_free_device_list(ibv_device **) {}
static inline const char *ibv_get_device_name(ibv_device *d) {
  return d->name;
}
static inline ibv_context *ibv_open_device(ibv_device *d) {
  return new ibv_context{d};
}
static inline int ibv_close_device(ibv_context *c) {
  delete c;
  return 0;
}
static inline ibv_pd *ibv_alloc_pd(ibv_context *c) { return new ibv_pd{c}; }
static inline int ibv_dealloc_pd(ibv_pd *p) {
  delete p;
  return 0;
}
static inline ibv_cq *ibv_create_cq(ibv_context *, int, void *, void *, int) {
  return new ibv_cq();
}
static inline int ibv_destroy_cq(ibv_cq *cq) {
  delete cq;
  return 0;
}
static inline int ibv_query_port(ibv_context *, uint8_t,
                                 ibv_port_attr *attr) {
  attr->lid = 7;  // a plausible LID: the skeleton ships it in rendezvous
  return 0;
}
static inline int ibv_query_gid(ibv_context *, uint8_t, int, ibv_gid *gid) {
  memset(gid->raw, 0xAB, 16);
  return 0;
}

static inline ibv_mr *ibv_reg_mr(ibv_pd *pd, void *addr, size_t len,
                                 int access) {
  if (!(access & IBV_ACCESS_REMOTE_WRITE)) return nullptr;  // domain needs it
  auto &f = tpr_mock_fabric::get();
  std::lock_guard<std::mutex> lk(f.mu);
  auto *mr = new ibv_mr{pd, addr, len, f.next_key, f.next_key + 1};
  f.next_key += 2;
  f.mrs_by_rkey[mr->rkey] = mr;
  return mr;
}
static inline int ibv_dereg_mr(ibv_mr *mr) {
  auto &f = tpr_mock_fabric::get();
  std::lock_guard<std::mutex> lk(f.mu);
  f.mrs_by_rkey.erase(mr->rkey);
  delete mr;
  return 0;
}

static inline ibv_qp *ibv_create_qp(ibv_pd *pd, ibv_qp_init_attr *ia) {
  if (ia->qp_type != IBV_QPT_RC) return nullptr;
  auto &f = tpr_mock_fabric::get();
  std::lock_guard<std::mutex> lk(f.mu);
  return new ibv_qp{pd, ia->send_cq, f.next_qpn++, IBV_QPS_RESET, 0};
}
static inline int ibv_destroy_qp(ibv_qp *qp) {
  delete qp;
  return 0;
}
static inline int ibv_modify_qp(ibv_qp *qp, ibv_qp_attr *a, int mask) {
  if (!(mask & IBV_QP_STATE)) return -1;
  // order-check the bring-up: the skeleton must walk RESET->INIT->RTR->RTS
  switch (a->qp_state) {
    case IBV_QPS_INIT:
      if (qp->state != IBV_QPS_RESET) return -1;
      if (!(mask & IBV_QP_ACCESS_FLAGS) ||
          !(a->qp_access_flags & IBV_ACCESS_REMOTE_WRITE))
        return -1;
      break;
    case IBV_QPS_RTR:
      if (qp->state != IBV_QPS_INIT) return -1;
      if (!(mask & IBV_QP_DEST_QPN)) return -1;
      qp->dest_qp_num = a->dest_qp_num;
      break;
    case IBV_QPS_RTS:
      if (qp->state != IBV_QPS_RTR) return -1;
      break;
    default:
      return -1;
  }
  qp->state = a->qp_state;
  return 0;
}

static inline int ibv_post_send(ibv_qp *qp, ibv_send_wr *wr,
                                ibv_send_wr **bad) {
  if (qp->state != IBV_QPS_RTS) {
    if (bad) *bad = wr;
    return -1;
  }
  auto &f = tpr_mock_fabric::get();
  for (; wr; wr = wr->next) {
    if (wr->opcode != IBV_WR_RDMA_WRITE) {
      if (bad) *bad = wr;
      return -1;
    }
    int status = IBV_WC_SUCCESS;
    {
      std::lock_guard<std::mutex> lk(f.mu);
      auto it = f.mrs_by_rkey.find(wr->wr.rdma.rkey);
      uint64_t off = 0;
      ibv_mr *mr = it == f.mrs_by_rkey.end() ? nullptr : it->second;
      if (mr) off = wr->wr.rdma.remote_addr - (uint64_t)(uintptr_t)mr->addr;
      uint64_t total = 0;
      for (int i = 0; i < wr->num_sge; ++i) total += wr->sg_list[i].length;
      if (!mr || off > mr->length || total > mr->length - off) {
        status = IBV_WC_REM_ACCESS_ERR;  // bad rkey/bounds: NIC would NAK
      } else {
        uint8_t *dst = (uint8_t *)mr->addr + off;
        for (int i = 0; i < wr->num_sge; ++i) {
          memcpy(dst, (const void *)(uintptr_t)wr->sg_list[i].addr,
                 wr->sg_list[i].length);
          dst += wr->sg_list[i].length;
        }
      }
    }
    if (wr->send_flags & IBV_SEND_SIGNALED) {
      std::lock_guard<std::mutex> lk(qp->send_cq->mu);
      qp->send_cq->completions.push(ibv_wc{wr->wr_id, status});
    }
  }
  return 0;
}

static inline int ibv_poll_cq(ibv_cq *cq, int max, ibv_wc *wc) {
  std::lock_guard<std::mutex> lk(cq->mu);
  int n = 0;
  while (n < max && !cq->completions.empty()) {
    wc[n++] = cq->completions.front();
    cq->completions.pop();
  }
  return n;
}

#endif  // TPURPC_TESTS_MOCK_VERBS_H
