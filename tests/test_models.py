"""Model-family smoke tests (thin variants keep CPU CI fast) + graft entry."""

import jax
import jax.numpy as jnp
import numpy as np

from tpurpc.models.resnet import (init_resnet, make_infer_fn, resnet18_thin,
                                  resnet50)


def test_thin_resnet_forward():
    model = resnet18_thin(num_classes=10)
    variables = init_resnet(jax.random.PRNGKey(0), model, image_size=32,
                            batch=2)
    logits = jax.jit(make_infer_fn(model))(
        variables, jnp.ones((2, 32, 32, 3), jnp.float32))
    assert logits.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_resnet50_has_50_conv_layers():
    model = resnet50()
    variables = init_resnet(jax.random.PRNGKey(0), model, image_size=64,
                            batch=1)
    flat = jax.tree_util.tree_leaves_with_path(variables["params"])
    conv_kernels = [p for p, v in flat if v.ndim == 4]
    # 1 stem + 3 per bottleneck * (3+4+6+3) + 4 projections = 53 convs
    assert len(conv_kernels) == 53
    dense = [v for p, v in flat if v.ndim == 2]
    assert dense[0].shape[-1] == 1000


def test_graft_entry_shapes():
    import __graft_entry__ as ge

    fn, (variables, images) = ge.entry()
    out = jax.eval_shape(fn, variables, images)
    assert out.shape == (images.shape[0], 1000)


def test_graft_dryrun_two_devices():
    import __graft_entry__ as ge

    ge.dryrun_multichip(2)
