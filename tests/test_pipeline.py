"""Pipelined serving path (ISSUE 3): multi-in-flight unary clients.

Deterministic coverage of the properties the bench's depth sweep can only
measure statistically:

* stream-id demux — N concurrent calls on ONE connection each get their
  own response, including when the server completes them out of order;
* window backpressure — the depth+1'th call_async blocks until a
  completion frees a slot;
* out-of-order completion — a parked call must not block siblings;
* deadline watchdog — a never-answered pipelined call fails
  DEADLINE_EXCEEDED and releases its window slot;
* cross-stream response coalescing — responses stay intact through the
  server's gathered writev (tag echo over many concurrent streams);
* the native plane's inline-window futures (lib permitting).
"""

import os
import threading
import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.status import RpcError, StatusCode

NATIVE_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "libtpurpc.so")


@pytest.fixture()
def echo_server():
    """Echo server with a parkable method for out-of-order scenarios."""
    park = threading.Event()

    def echo(req, ctx):
        return b"ok:" + bytes(req)

    def parked(req, ctx):
        park.wait(10)
        return b"late:" + bytes(req)

    def fail_odd(req, ctx):
        if int(bytes(req)) % 2:
            ctx.abort(StatusCode.FAILED_PRECONDITION, "odd rejected")
        return bytes(req)

    srv = rpc.Server(max_workers=8)
    srv.add_method("/p/Echo", rpc.unary_unary_rpc_method_handler(echo))
    srv.add_method("/p/EchoInline",
                   rpc.unary_unary_rpc_method_handler(echo, inline=True))
    srv.add_method("/p/Park", rpc.unary_unary_rpc_method_handler(parked))
    srv.add_method("/p/FailOdd",
                   rpc.unary_unary_rpc_method_handler(fail_odd))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port, park
    park.set()
    srv.stop(grace=0)


def test_stream_id_demux_many_in_flight(echo_server):
    port, _ = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        pl = ch.unary_unary("/p/Echo").pipeline(depth=16)
        futs = [pl.call_async(b"r%d" % i, timeout=30) for i in range(48)]
        for i, f in enumerate(futs):
            assert f.result(timeout=10) == b"ok:r%d" % i


def test_out_of_order_completion_does_not_block_siblings(echo_server):
    port, park = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        mc_park = ch.unary_unary("/p/Park").pipeline(depth=4)
        mc_echo = ch.unary_unary("/p/Echo").pipeline(depth=4)
        slow = mc_park.call_async(b"s", timeout=30)
        fasts = [mc_echo.call_async(b"f%d" % i, timeout=30)
                 for i in range(8)]
        for i, f in enumerate(fasts):
            assert f.result(timeout=10) == b"ok:f%d" % i
        assert not slow.done()  # still parked while siblings completed
        park.set()
        assert slow.result(timeout=10) == b"late:s"


def test_window_backpressure_blocks_depth_plus_one(echo_server):
    port, park = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        pl = ch.unary_unary("/p/Park").pipeline(depth=2)
        a = pl.call_async(b"a", timeout=30)
        b = pl.call_async(b"b", timeout=30)
        third = []

        def blocked():
            third.append(pl.call_async(b"c", timeout=30))

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        time.sleep(0.3)
        assert not third, "3rd call should block on the depth-2 window"
        park.set()  # completions free slots; the blocked call proceeds
        t.join(timeout=10)
        assert third and third[0].result(timeout=10) == b"late:c"
        assert a.result(10) == b"late:a" and b.result(10) == b"late:b"


def test_pipelined_deadline_fails_future_and_frees_window(echo_server):
    port, park = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        pl = ch.unary_unary("/p/Park").pipeline(depth=1)
        f = pl.call_async(b"never", timeout=0.3)
        with pytest.raises(RpcError) as ei:
            f.result(timeout=10)
        code = ei.value.code() if callable(ei.value.code) else ei.value.code
        assert code is StatusCode.DEADLINE_EXCEEDED
        # the expired call released its window slot: the next call on the
        # SAME depth-1 pipeline proceeds instead of wedging
        park.set()
        f2 = pl.call_async(b"after", timeout=30)
        assert f2.result(timeout=10) == b"late:after"


def test_pipelined_errors_demux_to_their_own_futures(echo_server):
    port, _ = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        pl = ch.unary_unary("/p/FailOdd").pipeline(depth=8)
        futs = [pl.call_async(b"%d" % i, timeout=30) for i in range(10)]
        for i, f in enumerate(futs):
            if i % 2:
                with pytest.raises(RpcError, match="odd rejected"):
                    f.result(timeout=10)
            else:
                assert f.result(timeout=10) == b"%d" % i


def test_coalesced_responses_survive_concurrent_streams(echo_server):
    """Responses completing close together flush as one gathered writev
    (server-side coalescing); every payload must still reach its own
    stream intact. The histogram proves multi-response flushes happened."""
    from tpurpc.utils import stats

    stats.reset_batch_stats()
    port, _ = echo_server
    n_conns, per_conn = 4, 32
    errors: list = []

    def one(conn_idx):
        try:
            with Channel(f"127.0.0.1:{port}") as ch:
                pl = ch.unary_unary("/p/Echo").pipeline(depth=16)
                futs = [pl.call_async(b"c%d-%d" % (conn_idx, i), timeout=30)
                        for i in range(per_conn)]
                for i, f in enumerate(futs):
                    got = f.result(timeout=15)
                    assert got == b"ok:c%d-%d" % (conn_idx, i), got
        except Exception as exc:
            errors.append(exc)

    ts = [threading.Thread(target=one, args=(i,)) for i in range(n_conns)]
    [t.start() for t in ts]
    [t.join(timeout=30) for t in ts]
    assert not errors, errors
    h = stats.batch_snapshot().get("resp_coalesce")
    assert h and h["count"] > 0  # the combiner ran
    # not asserting mean>1: coalescing opportunities are load-dependent;
    # correctness above is the deterministic claim


def test_inline_dispatch_pipelined(echo_server):
    """Inline (reader-thread) handlers serve pipelined clients too: the
    fused responses demux correctly and the connection stays healthy."""
    port, _ = echo_server
    with Channel(f"127.0.0.1:{port}") as ch:
        pl = ch.unary_unary("/p/EchoInline").pipeline(depth=8)
        futs = [pl.call_async(b"i%d" % i, timeout=30) for i in range(32)]
        for i, f in enumerate(futs):
            assert f.result(timeout=10) == b"ok:i%d" % i


def test_tensor_client_call_async_roundtrip(echo_server):
    import numpy as np

    from tpurpc.jaxshim import TensorClient, add_tensor_method

    srv = rpc.Server(max_workers=4)
    add_tensor_method(srv, "Dbl", lambda t: {"y": np.asarray(t["x"]) * 2})
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch, depth=8)
            xs = [np.full((2, 3), i, np.float32) for i in range(12)]
            futs = [cli.call_async("Dbl", {"x": x}, timeout=30) for x in xs]
            for i, f in enumerate(futs):
                out = f.result(timeout=10)
                assert np.array_equal(np.asarray(out["y"]), xs[i] * 2)
    finally:
        srv.stop(grace=0)


@pytest.mark.skipif(not os.path.exists(NATIVE_LIB),
                    reason="native lib not built")
def test_native_inline_window_futures(echo_server):
    """NativeChannel(inline_read=True).unary_unary(...).future — the CQ
    refuses on inline channels, so the bounded worker window carries the
    multi-in-flight contract there."""
    from tpurpc.rpc.native_client import NativeChannel

    port, _ = echo_server
    with NativeChannel("127.0.0.1", port, inline_read=True,
                       pipeline_depth=4) as ch:
        mc = ch.unary_unary("/p/Echo")
        futs = [mc.future(b"n%d" % i, timeout=30) for i in range(16)]
        for i, f in enumerate(futs):
            assert f.result(timeout=15) == b"ok:n%d" % i
