"""tpurpc-xray: the Python face of the C observability plane (ISSUE 19).

The merged-flight contract (``tpurpc/obs/native_obs.py`` + the
``flight.snapshot`` merge): the C core's shm flight ring and metrics
table surface through the SAME consumers the Python plane feeds —
one monotonic timeline with lane tags, protocol conformance over the
merged stream, ``native_*`` registry series into the tsdb, postfork
remapping in forked shard workers, and a clean off switch that leaves
the PR 18 ``tpr_rdv_counters`` ledger ABI untouched.
"""

import json
import os
import subprocess
import sys

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.channel import Channel

from tests.conftest import requires_native_lib  # noqa: E402

pytestmark = requires_native_lib

PY_PAYLOAD = bytes(512) * 4096  # 2 MiB: over the py-plane rdv floor
NATIVE_PAYLOAD = bytes(range(256)) * 4096  # 1 MiB on the C plane


@pytest.fixture
def ring_platform(monkeypatch):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    yield
    config_mod.set_config(None)


@pytest.fixture
def obs_plane(ring_platform):
    """Fresh C + py flight state; skips when the .so was built with the
    plane compiled out or disabled in this environment."""
    from tpurpc.obs import flight, native_obs

    if not native_obs.available():
        pytest.skip("native obs plane not available in this process")
    flight.RECORDER.reset()
    native_obs.reset()
    yield native_obs
    flight.RECORDER.reset()


def _totaling_server():
    srv = rpc.Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/nobs.S/Total",
                   rpc.stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _cross_plane_exchange():
    """One native-plane leg and one py-plane leg on the same wire, so the
    merged flight carries BOTH lanes."""
    srv, port = _totaling_server()
    try:
        assert srv._native_dp is not None, "server adoption did not engage"
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/nobs.S/Total")
            list(mc(iter([b"warm"]), timeout=30))
            out = list(mc(iter([NATIVE_PAYLOAD]), timeout=60))
            assert out[-1] == str(len(NATIVE_PAYLOAD)).encode(), out
            mc_py = ch.stream_stream("/nobs.S/Total", tpurpc_native=False)
            out = list(mc_py(iter([PY_PAYLOAD]), timeout=60))
            assert out[-1] == str(len(PY_PAYLOAD)).encode(), out
    finally:
        srv.stop(grace=1)


def test_merged_snapshot_two_lanes_one_timeline(obs_plane):
    """Cross-plane calls produce ONE time-ordered flight view: C records
    lane-tagged ``native`` on n* entities, py records tagged ``py``,
    interleaved on the shared CLOCK_MONOTONIC axis."""
    from tpurpc.obs import flight

    _cross_plane_exchange()
    snap = flight.snapshot()
    stamps = [e["t_ns"] for e in snap]
    assert stamps == sorted(stamps), "merged timeline out of order"
    native = [e for e in snap if e.get("lane") == "native"]
    py = [e for e in snap if e.get("lane") == "py"]
    assert native, "C plane contributed nothing to the merge"
    assert py, "python lane lost its tag in the merge"
    assert all(e["entity"].startswith("n") for e in native), native[:5]
    # the C rendezvous evidence arrives whole and in causal order
    evs = [e["event"] for e in native]
    for name in ("rdv-offer", "rdv-claim", "rdv-complete"):
        assert name in evs, (name, evs)
    assert evs.index("rdv-offer") < evs.index("rdv-claim") \
        < evs.index("rdv-complete")


def test_merged_snapshot_replays_through_protocol_machines(obs_plane):
    """The C plane emits the SAME event vocabulary the protocol machines
    were built for: the merged dump replays with zero violations, and the
    dump file round-trips through the offline checker."""
    from tpurpc.analysis import protocol
    from tpurpc.obs import flight

    _cross_plane_exchange()
    snap = flight.snapshot()
    assert any(e.get("lane") == "native" for e in snap)
    violations = protocol.check_events(snap, strict=False)
    assert violations == [], violations[:5]
    # and as a dump FILE (the TPURPC_FLIGHT_DUMP / CI-artifact path)
    path = "/tmp/_tpurpc_test_native_obs_dump.json"
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"events": snap}, f)
    try:
        total, violations = protocol.check_dump(path, strict=False)
        assert total == len(snap)
        assert violations == [], violations[:5]
    finally:
        os.unlink(path)


def test_counters_scrape_registry_and_tsdb_pickup(obs_plane):
    """The metrics table reaches every layered consumer: the raw dict,
    the registry mirror (``native_*``), /metrics rendering, and tsdb
    history — all without the C hot path seeing Python."""
    from tpurpc.obs import metrics as metrics_mod
    from tpurpc.obs import scrape, tsdb
    from tpurpc.rpc import native_client

    _cross_plane_exchange()
    tab = obs_plane.counters()
    assert tab["rdv_send_bytes"] >= len(NATIVE_PAYLOAD), tab
    assert tab["emitted"] > 0 and tab["conn_up"] >= 1, tab
    assert set(tab) == set(obs_plane.METRIC_NAMES)
    # registry mirror: externally-owned totals, assigned not inc()ed
    assert obs_plane.sync_registry() is True
    reg = metrics_mod.registry()
    assert reg.counter("native_rdv_send_bytes").value == \
        tab["rdv_send_bytes"]
    assert "tpurpc_native_rdv_send_bytes" in scrape.render_prometheus()
    # tsdb: one sampler tick picks the mirror up as history
    db = tsdb.Tsdb(fine_s=0.05)
    db.sample_once()
    kinds = db.series()
    assert kinds.get("native_rdv_send_bytes") == "counter", kinds
    assert kinds.get("native_dlv_depth") == "gauge", kinds
    pts = db.window("native_rdv_send_bytes", 60.0)
    assert pts and pts[-1][1] >= len(NATIVE_PAYLOAD), pts
    # the PR 18 rdv ledger rides alongside, not underneath: both ABIs
    # answer, from independent storage
    led = native_client.rdv_counters()
    assert led is not None
    assert set(led) == set(native_client.RDV_COUNTER_NAMES)


def test_postfork_reset_attaches_fresh_region(obs_plane):
    """A forked shard worker must NOT keep writing into the parent's shm
    region: postfork_reset drops the inherited mapping, the C side builds
    its own region under a new name, and the parent's stays intact."""
    parent_name = obs_plane._lib().tpr_obs_shm_name().decode()
    assert parent_name
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:
        status = 1
        try:
            os.close(r)
            obs_plane.postfork_reset()
            child_name = obs_plane._lib().tpr_obs_shm_name().decode()
            doc = {"name": child_name,
                   "available": obs_plane.available(),
                   "emitted": obs_plane.counters().get("emitted", -1)}
            os.write(w, json.dumps(doc).encode())
            os.close(w)
            status = 0
        finally:
            os._exit(status)
    os.close(w)
    try:
        raw = b""
        while True:
            chunk = os.read(r, 4096)
            if not chunk:
                break
            raw += chunk
    finally:
        os.close(r)
        _, code = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(code) == 0
    doc = json.loads(raw)
    assert doc["available"] is True
    assert doc["name"] and doc["name"] != parent_name, doc
    assert doc["emitted"] == 0, doc  # fresh table, not the parent's totals
    # the parent keeps its region AND its mapping (staleness probe holds)
    assert obs_plane._lib().tpr_obs_shm_name().decode() == parent_name
    assert obs_plane.available()


def test_off_switch_leaves_rdv_ledger_abi_intact(ring_platform):
    """TPURPC_NATIVE_OBS=0 (read by the C side at first use, hence the
    subprocess): the plane reports unavailable, the flight snapshot grows
    no lane tags, and the PR 18 ``tpr_rdv_counters`` ledger still answers
    — observability off must not degrade the data plane's own telemetry."""
    script = """
import json
from tpurpc.obs import flight, native_obs
import tpurpc.rpc as rpc
from tpurpc.rpc.channel import Channel
from tpurpc.rpc import native_client

srv = rpc.Server(max_workers=2)

def total(req_iter, ctx):
    yield str(sum(len(m) for m in req_iter)).encode()

srv.add_method("/off.S/Total", rpc.stream_stream_rpc_method_handler(total))
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
payload = bytes(512) * 4096
try:
    with Channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream("/off.S/Total")
        assert list(mc(iter([payload]), timeout=60))[-1] == \\
            str(len(payload)).encode()
        mc_py = ch.stream_stream("/off.S/Total", tpurpc_native=False)
        assert list(mc_py(iter([payload]), timeout=60))[-1] == \\
            str(len(payload)).encode()
finally:
    srv.stop(grace=1)
assert not native_obs.available()
assert native_obs.counters() == {}
assert native_obs.records() == []
snap = flight.snapshot()
assert snap, "py recorder must still record with the plane off"
assert all("lane" not in e for e in snap), "lane tags leaked"
led = native_client.rdv_counters()
assert led is not None
assert set(led) == set(native_client.RDV_COUNTER_NAMES)
assert native_client.rdv_counters_reset() is True
print("OFFSWITCH-OK")
"""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["TPURPC_NATIVE_OBS"] = "0"
    env["GRPC_PLATFORM_TYPE"] = "RDMA_BPEV"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=180)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "OFFSWITCH-OK" in res.stdout
