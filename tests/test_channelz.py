"""channelz-lite: live server/channel stats, call counters, RPC exposure."""

import json

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc import channelz


def test_counters_and_snapshot():
    srv = rpc.Server(max_workers=2)
    srv.add_method("/t.S/Ok",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: r))

    def bad(r, c):
        c.abort(rpc.StatusCode.INTERNAL, "x")

    srv.add_method("/t.S/Bad", rpc.unary_unary_rpc_method_handler(bad))
    channelz.add_channelz_service(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            ch.unary_unary("/t.S/Ok")(b"1", timeout=10)
            ch.unary_unary("/t.S/Ok")(b"2", timeout=10)
            with pytest.raises(rpc.RpcError):
                ch.unary_unary("/t.S/Bad")(b"3", timeout=10)
            raw = ch.unary_unary("/tpurpc.Channelz/Get")(b"", timeout=10)
            # counters finalize after trailers hit the wire — poll-settle
            import time

            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                info = channelz.server_info(srv)
                if info["calls_succeeded"] >= 3 and info["calls_failed"] >= 1:
                    break
                time.sleep(0.02)
            chan = channelz.channel_info(ch)
        # the channelz RPC itself is a successful call → >= 3 successes
        assert info["calls_started"] >= 4
        assert info["calls_succeeded"] >= 3
        assert info["calls_failed"] >= 1
        assert "/t.S/Ok" in info["methods"]
        assert chan["subchannels"] == 1 and chan["lb_policy"] == "pick_first"
        remote = json.loads(bytes(raw).decode())
        assert any("/t.S/Ok" in s["methods"] for s in remote["servers"])
    finally:
        srv.stop(grace=0)


def test_channelz_exposes_connection_management_state():
    """The new keepalive/max_age machinery is observable: draining counts
    and active stream totals appear in both server and channel views."""
    import threading
    import time as _time

    import tpurpc.rpc as rpc
    from tpurpc.rpc import channelz

    srv = rpc.Server(max_workers=2)
    release = threading.Event()

    def slow(req, ctx):
        release.wait(timeout=20)
        return b"ok"

    srv.add_method("/z.S/Slow", rpc.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            t = threading.Thread(
                target=lambda: ch.unary_unary("/z.S/Slow")(b"", timeout=30))
            t.start()
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                sinfo = channelz.server_info(srv)
                cinfo = channelz.channel_info(ch)
                if sinfo["active_streams"] >= 1 and cinfo["active_streams"] >= 1:
                    break
                _time.sleep(0.02)
            assert sinfo["active_streams"] >= 1
            assert cinfo["active_streams"] >= 1
            assert sinfo["draining_connections"] == 0
            release.set()
            t.join(timeout=10)
    finally:
        release.set()
        srv.stop(grace=0)
