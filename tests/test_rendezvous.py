"""tpurpc-express (ISSUE 9): one-sided rendezvous bulk-tensor plane.

Covers the landing pool's lifetime rules (weakref-finalize recycling, size
classes, budget refusal, death-path quarantine), the end-to-end transfer on
the native-framing plane (TCP and ring platforms) and the gRPC wire plane,
the copy-ledger zero-host-landing-copy proof, the framed fallback, the
flight/watchdog evidence, and the TPU-plane halves (HbmRing region leases,
SerializeFromDevice into a window, descriptor-only codec)."""

import gc
import threading
import time

import numpy as np
import pytest

import tpurpc.core.rendezvous as rdv
from tpurpc.tpu import ledger


@pytest.fixture
def fresh_config(monkeypatch):
    """Platform/env changes need a config rebuild; restore after."""
    from tpurpc.utils import config as config_mod

    yield monkeypatch
    config_mod.set_config(None)


def _reset_platform(monkeypatch, platform):
    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    config_mod.set_config(None)


# ---------------------------------------------------------------------------
# landing pool
# ---------------------------------------------------------------------------

def test_pool_size_classes_and_alignment():
    pool = rdv.LandingPool("local")
    lease = pool.lease(100_000, 1)
    assert lease.pr.capacity == 128 * 1024  # next pow2 ≥ 64 KiB floor
    wrapper = lease.deliver(100_000)
    flat = np.frombuffer(wrapper, np.uint8)
    assert flat.ctypes.data % 64 == 0  # dlpack-aliasable landing span


def test_pool_recycles_only_after_last_alias_dies():
    pool = rdv.LandingPool("local")
    lease = pool.lease(70_000, 1)
    body = lease.deliver(70_000)
    view = np.frombuffer(body, np.uint8)[10:20]  # consumer alias chain
    del body
    gc.collect()
    assert pool.stats()["free_regions"] == 0  # alias still pins the region
    del view
    gc.collect()
    assert pool.stats()["free_regions"] == 1
    # and the recycled region is reused, not re-allocated
    before = pool.stats()["allocated_bytes"]
    lease2 = pool.lease(70_000, 2)
    assert pool.stats()["allocated_bytes"] == before
    lease2.release()


def test_pool_budget_refuses_not_raises():
    pool = rdv.LandingPool("local", budget=256 * 1024)
    l1 = pool.lease(100_000, 1)
    assert l1 is not None
    assert pool.lease(100_000, 2) is None  # over budget: refusal
    l1.release()
    assert pool.lease(100_000, 3) is not None  # freed capacity reusable


def test_pool_discard_quarantines_instead_of_pooling():
    """The peer-death path must never re-lease a region a straggling
    window might still write (the Pair.init stale-write rule)."""
    pool = rdv.LandingPool("local")
    lease = pool.lease(65_536, 1)
    lease.release(discard=True)
    assert pool.stats()["free_regions"] == 0
    # a discarded-while-aliased region defers destruction to the alias GC
    lease2 = pool.lease(65_536, 2)
    body = lease2.deliver(65_536)
    lease2.release(discard=True)
    del body
    gc.collect()
    pool.lease(65_536, 3).release()  # sweeps zombies; no crash, no reuse


def test_standing_doorbell_rings_on_alias_death():
    pool = rdv.LandingPool("local")
    lease = pool.lease(65_536, 1)
    lease.standing = True
    db_off = lease.pr.offset + lease.pr.capacity + 16
    body = lease.deliver(1024)
    assert bytes(lease.pr.region.buf[db_off:db_off + 8]) == b"\x00" * 8
    del body
    gc.collect()
    assert lease.pr.region.buf[db_off] == 1  # consumer-freed count == 1
    # a second delivery is legal now (freed == delivered)
    body2 = lease.deliver(2048)
    # ... but a THIRD while body2 is aliased is the protocol violation
    with pytest.raises(RuntimeError):
        lease.deliver(512)
    del body2
    gc.collect()
    lease.release()


# ---------------------------------------------------------------------------
# end-to-end: native framing plane
# ---------------------------------------------------------------------------

def _echo_server(**kw):
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    # the Python data plane: ring-platform servers otherwise adopt
    # connections onto the native C loop, which does not speak the
    # rendezvous control frames (negotiation correctly leaves such
    # connections on the framed path)
    kw.setdefault("native_dataplane", False)
    srv = Server(max_workers=4, **kw)
    srv.add_method("/rdv.S/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_big_unary_roundtrip_both_directions(fresh_config, platform):
    _reset_platform(fresh_config, platform)
    from tpurpc.obs import metrics as _metrics
    from tpurpc.rpc.channel import Channel

    sent0 = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
    srv, port = _echo_server()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            # small calls keep the framed path untouched — and the first
            # one also settles the capability hello exchange (a big send
            # racing the hello simply frames; steady state never does)
            assert bytes(mc(b"tiny", timeout=10)) == b"tiny"
            big = bytes(range(256)) * (4096 + 13)  # ~1 MiB, patterned
            out = mc(big, timeout=30)
            assert bytes(out) == big
        sent = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
        assert sent >= sent0 + 2  # request AND response rode the bulk plane
    finally:
        srv.stop(grace=1)


def test_tensor_stream_zero_host_landing_copies(fresh_config):
    """The acceptance claim: on the rendezvous path the copy ledger shows
    the one-sided write (rdma_write) and the aliasing decode (zero_copy) —
    and ZERO host landing copies of the payload."""
    _reset_platform(fresh_config, "RDMA_BPEV")
    from tpurpc.jaxshim import TensorClient, add_tensor_method
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server

    srv = Server(max_workers=4, native_dataplane=False)

    def consume(req_iter):
        total = 0
        checks = 0.0
        for tree in req_iter:
            arr = tree["x"]          # zero-copy view over the landing region
            total += arr.nbytes
            checks += float(arr[0, 0]) + float(arr[-1, -1])
        yield {"bytes": np.int64(total), "check": np.float64(checks)}

    add_tensor_method(srv, "Sink", consume, kind="stream_stream")
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = np.random.default_rng(7).standard_normal(
        (512, 512)).astype(np.float32)  # 1 MiB
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            list(cli.duplex("Sink", gen(2), native=False, timeout=60))
            n = 8
            with ledger.track() as w:
                replies = list(cli.duplex("Sink", gen(n), native=False,
                                          timeout=60))
            total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
            assert total == n * payload.nbytes
            expect = n * (float(payload[0, 0]) + float(payload[-1, -1]))
            assert abs(float(np.asarray(
                replies[-1]["check"]).ravel()[0]) - expect) < 1e-3
            # every payload byte moved by exactly one one-sided write...
            assert w["rdma_write"] >= n * payload.nbytes
            # ...and landed ZERO host copies (the small control/reply
            # frames still ride the instrumented framed path)
            assert w["host_copy"] < 64 * 1024, w.delta
    finally:
        srv.stop(grace=1)


def test_disabled_rendezvous_keeps_framed_path(fresh_config):
    _reset_platform(fresh_config, "TCP")
    fresh_config.setenv("TPURPC_RENDEZVOUS", "0")
    from tpurpc.obs import metrics as _metrics
    from tpurpc.rpc.channel import Channel

    sent0 = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
    srv, port = _echo_server()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            big = b"q" * (1 << 20)
            assert bytes(mc(big, timeout=30)) == big
        assert _metrics.registry().metrics()[
            "rdv_transfers_sent"].snapshot() == sent0
    finally:
        srv.stop(grace=1)


def test_pool_exhaustion_falls_back_to_framed(fresh_config):
    """A refused claim degrades to the framed path — never an error,
    never a hang."""
    _reset_platform(fresh_config, "TCP")
    fresh_config.setenv("TPURPC_RENDEZVOUS_POOL_MB", "1")  # 1 MiB budget
    # fresh pools so the tiny budget binds (the process-global pool may
    # hold regions from earlier tests)
    old_pools = dict(rdv._pools)
    rdv._pools.clear()
    from tpurpc.obs import metrics as _metrics
    from tpurpc.rpc.channel import Channel

    srv, port = _echo_server()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            big = b"f" * (4 << 20)  # 4 MiB > the whole pool budget
            out = mc(big, timeout=60)
            assert bytes(out) == big
        assert _metrics.registry().metrics()[
            "rdv_fallbacks"].snapshot() >= 1
    finally:
        srv.stop(grace=1)
        rdv._pools.clear()
        rdv._pools.update(old_pools)


def test_flight_sequence_offer_claim_write_complete(fresh_config):
    _reset_platform(fresh_config, "TCP")
    from tpurpc.obs import flight
    from tpurpc.rpc.channel import Channel

    flight.RECORDER.reset()
    srv, port = _echo_server()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            assert bytes(mc(b"warm", timeout=10)) == b"warm"  # hello settles
            big = b"e" * (1 << 20)
            assert bytes(mc(big, timeout=30)) == big
        events = [e for e in flight.snapshot()
                  if e["event"].startswith("rdv-")]
        order = [e["event"] for e in events]
        for name in ("rdv-offer", "rdv-claim", "rdv-write", "rdv-complete"):
            assert name in order, order
        # per-transfer ordering: for every sender-side write, the SAME
        # link's claim of the SAME lease precedes it and its complete
        # follows (one link is sender for requests AND receiver for
        # responses, so ordering is per (tag, lease), not per tag)
        for w in [e for e in events if e["event"] == "rdv-write"]:
            tag, lease = w["tag"], w["a1"]
            t_claim = [e["t_ns"] for e in events
                       if e["event"] == "rdv-claim" and e["tag"] == tag
                       and e["a2"] == lease]
            t_done = [e["t_ns"] for e in events
                      if e["event"] == "rdv-complete" and e["tag"] == tag
                      and e["a1"] == lease]
            assert t_claim and min(t_claim) <= w["t_ns"], events
            assert t_done and w["t_ns"] <= max(t_done), events
    finally:
        srv.stop(grace=1)


def test_watchdog_names_rendezvous_stage(fresh_config):
    """A claim-starved sender (drop_offers chaos seam) must be diagnosed
    by the watchdog as stuck in the `rendezvous` stage."""
    _reset_platform(fresh_config, "TCP")
    fresh_config.setenv("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", "3")
    from tpurpc.obs import flight, watchdog
    from tpurpc.rpc.channel import Channel

    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s)
    wd.min_stall_s, wd.sweep_s = 0.3, 0.1
    srv, port = _echo_server()
    rdv.TEST_HOOKS["drop_offers"] = True
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            big = b"w" * (1 << 20)
            result = {}

            def call():
                result["out"] = bytes(mc(big, timeout=30))

            t = threading.Thread(target=call)
            t.start()
            diag = None
            deadline = time.monotonic() + 10
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.15)
                for d in wd.sweep_once():
                    if d["stage"] == "rendezvous":
                        diag = d
                        break
            assert diag is not None, wd.active()
            assert "offer" in diag["detail"]
            # after the claim timeout the sender falls back to the framed
            # path — the call COMPLETES despite the starved bulk plane
            t.join(timeout=30)
            assert result.get("out") == big
    finally:
        rdv.TEST_HOOKS.pop("drop_offers", None)
        wd.min_stall_s, wd.sweep_s = prev
        wd.reset()
        srv.stop(grace=1)


# ---------------------------------------------------------------------------
# end-to-end: gRPC wire plane
# ---------------------------------------------------------------------------

def test_h2_plane_big_payloads_bypass_data_frames(fresh_config):
    _reset_platform(fresh_config, "TCP")
    from tpurpc.obs import metrics as _metrics
    from tpurpc.wire.h2_client import H2Channel

    sent0 = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
    srv, port = _echo_server()
    try:
        with H2Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo")
            assert bytes(mc(b"small", timeout=10)) == b"small"  # settles
            big = bytes(range(251)) * 8192  # ~2 MiB patterned
            out = mc(big, timeout=30)
            assert bytes(out) == big
        assert _metrics.registry().metrics()[
            "rdv_transfers_sent"].snapshot() >= sent0 + 2
    finally:
        srv.stop(grace=1)


# ---------------------------------------------------------------------------
# TPU plane: region leases, SerializeFromDevice, descriptor codec
# ---------------------------------------------------------------------------

def test_hbm_lease_region_single_movement_ledger():
    from tpurpc.tpu.hbm_ring import HbmRing

    ring = HbmRing(1 << 20)
    x = np.arange(65536, dtype=np.float32)
    with ledger.track() as w:
        lease = ring.lease_region(x.nbytes)
        lease.fill(x)
    # the single-movement claim, assertable via op counts: ONE h2d DMA +
    # ONE in-ring landing write, zero host copies
    assert w["dma_h2d_ops"] == 1 and w["dma_d2d_ops"] == 1, w.delta
    assert w["host_copy"] == 0
    hl = lease.view(dtype=np.float32, shape=(65536,))
    assert np.allclose(np.asarray(hl.array), x)
    hl.release()
    lease.release()


def test_hbm_lease_region_death_release_frees_credit():
    from tpurpc.tpu.hbm_ring import HbmRing

    ring = HbmRing(1 << 18)
    writable0 = ring.writable()
    lease = ring.lease_region(1 << 17)
    assert ring.writable() == writable0 - (1 << 17)
    lease.release()  # peer died before any fill
    assert ring.writable() == writable0
    with pytest.raises(RuntimeError):
        lease.fill(np.zeros(1 << 17, np.uint8))  # released: no late landing


def test_serialize_into_zero_host_staging():
    import jax

    from tpurpc.tpu import serialize

    dst = bytearray(1 << 20)
    view = memoryview(dst)

    def write(off, seg):
        view[off:off + len(seg)] = seg

    tree = {"a": jax.device_put(np.ones((128, 128), np.float32)),
            "b": np.arange(64, dtype=np.int64)}
    with ledger.track() as w:
        n = serialize.serialize_tree_into(tree, write)
    assert n > 0
    assert w["host_copy"] == 0, w.delta       # no staging buffer, ever
    assert w["rdma_write"] == n               # the placement IS the move
    from tpurpc.jaxshim import codec

    back = codec.decode_tree(view)
    assert np.allclose(back["a"], 1.0) and back["b"][63] == 63


def test_codec_descriptor_only_encode_roundtrip():
    from tpurpc.jaxshim import codec

    x = np.random.default_rng(3).standard_normal((65, 3)).astype(np.float32)
    desc, payload = codec.encode_tensor_descriptor(x)
    assert len(desc) % 64 == 0          # descriptor pads to the alignment
    assert payload.nbytes == x.nbytes   # payload view aliases the array
    back = codec.decode_tensor_external(desc, payload)
    assert np.allclose(back, x)
    with pytest.raises(codec.CodecError):
        codec.decode_tensor_external(desc, payload[:100])  # short payload


def test_recv_limit_not_bypassed(fresh_config):
    """The bulk plane must not become a max_receive_message_length bypass:
    an over-limit OFFER is refused, the framed fallback carries the
    payload, and the framed oversize machinery rejects it properly."""
    _reset_platform(fresh_config, "TCP")
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
    from tpurpc.rpc.status import RpcError, StatusCode

    srv = Server(max_workers=4, native_dataplane=False,
                 max_receive_message_length=512 * 1024)
    srv.add_method("/rdv.S/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdv.S/Echo", tpurpc_native=False)
            assert bytes(mc(b"ok", timeout=10)) == b"ok"
            with pytest.raises(RpcError) as exc:
                mc(b"z" * (1 << 20), timeout=30)
            assert exc.value.code() == StatusCode.RESOURCE_EXHAUSTED
    finally:
        srv.stop(grace=1)


# ---------------------------------------------------------------------------
# cross-plane interop: the native (C) planes speak the same ladder
# ---------------------------------------------------------------------------
# tpurpc-ironclad: tpr_rdv.cc mirrors rendezvous.py byte for byte, so every
# pairing of {python, native} x {client, server} must move bulk payloads
# over the same OFFER/CLAIM/COMPLETE wire and the same ctrl-ring slots. The
# native ledger (tpr_rdv_counters) is process-global — both in-process C
# planes report into it.

def _native_counters():
    from tpurpc.rpc import native_client

    return native_client.rdv_counters()


def _stream_total_server(**kw):
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    srv = Server(max_workers=4, **kw)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/rdvnat.S/Total",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _require_native():
    if _native_counters() is None:
        pytest.skip("native data plane unavailable")


@pytest.mark.parametrize("platform", ["RDMA_BP", "RDMA_BPEV"])
def test_native_both_planes_stream_rendezvous(fresh_config, platform):
    """native client <-> native server: the stream's bulk payloads ride
    the C ladder — the native ledger proves zero fallbacks and (near-)zero
    host landing copies."""
    _reset_platform(fresh_config, platform)
    _require_native()
    from tpurpc.rpc.channel import Channel

    srv, port = _stream_total_server()
    payload = bytes(range(256)) * 4096  # 1 MiB, patterned
    n = 4
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/rdvnat.S/Total")
            # a tiny warmup stream settles the capability hello (a big
            # send racing the hello frames, correctly); snapshot after
            list(mc(iter([b"warm"]), timeout=30))
            c0 = _native_counters()
            out = list(mc(iter([payload] * n), timeout=60))
        assert out[-1] == str(n * len(payload)).encode()
        c1 = _native_counters()
        assert c1["rdv_sent"] - c0["rdv_sent"] >= n
        assert c1["rdv_recv"] - c0["rdv_recv"] >= n
        assert c1["rdv_fallback"] == c0["rdv_fallback"]
        assert (c1["rdv_bytes_sent"] - c0["rdv_bytes_sent"]
                >= n * len(payload))
        # the tiny reply is the only framed payload on the negotiated link
        assert c1["host_copy_bytes"] - c0["host_copy_bytes"] < 64 * 1024
    finally:
        srv.stop(grace=1)


def test_python_client_native_server_rendezvous(fresh_config):
    """python client plane -> native server plane: the Python CtrlPeer's
    offers land in the C Link, and the C server's bulk echo comes back
    through the Python receiver — both ledgers move."""
    _reset_platform(fresh_config, "RDMA_BPEV")
    _require_native()
    from tpurpc.obs import metrics as _metrics
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    srv = Server(max_workers=4)  # ring platform: adopts onto the C loop
    srv.add_method("/rdvnat.S/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    py_sent0 = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
    try:
        c0 = _native_counters()
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdvnat.S/Echo", tpurpc_native=False)
            assert bytes(mc(b"tiny", timeout=10)) == b"tiny"  # settle hello
            big = bytes(range(256)) * (4096 + 3)
            assert bytes(mc(big, timeout=60)) == big
        c1 = _native_counters()
        # the request landed in the C server's pool...
        assert c1["rdv_recv"] - c0["rdv_recv"] >= 1
        # ...and the response left through the C sender role
        assert c1["rdv_sent"] - c0["rdv_sent"] >= 1
        # the python client's own ledger saw its send
        assert _metrics.registry().metrics()[
            "rdv_transfers_sent"].snapshot() >= py_sent0 + 1
    finally:
        srv.stop(grace=1)


def test_native_client_python_server_rendezvous(fresh_config):
    """native client plane -> python server plane: the C Link's offers are
    claimed by rendezvous.py, and the bulk echo comes back the other way."""
    _reset_platform(fresh_config, "RDMA_BPEV")
    _require_native()
    from tpurpc.obs import metrics as _metrics
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    srv = Server(max_workers=4, native_dataplane=False)  # python loop
    srv.add_method("/rdvnat.S/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    py_sent0 = _metrics.registry().metrics()["rdv_transfers_sent"].snapshot()
    try:
        c0 = _native_counters()
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdvnat.S/Echo")  # native C client plane
            assert bytes(mc(b"tiny", timeout=10)) == b"tiny"
            big = bytes(range(256)) * (4096 + 7)
            assert bytes(mc(big, timeout=60)) == big
        c1 = _native_counters()
        assert c1["rdv_sent"] - c0["rdv_sent"] >= 1   # C sender role
        assert c1["rdv_recv"] - c0["rdv_recv"] >= 1   # C receiver role
        # the python server's ledger saw its (response) send
        assert _metrics.registry().metrics()[
            "rdv_transfers_sent"].snapshot() >= py_sent0 + 1
    finally:
        srv.stop(grace=1)


def test_native_disabled_rendezvous_stays_framed(fresh_config):
    """TPURPC_RENDEZVOUS=0: no hello, no Link — un-negotiated native peers
    move every byte framed, correctly."""
    _reset_platform(fresh_config, "RDMA_BP")
    _require_native()
    fresh_config.setenv("TPURPC_RENDEZVOUS", "0")
    from tpurpc.rpc.channel import Channel

    srv, port = _stream_total_server()
    payload = b"q" * (1 << 20)
    try:
        c0 = _native_counters()
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/rdvnat.S/Total")
            out = list(mc(iter([payload] * 3), timeout=60))
        assert out[-1] == str(3 * len(payload)).encode()
        c1 = _native_counters()
        assert c1["rdv_sent"] == c0["rdv_sent"]
        assert c1["ctrl_posts"] == c0["ctrl_posts"]
    finally:
        srv.stop(grace=1)


def test_native_pool_exhaustion_falls_back_framed(fresh_config):
    """A C-side refused claim (budget) degrades the transfer to framed —
    byte-exact, never an error, never a hang."""
    _reset_platform(fresh_config, "RDMA_BP")
    _require_native()
    # 11 MiB rounds to a 16 MiB landing class: over this 1 MiB budget, and
    # a class no earlier test leaves in the process-global recycle cache
    fresh_config.setenv("TPURPC_RENDEZVOUS_POOL_MB", "1")
    from tpurpc.rpc.channel import Channel

    srv, port = _stream_total_server()
    payload = b"x" * (11 << 20)
    try:
        c0 = _native_counters()
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/rdvnat.S/Total")
            # warmup settles the capability hello: an un-negotiated first
            # send frames WITHOUT offering, which is not this test's path
            list(mc(iter([b"warm"]), timeout=30))
            out = list(mc(iter([payload]), timeout=120))
        assert out[-1] == str(len(payload)).encode()
        c1 = _native_counters()
        assert (c1["rdv_refused"] > c0["rdv_refused"]
                or c1["rdv_fallback"] > c0["rdv_fallback"])
        assert c1["rdv_bytes_sent"] == c0["rdv_bytes_sent"]
    finally:
        srv.stop(grace=1)
