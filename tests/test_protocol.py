"""tpurpc-proof (ISSUE 12): protocol-machine conformance over flight events.

Contracts: the declared machines accept the protocols the tree actually
emits (synthesized good trace + real recorder output), every seeded
event-order mutant is flagged, tolerant mode absorbs mid-history streams
(wrapped rings), `assert_ordered` expresses the chaos suites' cross-
entity orderings, and the live verifier (TPURPC_VERIFY_PROTOCOL=1 path)
records a breadcrumb + trips the watchdog on a violated machine without
disturbing a clean workload.
"""

from __future__ import annotations

import json

import pytest

from tpurpc.analysis import protocol
from tpurpc.obs import flight


@pytest.fixture(autouse=True)
def _clean_hook():
    yield
    flight.set_verify_hook(None)


# -- machines vs. the declared protocols --------------------------------------

def test_good_trace_is_accepted_strict():
    assert protocol.check_events(protocol._good_trace(), strict=True) == []


@pytest.mark.parametrize("mutant", sorted(protocol.machine_mutants()))
def test_event_order_mutant_is_killed(mutant):
    trace = protocol.machine_mutants()[mutant]
    violations = protocol.check_events(trace, strict=True)
    assert violations, f"event-order mutant {mutant} SURVIVED"


def test_self_test_passes():
    assert protocol.self_test() == []


def test_tolerant_mode_absorbs_mid_history():
    """A dump starting mid-protocol (wrapped ring) must not flag — but an
    in-dump violation STILL must."""
    F = flight
    mid = [protocol._ev(F.MIG_END, tag=4, a1=9, a2=1, t_ns=1)]
    assert protocol.check_events(mid, strict=False) == []
    assert protocol.check_events(mid, strict=True)
    # in-dump violation survives tolerance: claim then an illegal second
    # write after the lease settled
    bad = [protocol._ev(F.RDV_CLAIM, tag=2, a1=5, a2=9, t_ns=1),
           protocol._ev(F.RDV_COMPLETE, tag=2, a1=9, t_ns=2),
           protocol._ev(F.RDV_WRITE, tag=2, a1=9, t_ns=3)]
    v = protocol.check_events(bad, strict=False)
    assert v and v[0].machine == "rdv-lease"


def test_real_recorder_roundtrip_conforms():
    """Events emitted through the real recorder (binary ring, snapshot
    decode) feed the checker without translation."""
    rec = flight.FlightRecorder(capacity=64)
    tag = flight.tag_for("proto-test-entity")
    rec.emit(flight.GEN_STEP_BEGIN, tag, 2, 0)
    rec.emit(flight.GEN_STEP_END, tag, 2, 2)
    rec.emit(flight.MIG_BEGIN, tag, 7, 12)
    rec.emit(flight.MIG_END, tag, 7, 1)
    assert protocol.check_events(rec.snapshot(), strict=False) == []


# -- dumps --------------------------------------------------------------------

def test_check_dump_file_and_directory(tmp_path):
    good = protocol._good_trace()
    f1 = tmp_path / "flight-1.json"
    f1.write_text(json.dumps(good))
    n, v = protocol.check_dump(str(f1))
    assert (n, v) == (len(good), [])
    # the /debug/flight body shape ({"events": [...]}) and a directory
    bad = protocol.machine_mutants()["mig_end_without_begin"]
    (tmp_path / "d").mkdir()
    (tmp_path / "d" / "flight-2.json").write_text(
        json.dumps({"events": good}))
    (tmp_path / "d" / "flight-3.json").write_text(json.dumps(bad))
    n, v = protocol.check_dump(str(tmp_path / "d"), strict=True)
    assert n == len(good) + len(bad)
    assert v, "strict dir check missed the seeded violation"
    # tolerant (the offline default) skips the mid-history MIG_END
    n, v = protocol.check_dump(str(tmp_path / "d"))
    assert v == []


# -- the chaos suites' ordering helper ----------------------------------------

def test_assert_ordered_matches_and_returns_events():
    evs = protocol._good_trace()
    hits = protocol.assert_ordered(
        evs, ["conn-connect", "call-first-ok",
              ("rdv-claim", {"tag": 2, "a2": 501}),
              ("rdv-complete", {"a1": 501}),
              "conn-dead"])
    assert [h["event"] for h in hits] == [
        "conn-connect", "call-first-ok", "rdv-claim", "rdv-complete",
        "conn-dead"]
    assert hits[0]["t_ns"] <= hits[-1]["t_ns"]


def test_assert_ordered_rejects_wrong_order_and_since():
    evs = protocol._good_trace()
    with pytest.raises(AssertionError):
        protocol.assert_ordered(evs, ["conn-dead", "conn-connect"])
    t_dead = next(e["t_ns"] for e in evs if e["event"] == "conn-dead")
    with pytest.raises(AssertionError):
        protocol.assert_ordered(evs, ["conn-connect"], since_ns=t_dead)


# -- the live verifier --------------------------------------------------------

def test_live_verifier_clean_stream_stays_silent():
    v = protocol.install_live()
    tag = flight.tag_for("live-clean-entity")
    flight.emit(flight.GEN_STEP_BEGIN, tag, 1, 0)
    flight.emit(flight.GEN_STEP_END, tag, 1, 1)
    assert v.checked >= 2
    assert v.violations == []


def test_live_verifier_trips_on_violation():
    from tpurpc.obs import watchdog

    wd = watchdog.get()
    wd.reset()
    before = len(wd._history)
    v = protocol.install_live()
    tag = flight.tag_for("live-bad-entity")
    flight.emit(flight.GEN_STEP_BEGIN, tag, 1, 0)
    flight.emit(flight.GEN_STEP_BEGIN, tag, 2, 0)  # nested begin: illegal
    assert len(v.violations) == 1
    assert v.violations[0].machine == "gen-step"
    # breadcrumb in the ring, watchdog history entry with the stage
    crumbs = [e for e in flight.snapshot()
              if e["event"] == "proto-violation" and e["tag"] == tag]
    assert crumbs and crumbs[-1]["a2"] == flight.GEN_STEP_BEGIN
    hist = list(wd._history)[before:]
    assert any(h.get("stage") == "protocol" for h in hist)


def test_live_verifier_is_tolerant_of_process_history():
    """The verifier installs mid-life: events whose openers predate it
    must not trip (the mid-history contract, live edition)."""
    v = protocol.install_live()
    tag = flight.tag_for("live-midlife-entity")
    flight.emit(flight.MIG_END, tag, 3, 1)  # its BEGIN predates us
    assert v.violations == []


def test_uninstall_live_detaches():
    protocol.install_live()
    protocol.uninstall_live()
    assert protocol.live_verifier() is None
    tag = flight.tag_for("live-detached-entity")
    flight.emit(flight.GEN_STEP_BEGIN, tag, 1, 0)  # no verifier: no-op


# -- merged per-process dumps (ISSUE 17) --------------------------------------

def _anchored_dump(path, events, pid, mono_ns, wall_ns, unc=1000):
    path.write_text(json.dumps({
        "events": events,
        "clock_anchor": {"pid": pid, "mono_ns": mono_ns,
                         "wall_ns": wall_ns, "uncertainty_ns": unc}}))


def test_real_disagg_ship_split_into_two_anchored_dumps(tmp_path):
    """The regression the merged checker exists for: a REAL in-process
    handoff + migration recorded through the real recorder, split into a
    source dump (migration bracket) and a destination dump (ship
    offer/complete) with DIFFERENT monotonic clocks anchored to one wall
    clock — the merged stream must conform, including the cross-process
    rule that the successful MIG_END covers the destination's
    KV_SHIP_COMPLETE."""
    F = flight
    src_rec = F.FlightRecorder(capacity=64)
    dst_rec = F.FlightRecorder(capacity=64)
    tag_s, tag_d = F.tag_for("mig-src"), F.tag_for("mig-dst")
    # two processes, two monotonic clocks: src t=100.., dst t=9000..,
    # anchored so wall(src 100) == wall(dst 9000)
    src_rec.emit(F.MIG_BEGIN, tag_s, 42, 4)          # src mono ~now
    dst_rec.emit(F.KV_SHIP_OFFER, tag_d, 11, 1 << 20)
    dst_rec.emit(F.KV_SHIP_COMPLETE, tag_d, 11, 1 << 20)
    src_rec.emit(F.MIG_END, tag_s, 42, 1)
    src_ev, dst_ev = src_rec.snapshot(), dst_rec.snapshot()
    # rebase both rings onto synthetic per-process clocks sharing a wall
    # anchor: src events at mono 100/400, dst at mono 9200/9300 — the
    # raw t_ns values would interleave WRONG without the anchors
    for ev, t in zip(src_ev, (100, 400)):
        ev["t_ns"] = t
    for ev, t in zip(dst_ev, (9150, 9250)):
        ev["t_ns"] = t
    a, b = tmp_path / "src.json", tmp_path / "dst.json"
    _anchored_dump(a, src_ev, pid=100, mono_ns=0, wall_ns=5_000_000)
    _anchored_dump(b, dst_ev, pid=200, mono_ns=9_000, wall_ns=5_000_000)
    total, v = protocol.check_dumps([str(a), str(b)])
    assert (total, v) == (4, []), list(map(str, v))


def test_merged_dumps_catch_missing_cross_process_landing(tmp_path):
    """Tampered pair: the destination's COMPLETE falls OUTSIDE the
    migration bracket on the shared wall clock — each per-process dump
    still conforms on its own, only the merged stream can see the
    successful migration whose bytes never landed."""
    F = flight
    src = [protocol._ev(F.MIG_BEGIN, tag=7, a1=42, a2=4, t_ns=100),
           protocol._ev(F.MIG_END, tag=7, a1=42, a2=1, t_ns=400)]
    dst = [protocol._ev(F.KV_SHIP_OFFER, tag=9, a1=11, a2=1, t_ns=50_000),
           protocol._ev(F.KV_SHIP_COMPLETE, tag=9, a1=11, a2=1,
                        t_ns=50_100)]
    a, b = tmp_path / "src.json", tmp_path / "dst.json"
    _anchored_dump(a, src, pid=100, mono_ns=0, wall_ns=5_000_000)
    _anchored_dump(b, dst, pid=200, mono_ns=0, wall_ns=5_000_000)
    total, v = protocol.check_dumps([str(a), str(b)])
    assert total == 4
    assert [x.machine for x in v] == ["xproc-mig-ship"], list(map(str, v))
    # each dump alone is blind to the defect
    assert protocol.check_dumps([str(a)])[1] == []
    assert protocol.check_dumps([str(b)])[1] == []


def test_explicit_multi_dump_without_anchors_is_loud(tmp_path):
    good = protocol._good_trace()
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(good))
    b.write_text(json.dumps({"events": []}))
    _, v = protocol.check_dumps([str(a), str(b)])
    assert [x.machine for x in v] == ["xproc-merge"]
    # ...but a DIRECTORY of historical anchorless dumps stays tolerant
    _, v = protocol.check_dumps([str(tmp_path)])
    assert v == []


def test_merged_tags_do_not_collide_across_processes(tmp_path):
    """Two processes both use tag 7 for UNRELATED machine instances; the
    per-process namespacing must keep them apart in the merged stream
    (without it, dst's open migration would collide with src's)."""
    F = flight
    src = [protocol._ev(F.MIG_BEGIN, tag=7, a1=42, a2=4, t_ns=100),
           protocol._ev(F.MIG_END, tag=7, a1=42, a2=0, t_ns=400)]
    dst = [protocol._ev(F.MIG_BEGIN, tag=7, a1=42, a2=4, t_ns=200)]
    merged = protocol.merge_anchored([
        (src, {"mono_ns": 0, "wall_ns": 0}),
        (dst, {"mono_ns": 0, "wall_ns": 0})])
    assert len({e["tag"] for e in merged}) == 2
    assert protocol.check_events(merged, strict=True) == []
