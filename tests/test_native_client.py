"""Python-over-native-C-API channel (tpurpc.rpc.native_client) — the
SURVEY §7 stage-7 ctypes binding: blocking calls run inside libtpurpc.so.
Served by the ordinary Python Server; also exercised over the ring
platform (the native loop bootstraps the shm ring under an unchanged
Python caller)."""

import os
import subprocess
import sys
import time

import pytest

import tpurpc.rpc as rpc

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native", "build", "libtpurpc.so")),
    reason="native lib not built")

from tpurpc.rpc.native_client import NativeChannel  # noqa: E402
from tpurpc.rpc.status import RpcError, StatusCode  # noqa: E402


@pytest.fixture()
def py_server():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/n.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))

    def double_each(req_iter, ctx):
        for m in req_iter:
            yield bytes(m) * 2

    srv.add_method("/n.S/Dbl", rpc.stream_stream_rpc_method_handler(double_each))

    def fail(r, c):
        c.abort(StatusCode.FAILED_PRECONDITION, "nope")

    srv.add_method("/n.S/Fail", rpc.unary_unary_rpc_method_handler(fail))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(grace=0)


def test_native_unary_and_ping(py_server):
    with NativeChannel("127.0.0.1", py_server) as ch:
        assert ch.ping(5) < 5
        echo = ch.unary_unary("/n.S/Echo")
        assert echo(b"hi", timeout=10) == b"hi"
        big = bytes(range(256)) * 8192  # 2MB: frame fragmentation
        assert echo(big, timeout=30) == big


def test_native_serializers(py_server):
    with NativeChannel("127.0.0.1", py_server) as ch:
        echo = ch.unary_unary("/n.S/Echo",
                              request_serializer=lambda s: s.encode(),
                              response_deserializer=lambda b: b.decode())
        assert echo("text", timeout=10) == "text"


def test_native_status_mapping(py_server):
    with NativeChannel("127.0.0.1", py_server) as ch:
        with pytest.raises(RpcError) as ei:
            ch.unary_unary("/n.S/Fail")(b"", timeout=10)
        assert ei.value.code() is StatusCode.FAILED_PRECONDITION
        assert "nope" in ei.value.details()
        with pytest.raises(RpcError) as ei:
            ch.unary_unary("/n.S/Missing")(b"", timeout=10)
        assert ei.value.code() is StatusCode.UNIMPLEMENTED


def test_native_streaming(py_server):
    with NativeChannel("127.0.0.1", py_server) as ch:
        dbl = ch.stream_stream("/n.S/Dbl")
        out = list(dbl(iter([b"a", b"bb", b"ccc"]), timeout=10))
        assert out == [b"aa", b"bbbb", b"cccccc"]


def test_native_futures_pipelined(py_server):
    """grpcio's .future() shape over the CQ async path: many unary calls
    in flight on one connection, resolved by the channel's puller."""
    with NativeChannel("127.0.0.1", py_server) as ch:
        echo = ch.unary_unary("/n.S/Echo")
        futs = [echo.future(b"m%d" % i, timeout=30) for i in range(64)]
        for i, f in enumerate(futs):
            assert f.result(timeout=30) == b"m%d" % i


def test_native_future_error_and_deserializer(py_server):
    with NativeChannel("127.0.0.1", py_server) as ch:
        fail = ch.unary_unary("/n.S/Fail")
        with pytest.raises(RpcError) as ei:
            fail.future(b"x", timeout=10).result(timeout=30)
        assert ei.value.code() is StatusCode.FAILED_PRECONDITION
        echo = ch.unary_unary("/n.S/Echo",
                              request_serializer=lambda s: s.encode(),
                              response_deserializer=lambda b: b.decode())
        assert echo.future("hi", timeout=10).result(timeout=30) == "hi"


def test_native_future_deadline():
    """A future to a stalled handler resolves with DEADLINE_EXCEEDED via
    the CQ puller's lazy deadline enforcement, and channel close with the
    dust settled is clean."""
    srv = rpc.Server(max_workers=2)
    import threading as _t
    release = _t.Event()
    srv.add_method("/n.S/Hang", rpc.unary_unary_rpc_method_handler(
        lambda r, c: release.wait(30) or b"late"))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with NativeChannel("127.0.0.1", port) as ch:
            hang = ch.unary_unary("/n.S/Hang")
            with pytest.raises(RpcError) as ei:
                hang.future(b"x", timeout=0.3).result(timeout=30)
            assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
    finally:
        release.set()
        srv.stop(grace=0)


def test_native_future_user_cancel_keeps_puller_alive(py_server):
    """Cancelling a pending Future must not kill the puller thread when
    its completion lands (set_running_or_notify_cancel guard) — later
    futures on the same channel still resolve."""
    with NativeChannel("127.0.0.1", py_server) as ch:
        echo = ch.unary_unary("/n.S/Echo")
        f1 = echo.future(b"one", timeout=10)
        f1.cancel()  # may or may not win vs the in-flight completion
        for i in range(8):  # puller must still be resolving
            assert echo.future(b"n%d" % i, timeout=10).result(30) == b"n%d" % i


def test_native_futures_closed_while_inflight():
    """Channel close with futures still in flight cancels them (the
    driver's teardown) instead of hanging or crashing."""
    srv = rpc.Server(max_workers=2)
    import threading as _t
    release = _t.Event()
    srv.add_method("/n.S/Hang", rpc.unary_unary_rpc_method_handler(
        lambda r, c: release.wait(30) or b"late"))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        ch = NativeChannel("127.0.0.1", port)
        hang = ch.unary_unary("/n.S/Hang")
        futs = [hang.future(b"x", timeout=20) for _ in range(4)]
        time.sleep(0.2)  # let the calls reach the server
        ch.close()
        for f in futs:
            with pytest.raises(RpcError):
                f.result(timeout=10)
    finally:
        release.set()
        srv.stop(grace=0)


def test_native_futures_survive_server_death():
    """Chaos: the server dies with a batch of futures in flight — every
    future must resolve (UNAVAILABLE or a late success), none may hang,
    and a fresh channel to a new server works."""
    srv = rpc.Server(max_workers=4)
    srv.add_method("/n.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    ch = NativeChannel("127.0.0.1", port)
    try:
        echo = ch.unary_unary("/n.S/Echo")
        futs = [echo.future(b"x" * 512, timeout=20) for _ in range(32)]
        srv.stop(grace=0)  # yank the server mid-batch
        import concurrent.futures as cf
        done, not_done = cf.wait(futs, timeout=45)
        assert not not_done, f"{len(not_done)} futures hung"
        for f in done:
            try:
                f.result()  # ok or RpcError both fine; anything else raises
            except RpcError:
                pass
    finally:
        ch.close()
        srv.stop(grace=0)
    # the world keeps turning: a new server + channel round-trips
    srv2 = rpc.Server(max_workers=2)
    srv2.add_method("/n.S/Echo",
                    rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port2 = srv2.add_insecure_port("127.0.0.1:0")
    srv2.start()
    try:
        with NativeChannel("127.0.0.1", port2) as ch2:
            assert ch2.unary_unary("/n.S/Echo")(b"hi", timeout=10) == b"hi"
    finally:
        srv2.stop(grace=0)


def test_native_channel_over_ring_platform():
    """The whole point: a PYTHON process on the native loop gets the ring
    data plane by env alone (GRPC_PLATFORM_TYPE honored inside the .so)."""
    env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="1024")
    code = (
        "import tpurpc.rpc as rpc\n"
        "from tpurpc.rpc.native_client import NativeChannel\n"
        "srv = rpc.Server(max_workers=4)\n"
        "srv.add_method('/n.S/Echo',"
        " rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))\n"
        "port = srv.add_insecure_port('127.0.0.1:0')\n"
        "srv.start()\n"
        "with NativeChannel('127.0.0.1', port) as ch:\n"
        "    echo = ch.unary_unary('/n.S/Echo')\n"
        "    assert echo(b'ring', timeout=20) == b'ring'\n"
        "    big = bytes(range(256)) * 4096\n"
        "    assert echo(big, timeout=60) == big\n"
        "print('RING_OK')\n"
        "srv.stop(grace=0)\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env=env, timeout=180)
    assert out.returncode == 0, out.stderr
    assert "RING_OK" in out.stdout


def test_native_stream_lease_gather_multifragment():
    """ISSUE 1 regression: a gather-list stream write larger than one frame
    rides the zero-copy send lease (tpr_call_send_reserve2) as MORE-flagged
    fragments and must arrive as ONE intact message, byte-identical —
    across the wrap (ring smaller than the stream total), mixed with
    sub-threshold writes that take the classic path."""
    env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BPEV",
               GRPC_RDMA_RING_BUFFER_SIZE_KB="8192")
    code = (
        "import hashlib\n"
        "import tpurpc.rpc as rpc\n"
        "from tpurpc.rpc.native_client import NativeChannel\n"
        "srv = rpc.Server(max_workers=4)\n"
        "def digest_each(req_iter, ctx):\n"
        "    for m in req_iter:\n"
        "        b = bytes(m)\n"
        "        yield ('%d:%s' % (len(b),"
        " hashlib.sha256(b).hexdigest())).encode()\n"
        "srv.add_method('/n.S/Digest',"
        " rpc.stream_stream_rpc_method_handler(digest_each))\n"
        "port = srv.add_insecure_port('127.0.0.1:0')\n"
        "srv.start()\n"
        "import hashlib as h\n"
        "msgs = [\n"
        "    [bytes(range(256)) * 8192, b'tail' * 7],      # 2MiB+: 3 frags\n"
        "    [b'x' * 100],                                 # classic path\n"
        "    [b'y' * (1 << 20), b'z' * 513],               # exactly 1 frame+\n"
        "]\n"
        "with NativeChannel('127.0.0.1', port) as ch:\n"
        "    call = ch.stream_stream('/n.S/Digest')\n"
        "    for got, m in zip(call(iter(msgs), timeout=60), msgs):\n"
        "        joined = b''.join(m)\n"
        "        want = ('%d:%s' % (len(joined),"
        " h.sha256(joined).hexdigest())).encode()\n"
        "        assert got == want, (got, want[:24])\n"
        "print('LEASE_STREAM_OK')\n"
        "srv.stop(grace=0)\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env=env, timeout=180)
    assert out.returncode == 0, out.stderr
    assert "LEASE_STREAM_OK" in out.stdout


def test_native_vs_python_latency(tmp_path):
    """The fast path must actually be faster. Measured against a C++
    callback-API echo server so the SERVER cost is constant and small —
    against the (slower) Python server both clients are server-bound and
    the comparison measures nothing (observed: 33us vs 95us/call here)."""
    import shutil
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = tmp_path / "echo_srv.cc"
    src.write_text(
        '#include <cstdio>\n#include "tpurpc/server.h"\n'
        'static int cb(tpr_server_call *c, const uint8_t *d, size_t n,'
        ' void *) { tpr_srv_send(c, d, n); return 0; }\n'
        'int main() { tpr_server *s = tpr_server_create(0);\n'
        '  tpr_server_register_callback(s, "/n.S/Echo", cb, nullptr);\n'
        '  tpr_server_start(s); printf("PORT %d\\n", tpr_server_port(s));\n'
        '  fflush(stdout); getchar(); tpr_server_destroy(s); }\n')
    binp = tmp_path / "echo_srv"
    subprocess.run(
        [gxx, "-std=c++17", "-O2", str(src),
         os.path.join(root, "native", "src", "tpurpc_server.cc"),
         os.path.join(root, "native", "src", "tpr_rdv.cc"),
         os.path.join(root, "native", "src", "tpr_obs.cc"),
         os.path.join(root, "native", "src", "ring.cc"),
         "-I", os.path.join(root, "native", "include"),
         "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=180, capture_output=True)
    proc = subprocess.Popen([str(binp)], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().split()[1])
        N = 500
        with NativeChannel("127.0.0.1", port) as ch:
            echo = ch.unary_unary("/n.S/Echo")
            echo(b"warm", timeout=10)
            t0 = time.perf_counter()
            for _ in range(N):
                echo(b"x", timeout=10)
            native_s = time.perf_counter() - t0
        with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            echo = ch.unary_unary("/n.S/Echo")
            echo(b"warm", timeout=10)
            t0 = time.perf_counter()
            for _ in range(N):
                echo(b"x", timeout=10)
            py_s = time.perf_counter() - t0
        sys.stderr.write(f"native {native_s/N*1e6:.0f}us/call vs python "
                         f"{py_s/N*1e6:.0f}us/call\n")
        # margin absorbs 1-core scheduling hiccups; the real ratio is ~3x
        assert native_s < py_s * 1.2
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_compressed_frame_fails_stream_not_connection():
    """ADVICE r3: a MESSAGE with FLAG_COMPRESSED addressed to one stream
    must fail THAT stream with UNIMPLEMENTED (the native client links no
    decompressor) — not tear down the multiplexed connection and every
    unrelated in-flight call. Exercised with a frame-level fake server so
    the compressed frame can be forged (real tpurpc servers only mirror
    compression the client asked for, which the native client never does)."""
    import socket
    import threading

    from tpurpc.core.endpoint import TcpEndpoint
    from tpurpc.rpc import frame as fr

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    server_err: list = []

    def fake_server():
        try:
            sock, _ = lsock.accept()
            ep = TcpEndpoint(sock)
            reader = fr.FrameReader(ep, expect_preface=True)
            writer = fr.FrameWriter(ep)
            sids = []  # HEADERS arrival order = call submission order
            # Collect the two calls (each: HEADERS + MESSAGE/END_STREAM).
            while len(sids) < 2:
                f = reader.read_frame(timeout=15)
                assert f is not None, "client hung up early"
                if f is fr.CONSUMED:
                    continue
                if f.type == fr.HEADERS:
                    sids.append(f.stream_id)
                # MESSAGE frames (sink=None) arrive as Frame objects: ignore
            a, b = sids
            # Stream A: forged compressed garbage — must kill only A.
            # Written raw at the endpoint: FrameWriter.send would helpfully
            # gzip (or strip the flag from) a FLAG_COMPRESSED payload.
            forged = b"\x1f\x8b-not-really-gzip"
            ep.write([fr.HEADER_FMT.pack(
                fr.MESSAGE, fr.FLAG_COMPRESSED | fr.FLAG_END_STREAM,
                a, len(forged)), forged])
            # Stream B: clean response + OK trailers — must still deliver.
            writer.send(fr.MESSAGE, 0, b, b"fine")
            writer.send(fr.TRAILERS, 0, b,
                        fr.trailers_payload(StatusCode.OK, ""))
            # A's RST (from the per-stream rejection) may arrive; drain
            # until EOF so the client can close cleanly.
            while True:
                f = reader.read_frame(timeout=15)
                if f is None:
                    break
        except Exception as exc:  # surfaced in the main thread's assert
            server_err.append(exc)

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    try:
        with NativeChannel("127.0.0.1", port) as ch:
            echo = ch.unary_unary("/n.S/Echo")
            fut_a = echo.future(b"a", timeout=15)
            fut_b = echo.future(b"b", timeout=15)
            with pytest.raises(RpcError) as ei:
                fut_a.result(timeout=20)
            assert ei.value.code() is StatusCode.UNIMPLEMENTED
            assert "compressed" in ei.value.details()
            # The unrelated in-flight call on the SAME connection survives:
            assert fut_b.result(timeout=20) == b"fine"
    finally:
        lsock.close()
        t.join(timeout=5)
    assert not server_err, server_err
