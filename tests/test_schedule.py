"""tpurpc-proof (ISSUE 12): the deterministic schedule explorer.

The contracts under test:

* every live-code scenario explores CLEAN at the quick bound (the
  explorer does not invent bugs);
* every seeded real-code mutant (a hoisted publish, two removed locks, a
  skipped quarantine — :mod:`tpurpc.analysis.schedmutants`) is found BY
  EXPLORATION — the acceptance gate's "runtime matches model" teeth;
* determinism: the same seed drives the identical schedule traces;
* preemption-bound monotonicity: a bug found at bound k is found at k+1
  (the CHESS iterative-bounding property the quick gate leans on);
* replay: a violating schedule's serialized trace re-runs to the same
  violation.
"""

from __future__ import annotations

import json

import pytest

from tpurpc.analysis import schedule
from tpurpc.analysis.schedmutants import SCHED_MUTANTS

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


# -- clean tree: no violations within the bound -------------------------------

@pytest.mark.parametrize("name", sorted(schedule.SCENARIOS))
def test_clean_scenarios_explore_ok_at_bound1(name):
    res = schedule.run_scenario(name, preemption_bound=1,
                                max_schedules=4000)
    assert res.ok, res.violation
    assert not res.capped, "bound-1 exploration should exhaust"
    assert res.schedules > 1, "no interleavings explored?"


def test_clean_handoff_exhausts_at_bound2():
    res = schedule.run_scenario("handoff-mpmc", preemption_bound=2,
                                max_schedules=2000)
    # capped is acceptable at bound 2 (honestly reported); violations not
    assert res.ok, res.violation


# -- seeded real-code mutants: found by exploration ---------------------------

@pytest.mark.parametrize("mutant", sorted(SCHED_MUTANTS))
def test_every_sched_mutant_is_killed(mutant):
    m = SCHED_MUTANTS[mutant]
    res = schedule.run_scenario(m.scenario, preemption_bound=1,
                                max_schedules=8000, mutant=mutant)
    assert res.violation is not None, (
        f"mutant {mutant} SURVIVED {res.schedules} schedules — the "
        "explorer lost its teeth")


def test_mutant_kill_is_a_real_interleaving_not_a_unit_failure():
    """The kv lost-update mutant must survive BOTH sequential orders —
    only an interleaving kills it (that is what makes it a concurrency
    mutant and exploration the right weapon)."""
    m = SCHED_MUTANTS["kv_free_unlocked"]
    scenario = schedule.SCENARIOS[m.scenario]()
    with m.applied():
        # preemption bound 0 = run-to-block only: both sequential-ish
        # orders, no mid-function preemption — the mutant must pass
        res = schedule.explore(scenario, preemption_bound=0,
                               max_schedules=500)
    assert res.ok, (
        f"kv_free_unlocked died without preemption ({res.violation}) — "
        "that is a sequential bug, not the seeded race")


def test_mutant_kill_suite_all_killed():
    kills = schedule.mutant_kill_suite(preemption_bound=1,
                                       max_schedules=8000)
    assert len(kills) >= 3  # the acceptance floor
    survivors = [k for k, v in kills.items() if not v]
    assert not survivors, survivors


# -- determinism --------------------------------------------------------------

def test_random_exploration_same_seed_identical_traces():
    scen = schedule.SCENARIOS["handoff-mpmc"]
    r1, traces1 = schedule.explore_random(scen(), seed=1234, schedules=6)
    r2, traces2 = schedule.explore_random(scen(), seed=1234, schedules=6)
    assert r1.ok and r2.ok
    assert traces1 == traces2, "same seed must drive identical schedules"


def test_random_exploration_seeds_differ():
    scen = schedule.SCENARIOS["handoff-mpmc"]
    _, traces1 = schedule.explore_random(scen(), seed=1, schedules=4)
    _, traces2 = schedule.explore_random(scen(), seed=2, schedules=4)
    assert traces1 != traces2, (
        "different seeds produced byte-identical schedules — the seed "
        "is not reaching the scheduler")


def test_dfs_is_deterministic():
    res1 = schedule.run_scenario("kv-refcount", preemption_bound=1,
                                 max_schedules=500)
    res2 = schedule.run_scenario("kv-refcount", preemption_bound=1,
                                 max_schedules=500)
    assert (res1.schedules, res1.steps) == (res2.schedules, res2.steps)


# -- preemption-bound monotonicity --------------------------------------------

@pytest.mark.parametrize("mutant", ["handoff_publish_before_store",
                                    "kv_free_unlocked"])
def test_bug_found_at_bound_k_is_found_at_k_plus_1(mutant):
    m = SCHED_MUTANTS[mutant]
    at_1 = schedule.run_scenario(m.scenario, preemption_bound=1,
                                 max_schedules=8000, mutant=mutant)
    assert at_1.violation is not None
    at_2 = schedule.run_scenario(m.scenario, preemption_bound=2,
                                 max_schedules=20000, mutant=mutant)
    assert at_2.violation is not None, (
        "found at bound 1 but NOT at bound 2 — the bound-k schedules "
        "are not a subset of bound-k+1's")
    assert at_2.violation.kind == at_1.violation.kind


# -- replay -------------------------------------------------------------------

@pytest.mark.parametrize("mutant", ["handoff_publish_before_store",
                                    "scheduler_unlocked_submit"])
def test_violating_trace_replays_to_same_violation(mutant):
    m = SCHED_MUTANTS[mutant]
    found = schedule.run_scenario(m.scenario, preemption_bound=2,
                                  max_schedules=8000, mutant=mutant)
    assert found.violation is not None
    # serialize the schedule the way an operator would ship it
    wire = json.dumps(found.violation.trace)
    trace = json.loads(wire)
    scenario = schedule.SCENARIOS[m.scenario]()
    with m.applied():
        replayed = schedule.replay(scenario, trace)
    assert replayed.violation is not None, "replay lost the violation"
    assert replayed.violation.kind == found.violation.kind
    assert replayed.violation.message == found.violation.message


def test_clean_trace_replays_clean():
    res = schedule.run_scenario("handoff-mpmc", preemption_bound=0,
                                max_schedules=10)
    assert res.ok
    scenario = schedule.SCENARIOS["handoff-mpmc"]()
    # replay an arbitrary fixed round-robin-ish schedule: still clean
    replayed = schedule.replay(scenario, [0, 1, 2] * 40)
    assert replayed.ok, replayed.violation


# -- the exploration machinery itself -----------------------------------------

def test_deadlock_is_reported_not_hung():
    """Two tasks each take one SchedLock then want the other's — the
    scheduler must report a deadlock violation, not hang the suite."""
    built = {}

    def setup(sched):
        built["a"] = schedule.SchedLock(sched, "a")
        built["b"] = schedule.SchedLock(sched, "b")
        return built

    def t1(state):
        with state["a"]:
            with state["b"]:
                pass

    def t2(state):
        with state["b"]:
            with state["a"]:
                pass

    scen = schedule.Scenario("deadlock-probe", setup, [t1, t2],
                             lambda state: None, instrument=[])
    res = schedule.explore(scen, preemption_bound=2, max_schedules=200)
    assert not res.ok
    assert res.violation.kind == "deadlock"


def test_quick_suite_is_green():
    results = schedule.quick_suite()
    bad = [r for r in results if not r.ok]
    assert not bad, bad
