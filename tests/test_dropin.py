"""The drop-in claim, library-level: a grpcio-style program runs against
``import tpurpc.rpc as grpc`` unchanged.

The reference's defining UX is unmodified gRPC apps transparently riding
a swapped transport (endpoint.cc:33-54); tpurpc reproduces that at two
levels — wire (stock grpcio binaries interop, test_grpc_compat /
test_h2_client) and LIBRARY (this file): the grpcio names application
code actually uses resolve on tpurpc.rpc with grpcio semantics, so a
port is the import line."""

import threading
import time

import pytest

import tpurpc.rpc as grpc  # <- the port


def test_grpcio_shaped_program_runs_verbatim():
    # -- server exactly as a grpcio app writes it --
    class Greeter:
        def SayHello(self, request, context):
            return b"Hello, " + bytes(request) + b"!"

    greeter = Greeter()
    server = grpc.server(max_workers=4)
    handlers = grpc.method_handlers_generic_handler(
        "demo.Greeter",
        {"SayHello": grpc.unary_unary_rpc_method_handler(greeter.SayHello)})
    server.add_generic_rpc_handlers((handlers,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        # -- client exactly as a grpcio app writes it --
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        hello = channel.unary_unary("/demo.Greeter/SayHello")
        assert hello(b"world", timeout=10) == b"Hello, world!"
        with pytest.raises(grpc.RpcError) as ei:
            channel.unary_unary("/no.Such/Method")(b"", timeout=10)
        assert ei.value.code() is grpc.StatusCode.UNIMPLEMENTED
        channel.close()
    finally:
        server.stop(grace=0)


def test_channel_connectivity_states():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        ch = grpc.Channel(f"127.0.0.1:{port}")
        assert ch.get_state() is CC.IDLE  # nothing dialed yet
        assert ch.unary_unary("/d.S/Echo")(b"x", timeout=10) == b"x"
        assert ch.get_state() is CC.READY
        srv.stop(grace=0)
        with pytest.raises(grpc.RpcError):
            ch.unary_unary("/d.S/Echo")(b"x", timeout=5)
        # connection died + redial failed somewhere in that window:
        # the channel must now report backoff, not READY
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if ch.get_state() in (CC.TRANSIENT_FAILURE, CC.IDLE):
                break
            time.sleep(0.05)
        assert ch.get_state() in (CC.TRANSIENT_FAILURE, CC.IDLE)
        ch.close()
        assert ch.get_state() is CC.SHUTDOWN
    finally:
        srv.stop(grace=0)


def test_try_to_connect_kicks_idle_channel():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        with grpc.Channel(f"127.0.0.1:{port}") as ch:
            st = ch.get_state(try_to_connect=True)
            assert st is CC.CONNECTING
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and ch.get_state() is not CC.READY):
                time.sleep(0.05)
            assert ch.get_state() is CC.READY  # dialed with no RPC issued
    finally:
        srv.stop(grace=0)


def test_channel_ready_future_and_wait_for_state_change():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        with grpc.Channel(f"127.0.0.1:{port}") as ch:
            grpc.channel_ready_future(ch).result(timeout=15)  # grpcio idiom
            assert ch.get_state() is CC.READY
        # closed channel: the future must fail, not spin forever
        ch2 = grpc.Channel(f"127.0.0.1:{port}")
        assert ch2.wait_for_state_change(CC.READY, timeout=0.2) is True
        ch2.close()
        with pytest.raises(grpc.RpcError):
            grpc.channel_ready_future(ch2).result(timeout=15)
    finally:
        srv.stop(grace=0)


def test_aio_attribute_lazy():
    assert hasattr(grpc, "aio")
    assert hasattr(grpc.aio, "insecure_channel")
