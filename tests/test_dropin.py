"""The drop-in claim, library-level: a grpcio-style program runs against
``import tpurpc.rpc as grpc`` unchanged.

The reference's defining UX is unmodified gRPC apps transparently riding
a swapped transport (endpoint.cc:33-54); tpurpc reproduces that at two
levels — wire (stock grpcio binaries interop, test_grpc_compat /
test_h2_client) and LIBRARY (this file): the grpcio names application
code actually uses resolve on tpurpc.rpc with grpcio semantics, so a
port is the import line."""

import threading
import time

import pytest

import tpurpc.rpc as grpc  # <- the port


def test_grpcio_shaped_program_runs_verbatim():
    # -- server exactly as a grpcio app writes it --
    class Greeter:
        def SayHello(self, request, context):
            return b"Hello, " + bytes(request) + b"!"

    greeter = Greeter()
    server = grpc.server(max_workers=4)
    handlers = grpc.method_handlers_generic_handler(
        "demo.Greeter",
        {"SayHello": grpc.unary_unary_rpc_method_handler(greeter.SayHello)})
    server.add_generic_rpc_handlers((handlers,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        # -- client exactly as a grpcio app writes it --
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        hello = channel.unary_unary("/demo.Greeter/SayHello")
        assert hello(b"world", timeout=10) == b"Hello, world!"
        with pytest.raises(grpc.RpcError) as ei:
            channel.unary_unary("/no.Such/Method")(b"", timeout=10)
        assert ei.value.code() is grpc.StatusCode.UNIMPLEMENTED
        channel.close()
    finally:
        server.stop(grace=0)


def test_channel_connectivity_states():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        ch = grpc.Channel(f"127.0.0.1:{port}")
        assert ch.get_state() is CC.IDLE  # nothing dialed yet
        assert ch.unary_unary("/d.S/Echo")(b"x", timeout=10) == b"x"
        assert ch.get_state() is CC.READY
        srv.stop(grace=0)
        with pytest.raises(grpc.RpcError):
            ch.unary_unary("/d.S/Echo")(b"x", timeout=5)
        # connection died + redial failed somewhere in that window:
        # the channel must now report backoff, not READY
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if ch.get_state() in (CC.TRANSIENT_FAILURE, CC.IDLE):
                break
            time.sleep(0.05)
        assert ch.get_state() in (CC.TRANSIENT_FAILURE, CC.IDLE)
        ch.close()
        assert ch.get_state() is CC.SHUTDOWN
    finally:
        srv.stop(grace=0)


def test_try_to_connect_kicks_idle_channel():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        with grpc.Channel(f"127.0.0.1:{port}") as ch:
            st = ch.get_state(try_to_connect=True)
            assert st is CC.CONNECTING
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and ch.get_state() is not CC.READY):
                time.sleep(0.05)
            assert ch.get_state() is CC.READY  # dialed with no RPC issued
    finally:
        srv.stop(grace=0)


def test_channel_ready_future_and_wait_for_state_change():
    srv = grpc.server(max_workers=2)
    srv.add_method("/d.S/Echo",
                   grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    CC = grpc.ChannelConnectivity
    try:
        with grpc.Channel(f"127.0.0.1:{port}") as ch:
            grpc.channel_ready_future(ch).result(timeout=15)  # grpcio idiom
            assert ch.get_state() is CC.READY
        # closed channel: the future must fail, not spin forever
        ch2 = grpc.Channel(f"127.0.0.1:{port}")
        assert ch2.wait_for_state_change(CC.READY, timeout=0.2) is True
        ch2.close()
        with pytest.raises(grpc.RpcError):
            grpc.channel_ready_future(ch2).result(timeout=15)
    finally:
        srv.stop(grace=0)


def test_wait_for_ready_queues_until_server_appears():
    """grpcio's per-call wait_for_ready=True: a call issued while the
    target is down QUEUES (keeps dialing) and completes once the server
    appears, instead of failing fast — on a port chosen before any server
    exists."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ch = grpc.Channel(f"127.0.0.1:{port}")
    try:
        # default fail-fast still fails fast while the target is down
        with pytest.raises(grpc.RpcError) as ei:
            ch.unary_unary("/d.S/Echo")(b"x", timeout=5)
        assert ei.value.code() is grpc.StatusCode.UNAVAILABLE

        srv_box = {}

        def start_late():
            time.sleep(0.8)
            srv = grpc.server(max_workers=2)
            srv.add_method("/d.S/Echo", grpc.unary_unary_rpc_method_handler(
                lambda r, c: bytes(r) + b"!"))
            srv.add_insecure_port(f"127.0.0.1:{port}")
            srv.start()
            srv_box["srv"] = srv

        t = threading.Thread(target=start_late, daemon=True)
        t.start()
        out = ch.unary_unary("/d.S/Echo")(b"hi", timeout=30,
                                          wait_for_ready=True)
        assert out == b"hi!"
        t.join()
        # and the deadline still binds when the server never comes:
        ch2 = grpc.Channel("127.0.0.1:1")  # reserved port, nothing there
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError) as ei:
            ch2.unary_unary("/x/Y")(b"", timeout=1.5, wait_for_ready=True)
        assert ei.value.code() is grpc.StatusCode.DEADLINE_EXCEEDED
        assert time.monotonic() - t0 < 10
        ch2.close()
    finally:
        ch.close()
        if "srv" in srv_box:
            srv_box["srv"].stop(grace=0)


def test_wait_for_ready_queue_time_counts_against_deadline():
    """Time spent queuing for readiness is part of the call's budget: a
    2.5s-timeout call that waits ~1.2s for the server and then hits a
    2s handler must DEADLINE_EXCEEDED — under the old post-dial re-anchor
    it would have been given a fresh 2.5s and succeeded."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv_box = {}

    def start_late():
        time.sleep(1.2)
        srv = grpc.server(max_workers=2)
        srv.add_method("/d.S/Slow", grpc.unary_unary_rpc_method_handler(
            lambda r, c: time.sleep(2.0) or b"late"))
        srv.add_insecure_port(f"127.0.0.1:{port}")
        srv.start()
        srv_box["srv"] = srv

    t = threading.Thread(target=start_late, daemon=True)
    t.start()
    try:
        with grpc.Channel(f"127.0.0.1:{port}") as ch:
            t0 = time.monotonic()
            with pytest.raises(grpc.RpcError) as ei:
                ch.unary_unary("/d.S/Slow")(b"", timeout=2.5,
                                            wait_for_ready=True)
            assert ei.value.code() is grpc.StatusCode.DEADLINE_EXCEEDED
            # and it fired near the ORIGINAL deadline, not a re-anchored one
            assert time.monotonic() - t0 < 4.0
    finally:
        t.join()
        if "srv" in srv_box:
            srv_box["srv"].stop(grace=0)


def test_grpcio_constructor_shapes():
    """The stock grpcio constructor calls run verbatim: an executor as the
    first server() argument, options lists on both sides."""
    from concurrent import futures as cf

    class Greeter:
        def SayHello(self, request, context):
            return bytes(request) + b"!"

    server = grpc.server(
        cf.ThreadPoolExecutor(max_workers=6),
        options=[("grpc.max_receive_message_length", 128),
                 ("grpc.so_reuseport", 0)])  # unknown arg: ignored
    assert server.max_receive_message_length == 128
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
        "d.G", {"SayHello": grpc.unary_unary_rpc_method_handler(
            Greeter().SayHello)}),))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        ch = grpc.insecure_channel(
            f"127.0.0.1:{port}",
            options=[("grpc.max_receive_message_length", 64 << 20),
                     ("grpc.lb_policy_name", "round_robin")])
        assert ch.max_receive_message_length == 64 << 20
        assert ch.unary_unary("/d.G/SayHello")(b"hi", timeout=10) == b"hi!"
        # server-side limit from options enforced: >128B rejected
        with pytest.raises(grpc.RpcError) as ei:
            ch.unary_unary("/d.G/SayHello")(b"x" * 256, timeout=10)
        assert ei.value.code() is grpc.StatusCode.RESOURCE_EXHAUSTED
        ch.close()
    finally:
        server.stop(grace=0)


def test_aio_attribute_lazy():
    assert hasattr(grpc, "aio")
    assert hasattr(grpc.aio, "insecure_channel")
