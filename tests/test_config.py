"""Config / trace / stats unit tests (SURVEY.md §5 config+tracing subsystems)."""

import pytest

from tpurpc.utils import config as config_mod
from tpurpc.utils import stats, trace
from tpurpc.utils.config import Config, Platform, get_config


def test_defaults_match_reference_readme():
    # README.md:17-25 documents: ring 4MB, 1 poller thread, 500us busy-poll,
    # 1000ms poller sleep.
    cfg = Config()
    assert cfg.platform is Platform.TCP
    assert cfg.ring_buffer_size == 4 * 1024 * 1024
    assert cfg.poller_thread_num == 1
    assert cfg.busy_polling_timeout_us == 500
    assert cfg.poller_sleep_timeout_ms == 1000
    assert cfg.send_chunk_size == 512 * 1024


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("TCP", Platform.TCP),
        ("RDMA_BP", Platform.RING_BP),
        ("RDMA_EVENT", Platform.RING_EVENT),
        ("RDMA_BPEV", Platform.RING_BPEV),
        ("RDMA_TPU", Platform.TPU),
        ("TPU", Platform.TPU),
        ("rdma_bpev", Platform.RING_BPEV),
    ],
)
def test_platform_env_aliases(monkeypatch, raw, expected):
    # The reference reads GRPC_PLATFORM_TYPE (iomgr_internal.cc:36-61); we accept
    # its exact values plus our own spellings.
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", raw)
    assert Config.from_env().platform is expected


def test_unknown_platform_raises(monkeypatch):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "CARRIER_PIGEON")
    with pytest.raises(ValueError, match="unknown platform"):
        Config.from_env()


def test_tpurpc_names_take_precedence(monkeypatch):
    monkeypatch.setenv("GRPC_RDMA_RING_BUFFER_SIZE_KB", "64")
    monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "128")
    assert Config.from_env().ring_buffer_size_kb == 128


def test_grpc_rdma_aliases_respected(monkeypatch):
    monkeypatch.setenv("GRPC_RDMA_POLLER_THREAD_NUM", "3")
    monkeypatch.setenv("GRPC_RDMA_BUSY_POLLING_TIMEOUT_US", "250")
    cfg = Config.from_env()
    assert cfg.poller_thread_num == 3
    assert cfg.busy_polling_timeout_us == 250


def test_ring_size_rounds_to_power_of_two(monkeypatch):
    monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "100")
    # 100KB → next pow2 = 128KB (ring_buffer.cc:22 requires power-of-two capacity)
    assert Config.from_env().ring_buffer_size == 128 * 1024


def test_singleton_reads_env_once(monkeypatch):
    monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "64")
    first = get_config()
    monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "256")
    assert get_config() is first
    config_mod.set_config(None)
    assert get_config().ring_buffer_size_kb == 256


def test_trace_env_grammar(monkeypatch):
    monkeypatch.setenv("TPURPC_TRACE", "all,-http2")
    trace.reapply_env()
    flags = trace.list_tracers()
    assert flags["ring"] is True
    assert flags["http2"] is False
    monkeypatch.setenv("TPURPC_TRACE", "ring_event")
    trace.reapply_env()
    flags = trace.list_tracers()
    assert flags["ring_event"] is True
    assert flags["ring"] is False
    monkeypatch.delenv("TPURPC_TRACE")
    trace.reapply_env()


def test_profile_spans_and_table():
    stats.enable(True)
    try:
        with stats.profile("unit_test_op"):
            pass
        snap = stats.snapshot()
        assert snap["unit_test_op"][0] >= 1
        table = stats.print_table()
        assert "unit_test_op" in table
    finally:
        stats.enable(False)


def test_copy_ledger_accumulates_and_resets():
    led = stats.CopyLedger()
    led.add("host_copy", 100)
    led.add("device_dma", 4096)
    assert led.as_dict()["host_copy"] == 100
    assert led.as_dict()["device_dma"] == 4096
    led.reset()
    assert all(v == 0 for v in led.as_dict().values())


def test_timer_wheel_schedules_and_cancels():
    """One wheel thread serves many timers (iomgr/timer.cc role); cancel is
    best-effort; a raising callback doesn't kill the wheel."""
    import threading
    import time as _t

    from tpurpc.utils import timers

    fired = []
    ev = threading.Event()
    timers.schedule(0.05, lambda: (fired.append("a"), ev.set()))
    h = timers.schedule(0.05, lambda: fired.append("cancelled"))
    h.cancel()
    timers.schedule(0.01, lambda: 1 / 0)  # must not kill the wheel
    assert ev.wait(5)
    ev2 = threading.Event()
    timers.schedule(0.02, ev2.set)  # wheel survived the exception
    assert ev2.wait(5)
    _t.sleep(0.15)
    assert fired == ["a"]
    # ordering: earlier deadline fires first even if scheduled later
    order = []
    done = threading.Event()
    timers.schedule(0.10, lambda: (order.append(2), done.set()))
    timers.schedule(0.02, lambda: order.append(1))
    assert done.wait(5)
    assert order == [1, 2]


def test_profiling_spans_record_real_ops(monkeypatch):
    """GRPCProfiler parity is only real if the hot paths actually carry
    spans: a profiled RPC must produce cli_unary + srv_handler (+ pair_send
    on ring transports) rows in the table."""
    import tpurpc.rpc as rpc
    from tpurpc.utils import stats

    monkeypatch.setenv("GRPC_PROFILING", "on")
    stats.enable(True)
    try:
        srv = rpc.Server(max_workers=2)
        srv.add_method("/p.S/E", rpc.unary_unary_rpc_method_handler(
            lambda r, c: bytes(r)))
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/p.S/E")(b"x", timeout=10) == b"x"
        srv.stop(grace=0)
        # the handler thread exits its span AFTER sending the response —
        # the client can get here first; poll briefly
        import time as _t

        deadline = _t.monotonic() + 5
        while _t.monotonic() < deadline:
            rows = stats.snapshot()
            if "srv_handler" in rows:
                break
            _t.sleep(0.02)
        assert "cli_unary" in rows and rows["cli_unary"][0] >= 1
        assert "srv_handler" in rows and rows["srv_handler"][0] >= 1
    finally:
        stats.enable(False)
