"""Batched ring primitives (ISSUE 1 tentpole): ``RingReader.drain_into`` /
``read_many`` and ``RingWriter.write_many`` must be byte-identical to the
message-at-a-time loops across wrap points, partial messages, and corruption
stamps — the batch is an amortization, never a semantic change.

Seeded-random property style (the repo's test_ring.py fuzz idiom; the
hypothesis dependency isn't in the image), run over both the native and the
pure-Python drain paths.
"""

import random

import pytest

from tpurpc.core import ring as R


def make_pipe(capacity=1024, native=True):
    buf = bytearray(capacity)
    reader = R.RingReader(buf)
    if not native:
        reader._nat = None  # force the pure-Python scan/copy path
    writer = R.RingWriter(capacity, lambda off, data: buf.__setitem__(
        slice(off, off + len(data)), bytes(data)))
    return reader, writer


def pump_credits(reader, writer, force=False):
    if force or reader.should_publish_head():
        writer.update_remote_head(reader.take_publish())


def _random_payload(rng, choices=(0, 1, 3, 8, 17, 64, 100, 255)):
    return bytes(rng.randrange(256) for _ in range(rng.choice(choices)))


# ---------------------------------------------------------------------------
# write_many ≡ write-at-a-time
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(4))
def test_write_many_byte_identical_to_write_loop(seed):
    """The batch encoder and the per-message encoder must produce identical
    ring byte streams (same framing, same stamps) for identical inputs."""
    rng = random.Random(seed)
    r_one, w_one = make_pipe(2048)
    r_many, w_many = make_pipe(2048)
    for _ in range(300):
        batch = [_random_payload(rng) for _ in range(rng.randrange(1, 5))]
        nonzero = [p for p in batch if p]
        pump_credits(r_one, w_one, force=True)
        pump_credits(r_many, w_many, force=True)
        nm, nb = w_many.write_many(batch)
        wrote = 0
        for p in nonzero[:nm]:
            wrote += w_one.write(p)
        assert nb == wrote
        assert w_one.tail == w_many.tail and w_one.seq == w_many.seq
        a = r_one.read(2048)
        b = r_many.read(2048)
        assert a == b == b"".join(nonzero[:nm])


@pytest.mark.parametrize("seed", range(3))
def test_write_many_respects_credits_and_resumes(seed):
    """A batch that exceeds current credits writes a prefix (all-or-nothing
    per message, in order); the rest goes through after the reader drains
    and credits return — and the reassembled stream is byte-exact."""
    rng = random.Random(seed)
    reader, writer = make_pipe(256)
    pending = [bytes([i]) * rng.choice([8, 24, 56]) for i in range(64)]
    expected = b"".join(pending)
    got = bytearray()
    stalls = 0
    while len(got) < len(expected):
        nm, _ = writer.write_many(pending[:6])
        assert nm <= 6
        del pending[:nm]
        if nm == 0:
            stalls += 1
            assert stalls < 1000, "no forward progress"
        dst = bytearray(256)
        n, _ = reader.drain_into(dst)
        got += dst[:n]
        pump_credits(reader, writer, force=True)
    assert bytes(got) == expected and not pending


def test_write_many_single_message_matches_writev():
    reader, writer = make_pipe(512)
    nm, nb = writer.write_many([[b"ab", b"cd", b"ef"]])
    assert (nm, nb) == (1, 6)
    assert reader.read(512) == b"abcdef"


def test_write_many_empty_messages_skipped():
    reader, writer = make_pipe(256)
    nm, nb = writer.write_many([b"", b"xy", b""])
    assert (nm, nb) == (1, 2)
    assert reader.read(256) == b"xy"


# ---------------------------------------------------------------------------
# drain_into ≡ read_into, across wraps and partial messages
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("native", [True, False], ids=["native", "python"])
@pytest.mark.parametrize("seed", range(4))
def test_drain_into_byte_identical_to_read_into(seed, native):
    """Interleaved random writes and drains with deliberately small dst
    buffers (forcing partial-message resumption) must yield the same byte
    stream as a reference reader using read_into on an identical ring."""
    rng = random.Random(seed)
    r_a, w_a = make_pipe(1024, native=native)
    r_b, w_b = make_pipe(1024, native=native)
    stream_a = bytearray()
    stream_b = bytearray()
    for _ in range(400):
        p = _random_payload(rng)
        pump_credits(r_a, w_a, force=True)
        pump_credits(r_b, w_b, force=True)
        if p and len(p) <= min(w_a.writable_payload(), w_b.writable_payload()):
            w_a.write(p)
            w_b.write(p)
        size = rng.choice([7, 33, 128, 1024])
        dst = bytearray(size)
        n, msgs = r_a.drain_into(dst)
        stream_a += dst[:n]
        assert msgs >= 0
        dst2 = bytearray(size)
        n2 = r_b.read_into(dst2)
        stream_b += dst2[:n2]
        assert n == n2
    assert stream_a == stream_b


def test_drain_into_message_count_matches_seq_delta():
    reader, writer = make_pipe(4096)
    for i in range(7):
        writer.write(bytes([i]) * 10)
    seq0 = reader.seq
    dst = bytearray(4096)
    n, msgs = reader.drain_into(dst)
    assert n == 70 and msgs == 7
    assert reader.seq - seq0 == 7


def test_drain_into_partial_message_counts_zero():
    """A drain that only moves part of one message reports 0 completed
    messages; the completion lands with the drain that finishes it."""
    reader, writer = make_pipe(1024)
    writer.write(b"z" * 100)
    n1, m1 = reader.drain_into(bytearray(40))
    n2, m2 = reader.drain_into(bytearray(100))
    assert (n1, m1) == (40, 0)
    assert (n2, m2) == (60, 1)


# ---------------------------------------------------------------------------
# read_many: whole messages, one segmented copy-out
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(4))
def test_read_many_returns_whole_messages_in_order(seed):
    rng = random.Random(seed)
    reader, writer = make_pipe(2048)
    outstanding = []
    for _ in range(300):
        p = _random_payload(rng)
        pump_credits(reader, writer, force=True)
        if p and len(p) <= writer.writable_payload():
            writer.write(p)
            outstanding.append(p)
        if rng.random() < 0.4:
            msgs = reader.read_many()
            assert [bytes(m) for m in msgs] == outstanding[:len(msgs)]
            del outstanding[:len(msgs)]
    msgs = reader.read_many()
    assert [bytes(m) for m in msgs] == outstanding


def test_read_many_spans_the_wrap_point():
    """Messages written across the ring's physical wrap come back intact —
    the batch copy splits into exactly the two wrap segments."""
    reader, writer = make_pipe(256)
    # advance the ring close to the wrap point
    for _ in range(3):
        writer.write(b"a" * 48)
    dst = bytearray(256)
    reader.drain_into(dst)
    writer.update_remote_head(reader.take_publish())
    # these two messages straddle capacity=256
    m1, m2 = b"b" * 40, b"c" * 40
    writer.write(m1)
    writer.write(m2)
    msgs = reader.read_many()
    assert [bytes(m) for m in msgs] == [m1, m2]


def test_read_many_respects_in_progress_partial():
    """read_many never interleaves with a partial read_into in flight — the
    caller finishes the partial message first."""
    reader, writer = make_pipe(1024)
    writer.write(b"x" * 64)
    writer.write(b"y" * 64)
    reader.read_into(bytearray(10))  # starts message 1, leaves it partial
    assert reader.read_many() == []
    rest = bytearray(1024)
    n = reader.read_into(rest)
    assert bytes(rest[:n]) == b"x" * 54 + b"y" * 64


def test_read_many_views_survive_ring_reuse():
    """The returned views are detached copies: overwriting the ring span
    afterward (a full wrap of new traffic) must not mutate them."""
    reader, writer = make_pipe(256)
    writer.write(b"m" * 64)
    (msg,) = reader.read_many()
    writer.update_remote_head(reader.take_publish())
    for i in range(6):  # enough traffic to lap the span
        writer.write(bytes([i]) * 32)
        reader.drain_into(bytearray(256))
        writer.update_remote_head(reader.take_publish())
    assert bytes(msg) == b"m" * 64


# ---------------------------------------------------------------------------
# corruption stamps: stale/garbage framing never surfaces as data
# ---------------------------------------------------------------------------

def test_batched_reads_ignore_stale_stamps_after_wrap():
    """Bytes left from previous laps (valid-looking headers with old seq
    stamps) must read as 'no message' to the batched scanners, exactly as
    they do to the one-at-a-time path."""
    reader, writer = make_pipe(256)
    for lap in range(8):  # several full laps leave stale framing behind
        writer.write(bytes([lap]) * 48)
        msgs = reader.read_many()
        assert len(msgs) == 1 and bytes(msgs[0]) == bytes([lap]) * 48
        writer.update_remote_head(reader.take_publish())
    assert reader.read_many() == []
    assert reader.drain_into(bytearray(64))[0] == 0


def test_drain_stops_at_corrupt_footer():
    """A message whose footer stamp is wrong is incomplete to the batch scan:
    everything before it drains, nothing after it does."""
    buf = bytearray(1024)
    reader = R.RingReader(buf)
    writer = R.RingWriter(1024, lambda off, data: buf.__setitem__(
        slice(off, off + len(data)), bytes(data)))
    writer.write(b"ok" * 8)
    tail_before = writer.tail
    writer.write(b"bad" * 8)
    # smash the second message's footer stamp
    footer_off = tail_before + R.HEADER_BYTES + R.align_up(24)
    buf[footer_off & (1024 - 1):(footer_off & (1024 - 1)) + 8] = b"\xde" * 8
    msgs = reader.read_many()
    assert [bytes(m) for m in msgs] == [b"ok" * 8]
    n, cnt = reader.drain_into(bytearray(1024))
    assert (n, cnt) == (0, 0)
