"""The grpcurl-shaped CLI (tpurpc.tools.cli) against a live server."""

import subprocess
import sys

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc import health


@pytest.fixture()
def served():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/c.S/Echo",
                   rpc.unary_unary_rpc_method_handler(
                       lambda r, c: bytes(r).upper(), inline=True))
    rpc.enable_server_reflection(srv)
    hs = rpc.add_health_servicer(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield srv, port, hs
    srv.stop(grace=0)


def _cli(*args):
    return subprocess.run([sys.executable, "-m", "tpurpc.tools.cli", *args],
                          capture_output=True, text=True, timeout=120)


def test_cli_list(served):
    _, port, _ = served
    out = _cli("list", f"127.0.0.1:{port}")
    assert out.returncode == 0, out.stderr
    assert "c.S" in out.stdout
    assert "grpc.reflection.v1alpha.ServerReflection" in out.stdout


def test_cli_call_and_status(served):
    _, port, _ = served
    out = _cli("call", f"127.0.0.1:{port}", "/c.S/Echo", "hello")
    assert out.returncode == 0 and out.stdout == "HELLO"
    out = _cli("call", f"127.0.0.1:{port}", "/c.S/Nope", "x")
    assert out.returncode == 12  # UNIMPLEMENTED, grpcurl-style exit code
    assert "UNIMPLEMENTED" in out.stderr


def test_cli_health_and_ping(served):
    _, port, hs = served
    out = _cli("health", f"127.0.0.1:{port}")
    assert out.returncode == 0 and "SERVING" in out.stdout
    hs.set("", health.ServingStatus.NOT_SERVING)
    out = _cli("health", f"127.0.0.1:{port}")
    assert out.returncode == 1 and "NOT_SERVING" in out.stdout
    out = _cli("ping", f"127.0.0.1:{port}")
    assert out.returncode == 0 and "us" in out.stdout


def test_cli_unreachable():
    out = _cli("--timeout", "2", "ping", "127.0.0.1:1")
    assert out.returncode == 14  # UNAVAILABLE


def test_cli_missing_payload_file_is_usage_error(served):
    _, port, _ = served
    out = _cli("call", f"127.0.0.1:{port}", "/c.S/Echo", "@/no/such/file")
    assert out.returncode == 2  # usage error, not UNAVAILABLE
    assert "cannot read payload file" in out.stderr
