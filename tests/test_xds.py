"""xDS-lite: bootstrap, the xds: resolver, and EDS-style dynamic updates.

The reference's xds client_channel family
(``ext/filters/client_channel/resolver/xds/``, ``lb_policy/xds/``) scoped
to tpurpc's lite shim (tpurpc/rpc/xds.py): gRPC's bootstrap/target UX over
tpurpc's own ADS-lite wire, feeding Channel.update_addresses.
"""

import json
import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.xds import (XdsServicer, load_bootstrap,
                            xds_channel)


def _echo_server(tag: bytes):
    srv = rpc.Server(max_workers=2)
    srv.add_method("/x.S/Who",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: tag))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _control_plane():
    xds = XdsServicer()
    srv = rpc.Server(max_workers=4)
    xds.attach(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return xds, srv, port


def test_bootstrap_parsing(monkeypatch, tmp_path):
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP_CONFIG", raising=False)
    with pytest.raises(RuntimeError):
        load_bootstrap()  # no bootstrap configured: loud
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG",
                       json.dumps({"xds_servers": []}))
    with pytest.raises(RuntimeError):
        load_bootstrap()  # malformed: needs server_uri
    # inline config works; a FILE wins over it (gRPC precedence)
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": "inline:1"}]}))
    assert load_bootstrap()["xds_servers"][0]["server_uri"] == "inline:1"
    bs = tmp_path / "bootstrap.json"
    bs.write_text(json.dumps({"xds_servers": [{"server_uri": "file:2"}],
                              "node": {"id": "n1"}}))
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP", str(bs))
    cfg = load_bootstrap()
    assert cfg["xds_servers"][0]["server_uri"] == "file:2"
    assert cfg["node"]["id"] == "n1"


def test_xds_target_resolves_via_control_plane(monkeypatch):
    """Channel("xds:///svc") works like grpcio's: bootstrap names the
    control plane, the resolver fetches the current EDS assignment."""
    backend, bport = _echo_server(b"b1")
    xds, cp, cport = _control_plane()
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{bport}"])
        monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
            {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}],
             "node": {"id": "test-node"}}))
        monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
        with rpc.Channel("xds:///svc") as ch:
            assert ch.unary_unary("/x.S/Who")(b"", timeout=15) == b"b1"
        # empty assignment: loud resolution failure, not a hang
        with pytest.raises(Exception):
            rpc.Channel("xds:///nonexistent-svc")
    finally:
        cp.stop(grace=0)
        backend.stop(grace=0)


def test_xds_watcher_moves_traffic_on_eds_update(monkeypatch):
    """set_endpoints (the EDS update) re-points a live channel: the
    watcher feeds update_addresses; calls land on the new backend."""
    b1, p1 = _echo_server(b"b1")
    b2, p2 = _echo_server(b"b2")
    xds, cp, cport = _control_plane()
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}]}))
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{p1}"])
        ch, watcher = xds_channel("xds:///svc")
        try:
            who = ch.unary_unary("/x.S/Who")
            assert who(b"", timeout=15) == b"b1"
            # hostname endpoint on purpose: the watcher must normalize it
            # the same way the channel's keep-live matching does (a raw
            # string would mismatch the resolved keys and churn live
            # connections on every identical push)
            xds.set_endpoints("svc", [f"localhost:{p2}"])
            deadline = time.monotonic() + 10
            seen = b""
            while time.monotonic() < deadline:
                try:
                    seen = who(b"", timeout=15)
                except rpc.RpcError as exc:
                    # a call racing the membership swap may land on the
                    # closing backend once (update_addresses' documented
                    # contract) — the next call re-dials
                    if exc.code() is not rpc.StatusCode.UNAVAILABLE:
                        raise
                if seen == b"b2":
                    break
                time.sleep(0.05)
            assert seen == b"b2", "EDS update never moved traffic"
            assert watcher.applied_versions, "watcher applied no update"
        finally:
            watcher.stop()
            ch.close()
    finally:
        cp.stop(grace=0)
        b1.stop(grace=0)
        b2.stop(grace=0)


# -- the real v3 ADS wire (round 5: tpurpc/rpc/xds_v3.py) ---------------------

ENVOY_SUBSET_PROTO = """
syntax = "proto3";
package envoy.test;
import "google/protobuf/any.proto";
message Node { string id = 1; string cluster = 2;
               string user_agent_name = 6; }
message DiscoveryRequest {
  string version_info = 1; Node node = 2;
  repeated string resource_names = 3;
  string type_url = 4; string response_nonce = 5; }
message DiscoveryResponse {
  string version_info = 1; repeated google.protobuf.Any resources = 2;
  string type_url = 4; string nonce = 5; }
message SocketAddress { string address = 2; uint32 port_value = 3; }
message Address { SocketAddress socket_address = 1; }
message Endpoint { Address address = 1; }
message LbEndpoint { Endpoint endpoint = 1; int32 health_status = 2; }
message LocalityLbEndpoints { repeated LbEndpoint lb_endpoints = 2;
                              uint32 priority = 5; }
message ClusterLoadAssignment {
  string cluster_name = 1;
  repeated LocalityLbEndpoints endpoints = 2; }
"""


def _compile_envoy_subset(tmp_path):
    """protoc-compile the REAL field layout (mirrors the lb_v1 validation
    pattern): an independent protobuf implementation judges the
    hand-rolled xds_v3 codec."""
    import importlib.util
    import shutil
    import subprocess

    if shutil.which("protoc") is None:
        pytest.skip("no protoc binary")
    proto = tmp_path / "envoy_subset.proto"
    proto.write_text(ENVOY_SUBSET_PROTO)
    r = subprocess.run(
        ["protoc", f"-I{tmp_path}", f"--python_out={tmp_path}", str(proto)],
        capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"protoc failed: {r.stderr[:200]}")
    spec = importlib.util.spec_from_file_location(
        "envoy_subset_pb2", tmp_path / "envoy_subset_pb2.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ads_v3_codec_against_real_protobuf(tmp_path):
    from tpurpc.rpc import xds_v3

    pb = _compile_envoy_subset(tmp_path)
    # our DiscoveryRequest parses with stock protobuf
    req = pb.DiscoveryRequest.FromString(xds_v3.encode_discovery_request(
        ["cluster-a"], version_info="7", response_nonce="n3",
        node_id="node-1", node_cluster="prod"))
    assert req.version_info == "7"
    assert list(req.resource_names) == ["cluster-a"]
    assert req.type_url == xds_v3.CLA_TYPE_URL
    assert req.response_nonce == "n3"
    assert req.node.id == "node-1" and req.node.cluster == "prod"
    # our DiscoveryResponse+CLA parse with stock protobuf
    resp = pb.DiscoveryResponse.FromString(xds_v3.encode_discovery_response(
        [("cluster-a", ["10.0.0.1:443", "[::1]:8080"])],
        version_info="9", nonce="n9"))
    assert resp.version_info == "9" and resp.nonce == "n9"
    assert resp.resources[0].type_url == xds_v3.CLA_TYPE_URL
    cla = pb.ClusterLoadAssignment.FromString(resp.resources[0].value)
    assert cla.cluster_name == "cluster-a"
    eps = cla.endpoints[0].lb_endpoints
    sock0 = eps[0].endpoint.address.socket_address
    assert (sock0.address, sock0.port_value) == ("10.0.0.1", 443)
    # stock protobuf encodes parse with our decoder — including multiple
    # localities with priorities and an unhealthy endpoint to exclude
    cla2 = pb.ClusterLoadAssignment(cluster_name="c2")
    lo_hi = cla2.endpoints.add(priority=1)
    lo_hi.lb_endpoints.add().endpoint.address.socket_address.address = "b"
    lo_hi.lb_endpoints[0].endpoint.address.socket_address.port_value = 2
    lo0 = cla2.endpoints.add()  # priority 0: must sort FIRST
    lo0.lb_endpoints.add().endpoint.address.socket_address.address = "a"
    lo0.lb_endpoints[0].endpoint.address.socket_address.port_value = 1
    sick = lo0.lb_endpoints.add(health_status=3)  # UNHEALTHY: excluded
    sick.endpoint.address.socket_address.address = "dead"
    sick.endpoint.address.socket_address.port_value = 9
    resp2 = pb.DiscoveryResponse(version_info="1", nonce="x",
                                 type_url=xds_v3.CLA_TYPE_URL)
    any_res = resp2.resources.add()
    any_res.type_url = xds_v3.CLA_TYPE_URL
    any_res.value = cla2.SerializeToString()
    out = xds_v3.decode_discovery_response(resp2.SerializeToString())
    assert out["version_info"] == "1" and out["nonce"] == "x"
    assert out["assignments"] == {"c2": ["a:1", "b:2"]}
    # our request decoder reads a stock-encoded subscribe
    sub = pb.DiscoveryRequest(type_url=xds_v3.CLA_TYPE_URL,
                              resource_names=["c3"], response_nonce="n")
    got = xds_v3.decode_discovery_request(sub.SerializeToString())
    assert got["resource_names"] == ["c3"] and got["response_nonce"] == "n"


def test_assignment_arrives_over_real_ads_stream():
    """VERDICT r4 next #7 done-criterion: the assignment arrives over a
    real AggregatedDiscoveryService/StreamAggregatedResources stream —
    driven here with raw hand-encoded DiscoveryRequests (what a stock
    client sends), including the ACK and a post-ACK push."""
    import queue as _queue

    from tpurpc.rpc import xds_v3

    xds, cp, cport = _control_plane()
    try:
        xds.set_endpoints("clu", ["10.1.1.1:443"])
        with rpc.Channel(f"127.0.0.1:{cport}") as ch:
            reqs: "_queue.Queue[bytes]" = _queue.Queue()
            reqs.put(xds_v3.encode_discovery_request(
                ["clu"], node_id="raw-client"))
            done = [False]

            def req_iter():
                while not done[0]:
                    try:
                        yield reqs.get(timeout=0.2)
                    except _queue.Empty:
                        continue

            call = ch.stream_stream(xds_v3.METHOD)(req_iter(), timeout=30)
            it = iter(call)
            first = xds_v3.decode_discovery_response(bytes(next(it)))
            assert first["assignments"]["clu"] == ["10.1.1.1:443"]
            assert first["type_url"] == xds_v3.CLA_TYPE_URL
            assert first["nonce"]
            # ACK, then a control-plane update must arrive as a second
            # DiscoveryResponse on the SAME stream
            reqs.put(xds_v3.encode_discovery_request(
                ["clu"], version_info=first["version_info"],
                response_nonce=first["nonce"], node_id="raw-client"))
            xds.set_endpoints("clu", ["10.1.1.2:444"])
            second = xds_v3.decode_discovery_response(bytes(next(it)))
            assert second["assignments"]["clu"] == ["10.1.1.2:444"]
            assert second["nonce"] != first["nonce"]
            done[0] = True
            call.cancel()
    finally:
        cp.stop(grace=0)


def test_ads_lite_feature_flag_selects_legacy_wire(monkeypatch):
    """bootstrap server_features ["ads_lite"] keeps the round-4 JSON wire
    working (mixed-version compat)."""
    backend, bport = _echo_server(b"lite")
    xds, cp, cport = _control_plane()
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{bport}"])
        monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
            {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}",
                              "server_features": ["ads_lite"]}]}))
        monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
        with rpc.Channel("xds:///svc") as ch:
            assert ch.unary_unary("/x.S/Who")(b"", timeout=15) == b"lite"
    finally:
        cp.stop(grace=0)
        backend.stop(grace=0)


def test_xds_watcher_keeps_last_assignment_on_control_plane_loss(monkeypatch):
    """Control-plane death must NOT churn a working assignment (gRPC's
    xds behavior): calls keep flowing to the last applied endpoints."""
    b1, p1 = _echo_server(b"b1")
    xds, cp, cport = _control_plane()
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}]}))
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{p1}"])
        ch, watcher = xds_channel("xds:///svc")
        try:
            who = ch.unary_unary("/x.S/Who")
            assert who(b"", timeout=15) == b"b1"
            cp.stop(grace=0)  # control plane goes away
            time.sleep(0.5)
            for _ in range(5):  # membership unchanged; calls keep working
                assert who(b"", timeout=15) == b"b1"
        finally:
            watcher.stop()
            ch.close()
    finally:
        b1.stop(grace=0)


def test_ads_v3_decoder_robust_to_garbage():
    """Truncation raises ValueError (protowire's corruption contract);
    unknown fields and foreign Any types are skipped, never crashes —
    a real control plane populates far more of these messages than the
    subset tpurpc consumes."""
    import random

    from tpurpc.rpc import xds_v3
    from tpurpc.wire.protowire import encode_varint, ld, vf

    rng = random.Random(5)
    for _ in range(200):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        try:
            xds_v3.decode_discovery_response(blob)
            xds_v3.decode_discovery_request(blob)
            xds_v3.decode_cluster_load_assignment(blob)
        except ValueError:
            pass  # truncation/corruption: the documented loud outcome
    # unknown fields interleaved with known ones decode fine — including
    # a multi-byte tag (field 1000, what a future envoy proto could use)
    big_tag_field = encode_varint((1000 << 3) | 0) + encode_varint(5)
    body = (ld(1, b"v9") + vf(29, 7) + ld(30, b"future-field")
            + big_tag_field
            + ld(5, b"n1") + ld(4, xds_v3.CLA_TYPE_URL.encode()))
    out = xds_v3.decode_discovery_response(body)
    assert out["version_info"] == "v9" and out["nonce"] == "n1"
    # a non-CLA Any resource is skipped, not an error
    foreign = ld(2, ld(1, b"type.googleapis.com/envoy.Listener") + ld(2, b"x"))
    out = xds_v3.decode_discovery_response(foreign + ld(5, b"n2"))
    assert out["assignments"] == {} and out["nonce"] == "n2"
