"""xDS-lite: bootstrap, the xds: resolver, and EDS-style dynamic updates.

The reference's xds client_channel family
(``ext/filters/client_channel/resolver/xds/``, ``lb_policy/xds/``) scoped
to tpurpc's lite shim (tpurpc/rpc/xds.py): gRPC's bootstrap/target UX over
tpurpc's own ADS-lite wire, feeding Channel.update_addresses.
"""

import json
import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.xds import (XdsServicer, XdsWatcher, load_bootstrap,
                            xds_channel)


def _echo_server(tag: bytes):
    srv = rpc.Server(max_workers=2)
    srv.add_method("/x.S/Who",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: tag))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _control_plane():
    xds = XdsServicer()
    srv = rpc.Server(max_workers=4)
    xds.attach(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return xds, srv, port


def test_bootstrap_parsing(monkeypatch, tmp_path):
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP_CONFIG", raising=False)
    with pytest.raises(RuntimeError):
        load_bootstrap()  # no bootstrap configured: loud
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG",
                       json.dumps({"xds_servers": []}))
    with pytest.raises(RuntimeError):
        load_bootstrap()  # malformed: needs server_uri
    # inline config works; a FILE wins over it (gRPC precedence)
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": "inline:1"}]}))
    assert load_bootstrap()["xds_servers"][0]["server_uri"] == "inline:1"
    bs = tmp_path / "bootstrap.json"
    bs.write_text(json.dumps({"xds_servers": [{"server_uri": "file:2"}],
                              "node": {"id": "n1"}}))
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP", str(bs))
    cfg = load_bootstrap()
    assert cfg["xds_servers"][0]["server_uri"] == "file:2"
    assert cfg["node"]["id"] == "n1"


def test_xds_target_resolves_via_control_plane(monkeypatch):
    """Channel("xds:///svc") works like grpcio's: bootstrap names the
    control plane, the resolver fetches the current EDS assignment."""
    backend, bport = _echo_server(b"b1")
    xds, cp, cport = _control_plane()
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{bport}"])
        monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
            {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}],
             "node": {"id": "test-node"}}))
        monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
        with rpc.Channel("xds:///svc") as ch:
            assert ch.unary_unary("/x.S/Who")(b"", timeout=15) == b"b1"
        # empty assignment: loud resolution failure, not a hang
        with pytest.raises(Exception):
            rpc.Channel("xds:///nonexistent-svc")
    finally:
        cp.stop(grace=0)
        backend.stop(grace=0)


def test_xds_watcher_moves_traffic_on_eds_update(monkeypatch):
    """set_endpoints (the EDS update) re-points a live channel: the
    watcher feeds update_addresses; calls land on the new backend."""
    b1, p1 = _echo_server(b"b1")
    b2, p2 = _echo_server(b"b2")
    xds, cp, cport = _control_plane()
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}]}))
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{p1}"])
        ch, watcher = xds_channel("xds:///svc")
        try:
            who = ch.unary_unary("/x.S/Who")
            assert who(b"", timeout=15) == b"b1"
            # hostname endpoint on purpose: the watcher must normalize it
            # the same way the channel's keep-live matching does (a raw
            # string would mismatch the resolved keys and churn live
            # connections on every identical push)
            xds.set_endpoints("svc", [f"localhost:{p2}"])
            deadline = time.monotonic() + 10
            seen = b""
            while time.monotonic() < deadline:
                try:
                    seen = who(b"", timeout=15)
                except rpc.RpcError as exc:
                    # a call racing the membership swap may land on the
                    # closing backend once (update_addresses' documented
                    # contract) — the next call re-dials
                    if exc.code() is not rpc.StatusCode.UNAVAILABLE:
                        raise
                if seen == b"b2":
                    break
                time.sleep(0.05)
            assert seen == b"b2", "EDS update never moved traffic"
            assert watcher.applied_versions, "watcher applied no update"
        finally:
            watcher.stop()
            ch.close()
    finally:
        cp.stop(grace=0)
        b1.stop(grace=0)
        b2.stop(grace=0)


def test_xds_watcher_keeps_last_assignment_on_control_plane_loss(monkeypatch):
    """Control-plane death must NOT churn a working assignment (gRPC's
    xds behavior): calls keep flowing to the last applied endpoints."""
    b1, p1 = _echo_server(b"b1")
    xds, cp, cport = _control_plane()
    monkeypatch.setenv("GRPC_XDS_BOOTSTRAP_CONFIG", json.dumps(
        {"xds_servers": [{"server_uri": f"127.0.0.1:{cport}"}]}))
    monkeypatch.delenv("GRPC_XDS_BOOTSTRAP", raising=False)
    try:
        xds.set_endpoints("svc", [f"127.0.0.1:{p1}"])
        ch, watcher = xds_channel("xds:///svc")
        try:
            who = ch.unary_unary("/x.S/Who")
            assert who(b"", timeout=15) == b"b1"
            cp.stop(grace=0)  # control plane goes away
            time.sleep(0.5)
            for _ in range(5):  # membership unchanged; calls keep working
                assert who(b"", timeout=15) == b"b1"
        finally:
            watcher.stop()
            ch.close()
    finally:
        b1.stop(grace=0)
