"""TPU data plane: copy ledger accounting, HBM ring leases, device serialization."""

import numpy as np
import pytest

from tpurpc.tpu import HbmRing, ledger
from tpurpc.tpu.serialize import deserialize_to_device, serialize_from_device


# -- ledger ------------------------------------------------------------------

def test_ledger_track_window():
    with ledger.track() as w:
        ledger.host_copy(100)
        ledger.dma_h2d(40)
    assert w["host_copy"] == 100 and w["dma_h2d"] == 40 and w["dma_d2h"] == 0


def test_rpc_path_reports_to_ledger():
    """An end-to-end tensor RPC over loopback rings reports its copies."""
    import jax

    from tpurpc.jaxshim import TensorClient, serve_jax
    from tpurpc.rpc.channel import Channel

    srv, port, _ = serve_jax(lambda t: t, "127.0.0.1:0")
    try:
        x = np.ones((256, 256), np.float32)  # 256KiB — AT the rendezvous bar
        with Channel(f"127.0.0.1:{port}") as ch, ledger.track() as w:
            TensorClient(ch).call("Call", {"x": x}, timeout=30)
        # request+response cross the wire: every payload byte's movement
        # must be visible and bounded (no hidden O(n) blowup). Since
        # tpurpc-express (ISSUE 9), payloads at/over the size bar move as
        # one-sided rendezvous writes (rdma_write) instead of framed
        # assembly copies (host_copy) — a racing first-message hello may
        # still frame a direction, so the TOTAL movement is the invariant.
        moved = w["host_copy"] + w["rdma_write"]
        assert moved >= 2 * x.nbytes
        assert moved <= 8 * x.nbytes
    finally:
        srv.stop(grace=0)


# -- serialize ---------------------------------------------------------------

def test_serialize_from_device_roundtrip():
    import jax.numpy as jnp

    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    with ledger.track() as w:
        segs = serialize_from_device(x)
    assert w["dma_d2h"] == 0  # host backend: no movement
    assert w["zero_copy"] == x.nbytes
    buf = b"".join(bytes(s) for s in segs)
    y, end = deserialize_to_device(buf)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_deserialize_counts_alias_on_host_backend():
    from tpurpc.jaxshim import codec

    x = np.arange(1024, dtype=np.float32)
    buf = bytearray(codec.encode_tensor_bytes(x))  # writable → dlpack alias
    with ledger.track() as w:
        y, _ = deserialize_to_device(buf)
    assert w["zero_copy"] >= x.nbytes
    assert w["host_copy"] == 0
    np.testing.assert_array_equal(np.asarray(y), x)


# -- HBM ring ----------------------------------------------------------------

def test_hbm_ring_place_view_roundtrip():
    ring = HbmRing(1 << 16)
    x = np.arange(512, dtype=np.float32)
    off, n = ring.place(x)
    with ring.view(off, n, np.float32, (512,)) as arr:
        np.testing.assert_array_equal(np.asarray(arr), x)


def test_hbm_ring_wrap_and_reuse():
    cap = 1 << 12  # 4KiB ring
    ring = HbmRing(cap)
    rng = np.random.default_rng(0)
    for i in range(10):  # 10 x 1.5KiB through a 4KiB ring forces wraps
        x = rng.standard_normal(384).astype(np.float32)  # 1536B
        off, n = ring.place(x)
        lease = ring.view(off, n, np.float32, (384,))
        np.testing.assert_array_equal(np.asarray(lease.array), x)
        lease.release()
    st = ring.stats()
    assert st["live_spans"] == 0 and st["writable"] == cap


def test_hbm_ring_lease_pins_span():
    ring = HbmRing(1 << 12)
    x = np.ones(256, np.float32)  # 1KiB
    off, n = ring.place(x)
    lease = ring.view(off, n)
    ring.place(x)  # second message fits
    before = ring.stats()["writable"]
    lease2 = ring.view(off, n)      # second lease on the same span
    lease.release()
    assert ring.stats()["writable"] == before  # still pinned by lease2
    lease2.release()
    assert ring.stats()["writable"] > before   # first span freed


def test_hbm_ring_full_raises():
    ring = HbmRing(1 << 12)
    with pytest.raises(BufferError):
        ring.place(np.zeros(5000, np.uint8))


def test_hbm_ring_ordered_head_advance():
    """Later spans released first must not advance the head past an earlier
    still-unconsumed span (credit ordering, pair.cc:276-284 analog)."""
    ring = HbmRing(1 << 12)
    a = ring.place(np.ones(128, np.uint8))
    b = ring.place(np.ones(128, np.uint8))
    lb = ring.view(*b)
    lb.release()
    assert ring.stats()["head"] == 0  # span a not consumed yet
    la = ring.view(*a)
    la.release()
    assert ring.stats()["head"] == a[1] + b[1]


def test_view_unwrapped_is_dlpack_alias_zero_copy():
    """Round-5 north star half two (VERDICT r4 next #3): an unwrapped span's
    view ALIASES ring memory — ledger zero_copy, no view-side d2d, and the
    aliasing is pointer-verifiable, not asserted on faith."""
    ring = HbmRing(1 << 16)
    x = np.arange(1024, dtype=np.float32)
    off, n = ring.place(x)
    with ledger.track() as w:
        lease = ring.view(off, n, np.float32, (1024,))
    assert lease.aliased, "CPU-backed unwrapped view should be a dlpack alias"
    assert w["zero_copy"] == x.nbytes and w["zero_copy_ops"] == 1
    assert w["dma_d2d"] == 0 and w["dma_d2d_ops"] == 0
    np.testing.assert_array_equal(np.asarray(lease.array), x)
    # independent pointer proof (same introspection chipcheck uses)
    ring_ptr = ring._ptr_of(ring.buf)
    view_ptr = ring._ptr_of(lease.array)
    if ring_ptr is not None and view_ptr is not None:
        assert view_ptr == ring_ptr + (off & (ring.capacity - 1))
    lease.release()
    assert ring._aliased == 0


def test_view_alias_survives_later_placements():
    """The stability invariant in practice: placements donate/rebind the
    ring while an aliased lease is live; the lease's bytes must stay
    correct (the allocation is reused in place, and place() asserts it)."""
    ring = HbmRing(1 << 14)
    x = np.arange(512, dtype=np.float32)
    off, n = ring.place(x)
    lease = ring.view(off, n, np.float32, (512,))
    assert lease.aliased
    for i in range(6):  # further traffic through the ring
        o2, n2 = ring.place(np.full(256, i, np.float32))
        ring.view(o2, n2).release()
    np.testing.assert_array_equal(np.asarray(lease.array), x)
    lease.release()


def test_view_wrapped_span_billed_as_d2d():
    """A wrapped span cannot alias (two discontiguous segments): the view
    is a materialization and the ledger must say so."""
    cap = 1 << 12
    ring = HbmRing(cap)
    filler = ring.place(np.zeros(900, np.uint8))
    ring.view(*filler).release()
    big = np.arange(900, dtype=np.float32)  # 3600B from offset 900: wraps
    off, n = ring.place(big)
    assert (off & (cap - 1)) + n > cap, "span did not wrap"
    with ledger.track() as w:
        lease = ring.view(off, n, np.float32, (900,))
    assert not lease.aliased
    assert w["zero_copy"] == 0 and w["dma_d2d"] >= n
    np.testing.assert_array_equal(np.asarray(lease.array), big)
    lease.release()


def test_view_failure_does_not_leak_credit():
    """A poison view request (dtype/shape inconsistent with nbytes —
    wire-reachable through decode_tensor_to_ring's header) must raise
    WITHOUT pinning the span: credit accounting survives, and a correct
    view of the same span still works (reviewer finding, round 5)."""
    ring = HbmRing(1 << 12)
    off, n = ring.place(np.arange(10, dtype=np.uint8))  # 10 bytes
    with pytest.raises(Exception):
        ring.view(off, n, np.float32)  # 10 % 4 != 0: shaping must fail
    # the failed attempt took no lease: a real consume-and-release drains it
    lease = ring.view(off, n)
    assert bytes(np.asarray(lease.array)) == bytes(range(10))
    lease.release()
    st = ring.stats()
    assert st["live_spans"] == 0 and st["head"] == st["tail"]


def test_view_alias_env_opt_out(monkeypatch):
    monkeypatch.setenv("TPURPC_DLPACK_VIEW", "0")
    ring = HbmRing(1 << 14)
    off, n = ring.place(np.ones(256, np.float32))
    with ledger.track() as w:
        lease = ring.view(off, n, np.float32, (256,))
    assert not lease.aliased and w["zero_copy"] == 0 and w["dma_d2d"] == n
    lease.release()


def test_end_to_end_rx_into_hbm_ring_zero_host_copy_after_assembly():
    """North-star shape: wire buffer → HBM placement → device view, with the
    ledger proving no host memcpy after frame assembly."""
    from tpurpc.jaxshim import codec

    x = np.arange(4096, dtype=np.float32)
    wire = bytearray(codec.encode_tensor_bytes(x))
    arr_view, _ = codec.decode_tensor(wire)      # zero-copy parse

    ring = HbmRing(1 << 16)
    with ledger.track() as w:
        off, n = ring.place(arr_view.view(np.uint8))
        with ring.view(off, n, np.float32, (4096,)) as dev:
            np.testing.assert_array_equal(np.asarray(dev), x)
    assert w["host_copy"] == 0
    assert w["dma_h2d"] == x.nbytes


def test_place_is_single_landing_write_all_spans():
    """VERDICT r3 next#6: every placement must be exactly ONE in-ring
    landing write (dma_d2d op), wrapped or not — the reference's placement
    is always one RDMA WRITE (pair.cc:587-622). The op-count ledger makes
    it assertable; on kernel-ineligible configs the fallback chain pays
    two writes for wrapped spans and the ledger says so honestly."""
    ring = HbmRing(32768)  # >= the kernel's 2*9*512 floor

    # unwrapped span
    with ledger.track() as w:
        off, n = ring.place(bytes(range(256)) * 16)  # 4KiB, fits at 0
    assert (w["dma_h2d_ops"], w["dma_d2d_ops"]) == (1, 1), w.delta
    lease = ring.view(off, n)
    assert bytes(np.asarray(lease.array)) == bytes(range(256)) * 16
    lease.release()

    # drive tail near the end so the next span WRAPS
    filler = 32768 - (ring.tail & (32768 - 1)) - 2048
    off2, n2 = ring.place(b"\0" * filler)
    ring.view(off2, n2).release()
    payload = bytes(range(256)) * 16  # 4KiB > the 2KiB left before the edge
    with ledger.track() as w:
        off3, n3 = ring.place(payload)
    assert (off3 & (32768 - 1)) + n3 > 32768, "span did not wrap"
    # kernel-eligible configs land the wrap in ONE aliased write; on
    # fallback configs (TPURPC_PALLAS=0, non-cpu/tpu backends, or a
    # latched kernel failure) the chain pays two and the ledger says so
    kernel = (not getattr(ring, "_pallas_place_broken", False)
              and ring._pallas_ok(off3 & (32768 - 1), n3, 2 * 9 * 512,
                                  "_pallas_place_broken"))
    expect = 1 if kernel else 2
    assert (w["dma_h2d_ops"], w["dma_d2d_ops"]) == (1, expect), w.delta
    lease3 = ring.view(off3, n3)
    assert bytes(np.asarray(lease3.array)) == payload
    lease3.release()
