"""tpurpc-keystone (ISSUE 11): disaggregated prefill/decode + migration.

The handoff protocol end-to-end (prefill tier computes KV, blocks land
one-sided in the decode arena, client re-attaches and streams exact
tokens), prefix-cache hits across the wire (shipped bytes shrink), live
migration between decode servers with index/value continuity, the
drain-hook wiring, registry reaping (pending => quarantine, parked =>
free), and the chaos satellite: decode-server death mid-migration fails
the sequence ALONE with UNAVAILABLE — never a hang — and the dead
handoff's blocks are quarantined, never reused. On TCP and RDMA_BPEV."""

import threading
import time

import numpy as np
import pytest

import tpurpc.serving.disagg as disagg
from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
from tpurpc.analysis import protocol
from tpurpc.obs import flight
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.status import RpcError, StatusCode
from tpurpc.serving import (DisaggClient, migrate, serve_decode,
                            serve_prefill)
from tpurpc.serving.scheduler import TokenStream
from tpurpc.tpu import ledger


@pytest.fixture(autouse=True)
def _fast_streams():
    old = TokenStream.MAX_IDLE_S
    TokenStream.MAX_IDLE_S = 10.0
    yield
    TokenStream.MAX_IDLE_S = old
    disagg.TEST_HOOKS.clear()


def _poll(pred, timeout=8.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return pred()


class _Stack:
    """One prefill + N decode servers with channels, torn down in order."""

    def __init__(self, n_decode=1, step_delay_s=0.0, **decode_kw):
        decode_kw.setdefault("kv_blocks", 128)
        decode_kw.setdefault("block_bytes", 256)
        self.decodes = []
        for i in range(n_decode):
            srv, port, sched, state = serve_decode(
                ToyDecodeModel(step_delay_s=step_delay_s),
                name=f"dec{i}", **decode_kw)
            self.decodes.append((srv, port, sched, state))
        self.d_ch = Channel(f"127.0.0.1:{self.decodes[0][1]}")
        self.p_srv, self.p_port, self.p_state = serve_prefill(
            ToyDecodeModel(), self.d_ch,
            f"127.0.0.1:{self.decodes[0][1]}")
        self.p_ch = Channel(f"127.0.0.1:{self.p_port}")
        self.client = DisaggClient(self.p_ch,
                                   f"127.0.0.1:{self.decodes[0][1]}")

    def close(self):
        self.client.close()
        self.p_srv.stop(grace=0)
        self.p_state.close()
        for srv, _port, sched, state in self.decodes:
            srv.stop(grace=0)
            sched.close()
            state.close()
            state.mgr.close()
        self.p_ch.close()
        self.d_ch.close()


# -- the handoff end-to-end ---------------------------------------------------

def test_disagg_stream_exact_tokens_and_ship_accounting():
    st = _Stack()
    try:
        prompt = list(range(20))
        with ledger.track() as w:
            pairs = list(st.client.generate_with_meta(prompt,
                                                      max_tokens=12,
                                                      timeout=20))
        assert [i for i, _ in pairs] == list(range(12))
        assert [t for _, t in pairs] == reference_decode(prompt, 12)
        # 21 entries of 16 bytes went one-sided into the decode arena
        assert w["rdma_write"] >= 21 * 16, w.delta
        snap = flight.snapshot()
        protocol.assert_ordered(snap, ["kv-ship-offer",
                                       "kv-ship-complete"])
        assert protocol.check_events(snap, strict=False) == []
    finally:
        st.close()


def test_disagg_repeated_prompt_scores_prefix_hit_and_ships_less():
    st = _Stack()
    try:
        prompt = list(range(32))   # 33 entries; aligned span = 32
        list(st.client.generate(prompt, max_tokens=4, timeout=20))
        shipped_cold = st.p_state.shipped_bytes
        list(st.client.generate(prompt, max_tokens=4, timeout=20))
        shipped_warm = st.p_state.shipped_bytes - shipped_cold
        _srv, _port, _sched, state = st.decodes[0]
        assert state.prefix_hits >= 1, state.stats()
        assert st.p_state.prefix_skipped_entries >= 32
        # only the uncached tail shipped the second time
        assert shipped_warm < shipped_cold, (shipped_warm, shipped_cold)
        assert shipped_warm == 16  # exactly the first-token entry
    finally:
        st.close()


def test_disagg_concurrent_streams_no_crosstalk():
    st = _Stack(step_delay_s=0.001)
    try:
        out = {}

        def run(i):
            out[i] = list(st.client.generate([i, i], max_tokens=10,
                                             timeout=20))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
        for i in range(5):
            assert out[i] == reference_decode([i, i], 10), i
    finally:
        st.close()


def test_resume_unknown_seq_is_not_found():
    st = _Stack()
    try:
        from tpurpc.jaxshim import codec

        mc = st.client._channel(
            f"127.0.0.1:{st.decodes[0][1]}").unary_stream(
            "/tpurpc.Kv/ResumeSeq", codec.tree_serializer,
            codec.tree_deserializer)
        with pytest.raises(RpcError) as ei:
            list(mc({"seq_key": np.int64(424242),
                     "max_tokens": np.int32(4)}, timeout=10))
        assert ei.value.code() is StatusCode.NOT_FOUND
    finally:
        st.close()


def test_reap_pending_quarantines_parked_frees():
    st = _Stack(pending_ttl_s=0.05, parked_ttl_s=0.05)
    try:
        _srv, port, _sched, state = st.decodes[0]
        mgr = state.mgr
        # a parked sequence nobody resumes: prefill only (max_tokens big,
        # but never call ResumeSeq)
        from tpurpc.jaxshim import codec

        pre = st.p_ch.unary_unary("/tpurpc.Kv/Prefill",
                                  codec.tree_serializer,
                                  codec.tree_deserializer)
        pre({"prompt": np.asarray([1, 2, 3], np.int32)}, timeout=10)
        assert state.stats()["parked"] == 1
        # a pending handoff whose sender vanished: offer, never complete
        offer = st.p_ch  # reuse transports? offer directly to decode
        och = st.client._channel(f"127.0.0.1:{port}")
        omc = och.unary_unary("/tpurpc.Kv/OfferKv", codec.tree_serializer,
                              codec.tree_deserializer)
        resp = omc({"seq_key": np.int64(777),
                    "prompt": np.asarray([9, 9], np.int32),
                    "n_tokens": np.int32(3)}, timeout=10)
        assert int(np.asarray(resp["ok"]).ravel()[0]) == 1
        assert state.stats()["pending"] == 1
        time.sleep(0.1)
        nq, nf = state.reap()
        assert nq >= 1, "pending handoff blocks were not quarantined"
        assert nf >= 1, "parked sequence was not freed"
        assert mgr.quarantined_count() >= 1
        # parked blocks came BACK (freed), pending blocks did NOT
        assert state.stats()["pending"] == 0
        assert state.stats()["parked"] == 0
    finally:
        st.close()


# -- live migration -----------------------------------------------------------

def test_migration_continues_stream_exact_on_peer():
    flight.RECORDER.reset()
    st = _Stack(n_decode=2, step_delay_s=0.003)
    try:
        a = st.decodes[0]
        b = st.decodes[1]
        b_ch = Channel(f"127.0.0.1:{b[1]}")
        out = {}

        def run():
            out["pairs"] = list(st.client.generate_with_meta(
                [5, 6], max_tokens=50, timeout=30))

        t = threading.Thread(target=run)
        t.start()
        assert _poll(lambda: a[2].running_depth() > 0)
        time.sleep(0.03)
        moved, failed = migrate(a[3], b_ch, f"127.0.0.1:{b[1]}")
        t.join(30)
        assert (moved, failed) == (1, 0)
        pairs = out["pairs"]
        assert [i for i, _ in pairs] == list(range(50))
        assert [v for _, v in pairs] == reference_decode([5, 6], 50)
        assert b[2].tokens_out > 0, "peer never stepped the migrated seq"
        snap = flight.snapshot()
        protocol.assert_ordered(snap, ["migration-begin",
                                       ("migration-end", {"a2": 1})])
        assert protocol.check_events(snap, strict=False) == []
        # the source arena let go of the sequence (prefix cache may hold
        # the block-aligned prompt span; [5,6] is below the span bar)
        assert _poll(lambda: a[3].mgr.used_count() == 0), a[3].mgr.stats()
        b_ch.close()
    finally:
        st.close()


def test_drain_hook_migrates_live_streams():
    """Server.drain on a decode server with migrate_to wired moves live
    sequences to the peer — the zero-failed-RPC drain, stateful
    edition."""
    b_srv, b_port, b_sched, b_state = serve_decode(
        ToyDecodeModel(step_delay_s=0.003), name="drainB",
        kv_blocks=128, block_bytes=256)
    b_ch = Channel(f"127.0.0.1:{b_port}")
    a_srv, a_port, a_sched, a_state = serve_decode(
        ToyDecodeModel(step_delay_s=0.003), name="drainA",
        kv_blocks=128, block_bytes=256,
        migrate_to=lambda: (b_ch, f"127.0.0.1:{b_port}"))
    a_ch = Channel(f"127.0.0.1:{a_port}")
    p_srv, p_port, p_state = serve_prefill(
        ToyDecodeModel(), a_ch, f"127.0.0.1:{a_port}")
    p_ch = Channel(f"127.0.0.1:{p_port}")
    cli = DisaggClient(p_ch, f"127.0.0.1:{a_port}")
    try:
        out = {}

        def run():
            out["pairs"] = list(cli.generate_with_meta(
                [3, 3], max_tokens=40, timeout=30))

        t = threading.Thread(target=run)
        t.start()
        assert _poll(lambda: a_sched.running_depth() > 0)
        time.sleep(0.03)
        a_srv.drain(linger=10.0)
        t.join(30)
        pairs = out["pairs"]
        assert [i for i, _ in pairs] == list(range(40))
        assert [v for _, v in pairs] == reference_decode([3, 3], 40)
        assert b_sched.tokens_out > 0, "drain did not migrate the stream"
    finally:
        cli.close()
        p_srv.stop(grace=0)
        p_state.close()
        a_srv.stop(grace=0)
        b_srv.stop(grace=0)
        a_sched.close()
        b_sched.close()
        a_state.close()
        b_state.close()
        a_state.mgr.close()
        b_state.mgr.close()
        for ch in (p_ch, a_ch, b_ch):
            ch.close()


# -- chaos: decode-server death mid-migration (the satellite) -----------------

@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_decode_death_mid_migration_fails_alone_and_quarantines(
        monkeypatch, platform):
    """Kill the migration TARGET between the one-sided block writes and
    the COMPLETE frame: the migrating sequence fails ALONE with
    UNAVAILABLE (never hangs), sibling streams on the source finish
    exactly, and the target's claimed blocks are QUARANTINED — never
    reused (the modeled reuse-before-quarantine rule, live)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    flight.RECORDER.reset()
    st = _Stack(n_decode=2, step_delay_s=0.003,
                pending_ttl_s=0.2)
    b_ch = None
    try:
        a = st.decodes[0]
        b = st.decodes[1]
        b_ch = Channel(f"127.0.0.1:{b[1]}")
        out = {}

        def run(key, prompt, n):
            try:
                out[key] = ("ok", list(st.client.generate_with_meta(
                    prompt, max_tokens=n, timeout=30)))
            except RpcError as exc:
                out[key] = ("err", exc)

        t1 = threading.Thread(target=run, args=("victim", [5, 6], 200))
        t1.start()
        assert _poll(lambda: a[2].running_depth() > 0)
        t2 = threading.Thread(target=run, args=("sibling", [7], 30))
        t2.start()
        assert _poll(lambda: a[2].running_depth() > 1)
        # wedge every shipper between write and complete, then migrate
        wedge = threading.Event()
        disagg.TEST_HOOKS["wedge_before_complete"] = wedge
        mig = {}

        def do_migrate():
            mig["r"] = migrate(a[3], b_ch, f"127.0.0.1:{b[1]}",
                               sids=[1], timeout_s=5.0)

        mt = threading.Thread(target=do_migrate)
        mt.start()
        # the target holds a PENDING handoff (blocks claimed, written,
        # not completed) — now it dies
        assert _poll(lambda: b[3].stats()["pending"] >= 1), b[3].stats()
        pending_blocks = b[3].mgr.used_count()
        assert pending_blocks > 0
        b[0].stop(grace=0)
        wedge.set()
        mt.join(20)
        assert not mt.is_alive(), "migration hung on a dead peer"
        moved, failed = mig["r"]
        assert moved == 0 and failed == 1
        # the victim failed ALONE with UNAVAILABLE...
        t1.join(20)
        assert not t1.is_alive(), "victim stream hung"
        kind, payload = out["victim"]
        assert kind == "err", payload
        assert payload.code() is StatusCode.UNAVAILABLE, payload
        # ...its sibling finished exactly...
        t2.join(20)
        kind, payload = out["sibling"]
        assert kind == "ok", payload
        assert [v for _, v in payload] == reference_decode([7], 30)
        # ...and the dead target's claimed blocks are quarantined, never
        # back on the free list
        time.sleep(0.25)
        nq, _nf = b[3].reap()
        assert nq >= 1, "dead handoff's blocks were not quarantined"
        assert b[3].mgr.quarantined_count() >= 1
        assert b[3].mgr.free_count() + b[3].mgr.used_count() \
            + b[3].mgr.quarantined_count() == b[3].mgr.n_blocks
        # the failed migration closed its bracket (a2=0 in MIG_END) and
        # the dead handoff's blocks left circulation — per-entity
        # legality via the declared machines, order via the one helper
        snap = flight.snapshot()
        protocol.assert_ordered(snap, ["migration-begin",
                                       ("migration-end", {"a2": 0})])
        protocol.assert_ordered(snap, ["kv-quarantine"])
        assert protocol.check_events(snap, strict=False) == []
    finally:
        if b_ch is not None:
            b_ch.close()
        st.close()
