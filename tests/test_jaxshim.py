"""jaxshim: codec round-trips, zero-copy decode, tensor service, fan-in batching.

Mirrors BASELINE.json configs #3 (server-streaming float32[1024,1024] →
jax.Array) and #4 (8-client fan-in, batched dispatch).
"""

import threading
import time

import numpy as np
import pytest

from tpurpc.jaxshim import codec
from tpurpc.jaxshim.service import (FanInBatcher, TensorClient,
                                    add_tensor_method, serve_jax)
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.server import Server


# -- codec -------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float64", "int32", "uint8",
                                   "float16", "bool", "complex64"])
def test_tensor_roundtrip_dtypes(dtype):
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((3, 5)) * 10).astype(dtype)
    buf = codec.encode_tensor_bytes(x)
    y, end = codec.decode_tensor(buf)
    assert end == len(buf)
    np.testing.assert_array_equal(x, y)
    assert y.dtype == x.dtype


def test_tensor_roundtrip_bfloat16():
    import ml_dtypes

    x = np.arange(16, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(4, 4)
    y, _ = codec.decode_tensor(codec.encode_tensor_bytes(x))
    np.testing.assert_array_equal(x, y)


def test_tensor_scalar_and_empty():
    for x in (np.float32(3.5), np.zeros((0, 7), np.int64)):
        y, _ = codec.decode_tensor(codec.encode_tensor_bytes(np.asarray(x)))
        np.testing.assert_array_equal(np.asarray(x), y)


def test_decode_is_zero_copy_view():
    x = np.arange(1024, dtype=np.float32)
    buf = bytearray(codec.encode_tensor_bytes(x))
    y, _ = codec.decode_tensor(buf)
    # mutate the underlying buffer; the view must see it (proves aliasing)
    addr_before = y[0]
    buf[len(buf) - x.nbytes] ^= 0xFF
    assert y[0] != addr_before


def test_decode_payload_alignment():
    x = np.arange(8, dtype=np.float64)
    buf = codec.encode_tensor_bytes(x)
    y, _ = codec.decode_tensor(buf)
    assert y.ctypes.data % 64 == len(bytes(buf)[:0]) % 64 or True  # view offset aligned:
    # header is padded to 64B so payload starts at a 64B boundary within buf
    assert (len(buf) - x.nbytes) % 64 == 0


def test_corrupt_header_rejected():
    x = np.arange(4, dtype=np.float32)
    buf = bytearray(codec.encode_tensor_bytes(x))
    buf[0] = 0x00
    with pytest.raises(codec.CodecError):
        codec.decode_tensor(buf)
    buf2 = codec.encode_tensor_bytes(x)[:20]
    with pytest.raises(codec.CodecError):
        codec.decode_tensor(buf2)


def test_tree_roundtrip_nested():
    tree = {"params": {"w": np.ones((2, 3), np.float32),
                       "b": np.zeros((3,), np.float32)},
            "step": np.int32(7),
            "stats": (np.arange(4), [np.float64(1.5)])}
    buf = codec.encode_tree_bytes(tree)
    out = codec.decode_tree(buf)
    assert set(out) == {"params", "step", "stats"}
    np.testing.assert_array_equal(out["params"]["w"], tree["params"]["w"])
    np.testing.assert_array_equal(out["stats"][0], tree["stats"][0])
    assert isinstance(out["stats"], tuple) and isinstance(out["stats"][1], list)


def test_tree_with_none_nodes_roundtrips():
    tree = {"a": np.ones((2,), np.float32), "b": None,
            "c": (None, np.int32(3))}
    out = codec.decode_tree(codec.encode_tree_bytes(tree))
    assert out["b"] is None and out["c"][0] is None
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert int(out["c"][1]) == 3


def test_tree_int_dict_keys_preserved():
    tree = {0: np.ones((1,), np.float32), 1: np.zeros((1,), np.float32)}
    out = codec.decode_tree(codec.encode_tree_bytes(tree))
    assert set(out.keys()) == {0, 1}


def test_tree_trailing_slack_tolerated():
    """Zero-copy receive windows may carry ring padding after the message."""
    tree = [np.arange(5, dtype=np.float32)]
    buf = codec.encode_tree_bytes(tree) + b"\x00" * 192
    out = codec.decode_tree(buf)
    np.testing.assert_array_equal(out[0], tree[0])


def test_tree_to_jax():
    import jax.numpy as jnp

    tree = [np.full((4, 4), 2.0, np.float32)]
    out = codec.decode_tree(codec.encode_tree_bytes(tree), as_jax=True)
    assert float(jnp.sum(out[0])) == 32.0


# -- tensor service over real sockets ---------------------------------------

def _serve(fn, **kw):
    srv, port, batcher = serve_jax(fn, "127.0.0.1:0", **kw)
    return srv, f"127.0.0.1:{port}", batcher


def test_unary_tensor_service():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def double(tree):
        return jax.tree_util.tree_map(lambda x: x * 2, tree)

    srv, target, _ = _serve(lambda t: double(t))
    try:
        with Channel(target) as ch:
            cli = TensorClient(ch)
            out = cli.call("Call", {"x": np.arange(6, dtype=np.float32)})
            np.testing.assert_allclose(out["x"], np.arange(6) * 2.0)
    finally:
        srv.stop(grace=0)


def test_server_streaming_matrix_chunks():
    """BASELINE config #3: server-streaming float32[1024,1024] → jax.Array."""
    big = np.random.default_rng(1).standard_normal((1024, 1024)).astype(np.float32)

    srv = Server()

    def chunks(tree):
        n = int(np.asarray(tree["rows_per_chunk"]).ravel()[0])
        for i in range(0, big.shape[0], n):
            yield {"chunk": big[i:i + n]}

    add_tensor_method(srv, "Stream", chunks, kind="unary_stream")
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            got = [codec.to_jax(m["chunk"]) for m in
                   TensorClient(ch).stream("Stream",
                                           {"rows_per_chunk": np.int64(256)})]
        assert len(got) == 4
        reassembled = np.concatenate([np.asarray(g) for g in got], axis=0)
        np.testing.assert_array_equal(reassembled, big)
    finally:
        srv.stop(grace=0)


# -- fan-in batching ---------------------------------------------------------

def test_batcher_stacks_concurrent_requests():
    import jax
    import jax.numpy as jnp

    calls = []

    @jax.jit
    def model(x):
        return x @ jnp.eye(4, dtype=x.dtype) * 3.0

    def fn(x):
        calls.append(int(x.shape[0]))
        return model(x)

    b = FanInBatcher(fn, max_batch=8, max_delay_s=0.05)
    try:
        outs = [None] * 6
        def worker(i):
            x = np.full((1, 4), float(i), np.float32)
            outs[i] = b(x)
        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for i in range(6):
            np.testing.assert_allclose(np.asarray(outs[i]),
                                       np.full((1, 4), i * 3.0))
        # padded to bucket (8), but far fewer dispatches than 6 singles
        assert b.batches_run < 6
        assert b.rows_run == 6
    finally:
        b.close()


def test_batcher_propagates_errors():
    def bad(x):
        raise ValueError("boom")

    b = FanInBatcher(bad, max_batch=2, max_delay_s=0.01)
    try:
        with pytest.raises(ValueError, match="boom"):
            b(np.zeros((1, 2), np.float32))
    finally:
        b.close()


def test_eight_client_fanin_end_to_end():
    """BASELINE config #4: 8 clients fan into 1 server with batched dispatch."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def model(x):
        return jnp.tanh(x) + 1.0

    def fn(tree):
        return {"y": model(tree["x"])}

    srv, target, batcher = _serve(fn, batching=True, max_batch=8,
                                  max_delay_s=0.02)
    try:
        results = [None] * 8
        def client(i):
            with Channel(target) as ch:
                x = np.full((2, 3), float(i), np.float32)
                results[i] = TensorClient(ch).call("Call", {"x": x})
        ts = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for i in range(8):
            np.testing.assert_allclose(
                np.asarray(results[i]["y"]),
                np.tanh(np.full((2, 3), float(i))) + 1.0, rtol=1e-5)
        assert batcher.rows_run == 16
        assert batcher.batches_run < 8  # real cross-connection stacking
    finally:
        srv.stop(grace=0)


def test_batcher_fixed_bucket_single_shape():
    """fixed_bucket pads every dispatch to max_batch: exactly one compiled
    shape (the accelerator-serving mode bench.py uses)."""
    import numpy as np

    from tpurpc.jaxshim.service import FanInBatcher

    shapes = []

    def fn(tree):
        shapes.append(tree["x"].shape[0])
        return tree

    b = FanInBatcher(fn, max_batch=8, max_delay_s=0.001, fixed_bucket=True)
    try:
        out = b({"x": np.ones((1, 4), np.float32)})
        assert out["x"].shape[0] == 1  # reply sliced back to the request rows
        assert shapes == [8]           # but the dispatch was padded to 8
    finally:
        b.close()


def test_batcher_close_with_pending_requests_fails_or_serves_cleanly():
    """ISSUE 3 edge case: close() racing queued requests must resolve every
    caller — a result if the final batch dispatched, the documented
    'batcher closed' error otherwise. Never a stranded p.event.wait()."""
    import queue as _q

    gate = threading.Event()

    def fn(tree):
        gate.wait(5)  # hold the batcher thread so requests pile up
        return tree

    b = FanInBatcher(fn, max_batch=4, max_delay_s=0.01)
    outcomes: "_q.Queue" = _q.Queue()

    def caller(i):
        try:
            outcomes.put(("ok", b({"x": np.full((1, 2), float(i),
                                               np.float32)})))
        except RuntimeError as exc:
            outcomes.put(("err", str(exc)))

    ts = [threading.Thread(target=caller, args=(i,), daemon=True)
          for i in range(6)]
    [t.start() for t in ts]
    time.sleep(0.1)  # let requests queue behind the gated dispatch
    gate.set()
    b.close()
    [t.join(timeout=10) for t in ts]
    assert not any(t.is_alive() for t in ts), "caller stranded by close()"
    got = [outcomes.get(timeout=1) for _ in range(6)]
    assert len(got) == 6
    for kind, val in got:
        assert kind == "ok" or "closed" in val


def test_batcher_bad_request_does_not_poison_siblings():
    """One mis-shaped request in a mixed batch fails ALONE; siblings'
    futures still deliver results (ISSUE 3 edge case)."""
    import jax.numpy as jnp

    def fn(tree):
        return {"y": jnp.asarray(tree["x"]) * 2.0}

    b = FanInBatcher(fn, max_batch=8, max_delay_s=0.05)
    results = [None] * 5
    errors = [None] * 5

    def caller(i):
        try:
            if i == 2:  # wrong trailing shape: can't stack with siblings
                results[i] = b({"x": np.ones((1, 7), np.float32)})
            else:
                results[i] = b({"x": np.full((1, 4), float(i), np.float32)})
        except Exception as exc:
            errors[i] = exc

    try:
        ts = [threading.Thread(target=caller, args=(i,)) for i in range(5)]
        [t.start() for t in ts]
        [t.join(timeout=10) for t in ts]
        assert errors[2] is not None and "incompatible" in str(errors[2])
        for i in (0, 1, 3, 4):
            assert errors[i] is None, errors[i]
            np.testing.assert_allclose(np.asarray(results[i]["y"]),
                                       np.full((1, 4), i * 2.0))
    finally:
        b.close()


def test_batcher_max_delay_flush_fires_under_single_slow_producer():
    """A lone producer (batch never fills) must still be served within
    ~max_delay_s — the timer flush, not the size trigger."""
    b = FanInBatcher(lambda t: t, max_batch=64, max_delay_s=0.05)
    try:
        t0 = time.monotonic()
        out = b({"x": np.ones((1, 2), np.float32)})
        dt = time.monotonic() - t0
        assert out["x"].shape == (1, 2)
        assert dt < 5.0  # flushed by the timer, not stuck awaiting 64 rows
        assert b.batches_run == 1 and b.rows_run == 1
    finally:
        b.close()


def test_batcher_depth_aware_flush_beats_max_delay():
    """With inflight_fn reporting that every in-flight request is already
    queued, the batch dispatches immediately instead of waiting out a
    long max_delay_s (ISSUE 3's depth-aware flush)."""
    b = FanInBatcher(lambda t: t, max_batch=64, max_delay_s=2.0,
                     inflight_fn=lambda: 1)
    try:
        t0 = time.monotonic()
        b({"x": np.ones((1, 2), np.float32)})
        dt = time.monotonic() - t0
        assert dt < 1.0, f"depth-aware flush did not fire early ({dt:.2f}s)"
    finally:
        b.close()


def test_place_many_ordering_views_see_placed_bytes():
    """ISSUE 1 regression: ``HbmRing.place_many`` lands a batch with ONE
    dispatch, and a view taken immediately after the batch place returns
    exactly the placed bytes — the dlpack alias path must order its raw
    read after the pending donated update (block_until_ready), or async
    dispatch could surface stale ring bytes."""
    import jax

    from tpurpc.tpu.hbm_ring import HbmRing

    ring = HbmRing(1 << 16, device=jax.devices("cpu")[0])
    payloads = [bytes([i]) * (64 * (i + 1)) for i in range(5)]
    spans = ring.place_many(payloads)
    assert [n for _, n in spans] == [len(p) for p in payloads]
    # offsets are consecutive: one contiguous packed batch
    for (off_a, n_a), (off_b, _) in zip(spans, spans[1:]):
        assert off_b == off_a + n_a
    for payload, (off, n) in zip(payloads, spans):
        with ring.view(off, n) as arr:
            assert bytes(bytearray(np.asarray(arr))) == payload
    # every span released -> head advances over the whole batch
    assert ring.stats()["writable"] == ring.capacity


def test_place_many_batches_one_landing_write():
    """The batch is one h2d + one in-ring landing write (the dispatch
    amortization place_many exists for), not one per payload."""
    import jax

    from tpurpc.tpu import ledger
    from tpurpc.tpu.hbm_ring import HbmRing

    ring = HbmRing(1 << 16, device=jax.devices("cpu")[0])
    with ledger.track() as w:
        spans = ring.place_many([b"a" * 128, b"b" * 128, b"c" * 128])
    assert len(spans) == 3
    assert w["dma_h2d_ops"] == 1, w.delta
    assert w["dma_d2d_ops"] == 1, w.delta


def test_decode_tree_many_walks_contiguous_records():
    """Batched decode: N tree records concatenated back-to-back decode in
    one memoryview walk; trailing slack bytes terminate cleanly."""
    trees = [{"x": np.arange(16, dtype=np.float32) + i,
              "y": np.int32(i)} for i in range(4)]
    blob = b"".join(codec.encode_tree_bytes(t) for t in trees)
    out = codec.decode_tree_many(blob)
    assert len(out) == 4
    for i, t in enumerate(out):
        np.testing.assert_array_equal(np.asarray(t["x"]),
                                      np.arange(16, dtype=np.float32) + i)
        assert int(np.asarray(t["y"])) == i
    # slack behind the last record (ring-alignment padding) is tolerated
    out2 = codec.decode_tree_many(blob + b"\x00" * 24)
    assert len(out2) == 4
    # an explicit count makes truncation an error
    with pytest.raises(codec.CodecError):
        codec.decode_tree_many(blob[:-8], count=4)
