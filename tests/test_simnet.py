"""tpurpc-simnet (ISSUE 17): the deterministic distributed simulator.

The contracts under test:

* every cross-process scenario — the REAL DisaggDecode/_KvShipper/
  migrate/DecodeScheduler/CtrlPlane classes wired as simulated nodes
  through the transport seam — explores CLEAN at the quick bound (the
  simulated fabric does not invent bugs);
* every seeded distributed mutant (:mod:`tpurpc.analysis.simmutants`:
  a COMPLETE hoisted over its one-sided write, a reap that frees instead
  of quarantining, a drain dropping resumable sequences, a skipped ring
  kick, the pre-fix close/complete park race) is found BY MESSAGE-LEVEL
  EXPLORATION — a violating delivery order or a reported deadlock, never
  a sequential unit failure;
* determinism and replay: DFS is repeatable, a violating pick trace
  serializes and replays to the same violation;
* crash coverage: killing the sender at EVERY message index of the
  handoff leaves the receiver's arena fully accounted (no leak at any
  crash point);
* the SimNet fabric itself: FIFO links, held-not-lost partitions,
  dead-node drops, crash-at-interaction-k, RPC abort/fault surfacing,
  and the arena-accounting invariant helper.
"""

from __future__ import annotations

import json

import pytest

from tpurpc.analysis import schedule, simnet
from tpurpc.analysis.schedule import SchedViolation
from tpurpc.analysis.simmutants import SIM_MUTANTS
from tpurpc.analysis.simnet import NodeCrashed, SimChannel, SimNet, SimRpcError

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


# -- clean tree: the simulated protocols hold within the bound ----------------

@pytest.mark.parametrize("name", sorted(simnet.SIM_SCENARIOS))
def test_clean_scenarios_explore_ok_at_bound1(name):
    res = simnet.run_scenario(name, preemption_bound=1, max_schedules=150)
    assert res.ok, res.violation
    assert res.schedules > 1, "no delivery interleavings explored?"


def test_kvship_exhausts_at_bound0():
    """Run-to-block exploration (delivery orders only, no mid-function
    preemption) must EXHAUST — uncapped — and stay clean."""
    res = simnet.run_scenario("simnet-kvship", preemption_bound=0,
                              max_schedules=4000)
    assert res.ok, res.violation
    assert not res.capped, "bound-0 delivery orders should exhaust"


# -- seeded distributed mutants: found by exploration -------------------------

@pytest.mark.parametrize("mutant", sorted(SIM_MUTANTS))
def test_every_sim_mutant_is_killed(mutant):
    m = SIM_MUTANTS[mutant]
    res = simnet.run_scenario(m.scenario, preemption_bound=2,
                              max_schedules=4000, mutant=mutant)
    assert res.violation is not None, (
        f"mutant {mutant} SURVIVED {res.schedules} schedules — the "
        "simulated fabric lost its teeth")


def test_mutant_kill_suite_all_killed():
    kills = simnet.mutant_kill_suite(preemption_bound=2,
                                     max_schedules=4000)
    assert len(kills) >= 4  # the acceptance floor
    survivors = [k for k, v in kills.items() if not v]
    assert not survivors, survivors


def test_skipped_kick_is_a_deadlock_report_not_a_hang():
    """The lost-wakeup mutant must surface as the explorer's DEADLOCK
    violation — every live task parked on untimed waits, with the pick
    trace — not as a hung test run (the liveness half of the contract)."""
    res = simnet.run_scenario("simnet-ctrl-kick", preemption_bound=1,
                              max_schedules=2000,
                              mutant="ctrl_kick_skipped")
    assert res.violation is not None
    assert res.violation.kind == "deadlock", res.violation
    assert res.violation.trace, "deadlock report lost its pick trace"


def test_hoisted_complete_dies_in_any_delivery_order():
    """ship_complete_before_write is an ORDERING bug at the message
    level: once the COMPLETE is posted before the write, the FIFO link
    delivers it first in EVERY schedule — the very first explored
    schedule must already kill it (the invariant runs at each
    delivery)."""
    res = simnet.run_scenario("simnet-kvship", preemption_bound=0,
                              max_schedules=50,
                              mutant="ship_complete_before_write")
    assert res.violation is not None
    assert "PARKED before its bytes landed" in res.violation.message


# -- determinism and replay ---------------------------------------------------

def test_dfs_is_deterministic():
    r1 = simnet.run_scenario("simnet-kvship", preemption_bound=1,
                             max_schedules=60)
    r2 = simnet.run_scenario("simnet-kvship", preemption_bound=1,
                             max_schedules=60)
    assert (r1.schedules, r1.steps) == (r2.schedules, r2.steps)


def test_random_exploration_same_seed_identical_traces():
    scen = simnet.SIM_SCENARIOS["simnet-ctrl-kick"]
    r1, traces1 = schedule.explore_random(scen(), seed=77, schedules=4)
    r2, traces2 = schedule.explore_random(scen(), seed=77, schedules=4)
    assert r1.ok and r2.ok
    assert traces1 == traces2, "same seed must drive identical schedules"


@pytest.mark.parametrize("mutant", ["ship_complete_before_write",
                                    "reap_free_instead_of_quarantine"])
def test_violating_trace_replays_to_same_violation(mutant):
    m = SIM_MUTANTS[mutant]
    found = simnet.run_scenario(m.scenario, preemption_bound=1,
                                max_schedules=2000, mutant=mutant)
    assert found.violation is not None
    # serialize the pick trace the way an operator would ship it
    wire = json.dumps(found.violation.trace)
    trace = json.loads(wire)
    scenario = simnet.SIM_SCENARIOS[m.scenario]()
    with m.applied():
        replayed = schedule.replay(scenario, trace)
    assert replayed.violation is not None, "replay lost the violation"
    assert replayed.violation.kind == found.violation.kind


@pytest.mark.parametrize("mutant", ["ship_complete_before_write"])
def test_bug_found_at_bound_k_is_found_at_k_plus_1(mutant):
    m = SIM_MUTANTS[mutant]
    at_1 = simnet.run_scenario(m.scenario, preemption_bound=1,
                               max_schedules=2000, mutant=mutant)
    assert at_1.violation is not None
    at_2 = simnet.run_scenario(m.scenario, preemption_bound=2,
                               max_schedules=4000, mutant=mutant)
    assert at_2.violation is not None, (
        "found at bound 1 but NOT at bound 2 — the bound-k schedules "
        "are not a subset of bound-k+1's")


# -- crash coverage: every message index of the handoff -----------------------

@pytest.mark.parametrize("crash_at", [0, 1, 2])
def test_sender_crash_at_every_message_point_leaks_nothing(crash_at):
    """Kill the prefill node at its (crash_at+1)-th transport interaction
    — before the offer (0), between offer and write (1), before the
    COMPLETE (2, the stock scenario) — and the receiver's arena must
    still be fully accounted (free + quarantined + cache + owned covers
    every block). The death scenario's own check pins the crash-at-2
    shape; this sweep asserts the universal no-leak contract at every
    point where the sender can actually die mid-handoff."""
    factory = simnet.SIM_SCENARIOS["simnet-kvship-death"]

    def patched():
        scen = factory()
        orig_setup = scen.setup

        def setup(sched):
            state = orig_setup(sched)
            state["net"].crash_after("P", crash_at)
            return state

        return schedule.Scenario(scen.name, setup, scen.threads,
                                 _crashpoint_check, scen.instrument,
                                 teardown=scen.teardown,
                                 max_steps=scen.max_steps)

    res = schedule.explore(patched(), preemption_bound=0,
                           max_schedules=300)
    assert res.ok, f"crash at interaction {crash_at}: {res.violation}"


def _crashpoint_check(state):
    # the stock death-scenario check pins q_after_reap to the crash-at-2
    # shape; the sweep only asserts the universal invariant — a dead
    # sender never strands or double-frees receiver blocks
    dec = state["decode"]
    simnet._accounted(state["mgr"],
                      owners=[p.kv for p in dec._parked.values()]
                      + [p.kv for p in dec._pending.values()])


# -- the fabric itself --------------------------------------------------------

def _explore_net(nodes, driver_nodes, drivers, check,
                 prepare=None, bound=0, max_schedules=50):
    """One-shot SimNet harness: build the net, run ``drivers`` on their
    nodes with couriers on every directed pair, explore, return result."""
    def setup(sched):
        net = SimNet(sched, nodes)
        state = {"net": net}
        if prepare is not None:
            prepare(net, state)
        net.drivers_expected = len(drivers)
        net.install()
        return state

    threads = [lambda state, n=n, fn=fn: state["net"].on_node(n, fn)(state)
               for n, fn in zip(driver_nodes, drivers)]
    for a in nodes:
        for b in nodes:
            if a != b:
                threads.append(
                    lambda state, a=a, b=b: state["net"]._courier(a, b))
    scen = schedule.Scenario("simnet-fabric", setup, threads, check,
                             instrument=[],
                             teardown=lambda state: state["net"].close())
    return schedule.explore(scen, preemption_bound=bound,
                            max_schedules=max_schedules)


def test_fifo_link_preserves_per_pair_order():
    """Two effects posted A->B arrive in post order in EVERY schedule —
    the same-QP/FIFO rule the real handoff's write-before-complete
    ordering leans on."""
    def driver(state):
        net, log = state["net"], state["log"]
        net.post("A", "B", "first", lambda: log.append(1))
        net.post("A", "B", "second", lambda: log.append(2))

    def check(state):
        assert state["log"] == [1, 2], state["log"]

    res = _explore_net(["A", "B"], ["A"], [driver], check,
                       prepare=lambda net, st: st.update(log=[]),
                       bound=2, max_schedules=200)
    assert res.ok, res.violation


def test_partition_holds_then_heal_delivers():
    def driver(state):
        net = state["net"]
        net.partition("A", "B")
        net.post("A", "B", "held", lambda: state["log"].append("x"))
        assert state["log"] == []  # held, not delivered, not lost
        net.heal("A", "B")

    def check(state):
        assert state["log"] == ["x"]
        state["net"].assert_delivered()

    res = _explore_net(["A", "B"], ["A"], [driver], check,
                       prepare=lambda net, st: st.update(log=[]))
    assert res.ok, res.violation


def test_permanent_partition_flushes_to_dropped():
    def driver(state):
        net = state["net"]
        net.partition("A", "B")
        net.post("A", "B", "lost-frame", lambda: state["log"].append("x"))

    def check(state):
        assert state["log"] == []
        assert state["net"].links[("A", "B")].dropped == ["lost-frame"]

    res = _explore_net(["A", "B"], ["A"], [driver], check,
                       prepare=lambda net, st: st.update(log=[]))
    assert res.ok, res.violation


def test_effects_to_a_dead_node_drop_with_attribution():
    def driver(state):
        net = state["net"]
        net.kill("B")
        net.post("A", "B", "to-the-dead", lambda: state["log"].append("x"))

    def check(state):
        assert state["log"] == []
        assert state["net"].links[("A", "B")].dropped == ["to-the-dead"]

    res = _explore_net(["A", "B"], ["A"], [driver], check,
                       prepare=lambda net, st: st.update(log=[]))
    assert res.ok, res.violation


def test_crash_after_k_interactions_unwinds_the_driver():
    def driver(state):
        net = state["net"]
        net.post("A", "B", "one", lambda: state["log"].append(1))
        net.post("A", "B", "two", lambda: state["log"].append(2))
        net.post("A", "B", "three", lambda: state["log"].append(3))
        state["ran-past-crash"] = True  # must be unreachable

    def check(state):
        # crash at the 3rd interaction: two effects queued, the third
        # never sent, the driver unwound via NodeCrashed (absorbed)
        assert state["log"] == [1, 2], state["log"]
        assert "ran-past-crash" not in state
        assert state["net"].alive["A"] is False

    res = _explore_net(["A", "B"], ["A"], [driver], check,
                       prepare=lambda net, st: (st.update(log=[]),
                                                net.crash_after("A", 2)))
    assert res.ok, res.violation


def test_sim_rpc_abort_surfaces_to_caller():
    from tpurpc.rpc.status import StatusCode

    def prepare(net, state):
        chan = SimChannel(net, "A", "B", {
            "/svc/deny": lambda req, ctx: ctx.abort(
                StatusCode.PERMISSION_DENIED, "no"),
        })
        state["m"] = chan.unary_unary("/svc/deny", None, None)

    def driver(state):
        with pytest.raises(SimRpcError) as ei:
            state["m"]({})
        state["code"] = ei.value.code

    def check(state):
        from tpurpc.rpc.status import StatusCode
        assert state["code"] == StatusCode.PERMISSION_DENIED

    res = _explore_net(["A", "B"], ["A"], [driver], check, prepare=prepare)
    assert res.ok, res.violation


def test_handler_fault_is_internal_error_not_a_hang():
    def prepare(net, state):
        def broken(req, ctx):
            raise RuntimeError("handler bug")
        chan = SimChannel(net, "A", "B", {"/svc/broken": broken})
        state["m"] = chan.unary_unary("/svc/broken", None, None)

    def driver(state):
        with pytest.raises(SimRpcError):
            state["m"]({})

    def check(state):
        assert state["net"].handler_faults, "fault not recorded"

    res = _explore_net(["A", "B"], ["A"], [driver], check, prepare=prepare)
    assert res.ok, res.violation


# -- the accounting invariant helper ------------------------------------------

def _arena(n_blocks=4):
    from tpurpc.serving import kv as _kv
    return _kv.KvBlockManager(n_blocks, _kv.ENTRY_BYTES * 2,
                              kind="local", name="simnet-test")


def test_accounted_passes_on_a_clean_arena():
    mgr = _arena()
    try:
        simnet._accounted(mgr)
    finally:
        mgr.close()


def test_accounted_catches_a_leaked_block():
    from types import SimpleNamespace
    mgr = _arena()
    try:
        blocks = mgr.alloc_blocks(999, 2)
        # unnamed allocation == leaked as far as the invariant knows
        with pytest.raises(SchedViolation):
            simnet._accounted(mgr)
        # named as a live owner: accounted
        simnet._accounted(mgr, owners=[SimpleNamespace(blocks=blocks)])
        # quarantined is accounted too (the reap discipline's bucket)
        mgr.quarantine(blocks)
        simnet._accounted(mgr)
    finally:
        mgr.close()


# -- the gate -----------------------------------------------------------------

@pytest.mark.slow
def test_quick_suite_is_green():
    results = simnet.quick_suite()
    bad = [r for r in results if not r.ok]
    assert not bad, bad
