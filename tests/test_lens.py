"""tpurpc-lens (ISSUE 8): waterfall hops, stage profiler, clock-anchored
timeline, shard fan-out of the new routes, concurrent-scrape safety.

The profiler tests drive ``sample_once`` with SYNTHETIC frames so the
stage attribution is deterministic; the scrape/shard tests run real
servers (the routes exist to be curled)."""

import json
import socket
import threading
import time

import pytest

from tpurpc.obs import lens, metrics, profiler, tracing
from tpurpc.obs.profiler import StageProfiler


def _http_get(port, path, timeout=5.0):
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        buf = bytearray()
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = bytes(buf).partition(b"\r\n\r\n")
    return int(head.split(None, 2)[1]), body


# ---------------------------------------------------------------------------
# waterfall hop registry + export
# ---------------------------------------------------------------------------

def test_hop_counters_known_hops_only():
    b, ns, cp = lens.hop_counters("wire")
    assert b.name == "lens_wire_bytes"
    with pytest.raises(ValueError):
        lens.hop_counters("warp-drive")


def test_waterfall_rates_and_slowest_hop():
    b, ns, cp = lens.hop_counters("send_ring")
    b0 = b.snapshot()
    b.inc(10_000_000)
    ns.inc(1_000_000)  # 10 MB in 1 ms = 10 GB/s on top of whatever was there
    doc = lens.waterfall()
    row = next(r for r in doc["hops"] if r["hop"] == "send_ring")
    # bounded, not exact: other live machinery (pollers, lingering
    # connections from earlier tests) may bump the process-global counter
    # between our snapshots
    assert b0 + 10_000_000 <= row["bytes"] <= b.snapshot()
    # the rate is DEFINED as bytes/busy_ns of one snapshot pair (both
    # fields are rounded for export: compare loosely)
    assert row["gbps"] == pytest.approx(
        row["bytes"] / (row["busy_ms"] * 1e6), rel=0.05, abs=0.002)
    assert doc["slowest_hop"] in {r["hop"] for r in doc["hops"]}
    assert "ledger" in doc
    # hop order is the declared data-flow order
    assert tuple(r["hop"] for r in doc["hops"]) == lens.HOP_NAMES


def test_waterfall_text_rendering_flags_slowest():
    slow_b, slow_ns, _ = lens.hop_counters("decode")
    # enough BYTES to clear the 1%-of-bulk-traffic share bar (a hop that
    # moved a negligible share cannot be the bulk flow's bottleneck) while
    # pathologically slow: must win the argmin
    slow_b.inc(500_000_000)
    slow_ns.inc(50_000_000_000_000)
    txt = lens.render_text()
    assert "slowest" in txt and "decode" in txt


def test_slowest_hop_ignores_control_only_traffic():
    """tpurpc-express: once bulk payloads ride the rendezvous hop, the
    framed wire hop carries only control frames — a few KB at low rates —
    and its low GB/s must NOT name it the bottleneck of the bulk flow."""
    rows = [
        {"hop": "wire", "bytes": 20_000, "busy_ms": 10.0, "gbps": 0.002},
        {"hop": "rendezvous", "bytes": 500_000_000, "busy_ms": 100.0,
         "gbps": 5.0},
        {"hop": "decode", "bytes": 480_000_000, "busy_ms": 60.0,
         "gbps": 8.0},
    ]
    assert lens.slowest_hop(rows) == "rendezvous"
    # ... but with comparable byte shares the true argmin wins as before
    rows[0] = {"hop": "wire", "bytes": 400_000_000, "busy_ms": 400.0,
               "gbps": 1.0}
    assert lens.slowest_hop(rows) == "wire"


def test_streaming_hops_account_ring_traffic():
    """A ring write/read round trip lands bytes in send_ring AND peer_ring
    with nonzero busy time."""
    from tpurpc.core.ring import RingReader, RingWriter

    sb, sn, sc = lens.hop_counters("send_ring")
    rb, rn, rc = lens.hop_counters("peer_ring")
    s0, r0 = sb.snapshot(), rb.snapshot()
    buf = bytearray(4096)

    def place(off, data):
        buf[off:off + len(data)] = bytes(data)

    w = RingWriter(4096, place)
    payload = b"z" * 1500
    w.writev([payload])
    reader = RingReader(buf)
    out = reader.read(4096)
    assert out == payload
    assert sb.snapshot() - s0 == 1500
    assert rb.snapshot() - r0 == 1500
    assert sc.snapshot() >= 1500  # ring bytes move by host memcpy: copies
    assert sn.snapshot() > 0 and rn.snapshot() > 0


# ---------------------------------------------------------------------------
# stage profiler: deterministic classification via synthetic frames
# ---------------------------------------------------------------------------

class _Code:
    def __init__(self, filename, name):
        self.co_filename = filename
        self.co_name = name


class _Frame:
    def __init__(self, filename, name, back=None):
        self.f_code = _Code(filename, name)
        self.f_back = back


def _stack(*frames):
    """Build a frame chain from (filename, funcname) outermost-first;
    returns the INNERMOST frame (what sys._current_frames yields)."""
    top = None
    for filename, name in frames:
        top = _Frame(filename, name, back=top)
    return top


_RING = "/x/tpurpc/core/ring.py"  # matches the registered basename markers


def test_classify_innermost_marker_wins():
    # innermost→outermost walk: drain_into (ring-read) shadows the outer
    # server dispatch frame
    f = _stack(("/x/tpurpc/rpc/server.py", "_run_handler"),
               (_RING, "drain_into"))
    stage, parts = StageProfiler.classify(f)
    assert stage == "ring-read"
    assert parts[-1].endswith("drain_into")  # leaf-last collapsed stack


def test_classify_stdlib_park_attributes_to_outer_tpurpc_frame():
    # a batcher thread parked in threading.Condition.wait: the stdlib
    # frame carries no marker, the outer jaxshim frame names the stage
    import tpurpc.jaxshim.service  # noqa: F401 — registers its markers

    f = _stack(("/x/tpurpc/jaxshim/service.py", "_loop"),
               ("/usr/lib/python3/threading.py", "wait"))
    stage, _ = StageProfiler.classify(f)
    assert stage == "batcher"


def test_classify_unattributed_vs_other():
    in_tree = profiler._TPURPC_DIR + "/rpc/mystery.py"
    stage, _ = StageProfiler.classify(_stack((in_tree, "enigma")))
    assert stage == "unattributed"
    stage, _ = StageProfiler.classify(
        _stack(("/usr/lib/python3/selectors.py", "select")))
    assert stage == "other"


def test_sample_once_aggregates_and_bounds():
    p = StageProfiler(hz=50)
    frames = {
        1: _stack((_RING, "writev")),
        2: _stack((_RING, "drain_into")),
        3: _stack(("/usr/lib/python3/queue.py", "get")),
    }
    for _ in range(10):
        p.sample_once(frames=frames, now_ns=123)
    assert p.samples == 30
    assert p.stages["ring-write"] == 10
    assert p.stages["ring-read"] == 10
    assert p.stages["other"] == 10
    snap = p.snapshot()
    # `other` is excluded from the attribution denominator
    assert snap["attributed_pct"] == 100.0
    assert snap["stage_pct"]["ring-write"] == 50.0
    assert len(p.recent) == 30
    collapsed = p.collapsed_text()
    assert "ring:writev 10" in collapsed


def test_sampler_thread_runs_and_stops():
    p = StageProfiler(hz=200)
    p.start()
    try:
        deadline = time.monotonic() + 5
        while p.samples == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert p.samples > 0
    finally:
        p.stop()
    assert not p.running()
    n = p.samples
    time.sleep(0.05)
    assert p.samples == n  # genuinely stopped


def test_register_stages_keys_by_basename():
    profiler.register_stages("/weird/path/fake_lens_mod.py",
                             {"fake_fn": "codec"})
    assert profiler.markers()[("fake_lens_mod.py", "fake_fn")] == "codec"
    stage, _ = StageProfiler.classify(
        _stack(("/other/prefix/fake_lens_mod.py", "fake_fn")))
    assert stage == "codec"


# ---------------------------------------------------------------------------
# clock anchor + timeline rebasing (the pinned-skew satellite)
# ---------------------------------------------------------------------------

def test_chrome_trace_carries_clock_anchor():
    doc = tracing.chrome_trace()
    a = doc["clock_anchor"]
    assert abs(a["mono_ns"] - time.monotonic_ns()) < 5e9
    assert abs(a["wall_ns"] - time.time_ns()) < 5e9  # tpr: allow(wallclock)
    assert a["uncertainty_ns"] >= 0 and a["pid"] > 0


def test_timeline_rebase_pinned_math():
    from tpurpc.tools.timeline import rebase_ns

    anchor = {"mono_ns": 1_000_000, "wall_ns": 500_000_000}
    # mono 1.5ms = wall 500.5ms; epoch 500ms -> 500us on the shared axis
    assert rebase_ns(1_500_000, anchor, 500_000_000) == pytest.approx(500.0)
    # no anchor: raw monotonic passes through (flagged upstream)
    assert rebase_ns(2_000, None, 0) == pytest.approx(2.0)


def test_timeline_aligns_two_processes_with_known_skew():
    """Two fake processes whose monotonic epochs differ by exactly 7s:
    events that happened at the SAME wall instant must land at the same
    rebased timestamp, and lanes stay distinct."""
    from tpurpc.tools.timeline import build_timeline

    wall = 1_700_000_000_000_000_000
    skew_ns = 7_000_000_000

    def member(target, mono_anchor, ev_mono_ns):
        return {
            "target": target,
            "traces": {
                "traceEvents": [
                    {"ph": "X", "name": "spanA", "cat": "tpurpc",
                     "ts": ev_mono_ns / 1e3, "dur": 10.0,
                     "pid": 1, "tid": 1},
                ],
                "displayTimeUnit": "ms",
                "clock_anchor": {"pid": 1, "mono_ns": mono_anchor,
                                 "wall_ns": wall},
            },
            "flight": {"events": []},
            "profile": {},
            "metrics": "",
        }

    # proc A: event 1ms after its anchor. proc B: its monotonic clock is
    # 7s AHEAD (started later), same wall anchor instant, event also 1ms
    # after the anchor — the two events are wall-simultaneous.
    a = member("a:1", 10_000_000, 10_000_000 + 1_000_000)
    b = member("b:1", 10_000_000 + skew_ns,
               10_000_000 + skew_ns + 1_000_000)
    doc = build_timeline([a, b])
    spans = [e for e in doc["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "spanA"]
    assert len(spans) == 2
    assert spans[0]["ts"] == pytest.approx(spans[1]["ts"], abs=1e-6)
    assert spans[0]["pid"] != spans[1]["pid"]  # distinct lanes
    assert not doc["otherData"]["unanchored"]
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert names == ["a:1", "b:1"]


def test_timeline_unanchored_member_is_flagged_not_dropped():
    from tpurpc.tools.timeline import build_timeline

    doc = build_timeline([{
        "target": "old:1",
        "traces": {"traceEvents": [
            {"ph": "X", "name": "s", "ts": 5.0, "dur": 1.0,
             "pid": 1, "tid": 1}]},
        "flight": None, "profile": None, "metrics": "",
    }])
    assert doc["otherData"]["unanchored"] == ["old:1"]
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


def test_merge_waterfalls_sums_and_recomputes_rate():
    import bench

    a = {"hops": [{"hop": "wire", "bytes": 1_000_000, "busy_ms": 1.0,
                   "copy_bytes": 0}]}
    b = {"hops": [{"hop": "wire", "bytes": 3_000_000, "busy_ms": 1.0,
                   "copy_bytes": 100}]}
    m = bench._merge_waterfalls([a, b])
    row = m["hops"][0]
    assert row["bytes"] == 4_000_000 and row["copy_bytes"] == 100
    assert row["gbps"] == pytest.approx(2.0, rel=0.01)  # 4MB / 2ms
    assert m["slowest_hop"] == "wire"


# ---------------------------------------------------------------------------
# scrape routes + concurrent-scraper hammering (satellite 3)
# ---------------------------------------------------------------------------

@pytest.fixture
def echo_server():
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    srv = Server(max_workers=8)
    srv.add_method("/lens/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield srv, port
    srv.stop(0)


def test_profile_and_waterfall_routes(echo_server):
    _srv, port = echo_server
    status, body = _http_get(port, "/debug/profile")
    assert status == 200
    doc = json.loads(body)
    assert doc["enabled"] and doc["hz"] > 0
    status, body = _http_get(port, "/debug/waterfall")
    assert status == 200
    doc = json.loads(body)
    assert tuple(r["hop"] for r in doc["hops"]) == lens.HOP_NAMES
    status, body = _http_get(port, "/debug/waterfall?text=1")
    assert status == 200 and b"GB/s" in body
    status, _body = _http_get(port, "/debug/profile?collapsed=1")
    assert status == 200


def test_lens_off_switch_disables_profile_route(echo_server, monkeypatch):
    _srv, port = echo_server
    monkeypatch.setenv("TPURPC_LENS", "0")
    try:
        status, body = _http_get(port, "/debug/profile")
        assert status == 200
        assert json.loads(body) == {"enabled": False,
                                    "reason": "TPURPC_LENS=0"}
    finally:
        monkeypatch.delenv("TPURPC_LENS", raising=False)


def test_concurrent_scrapers_vs_pipelined_traffic(echo_server):
    """N scraper threads hammer /metrics + /debug/profile +
    /debug/waterfall on the SERVING port while depth-4 pipelined traffic
    runs: no exception anywhere, no torn Prometheus output, and the
    scrape cost lands in the scrape_us histogram."""
    from tpurpc.rpc.channel import Channel
    from tpurpc.tools.top import parse_prometheus

    _srv, port = echo_server
    scrape_us = metrics.histogram("scrape_us", kind="latency")
    count0 = scrape_us.snapshot()["count"]
    errors = []
    stop = threading.Event()
    scrapes = {"n": 0}

    def scraper(k):
        paths = ["/metrics", "/debug/profile", "/debug/waterfall"]
        try:
            while not stop.is_set():
                path = paths[scrapes["n"] % len(paths)]
                status, body = _http_get(port, path)
                assert status == 200, (path, status)
                if path == "/metrics":
                    m = parse_prometheus(body.decode())
                    # a torn exposition drops whole families: the core
                    # series must be present in EVERY scrape
                    assert ("tpurpc_ring_msgs_read", "") in m, "torn scrape"
                else:
                    json.loads(body)  # torn JSON would raise
                scrapes["n"] += 1
        except Exception as exc:  # noqa: BLE001 — recorded, test asserts
            errors.append((k, repr(exc)))

    threads = [threading.Thread(target=scraper, args=(k,), daemon=True)
               for k in range(3)]
    [t.start() for t in threads]
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            pl = ch.unary_unary("/lens/Echo").pipeline(depth=4)
            for round_ in range(6):
                futs = [pl.call_async(b"m%d" % i, timeout=20)
                        for i in range(16)]
                for i, f in enumerate(futs):
                    assert f.result(20) == b"m%d" % i
    finally:
        stop.set()
        [t.join(timeout=10) for t in threads]
    assert not errors, errors
    assert scrapes["n"] >= 6, "scrapers barely ran"
    # the scrape cost is accounted where it runs — the scrape_us histogram
    got = metrics.histogram("scrape_us", kind="latency").snapshot()
    assert got["count"] >= count0 + scrapes["n"]
    assert got["p50"] > 0


# ---------------------------------------------------------------------------
# shard fan-out of /traces, /debug/profile, /debug/waterfall (satellite 1)
# ---------------------------------------------------------------------------

def _build_traced(shard_id):
    import tpurpc.rpc as tps
    from tpurpc.obs import tracing as _tracing

    _tracing.force(True)
    srv = tps.Server(max_workers=4)
    srv.add_method("/lens/Who", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: str(shard_id).encode()))
    return srv


def test_trace_on_non_answering_shard_appears_in_merged_view():
    """The satellite-1 regression: a sampled span born on shard k must be
    visible in GET /traces on the serving port no matter which worker
    answers the scrape — plus the new /debug/profile and /debug/waterfall
    fan-outs carry every live worker."""
    import tpurpc.rpc as tps
    from tpurpc.rpc.shard import ShardedServer

    sup = ShardedServer(_build_traced, workers=2,
                        listener="reuseport").start()
    tracing.force(True)  # client roots propagate; each serving worker
    try:                 # records its half of the span tree
        seen = set()
        deadline = time.monotonic() + 30
        while len(seen) < 2 and time.monotonic() < deadline:
            with tps.Channel(f"127.0.0.1:{sup.port}") as ch:
                seen.add(bytes(ch.unary_unary("/lens/Who")(
                    b"x", timeout=20)).decode())
        assert seen == {"0", "1"}, seen

        def merged_traces():
            status, body = _http_get(sup.port, "/traces")
            assert status == 200
            return json.loads(body)

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            doc = merged_traces()
            span_pids = {e["pid"] for e in doc.get("traceEvents", ())
                         if e.get("ph") == "X"}
            if span_pids >= {0, 1}:
                break
            time.sleep(0.25)
        # BOTH workers' spans are in the one merged doc — whichever shard
        # answered, the other one's spans crossed the fan-out
        assert span_pids >= {0, 1}, (span_pids, doc.get("shards"))
        assert set(doc["clock_anchors"]) == {"0", "1"}

        status, body = _http_get(sup.port, "/debug/profile")
        assert status == 200
        prof = json.loads(body)
        assert set(prof["shards"]) == {"0", "1"}, prof.get("shards")
        assert prof["samples"] >= 0 and prof["enabled"]

        status, body = _http_get(sup.port, "/debug/waterfall")
        assert status == 200
        wf = json.loads(body)
        assert set(wf["shards"]) == {"0", "1"}
        assert tuple(r["hop"] for r in wf["hops"]) == lens.HOP_NAMES
    finally:
        tracing.force(None)
        sup.stop()
