"""Pair lifecycle / flow-control / wakeup-discipline tests (SURVEY.md §2.1, §7 stage 5).

The reference validates this layer only via benchmarks (§4); we test it directly over
the loopback and shm domains, including a genuine cross-process shared-memory exchange.
"""

import os
import socket
import threading
import time

import pytest

from tpurpc.core import pair as P
from tpurpc.core.pair import Pair, PairState, create_loopback_pair
from tpurpc.core.poller import PairPool, Poller, wait_readable


@pytest.fixture(autouse=True)
def _fresh_singletons():
    yield
    Poller.reset()
    PairPool.reset()


def test_loopback_roundtrip():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        assert a.state is PairState.CONNECTED
        a.send([b"ping"])
        assert wait_readable(b, timeout=5, discipline="event")
        assert b.recv() == b"ping"
        b.send([b"pong", b"!"])
        assert wait_readable(a, timeout=5, discipline="event")
        assert a.recv() == b"pong!"
        assert a.total_sent == 4 and a.total_recv == 5
    finally:
        a.destroy()
        b.destroy()


def test_partial_send_and_credit_resume():
    a, b = create_loopback_pair(ring_size=1024)
    try:
        payload = bytes(range(256)) * 40  # 10240 bytes >> ring
        # First send fills the ring and stalls partway (want_write set) ...
        sent = a.send([payload])
        assert 0 < sent < len(payload)
        assert a.want_write
        # ... and with no credits returned yet, a retry accepts nothing.
        assert a.send([payload], byte_idx=sent) == 0
        received = bytearray()
        while sent < len(payload) or len(received) < len(payload):
            received += b.recv()  # draining publishes credits (half-ring rule)
            if sent < len(payload):
                sent += a.send([payload], byte_idx=sent)
        assert bytes(received) == payload
        assert not a.want_write
    finally:
        a.destroy()
        b.destroy()


def test_send_chunking_respects_chunk_size(monkeypatch):
    monkeypatch.setenv("TPURPC_SEND_CHUNK_SIZE", "128")
    a, b = create_loopback_pair(ring_size=1 << 16)
    try:
        payload = b"z" * 1000
        assert a.send([payload]) == 1000  # several 128B ring messages, one call
        got = bytearray()
        while len(got) < 1000:
            got += b.recv()
        assert bytes(got) == payload
    finally:
        a.destroy()
        b.destroy()


def test_graceful_close_half_close_then_drain():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        a.send([b"last words"])
        a.disconnect()
        assert a.state is PairState.DISCONNECTED
        # b observes peer_exit but can still drain in-flight data (HALF_CLOSED,
        # ref pair.cc:325-347 drain-then-close).
        assert wait_readable(b, timeout=5, discipline="event")
        assert b.get_status() is PairState.HALF_CLOSED
        assert b.recv() == b"last words"
    finally:
        a.destroy()
        b.destroy()


def test_abrupt_peer_death_detected():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        b.notify_sock.close()  # peer process dies without disconnect
        b.notify_sock = None
        deadline = time.monotonic() + 5
        while a.get_status() is PairState.CONNECTED and time.monotonic() < deadline:
            a.drain_notifications()
            time.sleep(0.01)
        assert a.state is PairState.ERROR
        with pytest.raises(BrokenPipeError):
            a.send([b"into the void"])
    finally:
        a.destroy()
        b.destroy()


def test_reentrancy_tripwire():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        with a._send_guard:
            with pytest.raises(AssertionError, match="concurrent entry"):
                a.send([b"nope"])
    finally:
        a.destroy()
        b.destroy()


def test_pair_pool_revival():
    pool = PairPool(max_idle_per_key=4)
    p1 = pool.take("server:1234")
    try:
        p1._mark_error("synthetic")
        pool.putback("server:1234", p1)
        assert pool.idle_count("server:1234") == 1
        p2 = pool.take("server:1234")
        assert p2 is p1
        assert p2.state is PairState.INITIALIZED  # init() revived it (pair.cc:85-141)
        assert p2.error is None
    finally:
        p1.destroy()
        pool.drain()


def test_poller_hybrid_wakeup():
    a, b = create_loopback_pair(ring_size=4096)
    poller = Poller.get()
    poller.add_pollable(b)
    try:
        def late_send():
            time.sleep(0.15)
            a.send([b"wake up"])

        t = threading.Thread(target=late_send)
        t.start()
        assert wait_readable(b, timeout=10, discipline="hybrid")
        assert b.recv() == b"wake up"
        t.join()
    finally:
        poller.remove_pollable(b)
        a.destroy()
        b.destroy()


def test_busy_discipline_bounded_spin():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        t0 = time.monotonic()
        assert not wait_readable(b, timeout=0.05, discipline="busy")
        assert time.monotonic() - t0 < 2
        a.send([b"x"])
        assert wait_readable(b, timeout=1, discipline="busy")
    finally:
        a.destroy()
        b.destroy()


def test_shm_domain_same_process():
    a, b = create_loopback_pair(ring_size=4096, domain=P.ShmDomain())
    try:
        a.send([b"via /dev/shm"])
        assert wait_readable(b, timeout=5, discipline="event")
        assert b.recv() == b"via /dev/shm"
    finally:
        a.destroy()
        b.destroy()


def test_shm_cross_process_echo():
    """The real thing: two processes, rings in POSIX shm, one-sided writes with zero
    kernel crossings per message, bootstrap + events over a socketpair."""
    parent_sock, child_sock = socket.socketpair()
    pid = os.fork()
    if pid == 0:
        # --- child: echo server ---
        status = 1
        try:
            parent_sock.close()
            pair = Pair(P.ShmDomain(), ring_size=8192)
            pair.init()
            pair.connect_over_socket(child_sock)
            echoed = 0
            while echoed < 3:
                if wait_readable(pair, timeout=10, discipline="event"):
                    data = pair.recv()
                    if data:
                        pair.send([b"echo:", data])
                        echoed += 1
                    elif pair.get_status() is not PairState.CONNECTED:
                        break
            pair.destroy()
            status = 0
        finally:
            os._exit(status)
    # --- parent: client ---
    child_sock.close()
    pair = Pair(P.ShmDomain(), ring_size=8192)
    pair.init()
    pair.connect_over_socket(parent_sock)
    try:
        for i in range(3):
            msg = f"msg-{i}".encode() * (i + 1)
            pair.send([msg])
            got = b""
            deadline = time.monotonic() + 10
            while len(got) < len(msg) + 5 and time.monotonic() < deadline:
                if wait_readable(pair, timeout=5, discipline="event"):
                    got += pair.recv()
            assert got == b"echo:" + msg
        pair.disconnect()
    finally:
        pair.destroy()
        _, code = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(code) == 0


def test_asymmetric_ring_sizes():
    domain = P.LocalDomain()
    a = Pair(domain, ring_size=1024)
    b = Pair(domain, ring_size=65536)
    a.init()
    b.init()
    sa, sb = socket.socketpair()
    t = threading.Thread(target=b.connect_over_socket, args=(sb,))
    t.start()
    a.connect_over_socket(sa)
    t.join()
    try:
        assert a.writer.layout.capacity == 65536  # a writes into b's big ring
        assert b.writer.layout.capacity == 1024
        a.send([b"a" * 2000])  # fits b's ring
        assert b.recv() == b"a" * 2000
    finally:
        a.destroy()
        b.destroy()


def test_bootstrap_negotiates_waitflag_caps():
    """Both sides of a bootstrap learn the peer's capability set; the notify
    skip is gated on the peer advertising 'waitflag' (an asymmetric peer —
    TPURPC_NATIVE=0 or an older build — must get unconditional notifies or it
    sleeps forever on data already in its ring)."""
    from tpurpc.core import _native

    a, b = P.create_loopback_pair()
    try:
        # "rdv" (tpurpc-express, ISSUE 9) is advertised whenever the
        # rendezvous plane is enabled — it rides alongside waitflag
        expect = {"waitflag"} if _native.load() else set()
        import os
        if os.environ.get("TPURPC_RENDEZVOUS", "1").lower() not in (
                "0", "off", "false"):
            expect.add("rdv")
        # park (tpurpc-hive, ISSUE 16): always advertised by Python pairs —
        # maybe_park initiates only against peers that answer the handshake
        expect.add("park")
        expect = frozenset(expect)
        assert a.peer_caps == expect and b.peer_caps == expect
    finally:
        a.destroy()
        b.destroy()


def test_peer_without_waitflag_always_notified():
    """A peer whose Address carried no caps (legacy/non-native) reads as
    'always waiting': every send must carry the notify byte."""
    a, b = P.create_loopback_pair()
    try:
        a.peer_caps = frozenset()  # simulate a legacy peer
        assert a._peer_waiting("read") is True
        assert a._peer_waiting("write") is True
    finally:
        a.destroy()
        b.destroy()


def test_address_caps_roundtrip_and_legacy_blob():
    addr = P.Address("t", "local", 4096, "r", "s", caps=["waitflag"])
    back = P.Address.from_bytes(addr.to_bytes())
    assert back.caps == frozenset(["waitflag"])
    # a legacy blob without the caps key parses as no capabilities
    import json as _json

    legacy = _json.dumps({"tag": "t", "domain": "local", "ring_size": 4096,
                          "ring": "r", "status": "s"}).encode()
    assert P.Address.from_bytes(legacy).caps == frozenset()


# -- idle-pair parking (tpurpc-hive, ISSUE 16) --------------------------------

def _pump(a, b):
    """One unconditional drain of both notify streams."""
    if b.drain_notifications():
        b.kick()
    if a.drain_notifications():
        a.kick()


def _pump_until(a, b, pred, rounds=200):
    """Drain both notify streams until ``pred()`` holds (or give up)."""
    for _ in range(rounds):
        if pred():
            return True
        _pump(a, b)
        time.sleep(0.001)
    return pred()


def _park(a, b):
    assert a.maybe_park(time.monotonic(), 0.0), "idle pair refused to park"
    assert _pump_until(a, b, lambda: a._parked or not a._park_pending)
    return a._parked


@pytest.fixture(autouse=True)
def _fresh_ring_pool():
    yield
    P.RingPool.reset()


def test_park_releases_rings_and_unpark_restores_traffic():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        base = P.RingPool.get().stats()["free_bytes"]
        assert _park(a, b)
        st = P.RingPool.get().stats()
        # a's recv ring + status page went to the shared pool; the stub
        # holds no ring memory (the C100K acceptance bound is <=4KiB)
        assert st["free_bytes"] - base == 4096 + P.STATUS_BYTES
        assert a.recv_region is None and a.reader is None
        assert a.resident_bytes_est() <= 4096
        # peer demand wakes the pair invisibly: first send reports 0 with
        # the WAKE in flight, the retry lands on the re-armed rings
        payload = b"wake-traffic" * 8
        sent = b.send([payload])
        assert _pump_until(a, b, lambda: not a._parked)
        deadline = time.monotonic() + 5
        while sent < len(payload) and time.monotonic() < deadline:
            _pump(a, b)
            sent += b.send([payload], sent)
        got = bytearray()
        deadline = time.monotonic() + 5
        while len(got) < len(payload) and time.monotonic() < deadline:
            if wait_readable(a, timeout=1, discipline="event"):
                got += a.recv()
        assert bytes(got) == payload
        assert a.parked_epochs == 1
    finally:
        a.destroy()
        b.destroy()


def test_park_epochs_survive_both_wake_directions():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        for epoch in range(1, 4):
            assert _park(a, b)
            if epoch % 2:
                a.unpark()  # local demand
            else:
                b.send([b"x"])  # remote demand
            assert _pump_until(a, b, lambda: not a._parked
                               and a.writer is not None
                               and b.writer is not None)
            # the fresh rings carry traffic both ways every epoch
            msg = f"epoch-{epoch}".encode()
            sent = 0
            deadline = time.monotonic() + 5
            while sent < len(msg) and time.monotonic() < deadline:
                _pump(a, b)
                sent += b.send([msg], sent)
            got = bytearray()
            deadline = time.monotonic() + 5
            while len(got) < len(msg) and time.monotonic() < deadline:
                if wait_readable(a, timeout=1, discipline="event"):
                    got += a.recv()
            assert bytes(got) == msg
            a.send([b"ack"])
            assert wait_readable(b, timeout=5, discipline="event")
            assert b.recv() == b"ack"
            assert a.parked_epochs == epoch
    finally:
        a.destroy()
        b.destroy()


def test_park_aborts_when_bytes_race_the_ack():
    """The park-decide vs incoming-byte race: bytes landing between the
    PARK decision and the peer's window-close must abort the park — the
    rings (with the payload inside) never enter the shared pool."""
    a, b = create_loopback_pair(ring_size=4096)
    try:
        assert a.maybe_park(time.monotonic(), 0.0)  # PARK sent, not yet seen
        payload = b"raced-bytes!"
        assert b.send([payload]) == len(payload)  # lands in a's live ring
        assert _pump_until(a, b, lambda: not a._park_pending)
        assert not a._parked, "park must abort with bytes in the ring"
        assert a.recv() == payload
        # the retained re-arm restored b's exact write position: the
        # stream continues uncorrupted
        assert _pump_until(a, b, lambda: b.writer is not None
                           and not b._peer_parked)
        assert b.send([b"after"]) == 5
        assert wait_readable(a, timeout=5, discipline="event")
        assert a.recv() == b"after"
    finally:
        a.destroy()
        b.destroy()


def test_parked_pair_recv_reads_zero_and_send_unparks():
    a, b = create_loopback_pair(ring_size=4096)
    try:
        assert _park(a, b)
        assert a.recv() == b""  # parked, not closed — callers keep waiting
        # a LOCAL send on the parked pair unparks on demand, invisibly
        sent = a.send([b"local-demand"])
        assert not a._parked
        deadline = time.monotonic() + 5
        while sent < 12 and time.monotonic() < deadline:
            _pump(a, b)
            sent += a.send([b"local-demand"], sent)
        assert wait_readable(b, timeout=5, discipline="event")
        assert b.recv() == b"local-demand"
    finally:
        a.destroy()
        b.destroy()


def test_maintenance_guard_entry_is_retryable_not_a_tripwire():
    """A send racing a park-protocol handler must get the retryable
    _ParkBusy (found by schedule exploration), while two CALLER threads
    colliding still trip the loud AssertionError."""
    a, b = create_loopback_pair(ring_size=4096)
    try:
        with a._send_guard.maintenance():
            with pytest.raises(P._ParkBusy):
                a._send_guard.__enter__()
        with a._send_guard:
            with pytest.raises(AssertionError, match="concurrent entry"):
                a._send_guard.__enter__()
        # and the guard is reusable after both
        a.send([b"still-works"])
        assert wait_readable(b, timeout=5, discipline="event")
        assert b.recv() == b"still-works"
    finally:
        a.destroy()
        b.destroy()


def test_destroy_while_parked_forgets_pool_accounting():
    a, b = create_loopback_pair(ring_size=4096)
    parked = _park(a, b)
    a.destroy()
    b.destroy()
    assert parked
    st = P.RingPool.get().stats()
    assert st["leased_regions"] == 0, "destroy left pool leases dangling"
