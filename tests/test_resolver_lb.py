"""Resolver + load balancing: target URIs, pick_first failover, round_robin."""

import threading

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.resolver import (make_policy, register_resolver,
                                 resolve_target)


def _echo_server():
    srv = rpc.Server(max_workers=4)
    marker = {}

    def who(req, ctx):
        return marker["name"].encode()

    srv.add_method("/t.S/Who", rpc.unary_unary_rpc_method_handler(who))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port, marker


def test_resolve_ipv4_list():
    assert resolve_target("ipv4:10.0.0.1:5,10.0.0.2:7") == [
        ("10.0.0.1", 5), ("10.0.0.2", 7)]


def test_resolve_dns_localhost():
    addrs = resolve_target("dns:///localhost:1234")
    assert ("127.0.0.1", 1234) in addrs or ("::1", 1234, 0, 0) in addrs \
        or any(a[1] == 1234 for a in addrs)


def test_resolve_bad_target():
    with pytest.raises(ValueError):
        resolve_target("ipv4:nonsense")


def test_custom_resolver_scheme():
    register_resolver("fake", lambda rest: [("127.0.0.1", int(rest))])
    assert resolve_target("fake:4242") == [("127.0.0.1", 4242)]


def test_unknown_policy_rejected():
    with pytest.raises(ValueError):
        make_policy("magic", 2)


def test_pick_first_fails_over_to_live_address():
    srv, port, marker = _echo_server()
    marker["name"] = "b"
    try:
        # first address is a dead port; pick_first must move on
        dead = port + 1 if port < 65000 else port - 1
        with rpc.Channel(f"ipv4:127.0.0.1:{dead},127.0.0.1:{port}",
                         connect_timeout=2) as ch:
            mc = ch.unary_unary("/t.S/Who")
            assert mc(b"", timeout=10) == b"b"
            # sticks with the live one on subsequent calls
            assert mc(b"", timeout=10) == b"b"
    finally:
        srv.stop(grace=0)


def test_round_robin_spreads_calls():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "s1"
    m2["name"] = "s2"
    try:
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy="round_robin") as ch:
            mc = ch.unary_unary("/t.S/Who")
            got = {bytes(mc(b"", timeout=10)) for _ in range(6)}
        assert got == {b"s1", b"s2"}
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_round_robin_skips_dead_member():
    s1, p1, m1 = _echo_server()
    m1["name"] = "alive"
    try:
        dead = p1 + 1 if p1 < 65000 else p1 - 1
        with rpc.Channel(f"ipv4:127.0.0.1:{dead},127.0.0.1:{p1}",
                         lb_policy="round_robin", connect_timeout=2) as ch:
            mc = ch.unary_unary("/t.S/Who")
            for _ in range(4):
                assert mc(b"", timeout=10) == b"alive"
    finally:
        s1.stop(grace=0)


# -- ring_hash ---------------------------------------------------------------

def test_ring_hash_deterministic_and_distributed():
    from tpurpc.rpc.resolver import RingHash, ring_hash_key

    pol = RingHash(4)
    with ring_hash_key("alpha"):
        first = list(pol.order())
        assert list(pol.order()) == first      # same key -> same order
    # distinct keys spread over backends
    firsts = set()
    for i in range(64):
        with ring_hash_key(f"key-{i}"):
            firsts.add(pol.order()[0])
    assert len(firsts) == 4
    # preference list is a permutation (failover covers every backend)
    with ring_hash_key("alpha"):
        assert sorted(pol.order()) == [0, 1, 2, 3]


def test_ring_hash_minimal_reshuffle():
    """Consistent hashing property: keys whose primary is NOT the removed
    backend keep their primary when it disappears (here: the ring order's
    second choice never changes for other-primary keys)."""
    from tpurpc.rpc.resolver import RingHash, ring_hash_key

    pol = RingHash(4)
    keys = [f"k{i}" for i in range(128)]
    primary = {}
    for k in keys:
        with ring_hash_key(k):
            primary[k] = pol.order()[0]
    victim = primary[keys[0]]
    for k in keys:
        with ring_hash_key(k):
            order = list(pol.order())
        if primary[k] != victim:
            # removing `victim` (skipping it) must not move this key
            assert [i for i in order if i != victim][0] == primary[k]


def test_ring_hash_without_key_rotates():
    from tpurpc.rpc.resolver import RingHash

    pol = RingHash(3)
    assert {pol.order()[0] for _ in range(6)} == {0, 1, 2}


def test_ring_hash_channel_stickiness():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "s1"
    m2["name"] = "s2"
    try:
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy="ring_hash") as ch:
            mc = ch.unary_unary("/t.S/Who")
            with rpc.ring_hash_key("session-9"):
                got = {bytes(mc(b"", timeout=10)) for _ in range(4)}
            assert len(got) == 1               # sticky under a fixed key
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)
