"""Resolver + load balancing: target URIs, pick_first failover, round_robin."""


import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.resolver import (make_policy, register_resolver,
                                 resolve_target)


def _echo_server():
    srv = rpc.Server(max_workers=4)
    marker = {}

    def who(req, ctx):
        return marker["name"].encode()

    srv.add_method("/t.S/Who", rpc.unary_unary_rpc_method_handler(who))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port, marker


def test_resolve_ipv4_list():
    assert resolve_target("ipv4:10.0.0.1:5,10.0.0.2:7") == [
        ("10.0.0.1", 5), ("10.0.0.2", 7)]


def test_resolve_dns_localhost():
    addrs = resolve_target("dns:///localhost:1234")
    assert ("127.0.0.1", 1234) in addrs or ("::1", 1234, 0, 0) in addrs \
        or any(a[1] == 1234 for a in addrs)


def test_resolve_bad_target():
    with pytest.raises(ValueError):
        resolve_target("ipv4:nonsense")


def test_custom_resolver_scheme():
    register_resolver("fake", lambda rest: [("127.0.0.1", int(rest))])
    assert resolve_target("fake:4242") == [("127.0.0.1", 4242)]


def test_unknown_policy_rejected():
    with pytest.raises(ValueError):
        make_policy("magic", 2)


def test_pick_first_fails_over_to_live_address():
    srv, port, marker = _echo_server()
    marker["name"] = "b"
    try:
        # first address is a dead port; pick_first must move on
        dead = port + 1 if port < 65000 else port - 1
        with rpc.Channel(f"ipv4:127.0.0.1:{dead},127.0.0.1:{port}",
                         connect_timeout=2) as ch:
            mc = ch.unary_unary("/t.S/Who")
            assert mc(b"", timeout=10) == b"b"
            # sticks with the live one on subsequent calls
            assert mc(b"", timeout=10) == b"b"
    finally:
        srv.stop(grace=0)


def test_round_robin_spreads_calls():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "s1"
    m2["name"] = "s2"
    try:
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy="round_robin") as ch:
            mc = ch.unary_unary("/t.S/Who")
            got = {bytes(mc(b"", timeout=10)) for _ in range(6)}
        assert got == {b"s1", b"s2"}
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_round_robin_skips_dead_member():
    s1, p1, m1 = _echo_server()
    m1["name"] = "alive"
    try:
        dead = p1 + 1 if p1 < 65000 else p1 - 1
        with rpc.Channel(f"ipv4:127.0.0.1:{dead},127.0.0.1:{p1}",
                         lb_policy="round_robin", connect_timeout=2) as ch:
            mc = ch.unary_unary("/t.S/Who")
            for _ in range(4):
                assert mc(b"", timeout=10) == b"alive"
    finally:
        s1.stop(grace=0)


# -- ring_hash ---------------------------------------------------------------

def test_ring_hash_deterministic_and_distributed():
    from tpurpc.rpc.resolver import RingHash, ring_hash_key

    pol = RingHash(4)
    with ring_hash_key("alpha"):
        first = list(pol.order())
        assert list(pol.order()) == first      # same key -> same order
    # distinct keys spread over backends
    firsts = set()
    for i in range(64):
        with ring_hash_key(f"key-{i}"):
            firsts.add(pol.order()[0])
    assert len(firsts) == 4
    # preference list is a permutation (failover covers every backend)
    with ring_hash_key("alpha"):
        assert sorted(pol.order()) == [0, 1, 2, 3]


def test_ring_hash_minimal_reshuffle():
    """Consistent hashing property: keys whose primary is NOT the removed
    backend keep their primary when it disappears (here: the ring order's
    second choice never changes for other-primary keys)."""
    from tpurpc.rpc.resolver import RingHash, ring_hash_key

    pol = RingHash(4)
    keys = [f"k{i}" for i in range(128)]
    primary = {}
    for k in keys:
        with ring_hash_key(k):
            primary[k] = pol.order()[0]
    victim = primary[keys[0]]
    for k in keys:
        with ring_hash_key(k):
            order = list(pol.order())
        if primary[k] != victim:
            # removing `victim` (skipping it) must not move this key
            assert [i for i in order if i != victim][0] == primary[k]


def test_ring_hash_without_key_rotates():
    from tpurpc.rpc.resolver import RingHash

    pol = RingHash(3)
    assert {pol.order()[0] for _ in range(6)} == {0, 1, 2}


def test_ring_hash_channel_stickiness():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "s1"
    m2["name"] = "s2"
    try:
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy="ring_hash") as ch:
            mc = ch.unary_unary("/t.S/Who")
            with rpc.ring_hash_key("session-9"):
                got = {bytes(mc(b"", timeout=10)) for _ in range(4)}
            assert len(got) == 1               # sticky under a fixed key
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


# -- retry policy ------------------------------------------------------------

def _flaky_server(fail_times: int, code=None):
    from tpurpc.rpc.status import StatusCode

    code = code or StatusCode.UNAVAILABLE
    srv = rpc.Server(max_workers=2)
    calls = {"n": 0}

    def handler(req, ctx):
        calls["n"] += 1
        if calls["n"] <= fail_times:
            ctx.abort(code, "flake")
        return b"ok:" + str(calls["n"]).encode()

    srv.add_method("/t.S/Flaky", rpc.unary_unary_rpc_method_handler(handler))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port, calls


def test_retry_unary_recovers():
    srv, port, calls = _flaky_server(2)
    try:
        pol = rpc.RetryPolicy(max_attempts=4, initial_backoff=0.01)
        with rpc.Channel(f"127.0.0.1:{port}", retry_policy=pol) as ch:
            out = ch.unary_unary("/t.S/Flaky")(b"", timeout=10)
        assert out == b"ok:3"
        assert calls["n"] == 3
    finally:
        srv.stop(grace=0)


def test_retry_exhaustion_surfaces_last_error():
    import pytest as _pytest

    from tpurpc.rpc.status import RpcError, StatusCode

    srv, port, calls = _flaky_server(10)
    try:
        pol = rpc.RetryPolicy(max_attempts=3, initial_backoff=0.01)
        with rpc.Channel(f"127.0.0.1:{port}", retry_policy=pol) as ch:
            with _pytest.raises(RpcError) as ei:
                ch.unary_unary("/t.S/Flaky")(b"", timeout=10)
        assert ei.value.code() == StatusCode.UNAVAILABLE
        assert calls["n"] == 3                 # exactly max_attempts
    finally:
        srv.stop(grace=0)


def test_retry_skips_non_retryable_codes():
    import pytest as _pytest

    from tpurpc.rpc.status import RpcError, StatusCode

    srv, port, calls = _flaky_server(10, code=StatusCode.INVALID_ARGUMENT)
    try:
        pol = rpc.RetryPolicy(max_attempts=4, initial_backoff=0.01)
        with rpc.Channel(f"127.0.0.1:{port}", retry_policy=pol) as ch:
            with _pytest.raises(RpcError) as ei:
                ch.unary_unary("/t.S/Flaky")(b"", timeout=10)
        assert ei.value.code() == StatusCode.INVALID_ARGUMENT
        assert calls["n"] == 1                 # no retry on non-retryable
    finally:
        srv.stop(grace=0)


def test_retry_off_by_default():
    import pytest as _pytest

    from tpurpc.rpc.status import RpcError

    srv, port, calls = _flaky_server(1)
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            with _pytest.raises(RpcError):
                ch.unary_unary("/t.S/Flaky")(b"", timeout=10)
        assert calls["n"] == 1
    finally:
        srv.stop(grace=0)


def test_retry_never_replays_committed_call():
    """A call whose response message was already delivered must NOT be
    retried even when trailers carry a retryable code (gRPC retry
    contract): the handler would re-execute."""
    import pytest as _pytest

    from tpurpc.rpc.status import RpcError, StatusCode

    srv = rpc.Server(max_workers=2)
    calls = {"n": 0}

    def handler(req, ctx):
        calls["n"] += 1
        ctx.set_code(StatusCode.UNAVAILABLE)   # non-OK trailers AFTER the
        return b"payload"                      # response message

    srv.add_method("/t.S/Committed", rpc.unary_unary_rpc_method_handler(handler))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        pol = rpc.RetryPolicy(max_attempts=4, initial_backoff=0.01)
        with rpc.Channel(f"127.0.0.1:{port}", retry_policy=pol) as ch:
            with _pytest.raises(RpcError) as ei:
                ch.unary_unary("/t.S/Committed")(b"", timeout=10)
        assert ei.value.code() == StatusCode.UNAVAILABLE
        assert calls["n"] == 1                 # executed exactly once
    finally:
        srv.stop(grace=0)


def test_retry_server_streaming_before_first_message():
    """Server-streaming retry rule: a stream failing BEFORE its first
    response replays; one that fails mid-stream (committed) does not."""
    import pytest as _pytest

    from tpurpc.rpc.status import RpcError, StatusCode

    srv = rpc.Server(max_workers=2)
    calls = {"early": 0, "mid": 0}

    def early_fail(req, ctx):
        calls["early"] += 1
        if calls["early"] <= 2:
            ctx.abort(StatusCode.UNAVAILABLE, "not yet")
        for i in range(3):
            yield b"m%d" % i

    def mid_fail(req, ctx):
        calls["mid"] += 1
        yield b"first"
        ctx.abort(StatusCode.UNAVAILABLE, "mid-stream")

    srv.add_method("/t.S/Early", rpc.unary_stream_rpc_method_handler(early_fail))
    srv.add_method("/t.S/Mid", rpc.unary_stream_rpc_method_handler(mid_fail))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        pol = rpc.RetryPolicy(max_attempts=4, initial_backoff=0.01)
        with rpc.Channel(f"127.0.0.1:{port}", retry_policy=pol) as ch:
            got = [bytes(m) for m in ch.unary_stream("/t.S/Early")(b"", timeout=10)]
            assert got == [b"m0", b"m1", b"m2"]
            assert calls["early"] == 3          # two retries then success

            with _pytest.raises(RpcError):
                list(ch.unary_stream("/t.S/Mid")(b"", timeout=10))
            assert calls["mid"] == 1            # committed: never replayed
    finally:
        srv.stop(grace=0)


# -- priority + weighted_target composition ---------------------------------
# (ref lb_policy/priority/priority.cc, weighted_target/weighted_target.cc)

def test_priority_prefers_high_then_fails_over_and_back():
    pol = make_policy({"priority": {
        "children": [{"policy": "pick_first", "indices": [0]},
                     {"policy": "pick_first", "indices": [1]}],
        "failover_timeout_s": 0.2}}, 2)
    assert list(pol.order())[0] == 0          # healthy: priority 0 leads
    pol.failed(0)
    assert list(pol.order())[0] == 1          # failover: priority 1 leads
    assert 0 in pol.order()                   # but 0 stays dialable in-order
    import time as _t
    _t.sleep(0.25)
    assert list(pol.order())[0] == 0          # mark expired: fail back

def test_priority_connected_clears_mark():
    pol = make_policy({"priority": [
        {"policy": "pick_first", "indices": [0]},
        {"policy": "pick_first", "indices": [1]}]}, 2)
    pol.failed(0)
    assert list(pol.order())[0] == 1
    pol.connected(0)                          # a dial succeeded: healthy now
    assert list(pol.order())[0] == 0

def test_weighted_target_split_is_weight_proportional():
    pol = make_policy({"weighted_target": [
        {"weight": 3, "policy": "pick_first", "indices": [0]},
        {"weight": 1, "policy": "pick_first", "indices": [1]}]}, 2)
    firsts = [pol.order()[0] for _ in range(8)]
    assert firsts.count(0) == 6 and firsts.count(1) == 2
    # smooth WRR: the weight-1 target is interleaved, not bunched at the end
    assert firsts[:4].count(1) == 1

def test_weighted_target_of_priority_nested_tree():
    # weighted_target of priority lists: indices in the nested spec are
    # local to the child's universe, remapped onto the channel's global ones
    pol = make_policy({"weighted_target": [
        {"weight": 1, "indices": [0, 1],
         "policy": {"priority": [{"policy": "pick_first", "indices": [0]},
                                 {"policy": "pick_first", "indices": [1]}]}},
        {"weight": 1, "indices": [2]},
    ]}, 3)
    orders = [list(pol.order()) for _ in range(4)]
    assert all(sorted(o) == [0, 1, 2] for o in orders)
    assert {o[0] for o in orders} == {0, 2}   # each target leads alternately
    pol.failed(0)                              # nested priority fails over
    lead = [o for o in (list(pol.order()) for _ in range(2)) if o[0] != 2][0]
    assert lead[0] == 1

def test_bad_composite_specs_rejected():
    with pytest.raises(ValueError):
        make_policy({"priority": {"children": []}}, 2)
    with pytest.raises(ValueError):
        make_policy({"weighted_target": [
            {"weight": 0, "policy": "pick_first", "indices": [0]}]}, 1)
    with pytest.raises(ValueError):
        make_policy({"priority": [{"policy": "pick_first",
                                   "indices": [5]}]}, 2)
    with pytest.raises(ValueError):
        make_policy({"mystery": []}, 1)

def test_priority_channel_integration_failover():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "primary"
    m2["name"] = "backup"
    try:
        spec = {"priority": {
            "children": [{"policy": "pick_first", "indices": [0]},
                         {"policy": "pick_first", "indices": [1]}],
            "failover_timeout_s": 30}}
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy=spec, connect_timeout=2) as ch:
            mc = ch.unary_unary("/t.S/Who")
            assert mc(b"", timeout=10) == b"primary"
            s1.stop(grace=0)
            # primary gone: calls land on the backup (walk-the-order dial
            # covers the transition; the failed mark keeps it there)
            deadline = 30
            import time as _t
            t0 = _t.monotonic()
            while _t.monotonic() - t0 < deadline:
                try:
                    if mc(b"", timeout=5) == b"backup":
                        break
                except rpc.RpcError:
                    _t.sleep(0.05)
            assert mc(b"", timeout=10) == b"backup"
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)

def test_weighted_target_channel_integration_split():
    s1, p1, m1 = _echo_server()
    s2, p2, m2 = _echo_server()
    m1["name"] = "w3"
    m2["name"] = "w1"
    try:
        spec = {"weighted_target": [
            {"weight": 3, "policy": "pick_first", "indices": [0]},
            {"weight": 1, "policy": "pick_first", "indices": [1]}]}
        with rpc.Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                         lb_policy=spec) as ch:
            mc = ch.unary_unary("/t.S/Who")
            got = [bytes(mc(b"", timeout=10)) for _ in range(8)]
        assert got.count(b"w3") == 6 and got.count(b"w1") == 2
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)
