"""tpurpc-odyssey (ISSUE 15): sequence journeys, token latency, cost ledgers.

Journey tracing stitched across the disagg split (one trace_id through
prefill -> KV ship -> decode -> migration, including two REAL processes),
ITL/TPOT correctness against the deterministic reference model's timing,
ledger conservation across preempt/swap/migrate (byte-seconds monotone,
no double-count), the new ITL/TTFT SLO track kinds' pending->firing->
resolved lifecycle, the shard/collector merges, the /debug/seq routes,
and the TPURPC_ODYSSEY=0 off-switch."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpurpc.analysis import protocol
from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
from tpurpc.obs import flight, metrics, odyssey
from tpurpc.obs import slo as obs_slo
from tpurpc.obs import tracing
from tpurpc.obs.tsdb import Tsdb
from tpurpc.serving.scheduler import DecodeScheduler, TokenStream

S = int(1e9)


@pytest.fixture(autouse=True)
def _clean_odyssey_state():
    flight.RECORDER.reset()
    odyssey.reset()
    tracing.reset()
    old_idle = TokenStream.MAX_IDLE_S
    TokenStream.MAX_IDLE_S = 10.0
    yield
    TokenStream.MAX_IDLE_S = old_idle
    tracing.force(None)
    tracing.reset()
    odyssey.reset()
    obs_slo.reset()
    flight.RECORDER.reset()


def _drain(stream):
    return list(stream)


def _wait_done(n=1, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = odyssey.seq_doc()
        if len(doc["recent"]) >= n and not doc["live"]:
            return doc
        time.sleep(0.01)
    return odyssey.seq_doc()


# ---------------------------------------------------------------------------
# Token-latency plane
# ---------------------------------------------------------------------------

def test_itl_matches_reference_step_timing():
    """ITL at the stream edge ~= the step cadence of the deterministic
    model — the 'honest methodology' check against reference_decode's
    known per-token timing (one token per step_delay_s step)."""
    step_s = 0.02
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=step_s),
                            max_batch=4, name="itl")
    try:
        st = sched.submit([1, 2, 3], max_tokens=10, account="t-itl")
        toks = _drain(st)
        assert toks == reference_decode(np.asarray([1, 2, 3], np.int32),
                                        10)
        doc = _wait_done()
    finally:
        sched.close()
    p99 = doc["itl_p99_rolling_us"]["interactive"]
    assert p99 is not None
    # each inter-token gap is one 20ms step (+scheduler overhead); far
    # under 2x step and far over half of it on any weather
    assert step_s * 1e6 * 0.5 < p99 < step_s * 1e6 * 3, p99
    hist = doc["itl"]["interactive"]
    assert hist["count"] >= 8  # 10 tokens -> 9 gaps (flushed at retire)
    led = doc["recent"][0]
    assert led["tokens"] == 10
    assert "tpot_us" in led and led["tpot_us"] > step_s * 1e6 * 0.5
    assert doc["tpot"]["interactive"]["count"] >= 1


def test_step_time_attribution_conserves():
    """Every device-step microsecond lands on exactly one set of
    sequences: the sum of per-ledger step_us equals the plane's measured
    step total (the >=95% acceptance instrument, exact in-process)."""
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.002),
                            max_batch=4, name="attr")
    try:
        streams = [sched.submit([i + 1], max_tokens=8, account="t-a")
                   for i in range(3)]
        for st in streams:
            _drain(st)
        doc = _wait_done(3)
    finally:
        sched.close()
    assert doc["attributed_pct"] is not None
    assert doc["attributed_pct"] >= 95.0
    total = sum(r["step_us"] for r in doc["recent"])
    assert abs(total - doc["step_us_attributed"]) < 1.0
    assert doc["step_us_total"] > 0


def test_account_rollup_and_anon_default():
    sched = DecodeScheduler(ToyDecodeModel(), max_batch=4, name="acct")
    try:
        _drain(sched.submit([1], max_tokens=4, account="tenant-a"))
        _drain(sched.submit([2], max_tokens=4, account="tenant-a"))
        _drain(sched.submit([3], max_tokens=4))  # no account -> anon
        doc = _wait_done(3)
    finally:
        sched.close()
    accts = doc["accounts"]
    assert accts["tenant-a"]["seqs"] == 2
    assert accts["tenant-a"]["tokens"] == 8
    assert accts["tenant-a"]["step_us"] > 0
    assert accts["anon"]["seqs"] == 1


def test_account_key_grammar():
    assert odyssey.sanitize_account(None) == "anon"
    assert odyssey.sanitize_account("") == "anon"
    assert odyssey.sanitize_account("team-a.prod:v2") == "team-a.prod:v2"
    assert odyssey.sanitize_account(b"bytes-ok") == "bytes-ok"
    assert odyssey.sanitize_account("has space/slash") == "has_space_slash"
    assert len(odyssey.sanitize_account("x" * 200)) == 64


# ---------------------------------------------------------------------------
# Ledger conservation across preempt / swap / migrate
# ---------------------------------------------------------------------------

def _paged_sched(name, **kw):
    from tpurpc.serving.kv import KvBlockManager

    mgr = KvBlockManager(n_blocks=64, block_bytes=256, name=name)
    kw.setdefault("max_batch", 1)
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.005), kv=mgr,
                            name=name, **kw)
    return sched, mgr


def test_kv_byte_seconds_monotone_across_preempt_swap():
    """A preempted-and-swapped sequence's ledger: arena byte-seconds stop
    growing while swapped (swap_byte_s grows instead), both are monotone
    non-decreasing, and neither interval is double-counted (their sum is
    bounded by max-residency x wall time)."""
    sched, mgr = _paged_sched("swap")
    try:
        t_start = time.monotonic()
        batch_st = sched.submit([1] * 40, max_tokens=60,
                                slo="batch", account="t-batch")
        for _ in range(5):  # running
            batch_st.next(timeout=2.0)
        reads = []
        # interactive work preempts the batch seq (max_batch=1 -> swap)
        inter_st = sched.submit([2, 3], max_tokens=20, account="t-int")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            doc = odyssey.seq_doc(
                {"account": "t-batch", "n": "4"})
            rows = doc["live"] or doc["recent"]
            if rows:
                reads.append((rows[0]["kv_byte_s"],
                              rows[0]["swap_byte_s"],
                              rows[0].get("state")))
                if rows[0].get("state") == "done":
                    break
            time.sleep(0.01)
        _drain(inter_st)
        _drain(batch_st)
        doc = _wait_done(2)
        dur_s = time.monotonic() - t_start
    finally:
        sched.close()
        mgr.close()
    led = [r for r in doc["recent"] if r["account"] == "t-batch"][0]
    assert led["preempts"] >= 1, led
    assert led["swaps"] >= 2, led          # out + back in
    assert led["swap_byte_s"] > 0, led     # swapped residency is charged
    assert led["kv_byte_s"] > 0, led
    # monotone under observation: no read ever went backwards
    for (a0, s0, _), (a1, s1, _) in zip(reads, reads[1:]):
        assert a1 >= a0 - 1e-6 and s1 >= s0 - 1e-6, reads
    # no double-count: total residency-seconds bounded by the arena's
    # worst case held for the whole wall window
    bound = mgr.n_blocks * mgr.block_bytes * dur_s
    assert led["kv_byte_s"] + led["swap_byte_s"] < bound


def test_shed_and_refused_settle_ledgers():
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.05),
                            max_batch=1, max_waiting=1, name="shed")
    try:
        st = sched.submit([1] * 4, max_tokens=30, account="t-ok")
        st.next(timeout=2.0)  # running now, not waiting
        # fill the one-slot waiting queue, then overflow it
        q = sched.submit([2], max_tokens=4, account="t-q")
        from tpurpc.serving.scheduler import ShedError

        with pytest.raises(ShedError):
            sched.submit([3], max_tokens=4, account="t-shed")
        q.cancel()
        st.cancel()
        accts = odyssey.accounts_snapshot()
        assert accts["t-shed"]["sheds"] == 1
        assert accts["t-shed"]["seqs"] == 1
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# Journey tracing
# ---------------------------------------------------------------------------

def test_journey_spans_single_trace_in_process():
    tracing.force(True)
    ctx = tracing.TraceContext(0xABCD1234, 1)
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.002),
                            max_batch=4, name="jrny")
    try:
        _drain(sched.submit([5, 6], max_tokens=6, trace=ctx,
                            account="t-j"))
        _wait_done()
    finally:
        sched.close()
    spans = tracing.spans(ctx.trace_id)
    names = [s["name"] for s in spans]
    for needed in ("seq-admit", "seq-prefill", "seq-decode"):
        assert needed in names, names
    assert all(s["trace_id"] == f"{ctx.trace_id:016x}" for s in spans)
    dec = [s for s in spans if s["name"] == "seq-decode"][0]
    assert dec["attrs"]["account"] == "t-j"
    assert dec["attrs"]["tokens"] == 6


def test_tail_commit_rules_interesting_journeys():
    """With head sampling OFF (tail-only), a preempted sequence's
    provisional journey COMMITS while a fast clean one ages out — the
    PR 5 rule at sequence granularity."""
    tracing.force(None)
    tracing.configure(0.0)  # no head sampling; tail capture stays on
    assert tracing.LIVE and not tracing.ACTIVE
    sched, mgr = _paged_sched("tail")
    try:
        ctx_b = tracing.maybe_sample()
        assert ctx_b is not None and ctx_b.provisional
        batch_st = sched.submit([1] * 8, max_tokens=40, slo="batch",
                                trace=ctx_b, account="t-b")
        for _ in range(3):
            batch_st.next(timeout=2.0)
        ctx_i = tracing.TraceContext(0x77, 1, provisional=True)
        tracing._tail_register(ctx_i.trace_id)
        inter_st = sched.submit([2], max_tokens=3, trace=ctx_i,
                                account="t-i")
        _drain(inter_st)
        _drain(batch_st)
        _wait_done(2)
    finally:
        sched.close()
        mgr.close()
    # the preempted batch journey committed: its spans are in the ring
    committed = {s["name"] for s in tracing.spans(ctx_b.trace_id)}
    assert "seq-decode" in committed, committed
    # the fast clean interactive one did not (still pending, uncommitted)
    assert tracing.spans(ctx_i.trace_id) == []
    assert tracing.tail_pending(ctx_i.trace_id) > 0


def test_flight_journey_order_and_strict_conformance():
    t0 = time.monotonic_ns()
    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.002),
                            max_batch=2, name="fl")
    try:
        _drain(sched.submit([1, 2], max_tokens=5, account="t-f"))
        _wait_done()
    finally:
        sched.close()
    events = flight.snapshot(since_ns=t0)
    assert protocol.check_events(events, strict=True) == []
    protocol.assert_ordered(events, [
        ("seq-submit", {"a2": 2}), "gen-join", "seq-first-token",
        "gen-retire",
    ], since_ns=t0)


def test_seq_journey_mutants_killed():
    muts = protocol.machine_mutants()
    assert "seq_token_after_retire" in muts
    assert "seq_join_without_submit" in muts
    kills = protocol.mutant_kill_suite()
    assert kills["seq_token_after_retire"]
    assert kills["seq_join_without_submit"]


# ---------------------------------------------------------------------------
# Disagg: the journey crosses the split; migration settles the ledger
# ---------------------------------------------------------------------------

def _disagg_stack(n_decode=2, step_delay_s=0.01):
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import DisaggClient, serve_decode, serve_prefill

    decodes = [serve_decode(ToyDecodeModel(step_delay_s=step_delay_s),
                            kv_blocks=96, block_bytes=256, name=f"d{i}")
               for i in range(n_decode)]
    d_ch = Channel(f"127.0.0.1:{decodes[0][1]}")
    p_srv, p_port, p_state = serve_prefill(
        ToyDecodeModel(), d_ch, f"127.0.0.1:{decodes[0][1]}")
    p_ch = Channel(f"127.0.0.1:{p_port}")
    cli = DisaggClient(p_ch, f"127.0.0.1:{decodes[0][1]}",
                       account="t-mig")

    def close():
        cli.close()
        p_srv.stop(grace=0)
        p_state.close()
        for srv, _p, sched, state in decodes:
            srv.stop(grace=0)
            sched.close()
            state.close()
            state.mgr.close()
        p_ch.close()
        d_ch.close()

    return decodes, p_ch, cli, close


def test_journey_and_ledger_across_migration():
    """In-process disagg pair: one trace_id carries seq-ship (handoff),
    seq-resume/seq-decode (decode A), seq-migrate (the hop), and the
    adopted sequence's decode spans on B; the source ledger settles
    'migrated' with shipped bytes, and the account rollup sums both
    halves under the account that rode the metadata."""
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import migrate

    tracing.force(True)
    decodes, p_ch, cli, close = _disagg_stack()
    b_ch = Channel(f"127.0.0.1:{decodes[1][1]}")
    try:
        prompt = np.arange(64, dtype=np.int32) % 31
        want = reference_decode(prompt, 32)
        ctx = tracing.TraceContext(0xFEED0001, 1)
        with tracing.use(ctx):
            it = cli.generate_with_meta(prompt, max_tokens=32, timeout=20)
            pairs = [next(it) for _ in range(5)]
            moved, failed = migrate(decodes[0][3], b_ch,
                                    f"127.0.0.1:{decodes[1][1]}")
            assert moved == 1 and failed == 0
            pairs.extend(it)
        assert [t for _i, t in pairs] == want
        assert [i for i, _t in pairs] == list(range(32))

        names = {s["name"] for s in tracing.spans(ctx.trace_id)}
        for needed in ("seq-ship", "seq-resume", "seq-decode",
                       "seq-migrate"):
            assert needed in names, names
        doc = _wait_done(2, timeout=8.0)
        by_outcome = {r["outcome"]: r for r in doc["recent"]
                      if r["account"] == "t-mig"}
        assert "migrated" in by_outcome, doc["recent"]
        src = by_outcome["migrated"]
        assert src["migrations"] == 1 and src["shipped_bytes"] > 0
        assert src["trace_id"] == f"{ctx.trace_id:016x}"
        assert "retire" in by_outcome  # the adopted half finished on B
        dst = by_outcome["retire"]
        assert dst["trace_id"] == src["trace_id"]
        assert dst["shipped_bytes"] > 0  # the handoff bytes it arrived by
        acct = doc["accounts"]["t-mig"]
        assert acct["migrations"] >= 1
        assert acct["tokens"] >= 31
    finally:
        b_ch.close()
        close()


def test_journey_across_two_real_processes():
    """The disagg split with the prefill tier in a REAL child process:
    the child's /traces (fetched over its serving port) carries spans of
    the SAME trace_id the parent's decode journey used."""
    import urllib.request

    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import DisaggClient, serve_decode

    tracing.force(True)
    d_srv, d_port, d_sched, d_state = serve_decode(
        ToyDecodeModel(), kv_blocks=96, block_bytes=256, kv_kind="shm",
        name="twoproc")
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    env["TPURPC_TRACE_SAMPLE"] = "1"
    child = subprocess.Popen(
        [sys.executable, "-m", "tpurpc.tools.odyssey_smoke", "--prefill",
         f"127.0.0.1:{d_port}"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True)
    try:
        line = child.stdout.readline().strip()
        assert line.startswith("PORT "), line
        p_port = int(line.split()[1])
        p_ch = Channel(f"127.0.0.1:{p_port}")
        cli = DisaggClient(p_ch, f"127.0.0.1:{d_port}", account="t-2p")
        prompt = np.arange(48, dtype=np.int32) % 23
        ctx = tracing.TraceContext(0xFEED0002, 1)
        with tracing.use(ctx):
            pairs = list(cli.generate_with_meta(prompt, max_tokens=8,
                                                timeout=20))
        assert [t for _i, t in pairs] == reference_decode(prompt, 8)
        # decode-side journey spans, locally
        local = {s["name"] for s in tracing.spans(ctx.trace_id)}
        assert "seq-ship" in local and "seq-decode" in local, local
        # prefill-side spans of the SAME trace, via the child's exporter
        with urllib.request.urlopen(
                f"http://127.0.0.1:{p_port}/traces?trace_id="
                f"{ctx.trace_id:016x}", timeout=5) as resp:
            peer = json.loads(resp.read())
        peer_spans = [e for e in peer["traceEvents"]
                      if e.get("ph") == "X"]
        assert peer_spans, "prefill process exported no spans"
        assert peer.get("clock_anchor"), "peer missing clock anchor"
        # merged journey: two anchored lanes
        doc = odyssey.journey([f"127.0.0.1:{d_port}",
                               f"127.0.0.1:{p_port}"], ctx.trace_id)
        assert doc["otherData"]["lanes"] >= 2
        assert not doc["otherData"]["unanchored"]
        cli.close()
        p_ch.close()
    finally:
        try:
            child.stdin.close()
            child.wait(timeout=10)
        except Exception:
            child.kill()
        d_srv.stop(grace=0)
        d_sched.close()
        d_state.close()
        d_state.mgr.close()


# ---------------------------------------------------------------------------
# SLO track kinds: ITL / TTFT burn-rate objectives
# ---------------------------------------------------------------------------

def _private_db(**kw) -> Tsdb:
    reg = metrics.Registry()
    kw.setdefault("fine_s", 1.0)
    kw.setdefault("fine_window_s", 32.0)
    kw.setdefault("coarse_s", 8.0)
    kw.setdefault("coarse_window_s", 64.0)
    return Tsdb(registry=reg, **kw)


def test_slo_itl_objective_pending_firing_resolved():
    db = _private_db()
    g = db._registry.gauge("gen_itl_p99_us{interactive}")
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    obj = ev.declare(obs_slo.SloObjective(
        "tok-itl", itl_ms=5.0, token_target_pct=50.0,
        windows=[(4.0, 8.0, 2.0)]))
    st = obj.tracks["itl"]
    assert obj._threshold_tracks["itl"][0] == \
        "gen_itl_p99_us{interactive}"
    for i in range(10):  # healthy: 1ms ITL
        g.set(1000.0)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert st.state == "ok"
    t = 10
    while st.state == "ok" and t < 30:  # degrade: 40ms ITL
        t += 1
        g.set(40_000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "pending"
    while st.state == "pending" and t < 45:
        t += 1
        g.set(40_000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "firing"
    fired_at = t
    while st.state == "firing" and t < fired_at + 30:  # recover
        t += 1
        g.set(1000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "ok"
    # flight bracket conforms to the slo-alert machine, track code 4
    evs = [e for e in flight.snapshot() if e["entity"] == "slo:tok-itl"]
    assert [e["event"] for e in evs] == ["slo-firing", "slo-resolved"]
    assert evs[0]["a1"] == obs_slo.TRACK_CODES["itl"] == 4
    assert protocol.check_events(flight.snapshot(), strict=False) == []


def test_slo_ttft_track_and_doc_shape():
    db = _private_db()
    db._registry.gauge("gen_ttft_p99_us{batch}").set(100.0)
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    obj = ev.declare(obs_slo.SloObjective(
        "tok-ttft", ttft_ms=200.0, slo_class="batch",
        windows=[(4.0, 8.0, 2.0)]))
    assert set(obj.tracks) == {"ttft"}
    assert obj._threshold_tracks["ttft"] == \
        ("gen_ttft_p99_us{batch}", 200_000.0)
    assert obs_slo.TRACK_CODES["ttft"] == 3
    doc = ev.doc()["objectives"][0]
    assert doc["ttft_ms"] == 200.0 and doc["slo_class"] == "batch"


def test_tsdb_samples_odyssey_rolling_series():
    """The process-wide tsdb picks up the odyssey rolling p99s (the
    sys.modules-gated hook) once tokens have flowed."""
    from tpurpc.obs import tsdb as tsdb_mod

    sched = DecodeScheduler(ToyDecodeModel(step_delay_s=0.002),
                            max_batch=2, name="roll")
    try:
        _drain(sched.submit([1], max_tokens=6, account="t-r"))
        _wait_done()
    finally:
        sched.close()
    assert odyssey.rolling_series().get(
        "gen_itl_p99_us{interactive}") is not None
    db = tsdb_mod.get()
    db.sample_once()
    assert "gen_itl_p99_us{interactive}" in db.series()
    assert "gen_ttft_p99_us{interactive}" in db.series()


# ---------------------------------------------------------------------------
# Routes, merges, off-switch
# ---------------------------------------------------------------------------

def test_debug_seq_route_filters_and_bounds():
    from tpurpc.obs import scrape

    sched = DecodeScheduler(ToyDecodeModel(), max_batch=4, name="route")
    try:
        _drain(sched.submit([1], max_tokens=4, account="t-x"))
        _drain(sched.submit([2], max_tokens=4, account="t-y"))
        _wait_done(2)
    finally:
        sched.close()
    status, ctype, body = scrape.route_local("/debug/seq")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["enabled"]
    assert {"t-x", "t-y"} <= set(doc["accounts"])
    status, _c, body = scrape.route_local("/debug/seq?account=t-x&n=1")
    filt = json.loads(body)
    assert all(r["account"] == "t-x" for r in filt["recent"])
    assert len(filt["recent"]) <= 1


def test_off_switch_env_and_force():
    odyssey.force(False)
    assert not odyssey.ACTIVE
    sched = DecodeScheduler(ToyDecodeModel(), max_batch=2, name="off")
    try:
        t0 = time.monotonic_ns()
        toks = _drain(sched.submit([1, 2], max_tokens=4,
                                   account="t-off"))
        assert len(toks) == 4  # serving is unaffected
    finally:
        sched.close()
    doc = odyssey.seq_doc()
    assert doc == {"enabled": False, "reason": "TPURPC_ODYSSEY=0"}
    # the flight SEQ_* edges stay (always-on postmortem contract)
    names = [e["event"] for e in flight.snapshot(since_ns=t0)]
    assert "seq-submit" in names and "seq-first-token" in names
    odyssey.force(None)
    # env gate honored by configure()
    os.environ["TPURPC_ODYSSEY"] = "0"
    try:
        odyssey.configure()
        assert not odyssey.ACTIVE
    finally:
        del os.environ["TPURPC_ODYSSEY"]
        odyssey.configure()
    assert odyssey.ACTIVE


def test_merge_seq_docs_sums_accounts_and_tags_rows():
    d1 = {"enabled": True,
          "live": [{"sid": 1, "account": "a", "step_us": 50.0}],
          "recent": [{"sid": 2, "account": "a", "step_us": 10.0}],
          "accounts": {"a": {"seqs": 2, "tokens": 10, "step_us": 60.0,
                             "kv_byte_s": 1.0}},
          "step_us_total": 100.0, "step_us_attributed": 98.0}
    d2 = {"enabled": True, "live": [],
          "recent": [{"sid": 9, "account": "a", "step_us": 70.0}],
          "accounts": {"a": {"seqs": 1, "tokens": 5, "step_us": 70.0},
                       "b": {"seqs": 1, "tokens": 2, "step_us": 5.0}},
          "step_us_total": 80.0, "step_us_attributed": 80.0}
    out = odyssey.merge_seq_docs({"0": d1, "1": d2}, label="shard")
    assert out["enabled"]
    assert out["accounts"]["a"]["seqs"] == 3
    assert out["accounts"]["a"]["tokens"] == 15
    assert out["accounts"]["b"]["seqs"] == 1
    assert out["step_us_total"] == 180.0
    assert out["attributed_pct"] == round(178 / 180 * 100, 2)
    assert out["live"][0]["shard"] == "0"
    assert {r["shard"] for r in out["recent"]} == {"0", "1"}
    # a disabled/unreachable source merges to disabled-only-if-all-are
    assert odyssey.merge_seq_docs({"0": {"enabled": False}})["enabled"] \
        is False


def test_collector_fleet_seq_member_merge():
    from tpurpc.obs.collector import FleetCollector
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import GenerationClient, serve_generation

    srv, port, sched = serve_generation(ToyDecodeModel(), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch, account="t-fleet")
            assert len(list(gen.generate([3, 4], max_tokens=5,
                                         timeout=20))) == 5
        col = FleetCollector([f"127.0.0.1:{port}"], poll_s=60)
        col.poll_once()
        status, ctype, body = col.route("/fleet/seq")
        assert status == 200
        doc = json.loads(body)
        assert doc["enabled"]
        assert doc["accounts"]["t-fleet"]["tokens"] >= 5
        member = f"127.0.0.1:{port}"
        assert doc["members"][member] == "up"
        assert all(r["member"] == member for r in doc["recent"])
    finally:
        srv.stop(grace=0)
        sched.close()


def test_generation_rpc_attaches_account_and_trace():
    """End-to-end over the RPC face: the tpurpc-account metadata key and
    the call's (tail-provisional) trace context reach the ledger."""
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import GenerationClient, serve_generation

    srv, port, sched = serve_generation(ToyDecodeModel(), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            toks = list(gen.generate([1, 2], max_tokens=4,
                                     account="t-rpc", timeout=20))
            assert len(toks) == 4
        doc = _wait_done()
    finally:
        srv.stop(grace=0)
        sched.close()
    led = [r for r in doc["recent"] if r["account"] == "t-rpc"]
    assert led, doc["recent"]
    assert "trace_id" in led[0]  # tail capture gave it a journey context
