"""Multi-connection fan-in on the shared-poller native server.

VERDICT r3 next-round #2: the reference's Poller multiplexes up to 4096
pairs over N background threads (``/root/reference/src/core/lib/ibverbs/
poller.cc:52-106``); round 3's native server spawned a reader thread per
connection plus a thread per call, an architecture that cannot reach
128-connection fan-in on shared cores. These tests pin the rework
(``native/src/tpurpc_server.cc``): many concurrent ring connections served
with BOUNDED server threads, every connection's calls succeeding.
"""

import os
import subprocess
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRV_BIN = os.path.join(ROOT, "native", "build", "cpp_server_example")

from tests.conftest import requires_native_lib  # noqa: E402

pytestmark = requires_native_lib


def _start_server(env):
    from tests.test_cpp_api import _build_server_example

    _build_server_example()
    proc = subprocess.Popen([SRV_BIN], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True, env=env)
    port = int(proc.stdout.readline().split()[1])
    return proc, port


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BP"])
def test_many_connections_bounded_server_threads(platform, monkeypatch):
    """64 concurrent connections, one RPC each, while the server runs a
    BOUNDED thread count (accept + pollers + main — not a reader per
    connection). 64 (not 128) keeps the CI cost sane on the 1-core host;
    bench/scalability.sh sweeps the full 1/8/32/128 axis."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    env = dict(os.environ, GRPC_PLATFORM_TYPE=platform)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc, port = _start_server(env)
    try:
        from tpurpc.rpc.native_client import NativeChannel

        N = 64
        chans, errs = [], []
        lock = threading.Lock()

        def mk():
            try:
                ch = NativeChannel("127.0.0.1", port, connect_timeout=60)
                with lock:
                    chans.append(ch)
            except Exception as exc:  # surfaced below
                errs.append(exc)

        ts = [threading.Thread(target=mk) for _ in range(N)]
        [t.start() for t in ts]
        [t.join(120) for t in ts]
        assert not errs, errs[:3]
        assert len(chans) == N
        ok = 0
        for ch in chans:
            if ch.unary_unary("/demo.Greeter/Echo")(b"x", timeout=60) == b"x":
                ok += 1
        nthreads = len(os.listdir(f"/proc/{proc.pid}/task"))
        assert ok == N
        # the old architecture held N reader threads here; the shared
        # poller holds accept + pollers (default 1) + handler stragglers
        assert nthreads <= 12, (
            f"server holds {nthreads} threads for {N} connections — "
            "thread-per-connection regression")
        for ch in chans:
            ch.close()
    finally:
        proc.kill()
        proc.wait()


def test_interleaved_traffic_across_connections():
    """Frames from many connections interleave on ONE poller thread: each
    stream's bytes must still demux to its own call (per-stream routing
    under multiplexing, with concurrent bursts)."""
    env = dict(os.environ, GRPC_PLATFORM_TYPE="RDMA_BP")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc, port = _start_server(env)
    try:
        from tpurpc.rpc.native_client import NativeChannel

        N, CALLS = 8, 25
        errs = []

        def client(idx):
            try:
                with NativeChannel("127.0.0.1", port,
                                   connect_timeout=60) as ch:
                    echo = ch.unary_unary("/demo.Greeter/Echo")
                    for j in range(CALLS):
                        body = (f"c{idx}-{j}-".encode() + b"p" * (idx * 37))
                        assert echo(body, timeout=60) == body
            except Exception as exc:
                errs.append(exc)

        ts = [threading.Thread(target=client, args=(i,)) for i in range(N)]
        [t.start() for t in ts]
        [t.join(180) for t in ts]
        assert not errs, errs[:3]
    finally:
        proc.kill()
        proc.wait()
