"""Codegen layer (SURVEY L8 / reference src/compiler):

1. the tpurpc protoc plugin generates working native stubs end to end,
2. modules shaped exactly like grpc_tools.protoc output (stub calling
   ``channel.unary_unary(..., _registered_method=True)``; server side
   calling ``add_generic_rpc_handlers`` + ``add_registered_method_handlers``
   with grpcio handler OBJECTS) run unchanged on tpurpc, and
3. protobuf_codec wires generated message classes to any handler.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import grpc
import pytest

import tpurpc.rpc as tps
from tpurpc.codegen import protobuf_codec

PROTO = textwrap.dedent("""\
    syntax = "proto3";
    package demo;

    message Ping { string text = 1; int32 n = 2; }
    message Pong { string text = 1; int32 total = 2; }

    service Greeter {
      rpc Hello (Ping) returns (Pong);
      rpc Tail (Ping) returns (stream Pong);
      rpc Sum (stream Ping) returns (Pong);
      rpc Chat (stream Ping) returns (stream Pong);
    }
    """)


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    """protoc --python_out + our plugin --tpurpc_out, imported from tmp."""
    out = tmp_path_factory.mktemp("gen")
    (out / "demo.proto").write_text(PROTO)
    shim = out / "protoc-gen-tpurpc"
    shim.write_text(f"#!/bin/sh\nexec {sys.executable} -m tpurpc.codegen.plugin\n")
    shim.chmod(0o755)
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(Path(__file__).resolve().parent.parent)
                         + os.pathsep + env.get("PYTHONPATH", ""))
    subprocess.run(
        ["protoc", f"--plugin=protoc-gen-tpurpc={shim}",
         f"--python_out={out}", f"--tpurpc_out={out}",
         f"-I{out}", "demo.proto"],
        check=True, env=env)
    sys.path.insert(0, str(out))
    try:
        import demo_pb2
        import demo_tpurpc
        yield demo_pb2, demo_tpurpc
    finally:
        sys.path.remove(str(out))
        for mod in ("demo_pb2", "demo_tpurpc"):
            sys.modules.pop(mod, None)


class _GreeterImpl:
    def Hello(self, request, context):
        import demo_pb2

        return demo_pb2.Pong(text=f"hello {request.text}", total=request.n)

    def Tail(self, request, context):
        import demo_pb2

        for i in range(request.n):
            yield demo_pb2.Pong(text=request.text, total=i)

    def Sum(self, request_iterator, context):
        import demo_pb2

        total = sum(r.n for r in request_iterator)
        return demo_pb2.Pong(text="sum", total=total)

    def Chat(self, request_iterator, context):
        import demo_pb2

        for r in request_iterator:
            yield demo_pb2.Pong(text=f"re:{r.text}", total=r.n)


def test_plugin_generated_stubs_end_to_end(generated):
    demo_pb2, demo_tpurpc = generated
    srv = tps.Server(max_workers=4)
    demo_tpurpc.add_GreeterServicer_to_server(_GreeterImpl(), srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            stub = demo_tpurpc.GreeterStub(ch)
            pong = stub.Hello(demo_pb2.Ping(text="tpu", n=7), timeout=20)
            assert (pong.text, pong.total) == ("hello tpu", 7)
            tails = list(stub.Tail(demo_pb2.Ping(text="t", n=3), timeout=20))
            assert [p.total for p in tails] == [0, 1, 2]
            s = stub.Sum(iter([demo_pb2.Ping(n=i) for i in (1, 2, 3)]),
                         timeout=20)
            assert s.total == 6
            chats = list(stub.Chat(iter([demo_pb2.Ping(text="x", n=1)]),
                                   timeout=20))
            assert chats[0].text == "re:x"
    finally:
        srv.stop(grace=0)


def test_plugin_unimplemented_servicer_base(generated):
    demo_pb2, demo_tpurpc = generated
    srv = tps.Server(max_workers=2)
    demo_tpurpc.add_GreeterServicer_to_server(
        demo_tpurpc.GreeterServicer(), srv)  # base class: all UNIMPLEMENTED
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            stub = demo_tpurpc.GreeterStub(ch)
            with pytest.raises(tps.RpcError) as ei:
                stub.Hello(demo_pb2.Ping(text="x"), timeout=20)
            assert ei.value.code() is tps.StatusCode.UNIMPLEMENTED
    finally:
        srv.stop(grace=0)


# ---------------------------------------------------------------------------
# Stock grpc_tools-SHAPED module (faithful mimic of its generated output —
# grpcio-tools isn't installed here, so the generated text is inlined).
# ---------------------------------------------------------------------------

def _make_grpcio_style_module(demo_pb2):
    class GreeterStub:
        """Byte-for-byte the call shape grpc_tools.protoc emits."""

        def __init__(self, channel):
            self.Hello = channel.unary_unary(
                "/demo.Greeter/Hello",
                request_serializer=demo_pb2.Ping.SerializeToString,
                response_deserializer=demo_pb2.Pong.FromString,
                _registered_method=True)
            self.Tail = channel.unary_stream(
                "/demo.Greeter/Tail",
                request_serializer=demo_pb2.Ping.SerializeToString,
                response_deserializer=demo_pb2.Pong.FromString,
                _registered_method=True)
            self.Sum = channel.stream_unary(
                "/demo.Greeter/Sum",
                request_serializer=demo_pb2.Ping.SerializeToString,
                response_deserializer=demo_pb2.Pong.FromString,
                _registered_method=True)

    def add_GreeterServicer_to_server(servicer, server):
        rpc_method_handlers = {
            "Hello": grpc.unary_unary_rpc_method_handler(
                servicer.Hello,
                request_deserializer=demo_pb2.Ping.FromString,
                response_serializer=demo_pb2.Pong.SerializeToString),
            "Tail": grpc.unary_stream_rpc_method_handler(
                servicer.Tail,
                request_deserializer=demo_pb2.Ping.FromString,
                response_serializer=demo_pb2.Pong.SerializeToString),
            "Sum": grpc.stream_unary_rpc_method_handler(
                servicer.Sum,
                request_deserializer=demo_pb2.Ping.FromString,
                response_serializer=demo_pb2.Pong.SerializeToString),
        }
        generic_handler = grpc.method_handlers_generic_handler(
            "demo.Greeter", rpc_method_handlers)
        server.add_generic_rpc_handlers((generic_handler,))
        server.add_registered_method_handlers("demo.Greeter",
                                              rpc_method_handlers)

    return GreeterStub, add_GreeterServicer_to_server


def test_stock_grpcio_generated_shapes_run_on_tpurpc(generated):
    """The mechanical-port claim: a grpc_tools-generated module — grpcio
    handler objects, generic handler registration, _registered_method kwarg
    and all — drives a tpurpc server AND a tpurpc channel unchanged."""
    demo_pb2, _ = generated
    GreeterStub, add_to_server = _make_grpcio_style_module(demo_pb2)

    srv = tps.Server(max_workers=4)
    add_to_server(_GreeterImpl(), srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            stub = GreeterStub(ch)
            pong = stub.Hello(demo_pb2.Ping(text="port", n=3), timeout=20)
            assert (pong.text, pong.total) == ("hello port", 3)
            assert [p.total for p in
                    stub.Tail(demo_pb2.Ping(text="t", n=2), timeout=20)] == [0, 1]
            assert stub.Sum(iter([demo_pb2.Ping(n=5), demo_pb2.Ping(n=6)]),
                            timeout=20).total == 11
        # and the same stub drives a STOCK grpcio client channel against the
        # tpurpc server's h2 path (generated modules are channel-agnostic)
        with grpc.insecure_channel(f"127.0.0.1:{port}") as gch:
            gstub = GreeterStub(gch)
            assert gstub.Hello(demo_pb2.Ping(text="h2", n=1),
                               timeout=20).text == "hello h2"
    finally:
        srv.stop(grace=0)


def test_protobuf_codec_roundtrip(generated):
    demo_pb2, _ = generated
    ser, deser = protobuf_codec(demo_pb2.Ping)
    msg = demo_pb2.Ping(text="abc", n=42)
    back = deser(memoryview(ser(msg)))  # views, as the rpc layer delivers
    assert (back.text, back.n) == ("abc", 42)


def test_protobuf_codec_with_handlers(generated):
    demo_pb2, _ = generated
    ping_ser, ping_deser = protobuf_codec(demo_pb2.Ping)
    pong_ser, pong_deser = protobuf_codec(demo_pb2.Pong)

    srv = tps.Server(max_workers=2)
    srv.add_method("/demo.Greeter/Hello", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: demo_pb2.Pong(text=req.text.upper(), total=req.n),
        request_deserializer=ping_deser, response_serializer=pong_ser))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/demo.Greeter/Hello", ping_ser, pong_deser)
            pong = mc(demo_pb2.Ping(text="up", n=9), timeout=20)
            assert (pong.text, pong.total) == ("UP", 9)
    finally:
        srv.stop(grace=0)


def test_plugin_cross_file_message_types(tmp_path):
    """Service methods using messages from an IMPORTED .proto must resolve
    to THAT file's pb2 module (reviewer finding: broken refs crashed the
    generated module on import)."""
    (tmp_path / "types.proto").write_text(textwrap.dedent("""\
        syntax = "proto3";
        package shared;
        message Blob { bytes data = 1; }
        """))
    (tmp_path / "svc.proto").write_text(textwrap.dedent("""\
        syntax = "proto3";
        package app;
        import "types.proto";
        message Ack { int32 size = 1; }
        service Store { rpc Put (shared.Blob) returns (Ack); }
        """))
    shim = tmp_path / "protoc-gen-tpurpc"
    shim.write_text(
        f"#!/bin/sh\nexec {sys.executable} -m tpurpc.codegen.plugin\n")
    shim.chmod(0o755)
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(Path(__file__).resolve().parent.parent)
                         + os.pathsep + env.get("PYTHONPATH", ""))
    subprocess.run(
        ["protoc", f"--plugin=protoc-gen-tpurpc={shim}",
         f"--python_out={tmp_path}", f"--tpurpc_out={tmp_path}",
         f"-I{tmp_path}", "svc.proto", "types.proto"],
        check=True, env=env)
    sys.path.insert(0, str(tmp_path))
    try:
        import svc_pb2
        import svc_tpurpc
        import types_pb2

        srv = tps.Server(max_workers=2)

        class Impl(svc_tpurpc.StoreServicer):
            def Put(self, request, context):
                return svc_pb2.Ack(size=len(request.data))

        svc_tpurpc.add_StoreServicer_to_server(Impl(), srv)
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                stub = svc_tpurpc.StoreStub(ch)
                ack = stub.Put(types_pb2.Blob(data=b"12345"), timeout=20)
                assert ack.size == 5
        finally:
            srv.stop(grace=0)
    finally:
        sys.path.remove(str(tmp_path))
        for mod in ("svc_pb2", "svc_tpurpc", "types_pb2"):
            sys.modules.pop(mod, None)
