"""RPC layer tests: four call shapes × transports, status/deadline/cancel semantics.

Mirrors the reference's test strategy (SURVEY.md §4): the end2end matrix runs the
*same* RPC behaviors over every byte pipe — inproc (passthru endpoints), loopback TCP,
and the shm ring platforms — because the layers above the endpoint seam must not be
able to tell the difference.
"""

import threading
import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc import frame as fr
from tpurpc.rpc.status import StatusCode


# ---------------------------------------------------------------------------
# Frame codec unit tests
# ---------------------------------------------------------------------------

def test_metadata_roundtrip():
    md = [("k", "v"), ("data-bin", b"\x00\xff"), ("empty", "")]
    blob = fr.encode_metadata(md)
    out, consumed = fr.decode_metadata(blob)
    assert consumed == len(blob)
    assert out == md


def test_headers_roundtrip():
    payload = fr.headers_payload("/svc/M", [("a", "b")], timeout_us=123456)
    path, timeout_us, md = fr.parse_headers(payload)
    assert path == "/svc/M"
    assert timeout_us == 123456
    assert md == [("a", "b")]


def test_trailers_roundtrip():
    payload = fr.trailers_payload(StatusCode.NOT_FOUND, "nope", [("x", "y")])
    code, details, md = fr.parse_trailers(payload)
    assert code is StatusCode.NOT_FOUND
    assert details == "nope"
    assert md == [("x", "y")]


# ---------------------------------------------------------------------------
# Service fixture used across transports
# ---------------------------------------------------------------------------

def _echo(request: bytes, context) -> bytes:
    return request


def _fail(request: bytes, context):
    context.abort(StatusCode.PERMISSION_DENIED, "not allowed")


def _slow(request: bytes, context) -> bytes:
    time.sleep(1.0)
    return request


def _count(request: bytes, context):
    for i in range(int(request)):
        yield str(i).encode()


def _total(request_iterator, context) -> bytes:
    return str(sum(int(x) for x in request_iterator)).encode()


def _double_each(request_iterator, context):
    for x in request_iterator:
        yield str(int(x) * 2).encode()


def _md_echo(request: bytes, context) -> bytes:
    context.set_trailing_metadata([("seen", str(len(context.invocation_metadata())))])
    return request


def make_server() -> rpc.Server:
    srv = rpc.server(max_workers=8)
    srv.add_service("t.Echo", {
        "Echo": rpc.unary_unary_rpc_method_handler(_echo),
        "Fail": rpc.unary_unary_rpc_method_handler(_fail),
        "Slow": rpc.unary_unary_rpc_method_handler(_slow),
        "Count": rpc.unary_stream_rpc_method_handler(_count),
        "Total": rpc.stream_unary_rpc_method_handler(_total),
        "DoubleEach": rpc.stream_stream_rpc_method_handler(_double_each),
        "MdEcho": rpc.unary_unary_rpc_method_handler(_md_echo),
    })
    return srv


@pytest.fixture(params=["inproc", "tcp"])
def channel(request):
    srv = make_server()
    if request.param == "inproc":
        srv.start()
        ch = rpc.inproc_channel(srv)
    else:
        srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        ch = rpc.insecure_channel(f"127.0.0.1:{srv.bound_ports[0]}")
    yield ch
    ch.close()
    srv.stop(grace=0.2)


# ---------------------------------------------------------------------------
# The four call shapes
# ---------------------------------------------------------------------------

def test_unary_unary(channel):
    echo = channel.unary_unary("/t.Echo/Echo")
    assert echo(b"hello tpu", timeout=10) == b"hello tpu"


def test_unary_unary_large_fragmented(channel):
    echo = channel.unary_unary("/t.Echo/Echo")
    big = bytes(range(256)) * (3 * fr.MAX_FRAME_PAYLOAD // 256 // 2)  # ~1.5 frames
    assert echo(big, timeout=30) == big


def test_unary_stream(channel):
    count = channel.unary_stream("/t.Echo/Count")
    got = [int(x) for x in count(b"5", timeout=10)]
    assert got == [0, 1, 2, 3, 4]


def test_stream_unary(channel):
    total = channel.stream_unary("/t.Echo/Total")
    assert total(iter([b"1", b"2", b"3"]), timeout=10) == b"6"


def test_stream_stream(channel):
    double = channel.stream_stream("/t.Echo/DoubleEach")
    got = [int(x) for x in double(iter([b"1", b"2", b"3"]), timeout=10)]
    assert got == [2, 4, 6]


def test_concurrent_calls_multiplexed(channel):
    echo = channel.unary_unary("/t.Echo/Echo")
    results = {}
    errs = []

    def worker(i):
        try:
            results[i] = echo(str(i).encode() * 100, timeout=20)
        except Exception as exc:  # pragma: no cover
            errs.append(exc)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs
    assert results == {i: str(i).encode() * 100 for i in range(16)}


# ---------------------------------------------------------------------------
# Status, deadline, cancel, metadata
# ---------------------------------------------------------------------------

def test_abort_surfaces_status(channel):
    fail = channel.unary_unary("/t.Echo/Fail")
    with pytest.raises(rpc.RpcError) as ei:
        fail(b"x", timeout=10)
    assert ei.value.code() is StatusCode.PERMISSION_DENIED
    assert "not allowed" in ei.value.details()


def test_unimplemented(channel):
    nope = channel.unary_unary("/t.Echo/NoSuchMethod")
    with pytest.raises(rpc.RpcError) as ei:
        nope(b"x", timeout=10)
    assert ei.value.code() is StatusCode.UNIMPLEMENTED


def test_deadline_exceeded(channel):
    slow = channel.unary_unary("/t.Echo/Slow")
    t0 = time.monotonic()
    with pytest.raises(rpc.RpcError) as ei:
        slow(b"x", timeout=0.2)
    assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
    assert time.monotonic() - t0 < 0.9  # did not wait for the handler


def test_cancel_streaming(channel):
    count = channel.unary_stream("/t.Echo/Count")
    call = count(b"1000000", timeout=30)
    it = iter(call)
    next(it)
    call.cancel()
    with pytest.raises(rpc.RpcError) as ei:
        for _ in it:
            pass
    assert ei.value.code() is StatusCode.CANCELLED


def test_trailing_metadata(channel):
    md = channel.unary_unary("/t.Echo/MdEcho")
    resp, call = md.with_call(b"x", timeout=10, metadata=[("a", "1"), ("b", "2")])
    assert resp == b"x"
    assert ("seen", "2") in list(call.trailing_metadata())
    assert call.code() is StatusCode.OK


def test_handler_exception_maps_to_unknown(channel):
    count = channel.unary_stream("/t.Echo/Count")
    with pytest.raises(rpc.RpcError) as ei:
        list(count(b"not-a-number", timeout=10))
    assert ei.value.code() is StatusCode.UNKNOWN


# ---------------------------------------------------------------------------
# Transport failure → UNAVAILABLE → reconnect
# ---------------------------------------------------------------------------

def test_stopped_server_refuses_late_adoptions():
    """Regression (round-2 reconnect bug): a connection whose protocol sniff
    completes after ``stop()`` must be refused, not adopted — an adopted one
    would answer every call "server shutting down" forever and the client,
    seeing healthy trailers, would never redial."""
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    srv = make_server()
    srv.start()
    srv.stop(grace=0)
    a, b = passthru_endpoint_pair()
    srv.serve_endpoint(b)  # the racy late adoption, made deterministic
    ch = Channel(endpoint_factory=lambda: a)
    echo = ch.unary_unary("/t.Echo/Echo")
    with pytest.raises(rpc.RpcError) as ei:
        echo(b"x", timeout=3)
    assert ei.value.code() in (StatusCode.UNAVAILABLE,
                               StatusCode.DEADLINE_EXCEEDED)
    # the stopped server must hold no live connection
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and srv._connections:
        time.sleep(0.02)
    assert not srv._connections
    ch.close()


def test_pool_rejection_kills_connection_so_client_redials(monkeypatch):
    """Regression (round-2 reconnect bug, defense in depth): if a live
    connection's server can no longer run handlers, the *connection* must
    die with the rejected call — a client stuck on it would otherwise retry
    against the same husk for its whole deadline."""
    # this test drives the PYTHON transport's connection machinery; keep
    # the unary fast path (which would bypass it entirely) off
    monkeypatch.setenv("TPURPC_NATIVE_FAST_UNARY", "0")
    srv = make_server()
    srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    port = srv.bound_ports[0]
    ch = rpc.insecure_channel(f"127.0.0.1:{port}")
    echo = ch.unary_unary("/t.Echo/Echo")
    assert echo(b"a", timeout=10) == b"a"
    conn = ch._subchannels[0]._conn
    assert conn is not None and conn.alive
    srv._pool.shutdown(wait=False)  # simulate the stale-adoption state
    with pytest.raises(rpc.RpcError) as ei:
        echo(b"b", timeout=3)
    assert ei.value.code() is StatusCode.UNAVAILABLE
    # the husk connection must be torn down so the next call redials
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and conn.alive:
        time.sleep(0.02)
    assert not conn.alive
    ch.close()
    srv.stop(grace=0)


def test_server_gone_maps_unavailable_then_reconnects():
    srv = make_server()
    srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    port = srv.bound_ports[0]
    ch = rpc.insecure_channel(f"127.0.0.1:{port}")
    echo = ch.unary_unary("/t.Echo/Echo")
    assert echo(b"a", timeout=10) == b"a"

    srv.stop(grace=0)
    with pytest.raises(rpc.RpcError) as ei:
        echo(b"b", timeout=3)
    assert ei.value.code() is StatusCode.UNAVAILABLE

    # Bring a fresh server up on the same port: channel must recover.
    srv2 = make_server()
    srv2.add_insecure_port(f"127.0.0.1:{port}")
    srv2.start()
    deadline = time.monotonic() + 60  # generous: shared-core CI jitter
    attempts = 0
    while True:
        try:
            assert echo(b"c", timeout=5) == b"c"
            break
        except rpc.RpcError as exc:
            attempts += 1
            if time.monotonic() > deadline:
                # rare load-dependent flake: make the escape diagnosable
                raise AssertionError(
                    f"reconnect never succeeded: {attempts} attempts over "
                    f"60s, last error {exc!r}, subchannel "
                    f"{ch._subchannels[0].__dict__}") from exc
            time.sleep(0.1)
    ch.close()
    srv2.stop(grace=0.2)


def test_ping(channel):
    rtt = channel.ping(timeout=5)
    assert rtt < 5


def test_ping_unresponsive_peer_times_out():
    """A peer that accepts bytes but never replies must fail the ping, not
    fake success (regression: ping used to return unconditionally)."""
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    a, b = passthru_endpoint_pair()  # nobody reads b: silent peer
    ch = Channel(endpoint_factory=lambda: a)
    with pytest.raises(rpc.RpcError) as ei:
        ch.ping(timeout=0.3)
    assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
    ch.close()


# ---------------------------------------------------------------------------
# Regressions from code review
# ---------------------------------------------------------------------------

def test_empty_unary_request_delivered(channel):
    """b'' is a legal request (default-valued proto) and must reach the handler."""
    echo = channel.unary_unary("/t.Echo/Echo")
    assert echo(b"", timeout=10) == b""


def test_empty_messages_in_streams(channel):
    total = channel.stream_unary("/t.Echo/Total")
    # empty payloads are still messages; int(b"") raises → UNKNOWN, which proves
    # the empty message was delivered rather than swallowed as a half-close
    with pytest.raises(rpc.RpcError) as ei:
        total(iter([b"1", b""]), timeout=10)
    assert ei.value.code() is StatusCode.UNKNOWN


def test_crashing_request_iterator_fails_fast(channel):
    """An exception in the user's request iterator must terminate the call
    promptly (regression: used to hang until deadline)."""

    def bad_iter():
        yield b"1"
        raise ValueError("boom")

    total = channel.stream_unary("/t.Echo/Total")
    t0 = time.monotonic()
    with pytest.raises(rpc.RpcError) as ei:
        total(bad_iter(), timeout=30)
    assert time.monotonic() - t0 < 5
    assert ei.value.code() is StatusCode.CANCELLED


def test_oversized_metadata_fails_stream_not_connection(channel):
    echo = channel.unary_unary("/t.Echo/Echo")
    with pytest.raises(rpc.RpcError) as ei:
        echo(b"x", timeout=10, metadata=[("big", "v" * (2 * fr.MAX_FRAME_PAYLOAD))])
    assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
    # connection survives: next call works
    assert echo(b"still alive", timeout=10) == b"still alive"


def test_keepalive_detects_dead_peer(monkeypatch):
    """GRPC_ARG_KEEPALIVE_TIME_MS: an unresponsive peer (accepts bytes,
    never answers the PING) must be detected and the connection killed, so
    the next call dials fresh instead of hanging."""
    import time as _time

    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel
    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIME_MS", "100")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIMEOUT_MS", "200")
    config_mod.set_config(None)  # re-read env

    a, b = passthru_endpoint_pair()  # b swallows everything, answers nothing
    ch = Channel(endpoint_factory=lambda: a)
    conn = ch._connection()
    assert conn.alive
    deadline = _time.monotonic() + 5
    while conn.alive and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert not conn.alive  # keepalive declared the silent peer dead
    ch.close()


def test_max_connection_age_drains_gracefully(monkeypatch):
    """GRPC_ARG_MAX_CONNECTION_AGE_MS: the server GOAWAYs an aged
    connection; an in-flight call completes, and the NEXT call transparently
    lands on a fresh connection."""
    monkeypatch.setenv("TPURPC_NATIVE_FAST_UNARY", "0")  # tests the Python transport
    import time as _time

    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_ARG_MAX_CONNECTION_AGE_MS", "300")
    config_mod.set_config(None)

    srv = rpc.Server(max_workers=4)

    def slow_echo(req, ctx):
        _time.sleep(0.6)           # alive across the age expiry
        return bytes(req)

    srv.add_method("/t.Age/Slow", rpc.unary_unary_rpc_method_handler(slow_echo))
    srv.add_method("/t.Age/Fast",
                   rpc.unary_unary_rpc_method_handler(lambda b, c: bytes(b)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            # starts before the age expires, finishes after: must succeed
            assert ch.unary_unary("/t.Age/Slow")(b"x", timeout=10) == b"x"
            conn1 = ch._subchannels[0]._conn
            # subsequent calls re-dial (old conn drained); repeated calls
            # must keep working across successive aged connections
            for _ in range(3):
                assert ch.unary_unary("/t.Age/Fast")(b"y", timeout=10) == b"y"
            assert ch._subchannels[0]._conn is not conn1 \
                or not conn1.alive or conn1.draining
    finally:
        srv.stop(grace=0)


def test_client_idle_timeout_closes_and_redials(monkeypatch):
    """GRPC_ARG_CLIENT_IDLE_TIMEOUT_MS: an idle connection is dropped;
    the next call dials fresh and succeeds."""
    monkeypatch.setenv("TPURPC_NATIVE_FAST_UNARY", "0")  # tests the Python transport
    import time as _time

    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_ARG_CLIENT_IDLE_TIMEOUT_MS", "200")
    config_mod.set_config(None)

    srv = make_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            echo = ch.unary_unary("/t.Echo/Echo")
            assert echo(b"1", timeout=10) == b"1"
            conn = ch._subchannels[0]._conn
            deadline = _time.monotonic() + 5
            while conn.alive and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert not conn.alive          # idle monitor closed it
            assert echo(b"2", timeout=10) == b"2"   # transparent re-dial
    finally:
        srv.stop(grace=0)


def test_graceful_stop_drains_inflight_and_refuses_new():
    """stop(grace): in-flight calls complete through the grace window
    (GOAWAY announced, grpcio parity); calls started after stop fail fast
    with UNAVAILABLE instead of hanging."""
    import time as _time

    srv = rpc.Server(max_workers=4)
    entered = threading.Event()

    def slow(req, ctx):
        entered.set()
        _time.sleep(0.5)
        return b"done:" + bytes(req)

    srv.add_method("/t.Stop/Slow", rpc.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    ch = rpc.Channel(f"127.0.0.1:{port}")
    result = {}

    def call():
        result["v"] = bytes(ch.unary_unary("/t.Stop/Slow")(b"x", timeout=10))

    t = threading.Thread(target=call)
    t.start()
    assert entered.wait(timeout=10)
    stopper = threading.Thread(target=lambda: srv.stop(grace=5))
    stopper.start()
    t.join(timeout=10)
    stopper.join(timeout=10)
    assert result.get("v") == b"done:x"        # drained, not killed
    with pytest.raises(rpc.RpcError) as ei:
        ch.unary_unary("/t.Stop/Slow")(b"y", timeout=3)
    assert ei.value.code() is StatusCode.UNAVAILABLE
    ch.close()


def test_server_keepalive_reaps_silent_client(monkeypatch):
    """Symmetric server keepalive: a client that connects, talks once, then
    goes silent without closing (half-dead peer) is reaped within
    time+timeout, freeing the server-side connection state."""
    import socket as _socket
    import time as _time

    from tpurpc.rpc import frame as fr
    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIME_MS", "150")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIMEOUT_MS", "300")
    config_mod.set_config(None)

    srv = make_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        raw = _socket.create_connection(("127.0.0.1", port), timeout=5)
        raw.sendall(fr.MAGIC)          # valid preface, then dead air
        _time.sleep(0.3)
        with srv._lock:
            assert any(c.alive for c in srv._connections)  # admitted
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            with srv._lock:
                if not any(c.alive for c in srv._connections):
                    break
            _time.sleep(0.05)
        with srv._lock:
            assert not any(c.alive for c in srv._connections)  # reaped
        raw.close()
    finally:
        srv.stop(grace=0)


def test_server_keepalive_spares_ponging_idle_client(monkeypatch):
    """A client that answers the server's keepalive PINGs — and sends
    NOTHING else (its own keepalive disabled via a raw responder, so the
    PONG path itself is what keeps it alive) — must not be reaped."""
    import socket as _socket
    import struct as _struct
    import threading as _threading
    import time as _time

    from tpurpc.rpc import frame as fr
    from tpurpc.utils import config as config_mod

    # generous timeout: the PONG responder is a Python thread that polls at
    # 200 ms — on a loaded 1-core CI box it can be starved for over a
    # second, which must not read as a dead peer
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIME_MS", "300")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIMEOUT_MS", "3000")
    config_mod.set_config(None)

    srv = make_server()
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    raw = _socket.create_connection(("127.0.0.1", port), timeout=5)
    stop = _threading.Event()

    def pong_responder():
        raw.sendall(fr.MAGIC)
        buf = b""
        raw.settimeout(0.2)
        while not stop.is_set():
            try:
                data = raw.recv(4096)
            except _socket.timeout:
                continue
            except OSError:
                return
            if not data:
                return
            buf += data
            while len(buf) >= 10:
                ftype, flags, sid, ln = _struct.unpack_from("<BBII", buf)
                if len(buf) < 10 + ln:
                    break
                payload, buf = buf[10:10 + ln], buf[10 + ln:]
                if ftype == fr.PING:   # answer ONLY pings
                    raw.sendall(_struct.pack("<BBII", fr.PONG, 0, 0,
                                             len(payload)) + payload)

    t = _threading.Thread(target=pong_responder, daemon=True)
    t.start()
    try:
        _time.sleep(1.5)               # several ping windows
        with srv._lock:
            assert any(c.alive for c in srv._connections)  # spared
    finally:
        stop.set()
        t.join(timeout=2)
        raw.close()
        srv.stop(grace=0)


# ---------------------------------------------------------------------------
# Inline (reactor) unary handlers — the Python twin of the native callback
# API (tpr_server_register_callback): handler runs on the reader thread.
# ---------------------------------------------------------------------------

def test_inline_unary_handler_end_to_end():
    srv = rpc.Server(max_workers=2)
    srv.add_method("/i.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r) + b"!", inline=True))

    def md_reader(req, ctx):
        return dict(ctx.invocation_metadata()).get("k", "?").encode()

    srv.add_method("/i.S/Md", rpc.unary_unary_rpc_method_handler(
        md_reader, inline=True))

    def boom(req, ctx):
        raise RuntimeError("kaboom")

    srv.add_method("/i.S/Boom", rpc.unary_unary_rpc_method_handler(
        boom, inline=True))

    def abort(req, ctx):
        ctx.abort(StatusCode.PERMISSION_DENIED, "no")

    srv.add_method("/i.S/Abort", rpc.unary_unary_rpc_method_handler(
        abort, inline=True))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/i.S/Echo")(b"hi", timeout=10) == b"hi!"
            assert ch.unary_unary("/i.S/Echo")(b"", timeout=10) == b"!"
            big = b"B" * (2 << 20)  # fragmented request reassembles first
            assert ch.unary_unary("/i.S/Echo")(big, timeout=30) == big + b"!"
            assert ch.unary_unary("/i.S/Md")(
                b"", timeout=10, metadata=[("k", "v")]) == b"v"
            # handler exceptions map to UNKNOWN and the connection survives
            with pytest.raises(rpc.RpcError) as ei:
                ch.unary_unary("/i.S/Boom")(b"", timeout=10)
            assert ei.value.code() is StatusCode.UNKNOWN
            with pytest.raises(rpc.RpcError) as ei:
                ch.unary_unary("/i.S/Abort")(b"", timeout=10)
            assert ei.value.code() is StatusCode.PERMISSION_DENIED
            # the SAME connection keeps serving after inline errors
            assert ch.unary_unary("/i.S/Echo")(b"again", timeout=10) == b"again!"
    finally:
        srv.stop(grace=0)


def test_inline_rejected_for_streaming_kinds():
    from tpurpc.rpc.server import RpcMethodHandler

    with pytest.raises(ValueError):
        RpcMethodHandler("unary_stream", lambda r, c: iter([]), inline=True)


def test_inline_handler_deadline_without_body():
    """A client that opens an inline-method stream with a deadline but never
    sends the body must get DEADLINE_EXCEEDED and the stream must be reaped
    (review finding: the parked call used to leak forever)."""
    from tpurpc.core.endpoint import passthru_endpoint_pair

    srv = rpc.Server(max_workers=2)
    srv.add_method("/i.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r), inline=True))
    srv.start()
    a, b = passthru_endpoint_pair()
    srv.serve_endpoint(b)
    w = fr.FrameWriter(a)
    w.send_preface()
    # HEADERS with a 300ms deadline, then silence
    w.send(fr.HEADERS, 0, 1,
           fr.headers_payload("/i.S/Echo", [], timeout_us=300000))
    reader = fr.FrameReader(a)
    deadline = time.monotonic() + 10
    got = None
    while time.monotonic() < deadline:
        f = reader.read_frame()
        if f is None:
            break
        if f is not fr.CONSUMED and f.type == fr.TRAILERS:
            got = fr.parse_trailers(f.payload)
            break
    assert got is not None, "no trailers within 10s"
    assert got[0] is StatusCode.DEADLINE_EXCEEDED
    # the stream itself was reaped
    conn = srv._connections[0]
    t0 = time.monotonic()
    while conn._streams and time.monotonic() - t0 < 5:
        time.sleep(0.02)
    assert not conn._streams
    srv.stop(grace=0)


def test_keepalive_healthy_idle_survives_aggressive_knobs(monkeypatch):
    """Both sides keepalive at 400ms/400ms: a healthy-but-quiet connection
    must survive indefinitely (regression: stamp-after-send raced the
    loopback PONG and read the PING as ignored, reaping healthy clients),
    and a silent peer must still die within interval+timeout."""
    monkeypatch.setenv("TPURPC_NATIVE_FAST_UNARY", "0")  # tests the Python transport
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIME_MS", "400")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIMEOUT_MS", "400")
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)  # re-read env
    try:
        srv = make_server()
        srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        ch = rpc.insecure_channel(f"127.0.0.1:{srv.bound_ports[0]}")
        echo = ch.unary_unary("/t.Echo/Echo")
        assert echo(b"a", timeout=10) == b"a"
        conn = ch._subchannels[0]._conn
        time.sleep(2.0)  # ~5 silence windows, PINGs ping-ponging both ways
        assert conn.alive
        assert conn.pong_count >= 1  # client really pinged and was answered
        ch.close()
        srv.stop(grace=0)

        a, _b = passthru_endpoint_pair()  # nobody reads _b: silent peer
        ch2 = Channel(endpoint_factory=lambda: a)
        c2 = ch2._subchannels[0].get()
        deadline = time.monotonic() + 5
        while c2.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not c2.alive
        ch2.close()
    finally:
        config_mod.set_config(None)


def test_listener_survives_garbage_connections():
    """Adversarial bytes at the protocol sniff and past it: random junk,
    a truncated native preface, an oversized frame header — each kills
    only ITS connection; the listener and live channels keep working."""
    import os
    import socket
    import struct

    import tpurpc.rpc as rpc

    import threading

    # Pin the containment: an exception ESCAPING the sniff thread would
    # previously only print a traceback (daemon thread), so the listener
    # "survived" either way — record escapes and assert there were none.
    escapes = []
    prev_hook = threading.excepthook
    threading.excepthook = lambda args: escapes.append(args)
    srv = rpc.Server(max_workers=2)
    srv.add_method("/g.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        payloads = [
            os.urandom(64),                     # junk at the sniff
            b"TPURPC\x01\x00" + os.urandom(64),  # junk after a valid preface
            b"TPURPC\x01\x00" + struct.pack(     # oversized frame header
                "<BBII", 2, 0, 1, 0xFFFFFFF0),
            b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" + os.urandom(32),  # h2 junk
            b"TRB",                              # truncated ring magic + EOF
        ]
        for _ in range(6):  # repeat: the adoption-write race is timing-y
            for junk in payloads:
                s = socket.create_connection(("127.0.0.1", port), timeout=10)
                try:
                    s.sendall(junk)
                except OSError:
                    pass
                finally:
                    s.close()
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/g.S/Echo")(b"alive", timeout=15) == b"alive"
        time.sleep(0.3)  # let straggler sniff threads finish dying
        assert not escapes, escapes[0]
    finally:
        threading.excepthook = prev_hook
        srv.stop(grace=0)
