"""The canonical gRPC interop-suite cases (doc/interop-test-descriptions
in the grpc repo), run across the wire against STOCK grpcio — the named
conformance battery the ecosystem recognizes, adapted to raw-bytes
payloads (the canon's grpc.testing protos test the same behaviors; the
payload schema is not the subject).

Direction A: stock grpcio CLIENT -> tpurpc server (wire/grpc_h2.py).
Direction B: tpurpc H2Channel CLIENT -> stock grpcio server
(selected cases; B-side plumbing mirrors test_h2_client.py).
"""

import threading
import time

import grpc
import pytest

import tpurpc.rpc as tps
from tpurpc.rpc.status import StatusCode

_ID = lambda x: x


def _interop_server():
    srv = tps.Server(max_workers=8)
    release = threading.Event()

    def empty_call(req, ctx):
        assert bytes(req) == b""
        return b""

    def unary_call(req, ctx):
        return bytes(req)

    def streaming_input(req_iter, ctx):
        return str(sum(len(m) for m in req_iter)).encode()

    def streaming_output(req, ctx):
        for n in (31415, 9, 2653, 58979):
            yield bytes(n % 251 for _ in range(1))  # sized markers
            yield b"x" * (n % 1024)

    def full_duplex(req_iter, ctx):
        for m in req_iter:
            yield b"pong:" + bytes(m)

    def custom_status(req, ctx):
        ctx.abort(StatusCode.UNKNOWN, bytes(req).decode("utf-8"))

    def sleeping(req, ctx):
        release.wait(timeout=30)
        return b"late"

    def md_echo(req, ctx):
        md = {k: v for k, v in ctx.invocation_metadata()}
        ctx.set_trailing_metadata((
            ("x-grpc-test-echo-trailing-bin",
             md.get("x-grpc-test-echo-trailing-bin", b"")),))
        ctx.send_initial_metadata((
            ("x-grpc-test-echo-initial",
             md.get("x-grpc-test-echo-initial", "?")),))
        return bytes(req)

    S = "/grpc.testing.TestService/"
    srv.add_method(S + "EmptyCall",
                   tps.unary_unary_rpc_method_handler(empty_call))
    srv.add_method(S + "UnaryCall",
                   tps.unary_unary_rpc_method_handler(unary_call))
    srv.add_method(S + "StreamingInputCall",
                   tps.stream_unary_rpc_method_handler(streaming_input))
    srv.add_method(S + "StreamingOutputCall",
                   tps.unary_stream_rpc_method_handler(streaming_output))
    srv.add_method(S + "FullDuplexCall",
                   tps.stream_stream_rpc_method_handler(full_duplex))
    srv.add_method(S + "CustomStatus",
                   tps.unary_unary_rpc_method_handler(custom_status))
    srv.add_method(S + "Sleeping",
                   tps.unary_unary_rpc_method_handler(sleeping))
    srv.add_method(S + "MetadataEcho",
                   tps.unary_unary_rpc_method_handler(md_echo))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port, release


@pytest.fixture(scope="module")
def interop():
    srv, port, release = _interop_server()
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield ch
    release.set()
    ch.close()
    srv.stop(grace=0)


S = "/grpc.testing.TestService/"


def test_empty_unary(interop):
    mc = interop.unary_unary(S + "EmptyCall", _ID, _ID)
    assert mc(b"", timeout=15) == b""


def test_large_unary(interop):
    mc = interop.unary_unary(S + "UnaryCall", _ID, _ID)
    body = bytes(range(256)) * 1109  # ~284KB, the canon's 271828-ish size
    assert mc(body, timeout=30) == body


def test_client_streaming(interop):
    mc = interop.stream_unary(S + "StreamingInputCall", _ID, _ID)
    sizes = [27182, 8, 1828, 45904]  # the canon's request sizes
    out = mc(iter(b"q" * n for n in sizes), timeout=30)
    assert int(out) == sum(sizes)


def test_server_streaming(interop):
    mc = interop.unary_stream(S + "StreamingOutputCall", _ID, _ID)
    msgs = list(mc(b"", timeout=30))
    assert len(msgs) == 8


def test_ping_pong(interop):
    """Bidi lockstep: each request answered before the next is sent."""
    mc = interop.stream_stream(S + "FullDuplexCall", _ID, _ID)
    lock = threading.Semaphore(1)

    def gen():
        for i in range(4):
            lock.acquire()
            yield b"m%d" % i

    replies = []
    for reply in mc(gen()):
        replies.append(reply)
        lock.release()
    assert replies == [b"pong:m%d" % i for i in range(4)]


def test_custom_metadata(interop):
    mc = interop.unary_unary(S + "MetadataEcho", _ID, _ID)
    resp, call = mc.with_call(
        b"payload", timeout=15,
        metadata=(("x-grpc-test-echo-initial", "test_initial_metadata_value"),
                  ("x-grpc-test-echo-trailing-bin", b"\xab\xab\xab")))
    assert resp == b"payload"
    init = dict(call.initial_metadata())
    assert init.get("x-grpc-test-echo-initial") == "test_initial_metadata_value"
    trail = dict(call.trailing_metadata())
    assert trail.get("x-grpc-test-echo-trailing-bin") == b"\xab\xab\xab"


def test_status_code_and_message(interop):
    mc = interop.unary_unary(S + "CustomStatus", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"test status message", timeout=15)
    assert ei.value.code() is grpc.StatusCode.UNKNOWN
    assert ei.value.details() == "test status message"


def test_special_status_message(interop):
    """Unicode + whitespace survive the percent-encoded grpc-message."""
    msg = "\t\ntest with whitespace\r\nand Unicode BMP ☺ and non-BMP \U0001f600\t\n"
    mc = interop.unary_unary(S + "CustomStatus", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(msg.encode("utf-8"), timeout=15)
    assert ei.value.details() == msg


def test_unimplemented_method(interop):
    mc = interop.unary_unary(S + "UnimplementedCall", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"", timeout=15)
    assert ei.value.code() is grpc.StatusCode.UNIMPLEMENTED


def test_timeout_on_sleeping_server(interop):
    mc = interop.unary_unary(S + "Sleeping", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"", timeout=0.5)
    assert ei.value.code() is grpc.StatusCode.DEADLINE_EXCEEDED


def test_cancel_after_begin(interop):
    mc = interop.stream_unary(S + "StreamingInputCall", _ID, _ID)
    feed = threading.Event()

    def gen():
        feed.wait(timeout=30)  # hold the stream open, nothing sent
        return
        yield  # pragma: no cover

    fut = mc.future(gen())
    time.sleep(0.2)
    fut.cancel()
    # grpcio surfaces a cancelled future as FutureCancelledError on result()
    with pytest.raises((grpc.RpcError, grpc.FutureCancelledError)):
        fut.result(timeout=15)
    assert fut.cancelled()
    feed.set()


def test_cancel_after_first_response(interop):
    mc = interop.stream_stream(S + "FullDuplexCall", _ID, _ID)
    hold = threading.Event()

    def gen():
        yield b"one"
        hold.wait(timeout=30)

    call = mc(gen())
    assert next(call) == b"pong:one"
    call.cancel()
    with pytest.raises(grpc.RpcError) as ei:
        next(call)
    assert ei.value.code() is grpc.StatusCode.CANCELLED
    hold.set()


# -- Direction B: tpurpc H2Channel client vs a STOCK grpcio server -----------

@pytest.fixture(scope="module")
def stock_server():
    from concurrent import futures as cf

    class Handler(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method == S + "UnaryCall":
                return grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx: bytes(req), _ID, _ID)
            if details.method == S + "CustomStatus":
                def boom(req, ctx):
                    ctx.abort(grpc.StatusCode.UNKNOWN,
                              bytes(req).decode("utf-8"))
                return grpc.unary_unary_rpc_method_handler(boom, _ID, _ID)
            if details.method == S + "FullDuplexCall":
                def duplex(req_iter, ctx):
                    for m in req_iter:
                        yield b"pong:" + bytes(m)
                return grpc.stream_stream_rpc_method_handler(duplex, _ID, _ID)
            return None

    srv = grpc.server(cf.ThreadPoolExecutor(max_workers=8))
    srv.add_generic_rpc_handlers((Handler(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(grace=0)


def test_b_large_unary_and_status(stock_server):
    from tpurpc.wire.h2_client import H2Channel

    with H2Channel(f"127.0.0.1:{stock_server}") as ch:
        body = bytes(range(256)) * 1109
        assert ch.unary_unary(S + "UnaryCall")(body, timeout=30) == body
        msg = "\ttest with whitespace\nand Unicode BMP ☺\t"
        with pytest.raises(tps.RpcError) as ei:
            ch.unary_unary(S + "CustomStatus")(msg.encode(), timeout=15)
        assert ei.value.details() == msg


def test_b_ping_pong(stock_server):
    from tpurpc.wire.h2_client import H2Channel

    with H2Channel(f"127.0.0.1:{stock_server}") as ch:
        mc = ch.stream_stream(S + "FullDuplexCall")
        out = list(mc(iter([b"a", b"bb"]), timeout=30))
        assert out == [b"pong:a", b"pong:bb"]


def test_concurrent_stock_clients(interop):
    """Server-side shake-out: several stock grpcio client threads hammer
    one tpurpc h2 server concurrently with mixed shapes — races in the
    server's HPACK/flow-control/stream bookkeeping would surface as
    protocol kills (the client-side analog hid the SETTINGS-ACK race)."""
    errors: list = []

    def worker(n: int):
        try:
            u = interop.unary_unary(S + "UnaryCall", _ID, _ID)
            d = interop.stream_stream(S + "FullDuplexCall", _ID, _ID)
            for i in range(40):
                body = bytes((n + i) % 256 for _ in range(512 * (1 + i % 4)))
                assert u(body, timeout=30) == body
                if i % 8 == 0:
                    out = list(d(iter([b"a", b"b"]), timeout=30))
                    assert out == [b"pong:a", b"pong:b"]
        except Exception as exc:  # noqa: BLE001 — surfaced after join
            errors.append(exc)

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    [t.start() for t in ts]
    [t.join(timeout=240) for t in ts]
    assert not errors, errors[0]
    assert not any(t.is_alive() for t in ts)
