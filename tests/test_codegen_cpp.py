"""C++ stub codegen (--tpurpc_out=cpp:DIR) — the src/compiler/
cpp_generator.cc analog: typed protobuf stubs + service bases over the
native app API, compiled with the system protobuf and exercised end to end
(C++ client vs C++ service for all four shapes, then the Python generated
stub against the same C++ service — cross-language, one proto)."""

import os
import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

PROTO = textwrap.dedent("""\
    syntax = "proto3";
    package demo;

    message Ping { string text = 1; int32 n = 2; }
    message Pong { string text = 1; int32 total = 2; }

    service Greeter {
      rpc Hello (Ping) returns (Pong);
      rpc Tail (Ping) returns (stream Pong);
      rpc Sum (stream Ping) returns (Pong);
      rpc Chat (stream Ping) returns (stream Pong);
    }
    """)

MAIN_CC = textwrap.dedent("""\
    // Generated-stub exercise: C++ service + C++ client, all four shapes.
    #include <cstdio>
    #include "demo_tpurpc.pb.h"

    class GreeterImpl : public demo::GreeterService {
     public:
      int Hello(const ::demo::Ping &req, ::demo::Pong *resp) override {
        resp->set_text("hello " + req.text());
        resp->set_total(req.n());
        return 0;
      }
      int Tail(const ::demo::Ping &req,
               ::tpurpc::ServerCall<::demo::Ping, ::demo::Pong> &call)
          override {
        for (int i = 0; i < req.n(); ++i) {
          ::demo::Pong p;
          p.set_total(i);
          if (!call.Write(p)) return TPR_UNAVAILABLE;
        }
        return 0;
      }
      int Sum(::tpurpc::ServerCall<::demo::Ping, ::demo::Pong> &call)
          override {
        ::demo::Ping in;
        int total = 0;
        while (call.Read(&in)) total += in.n();
        if (call.parse_error()) return TPR_INTERNAL;
        ::demo::Pong out;
        out.set_total(total);
        return call.Write(out) ? 0 : TPR_UNAVAILABLE;
      }
      int Chat(::tpurpc::ServerCall<::demo::Ping, ::demo::Pong> &call)
          override {
        ::demo::Ping in;
        while (call.Read(&in)) {
          ::demo::Pong out;
          out.set_text("echo:" + in.text());
          if (!call.Write(out)) return TPR_UNAVAILABLE;
        }
        return 0;
      }
    };

    int main(int argc, char **argv) {
      tpr_server *srv = tpr_server_create(0);
      GreeterImpl impl;
      impl.RegisterWith(srv);
      tpr_server_start(srv);
      int port = tpr_server_port(srv);
      if (argc > 1) {  // serve-only mode for the cross-language test
        printf("PORT %d\\n", port);
        fflush(stdout);
        getchar();
        tpr_server_destroy(srv);
        return 0;
      }

      ::tpurpc::Channel ch("127.0.0.1", port);
      demo::GreeterClient stub(ch);

      ::demo::Ping req;
      req.set_text("cpp");
      req.set_n(7);
      ::demo::Pong resp;
      auto st = stub.Hello(req, &resp, 5000);
      printf("hello_ok=%d text=%s total=%d\\n", st.ok(),
             resp.text().c_str(), resp.total());

      auto tail = stub.Tail(req, 5000);
      int seen = 0, last = -1;
      ::demo::Pong m;
      while (tail.Read(&m)) { seen++; last = m.total(); }
      auto tst = tail.Finish();
      printf("tail_ok=%d seen=%d last=%d\\n", tst.ok(), seen, last);

      auto sum = stub.Sum(5000);
      for (int i = 1; i <= 4; ++i) {
        ::demo::Ping p;
        p.set_n(i);
        sum.Write(p);
      }
      sum.WritesDone();
      ::demo::Pong total;
      bool got = sum.Read(&total);
      auto sst = sum.Finish();
      printf("sum_ok=%d got=%d total=%d\\n", sst.ok(), got, total.total());

      auto chat = stub.Chat(5000);
      ::demo::Ping c1;
      c1.set_text("x");
      chat.Write(c1);
      ::demo::Pong r1;
      bool cgot = chat.Read(&r1);
      chat.WritesDone();
      ::demo::Pong drain;
      while (chat.Read(&drain)) {}
      auto cst = chat.Finish();
      printf("chat_ok=%d echo=%s\\n", cst.ok() && cgot, r1.text().c_str());

      // unimplemented-by-default base behavior via a raw path
      auto [ust, _body] = ch.UnaryCall("/demo.Greeter/Nope", "", 5000);
      printf("unknown_code=%d\\n", ust.code);

      tpr_server_destroy(srv);
      return 0;
    }
    """)


@pytest.fixture(scope="module")
def cpp_build(tmp_path_factory):
    if shutil.which("g++") is None or shutil.which("protoc") is None:
        pytest.skip("no g++/protoc toolchain")
    try:
        pb_flags = subprocess.run(
            ["pkg-config", "--cflags", "--libs", "protobuf"],
            capture_output=True, text=True, check=True).stdout.split()
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("no C++ protobuf")
    out = tmp_path_factory.mktemp("cppgen")
    (out / "demo.proto").write_text(PROTO)
    shim = out / "protoc-gen-tpurpc"
    shim.write_text(
        f"#!/bin/sh\nexec {sys.executable} -m tpurpc.codegen.plugin\n")
    shim.chmod(0o755)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(
        ["protoc", f"--plugin=protoc-gen-tpurpc={shim}",
         f"--cpp_out={out}", f"--python_out={out}",
         f"--tpurpc_out=cpp:{out}", f"-I{out}", "demo.proto"],
        check=True, env=env)
    # second protoc run for the PYTHON tpurpc stubs (cross-language test)
    subprocess.run(
        ["protoc", f"--plugin=protoc-gen-tpurpc={shim}",
         f"--tpurpc_out={out}", f"-I{out}", "demo.proto"],
        check=True, env=env)
    (out / "main.cc").write_text(MAIN_CC)
    binp = out / "demo_app"
    subprocess.run(
        ["g++", "-std=c++17", "-O1", str(out / "main.cc"),
         str(out / "demo.pb.cc"),
         str(ROOT / "native" / "src" / "tpurpc_client.cc"),
         str(ROOT / "native" / "src" / "tpurpc_server.cc"),
         str(ROOT / "native" / "src" / "tpr_rdv.cc"),
         str(ROOT / "native" / "src" / "tpr_obs.cc"),
         str(ROOT / "native" / "src" / "ring.cc"),
         "-I", str(out), "-I", str(ROOT / "native" / "include"),
         *pb_flags, "-lpthread", "-lrt", "-o", str(binp)],
        check=True, timeout=300, capture_output=True)
    return out, binp


def test_cpp_generated_stubs_all_shapes(cpp_build):
    _, binp = cpp_build
    out = subprocess.run([str(binp)], capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "hello_ok=1 text=hello cpp total=7" in out.stdout
    assert "tail_ok=1 seen=7 last=6" in out.stdout
    assert "sum_ok=1 got=1 total=10" in out.stdout
    assert "chat_ok=1 echo=echo:x" in out.stdout
    assert "unknown_code=12" in out.stdout  # UNIMPLEMENTED


def test_python_stub_against_cpp_generated_service(cpp_build):
    gen_dir, binp = cpp_build
    proc = subprocess.Popen([str(binp), "serve"], stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True)
    sys.path.insert(0, str(gen_dir))
    try:
        port = int(proc.stdout.readline().split()[1])
        import demo_pb2
        import demo_tpurpc

        import tpurpc.rpc as tps

        with tps.Channel(f"127.0.0.1:{port}") as ch:
            stub = demo_tpurpc.GreeterStub(ch)
            pong = stub.Hello(demo_pb2.Ping(text="py", n=3), timeout=10)
            assert pong.text == "hello py" and pong.total == 3
            totals = [p.total for p in
                      stub.Tail(demo_pb2.Ping(n=4), timeout=10)]
            assert totals == [0, 1, 2, 3]
            s = stub.Sum(iter([demo_pb2.Ping(n=i) for i in (5, 6)]),
                         timeout=10)
            assert s.total == 11
    finally:
        sys.path.remove(str(gen_dir))
        for mod in ("demo_pb2", "demo_tpurpc"):
            sys.modules.pop(mod, None)
        proc.stdin.close()
        proc.wait(timeout=10)
