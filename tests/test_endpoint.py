"""Endpoint conformance harness.

Reference: ``test/core/iomgr/endpoint_tests.{h,cc}`` — one suite of read/write/shutdown
semantics run against *every* endpoint implementation, which is how the upstream suite
exercises the RDMA endpoints for free (SURVEY.md §4.1).  Our matrix: TCP, three ring
disciplines (over the platform env switch, exactly as a user selects them), mock, and
passthru.
"""

import queue
import threading
import time

import pytest

from tpurpc.core.endpoint import (
    EndpointError,
    EndpointListener,
    MockEndpoint,
    ReadTimeout,
    connect_endpoint,
    passthru_endpoint_pair,
)


def _listener_fixture(monkeypatch, platform):
    """Stand up listener+client with GRPC_PLATFORM_TYPE=<platform> — the documented
    UX (reference README.md:17-25). The "+tcpw" suffix additionally selects
    the cross-host tcp_window ring domain (TPURPC_RING_DOMAIN), running the
    identical conformance battery over the socket-carried one-sided fabric."""
    if platform.endswith("+tcpw"):
        platform = platform[:-5]
        monkeypatch.setenv("TPURPC_RING_DOMAIN", "tcp_window")
        monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "256")
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)  # re-read env, like a fresh process
    accepted: "queue.Queue" = queue.Queue()
    listener = EndpointListener("127.0.0.1", 0, accepted.put)
    client = connect_endpoint("127.0.0.1", listener.port)
    server = accepted.get(timeout=10)
    return listener, client, server


PLATFORMS = ["TCP", "RDMA_BP", "RDMA_EVENT", "RDMA_BPEV",
             "RDMA_BP+tcpw", "RDMA_BPEV+tcpw"]


@pytest.fixture(params=PLATFORMS + ["passthru"])
def endpoint_pair(request, monkeypatch):
    if request.param == "passthru":
        a, b = passthru_endpoint_pair()
        yield a, b
        a.close()
        b.close()
        return
    listener, client, server = _listener_fixture(monkeypatch, request.param)
    yield client, server
    client.close()
    server.close()
    listener.close()


def _read_exact(ep, n, timeout=30):
    out = b""
    deadline = time.monotonic() + timeout
    while len(out) < n:
        remain = deadline - time.monotonic()
        assert remain > 0, f"timed out with {len(out)}/{n} bytes"
        chunk = ep.read(n - len(out), timeout=remain)
        assert chunk != b"", "unexpected EOF"
        out += chunk
    return out


def test_roundtrip_small(endpoint_pair):
    a, b = endpoint_pair
    a.write(b"hello")
    assert _read_exact(b, 5) == b"hello"
    b.write([b"wor", b"ld"])  # gather write
    assert _read_exact(a, 5) == b"world"


def test_large_transfer_both_directions(endpoint_pair):
    a, b = endpoint_pair
    blob = bytes(i & 0xFF for i in range(1 << 20))  # 1 MiB

    def pump_a():
        a.write(blob)

    t = threading.Thread(target=pump_a)
    t.start()
    got = _read_exact(b, len(blob), timeout=60)
    t.join(timeout=60)
    assert got == blob
    t2 = threading.Thread(target=lambda: b.write(blob))
    t2.start()
    assert _read_exact(a, len(blob), timeout=60) == blob
    t2.join(timeout=60)


def test_many_small_writes_preserve_stream(endpoint_pair):
    a, b = endpoint_pair
    msgs = [f"m{i:04d}|".encode() for i in range(200)]

    def pump():
        for m in msgs:
            a.write(m)

    t = threading.Thread(target=pump)
    t.start()
    expect = b"".join(msgs)
    assert _read_exact(b, len(expect), timeout=60) == expect
    t.join()


def test_clean_eof_on_close(endpoint_pair):
    a, b = endpoint_pair
    a.write(b"bye")
    a.close()
    assert _read_exact(b, 3) == b"bye"
    assert b.read(100, timeout=10) == b""  # clean EOF after drain


def test_read_timeout(endpoint_pair):
    a, b = endpoint_pair
    with pytest.raises(ReadTimeout):
        b.read(100, timeout=0.2)
    # endpoint still usable afterwards
    a.write(b"late")
    assert _read_exact(b, 4) == b"late"


def test_peer_and_local_names(endpoint_pair):
    a, b = endpoint_pair
    for ep in (a, b):
        assert ep.peer
        assert ep.local_address


@pytest.mark.parametrize("platform", PLATFORMS)
def test_write_after_peer_close_fails(monkeypatch, platform):
    listener, client, server = _listener_fixture(monkeypatch, platform)
    try:
        server.close()
        with pytest.raises((EndpointError, ReadTimeout)):
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                client.write(b"x" * 65536)  # must eventually surface the close
        # read side reports EOF or error, never hangs
        try:
            assert client.read(100, timeout=5) == b""
        except (EndpointError, ReadTimeout):
            pass
    finally:
        client.close()
        listener.close()


def test_mock_endpoint_scriptability():
    m = MockEndpoint()
    m.inject(b"scripted")
    assert m.read(100) == b"scripted"
    m.write([b"cap", b"tured"])
    assert bytes(m.written) == b"captured"
    m.inject_eof()
    assert m.read(10) == b""
    assert m.read(10) == b""  # EOF is sticky
    m.close()
    with pytest.raises(EndpointError):
        m.read(1)


def test_mock_endpoint_retains_tail_beyond_max_bytes():
    m = MockEndpoint()
    m.inject(b"x" * 100)
    assert m.read(10) == b"x" * 10
    rest = b""
    while len(rest) < 90:
        rest += m.read(40)
    assert rest == b"x" * 90  # nothing dropped


def test_ring_pool_recycles_pairs(monkeypatch):
    from tpurpc.core.poller import PairPool

    listener, client, server = _listener_fixture(monkeypatch, "RDMA_BPEV")
    key = client.pool_key
    client.close()
    server.close()
    listener.close()
    assert PairPool.get().idle_count(key) == 1  # returned on close (pool recycle)