"""JSON service config via the resolver: per-method timeout/retryPolicy +
channel-wide retry throttling (VERDICT r4 next #4).

Reference analogs: ``ext/filters/client_channel/service_config.cc`` (the
resolver-result attachment), ``retry_service_config.cc`` (gRFC A6
retryPolicy parsing), ``retry_throttle.cc`` (the token bucket). The cases
mirror gRFC A6's: per-method lookup precedence, maxAttempts, retryable
codes, throttling suppressing retries, config delivered AND updated by the
resolver without touching call sites.
"""

import threading

import pytest

from tpurpc.rpc import resolver as resolver_mod
from tpurpc.rpc.channel import Channel, RetryPolicy
from tpurpc.rpc.resolver import Resolution, register_resolver
from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
from tpurpc.rpc.service_config import (RetryThrottle, ServiceConfig,
                                       _parse_duration)
from tpurpc.rpc.status import RpcError, StatusCode


# -- parsing ------------------------------------------------------------------

def test_parse_durations_and_precedence():
    cfg = ServiceConfig.from_json({
        "methodConfig": [
            {"name": [{"service": "pkg.Svc", "method": "Echo"}],
             "timeout": "1.5s"},
            {"name": [{"service": "pkg.Svc"}], "timeout": "2s"},
            {"name": [{}], "timeout": "3s"},
        ]})
    assert cfg.for_method("/pkg.Svc/Echo").timeout == 1.5
    assert cfg.for_method("/pkg.Svc/Other").timeout == 2.0
    assert cfg.for_method("/other.Svc/X").timeout == 3.0
    assert _parse_duration("0.25s") == 0.25
    assert _parse_duration(2) == 2.0
    with pytest.raises(ValueError):
        _parse_duration("1500ms")  # proto3 JSON durations are seconds-only


def test_parse_retry_policy_fields():
    cfg = ServiceConfig.from_json({
        "methodConfig": [{
            "name": [{"service": "s", "method": "m"}],
            "retryPolicy": {"maxAttempts": 4, "initialBackoff": "0.01s",
                            "maxBackoff": "0.1s", "backoffMultiplier": 3,
                            "retryableStatusCodes": ["UNAVAILABLE",
                                                     "ABORTED"]}}]})
    rp = cfg.for_method("/s/m").retry_policy
    assert isinstance(rp, RetryPolicy)
    assert rp.max_attempts == 4
    assert rp.initial_backoff == 0.01
    assert rp.backoff_multiplier == 3
    assert StatusCode.ABORTED in rp.retryable_codes
    assert cfg.for_method("/s/other").retry_policy is None


def test_parse_rejects_malformed_whole():
    with pytest.raises(ValueError):
        ServiceConfig.from_json({"methodConfig": [
            {"name": [{"service": "s"}],
             "retryPolicy": {"maxAttempts": 1,  # < 2: invalid per gRFC A6
                             "retryableStatusCodes": ["UNAVAILABLE"]}}]})
    with pytest.raises(ValueError):
        ServiceConfig.from_json({"methodConfig": [
            {"name": [{"method": "m"}]}]})  # method without service
    with pytest.raises(ValueError):
        ServiceConfig.from_json({"methodConfig": [
            {"name": [{"service": "s"}],
             "retryPolicy": {"maxAttempts": 2,
                             "retryableStatusCodes": ["NO_SUCH_CODE"]}}]})


def test_parse_rejects_nonpositive_backoff_and_caps_attempts():
    with pytest.raises(ValueError):
        ServiceConfig.from_json({"methodConfig": [
            {"name": [{"service": "s"}],
             "retryPolicy": {"maxAttempts": 3, "initialBackoff": "0s",
                             "retryableStatusCodes": ["UNAVAILABLE"]}}]})
    cfg = ServiceConfig.from_json({"methodConfig": [
        {"name": [{"service": "s"}],
         "retryPolicy": {"maxAttempts": 100000,
                         "retryableStatusCodes": ["UNAVAILABLE"]}}]})
    # gRPC clamps at 5 (retry_service_config.cc): a resolver cannot
    # configure an unbounded hammer loop
    assert cfg.for_method("/s/m").retry_policy.max_attempts == 5


def test_parse_type_errors_are_value_errors():
    """The reject-whole contract promises ValueError — keep-last-good
    callers catch exactly that, so type confusion must not leak
    AttributeError."""
    for bad in ({"retryThrottling": None},
                {"methodConfig": ["x"]},
                {"methodConfig": [{"name": "x"}]},
                {"methodConfig": [{"name": [["s"]]}]},
                {"methodConfig": [{"name": [{"service": "s"}],
                                   "retryPolicy": "on"}]},
                []):
        with pytest.raises(ValueError):
            ServiceConfig.from_json(bad)


def test_retry_throttle_bucket():
    t = RetryThrottle(max_tokens=4, token_ratio=0.5)
    assert t.allow_retry()
    t.record_failure()
    t.record_failure()  # tokens 2 == max/2: NOT above half
    assert not t.allow_retry()
    t.record_success()  # 2.5
    assert t.allow_retry()


# -- end-to-end: resolver-delivered config ------------------------------------

class _Flaky:
    """Handler failing with UNAVAILABLE until `fail` attempts happened."""

    def __init__(self, fail: int):
        self.fail = fail
        self.calls = 0
        self.lock = threading.Lock()

    def __call__(self, req, ctx):
        with self.lock:
            self.calls += 1
            n = self.calls
        if n <= self.fail:
            ctx.abort(StatusCode.UNAVAILABLE, "flaky")
        return bytes(req)


def _server(handlers: dict):
    srv = Server(max_workers=4)
    for method, fn in handlers.items():
        srv.add_method(method, unary_unary_rpc_method_handler(fn))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


RETRY_CFG = {
    "methodConfig": [{
        "name": [{"service": "cfg.Svc", "method": "Flaky"}],
        "retryPolicy": {"maxAttempts": 4, "initialBackoff": "0.01s",
                        "maxBackoff": "0.05s", "backoffMultiplier": 2,
                        "retryableStatusCodes": ["UNAVAILABLE"]}}]}


def test_resolver_delivered_retry_policy_applies_per_method():
    """The A6 shape: the RESOLVER attaches retryPolicy for one method; calls
    to it retry transparently, calls to other methods don't — no call-site
    or constructor involvement."""
    flaky = _Flaky(fail=2)
    flaky2 = _Flaky(fail=1)
    srv, port = _server({"/cfg.Svc/Flaky": flaky,
                         "/cfg.Svc/NoRetry": flaky2})
    register_resolver("svctest",
                      lambda rest: Resolution([("127.0.0.1", port)],
                                              RETRY_CFG))
    try:
        with Channel("svctest:///x") as ch:
            ok = ch.unary_unary("/cfg.Svc/Flaky")(b"p", timeout=10)
            assert bytes(ok) == b"p"
            assert flaky.calls == 3  # 2 failures + 1 success
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/cfg.Svc/NoRetry")(b"p", timeout=10)
            assert ei.value.code() is StatusCode.UNAVAILABLE
            assert flaky2.calls == 1  # not configured: no retry
    finally:
        resolver_mod._RESOLVERS.pop("svctest", None)
        srv.stop(grace=0)


def test_constructor_policy_wins_over_config():
    flaky = _Flaky(fail=10)  # always fails within maxAttempts
    srv, port = _server({"/cfg.Svc/Flaky": flaky})
    register_resolver("svctest2",
                      lambda rest: Resolution([("127.0.0.1", port)],
                                              RETRY_CFG))
    try:
        explicit = RetryPolicy(max_attempts=2, initial_backoff=0.01,
                               retryable_codes=(StatusCode.UNAVAILABLE,))
        with Channel("svctest2:///x", retry_policy=explicit) as ch:
            with pytest.raises(RpcError):
                ch.unary_unary("/cfg.Svc/Flaky")(b"p", timeout=10)
        assert flaky.calls == 2  # explicit policy's budget, not the config's 4
    finally:
        resolver_mod._RESOLVERS.pop("svctest2", None)
        srv.stop(grace=0)


def test_method_timeout_from_config_and_min_rule():
    import time as _time

    def slow(req, ctx):
        _time.sleep(1.0)
        return bytes(req)

    srv, port = _server({"/cfg.Svc/Slow": slow})
    cfg = {"methodConfig": [{"name": [{"service": "cfg.Svc",
                                       "method": "Slow"}],
                             "timeout": "0.2s"}]}
    register_resolver("svctest3",
                      lambda rest: Resolution([("127.0.0.1", port)], cfg))
    try:
        with Channel("svctest3:///x") as ch:
            mc = ch.unary_unary("/cfg.Svc/Slow")
            t0 = _time.monotonic()
            with pytest.raises(RpcError) as ei:
                mc(b"p")  # NO call-site timeout: config's 0.2s applies
            assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
            assert _time.monotonic() - t0 < 0.9  # not the handler's 1s
            # the min rule: an explicit LONGER timeout cannot widen it
            t0 = _time.monotonic()
            with pytest.raises(RpcError) as ei:
                mc(b"p", timeout=30)
            assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
            assert _time.monotonic() - t0 < 0.9
    finally:
        resolver_mod._RESOLVERS.pop("svctest3", None)
        srv.stop(grace=0)


def test_retry_throttling_suppresses_retry_storm():
    """gRFC A6 throttling: with the bucket drained below half, retryable
    failures surface immediately instead of burning the attempt budget."""
    flaky = _Flaky(fail=10 ** 6)
    srv, port = _server({"/cfg.Svc/Flaky": flaky})
    cfg = dict(RETRY_CFG)
    cfg["retryThrottling"] = {"maxTokens": 2, "tokenRatio": 0.1}
    register_resolver("svctest4",
                      lambda rest: Resolution([("127.0.0.1", port)], cfg))
    try:
        with Channel("svctest4:///x") as ch:
            mc = ch.unary_unary("/cfg.Svc/Flaky")
            # 1st call: failure drains 1 token (2→1 == max/2: throttled);
            # retries stop right there — 1 attempt, not 4
            with pytest.raises(RpcError):
                mc(b"p", timeout=10)
            assert flaky.calls == 1
            with pytest.raises(RpcError):
                mc(b"p", timeout=10)
            assert flaky.calls == 2  # still suppressed
    finally:
        resolver_mod._RESOLVERS.pop("svctest4", None)
        srv.stop(grace=0)


def test_update_carries_throttle_drain_state():
    """retry_throttle.cc behavior: a re-resolution re-delivering the config
    must NOT refill the bucket — otherwise every resolver refresh resumes a
    suppressed retry storm against a collapsing backend."""
    flaky = _Flaky(fail=10 ** 6)
    srv, port = _server({"/cfg.Svc/Flaky": flaky})
    cfg = dict(RETRY_CFG)
    cfg["retryThrottling"] = {"maxTokens": 2, "tokenRatio": 0.1}
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            ch.update_service_config(cfg)
            mc = ch.unary_unary("/cfg.Svc/Flaky")
            with pytest.raises(RpcError):
                mc(b"p", timeout=10)  # drains to 1 == max/2: throttled
            drained = ch._service_config.retry_throttle.tokens()
            assert drained == 1.0
            ch.update_service_config(cfg)  # resolver refresh, same config
            assert ch._service_config.retry_throttle.tokens() == drained
            with pytest.raises(RpcError):
                mc(b"p", timeout=10)
            assert flaky.calls == 2  # still suppressed post-update
            # changed maxTokens: drain state scales, doesn't reset
            now = ch._service_config.retry_throttle.tokens()
            cfg2 = dict(cfg)
            cfg2["retryThrottling"] = {"maxTokens": 4, "tokenRatio": 0.1}
            ch.update_service_config(cfg2)
            assert ch._service_config.retry_throttle.tokens() == \
                pytest.approx(now * 2)  # proportional carry (4/2)
    finally:
        srv.stop(grace=0)


def test_wait_for_ready_from_config():
    cfg = {"methodConfig": [{"name": [{"service": "cfg.Svc",
                                       "method": "W"}],
                             "waitForReady": True}]}
    srv, port = _server({})
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            ch.update_service_config(cfg)
            assert ch._call_plan("/cfg.Svc/W", None)[3] is True
            assert ch._call_plan("/cfg.Svc/Other", None)[3] is False
            assert ch._call_plan("/cfg.Svc/Other", None, True) is not None
            assert ch._call_plan("/cfg.Svc/Other", None, True)[3] is True
    finally:
        srv.stop(grace=0)


def test_update_service_config_reconfigures_live_channel():
    """VERDICT done-criterion: a resolver update reconfigures per-method
    retries/timeouts on a LIVE channel without touching call sites."""
    flaky = _Flaky(fail=2)
    srv, port = _server({"/cfg.Svc/Flaky": flaky})
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/cfg.Svc/Flaky")
            with pytest.raises(RpcError):
                mc(b"p", timeout=10)  # no config yet: first failure surfaces
            assert flaky.calls == 1
            ch.update_service_config(RETRY_CFG)  # the resolver-push analog
            assert bytes(mc(b"p", timeout=10)) == b"p"  # retried through
            assert flaky.calls == 3  # 1 (above) + 1 failure + 1 success
            # malformed update: rejected whole, previous config kept
            with pytest.raises(ValueError):
                ch.update_service_config({"methodConfig": [{"name": []}]})
            assert ch._service_config is not None
            assert (ch._service_config.for_method("/cfg.Svc/Flaky")
                    .retry_policy is not None)
    finally:
        srv.stop(grace=0)


def test_grpc_service_config_channel_option():
    """grpcio drop-in parity: options=[("grpc.service_config", json)] is
    the FALLBACK config — applied when the resolver delivers none,
    IGNORED when it does (gRPC documents GRPC_ARG_SERVICE_CONFIG as
    ignored when the name resolver returns a service config)."""
    import json

    flaky = _Flaky(fail=2)
    srv, port = _server({"/cfg.Svc/Flaky": flaky})
    try:
        # no resolver config: the option applies
        with Channel(f"127.0.0.1:{port}",
                     options=[("grpc.service_config",
                               json.dumps(RETRY_CFG))]) as ch:
            assert bytes(ch.unary_unary("/cfg.Svc/Flaky")(
                b"p", timeout=10)) == b"p"
            assert flaky.calls == 3  # retried per the option's config
        # resolver DELIVERS a config: the resolver wins, the option is
        # ignored. The resolver's config has no retry for this method
        # (service-level entry with timeout only), the option's would
        # retry — so a single attempt proves the resolver governed.
        resolver_cfg = {"methodConfig": [{
            "name": [{"service": "cfg.Svc"}], "timeout": "5s"}]}
        register_resolver(
            "svcopt", lambda rest: Resolution([("127.0.0.1", port)],
                                              resolver_cfg))
        try:
            flaky2 = _Flaky(fail=10 ** 6)
            srv.add_method("/cfg.Svc/Flaky",  # replace with always-flaky
                           unary_unary_rpc_method_handler(flaky2))
            with Channel("svcopt:///x",
                         options=[("grpc.service_config",
                                   json.dumps(RETRY_CFG))]) as ch:
                with pytest.raises(RpcError):
                    ch.unary_unary("/cfg.Svc/Flaky")(b"p", timeout=10)
                assert flaky2.calls == 1  # resolver won: no retries
        finally:
            resolver_mod._RESOLVERS.pop("svcopt", None)
    finally:
        srv.stop(grace=0)
