"""Flagship transformer: the same program must produce the same numbers on a
1-device mesh and on 8 devices split across dp/pp/sp/tp/ep (capacity high
enough that MoE never drops → factorization invariance is exact math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpurpc.models import transformer as tfm
from tpurpc.parallel import mesh as meshlib

CFG = tfm.TransformerConfig(
    vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=64,
    n_layers=2, n_experts=2, capacity_factor=16.0, n_micro=2)


def _data(B=8, S=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab, (B, S)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, CFG.vocab, (B, S)), jnp.int32)
    return tokens, targets


def _loss_on(mesh_sizes, n, tokens, targets):
    m = meshlib.build_mesh(n, sizes=mesh_sizes)
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    loss_fn = jax.jit(tfm.build_loss_fn(CFG, m))
    params = tfm.shard_params(params, CFG, m)
    return float(loss_fn(params, tokens, targets))


def test_loss_invariant_to_mesh_factorization():
    tokens, targets = _data()
    base = _loss_on({}, 1, tokens, targets)
    for sizes, n in [({"dp": 2, "pp": 2, "sp": 2}, 8),
                     ({"sp": 2, "tp": 2, "ep": 2}, 8),
                     ({"dp": 2, "tp": 2, "pp": 2}, 8),
                     ({"ep": 2, "pp": 2, "dp": 2}, 8)]:
        got = _loss_on(sizes, n, tokens, targets)
        assert got == pytest.approx(base, rel=2e-4), (sizes, got, base)


def test_forward_logits_match_across_meshes():
    tokens, _ = _data()
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)

    m1 = meshlib.build_mesh(1)
    f1 = tfm.build_forward(CFG, m1)
    l1 = np.asarray(f1(tfm.shard_params(params, CFG, m1), tokens))

    m8 = meshlib.build_mesh(8, sizes={"sp": 2, "tp": 2, "ep": 2})
    f8 = tfm.build_forward(CFG, m8)
    l8 = np.asarray(f8(tfm.shard_params(params, CFG, m8), tokens))
    np.testing.assert_allclose(l1, l8, rtol=5e-4, atol=5e-4)


def test_train_step_learns_and_shards():
    """Full sharded train step on the 5-axis mesh: loss must drop on a
    memorization task, params keep their shardings across steps."""
    m = meshlib.build_mesh(8, sizes={"dp": 2, "pp": 2, "tp": 2})
    params = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(1), CFG),
                              CFG, m)
    step, opt = tfm.build_train_step(CFG, m, lr=3e-3)
    opt_state = opt.init(params)
    tokens, _ = _data(seed=3)
    targets = jnp.roll(tokens, -1, axis=1)  # next-token on a fixed batch

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
    # shardings preserved (no silent full replication after update)
    wq = params["wq"]
    assert not wq.sharding.is_fully_replicated


def test_validate_rejects_bad_mesh():
    m = meshlib.build_mesh(8, sizes={"tp": 8})
    with pytest.raises(AssertionError):
        CFG.validate(m)  # 4 heads % tp=8 != 0
