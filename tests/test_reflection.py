"""Server reflection (grpc.reflection.v1alpha/v1) — the grpcurl hook.

Wire-compat is proven with a STOCK grpcio client driving the bidi stream
with hand-encoded request bytes (the grpc_reflection package isn't in this
image; the bytes on the wire are what grpcurl sends). Ref:
``src/cpp/ext/proto_server_reflection.cc``.
"""

import grpc
import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.reflection import (V1_SERVICE, V1ALPHA_SERVICE,
                                   enable_server_reflection)
from tpurpc.wire.protowire import fields as _fields
from tpurpc.wire.protowire import ld as _ld

_ID = lambda b: b  # identity (de)serializers: raw proto bytes


def _list_services_request(host: bytes = b"") -> bytes:
    # ServerReflectionRequest{ list_services = 7 }
    return _ld(7, b"")


def _decode_list_services(raw: bytes):
    """-> (valid_host, [service names]) from a ServerReflectionResponse."""
    names = []
    host = b""
    for field_no, _wt, val in _fields(bytes(raw)):
        if field_no == 1:
            host = val
        elif field_no == 6:  # ListServiceResponse
            for f2, _w2, v2 in _fields(bytes(val)):
                if f2 == 1:  # ServiceResponse
                    for f3, _w3, v3 in _fields(bytes(v2)):
                        if f3 == 1:
                            names.append(bytes(v3).decode())
    return host, names


def _decode_error(raw: bytes):
    """-> (code, message) from an error_response, or None."""
    for field_no, _wt, val in _fields(bytes(raw)):
        if field_no == 7:
            code, msg = 0, b""
            for f2, _w2, v2 in _fields(bytes(val)):
                if f2 == 1:
                    code = v2
                elif f2 == 2:
                    msg = v2
            return code, bytes(msg).decode()
    return None


def _decode_file_descriptors(raw: bytes):
    out = []
    for field_no, _wt, val in _fields(bytes(raw)):
        if field_no == 4:
            for f2, _w2, v2 in _fields(bytes(val)):
                if f2 == 1:
                    out.append(bytes(v2))
    return out


@pytest.fixture()
def refl_server():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/demo.Greeter/Hello",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: r))
    srv.add_method("/demo.Greeter/Bye",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: r))
    srv.add_method("/other.Thing/Do",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: r))
    servicer = enable_server_reflection(srv)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield srv, port, servicer
    srv.stop(grace=0)


def test_list_services_stock_grpcio_client(refl_server):
    _, port, _ = refl_server
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream(
            f"/{V1ALPHA_SERVICE}/ServerReflectionInfo", _ID, _ID)
        replies = list(mc(iter([_list_services_request()])))
    assert len(replies) == 1
    _, names = _decode_list_services(replies[0])
    assert "demo.Greeter" in names and "other.Thing" in names
    # a reflective server lists its own reflection services (C++ parity)
    assert V1ALPHA_SERVICE in names and V1_SERVICE in names


def test_v1_alias_and_native_channel(refl_server):
    _, port, _ = refl_server
    with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream(f"/{V1_SERVICE}/ServerReflectionInfo")
        replies = [bytes(r) for r in mc(iter([_list_services_request()]),
                                        timeout=10)]
    _, names = _decode_list_services(replies[0])
    assert "demo.Greeter" in names


def test_echoes_host_and_original_request(refl_server):
    _, port, _ = refl_server
    req = _ld(1, b"somehost") + _ld(7, b"")
    with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
        reply = bytes(next(iter(mc(iter([req]), timeout=10))))
    host, _ = _decode_list_services(reply)
    assert host == b"somehost"
    fields = {f: v for f, _w, v in _fields(reply)}
    assert fields[2] == req  # original_request echoed verbatim


def test_descriptor_lookup_and_not_found(refl_server):
    _, port, servicer = refl_server
    # a hand-built FileDescriptorProto: name(1), package(2),
    # service(6){name(1), method(2){name(1)}}
    svc = _ld(1, b"Greeter") + _ld(2, _ld(1, b"Hello"))
    fdp = _ld(1, b"demo.proto") + _ld(2, b"demo") + _ld(6, svc)
    servicer.add_file_descriptor_protos([fdp])

    def ask(req):
        with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
            return bytes(next(iter(mc(iter([req]), timeout=10))))

    # by filename
    got = _decode_file_descriptors(ask(_ld(3, b"demo.proto")))
    assert got == [fdp]
    # by symbol: service, and service.method
    assert _decode_file_descriptors(ask(_ld(4, b"demo.Greeter"))) == [fdp]
    assert _decode_file_descriptors(ask(_ld(4, b"demo.Greeter.Hello"))) == [fdp]
    # unknown symbol -> error_response NOT_FOUND(5), stream stays usable
    code, msg = _decode_error(ask(_ld(4, b"no.such.Thing")))
    assert code == 5 and "no.such.Thing" in msg


def test_multiple_requests_one_stream(refl_server):
    _, port, _ = refl_server
    reqs = [_list_services_request(), _ld(4, b"nope"), _list_services_request()]
    with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
        replies = [bytes(r) for r in mc(iter(reqs), timeout=10)]
    assert len(replies) == 3
    assert _decode_error(replies[1])[0] == 5
    assert "demo.Greeter" in _decode_list_services(replies[2])[1]


def test_malformed_oneof_wire_type_gets_error_response(refl_server):
    """A oneof arm sent as a varint (wire type 0) is malformed — the stream
    must answer INVALID_ARGUMENT(3) and stay usable, not crash."""
    _, port, _ = refl_server
    bad = b"\x18\x05"  # field 3 (file_by_filename), wire type 0, value 5
    with rpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
        replies = [bytes(r) for r in
                   mc(iter([bad, _list_services_request()]), timeout=10)]
    assert len(replies) == 2
    assert _decode_error(replies[0])[0] == 3
    assert "demo.Greeter" in _decode_list_services(replies[1])[1]
