"""ring_scatter Pallas kernel vs numpy oracle (interpret mode, CPU).

The write twin of test_ring_window: every wrap phase, the clamp case (start
inside the last 9 rows), masks at payload edges, and preservation of
untouched ring bytes. On real TPU hardware the same kernel runs with
interpret=False (chip validation is part of the bench round).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpurpc.ops.ring_scatter import ring_scatter, ring_scatter_reference

CAP = 16384  # 32 rows of 128 u32 lanes = 2x the 18-row minimum


def _rng(seed):
    return np.random.default_rng(seed)


def _check(cap, start, n, seed=0):
    import jax.numpy as jnp

    r = _rng(seed)
    ring0 = r.integers(0, 256, cap, dtype=np.uint8)
    payload = r.integers(0, 256, n, dtype=np.uint8)
    want = ring_scatter_reference(ring0, payload, start)
    buf = jnp.asarray(ring0)
    pay = jnp.asarray(payload)
    got = np.asarray(ring_scatter(buf, pay, start, interpret=True))
    np.testing.assert_array_equal(got, want)


def test_no_wrap_aligned():
    _check(CAP, 0, 4096)


def test_no_wrap_misaligned_start():
    _check(CAP, 4 * 37, 4096, seed=1)


def test_wrap_crossing():
    _check(CAP, CAP - 2048, 8192, seed=2)


def test_start_in_last_nine_rows_clamp():
    # start within the final 9 rows of the ring: the kernel's row clamp +
    # exact pre-wrap mask is what keeps window A inside the ring
    _check(CAP, CAP - 4 * 100, 4096, seed=3)


def test_tiny_payload_one_word():
    _check(CAP, 4 * 1001, 4, seed=4)


def test_payload_not_block_multiple():
    # 4-byte-aligned but not a multiple of the (8,128) block: the padded
    # tail must NOT be written into the ring
    _check(CAP, 4 * 513, 4 * 300, seed=5)


def test_full_capacity_payload():
    _check(CAP, 4 * 77, CAP, seed=6)


def test_wrap_exactly_at_end():
    _check(CAP, CAP - 4096, 4096, seed=7)  # lands flush, no wrap


def test_untouched_bytes_preserved():
    import jax.numpy as jnp

    r = _rng(8)
    ring0 = r.integers(0, 256, CAP, dtype=np.uint8)
    payload = r.integers(0, 256, 512, dtype=np.uint8)
    start = 4 * 613
    got = np.asarray(ring_scatter(jnp.asarray(ring0), jnp.asarray(payload),
                                  start, interpret=True))
    # the written span
    np.testing.assert_array_equal(got[start:start + 512], payload)
    # everything else identical
    mask = np.ones(CAP, bool)
    mask[start:start + 512] = False
    np.testing.assert_array_equal(got[mask], ring0[mask])


def test_sequential_places_accumulate():
    """Back-to-back placements (the ring's real usage) compose correctly,
    including across the wrap."""
    import jax.numpy as jnp

    r = _rng(9)
    ring = r.integers(0, 256, CAP, dtype=np.uint8)
    want = ring.copy()
    buf = jnp.asarray(ring)
    off = CAP - 3000
    for i, n in enumerate((1024, 2048, 512, 4096)):
        payload = r.integers(0, 256, n, dtype=np.uint8)
        want = ring_scatter_reference(want, payload, off)
        buf = ring_scatter(buf, jnp.asarray(payload), off, interpret=True)
        off = (off + n) % CAP
    np.testing.assert_array_equal(np.asarray(buf), want)


def test_shape_guards():
    import jax.numpy as jnp

    buf = jnp.zeros((CAP,), jnp.uint8)
    with pytest.raises(ValueError):
        ring_scatter(buf, jnp.zeros((10,), jnp.uint8), 0, interpret=True)
    with pytest.raises(ValueError):
        ring_scatter(buf, jnp.zeros((8,), jnp.uint8), 2, interpret=True)
    with pytest.raises(ValueError):
        ring_scatter(jnp.zeros((4096,), jnp.uint8),
                     jnp.zeros((8,), jnp.uint8), 0, interpret=True)
    # zero-length payload: identity, no kernel
    out = ring_scatter(buf, jnp.zeros((0,), jnp.uint8), 0, interpret=True)
    assert out.shape == (CAP,)


def test_hbm_ring_place_uses_kernel():
    """HbmRing.place routes through ring_scatter (no fallback tripped) and
    wrapped placements round-trip through view."""
    import warnings

    from tpurpc.tpu.hbm_ring import HbmRing

    ring = HbmRing(16384)
    r = _rng(10)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a kernel failure warning = test fail
        # advance near the end so the next placement wraps
        spans = []
        for n in (8192, 4096):
            payload = r.integers(0, 256, n, dtype=np.uint8).tobytes()
            spans.append((ring.place(payload), payload))
        for (off, n), payload in spans:
            lease = ring.view(off, n)
            got = np.asarray(lease.array)
            np.testing.assert_array_equal(got, np.frombuffer(payload, np.uint8))
            lease.release()
        # wrap case: head advanced, place 8KB crossing the 16KB boundary
        payload = r.integers(0, 256, 8192, dtype=np.uint8).tobytes()
        off, n = ring.place(payload)
        assert (off & (16384 - 1)) + n > 16384  # really wraps
        with ring.view(off, n) as arr:
            np.testing.assert_array_equal(
                np.asarray(arr), np.frombuffer(payload, np.uint8))
    assert not getattr(ring, "_pallas_place_broken", False)
    assert not getattr(ring, "_pallas_broken", False)
