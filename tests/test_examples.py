"""Examples stay runnable (BASELINE config #5 end-to-end with thin model)."""

import subprocess
import sys

import numpy as np


def test_helloworld_example():
    out = subprocess.run([sys.executable, "examples/helloworld.py"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "Hello, tpu!" in out.stdout


def test_resnet_serving_end_to_end_thin():
    sys.path.insert(0, "examples")
    try:
        from resnet_server import build_server
    finally:
        sys.path.pop(0)

    import tpurpc.rpc as rpc
    from tpurpc.jaxshim import TensorClient

    srv, port, batcher, size = build_server(0, thin=True, batch=4,
                                            max_delay_s=0.005)
    try:
        rng = np.random.default_rng(1)
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            out = cli.call("Classify", {
                "images": rng.standard_normal((2, size, size, 3))
                .astype(np.float32)}, timeout=120)
        assert np.asarray(out["logits"]).shape == (2, 10)
        assert np.asarray(out["top1"]).shape == (2,)
        assert batcher.rows_run == 2
    finally:
        srv.stop(grace=0)


def test_secure_aio_inference_example():
    out = subprocess.run([sys.executable, "examples/secure_aio_inference.py"],
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    assert "secure aio inference ok" in out.stdout


def test_sharded_inference_example():
    """RPC fan-in feeding a pjit'd 8-virtual-device MoE transformer: the
    full transport→batcher→sharded-model→reply loop, row-exact."""
    out = subprocess.run([sys.executable, "examples/sharded_inference.py"],
                         capture_output=True, text=True, timeout=400)
    assert out.returncode == 0, out.stderr
    assert "row-exact logits" in out.stdout


def test_lookaside_demo_example():
    """Blue/green traffic shifting through the look-aside balancer."""
    out = subprocess.run([sys.executable, "examples/lookaside_demo.py"],
                         capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr
    assert "live blue->green shift" in out.stdout


def test_xds_demo_example():
    """Control-plane-driven traffic movement through the xds shim."""
    out = subprocess.run([sys.executable, "examples/xds_demo.py"],
                         capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr
    assert "traffic followed the control plane" in out.stdout


def test_service_config_demo_example():
    """Resolver-delivered per-method retry/timeout (gRFC A2/A6 shape)."""
    out = subprocess.run([sys.executable, "examples/service_config_demo.py"],
                         capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr
    assert "ok after 3 attempts" in out.stdout
    assert "DEADLINE_EXCEEDED" in out.stdout
    assert "done" in out.stdout
