"""tpurpc-keystone (ISSUE 11): the paged KV-cache plane.

The block manager's contracts — alloc/free accounting, block tables,
copy-on-write prefix reuse, preempt-to-host swap, quarantine — then the
explicit-KV model contract's exact-token equivalence with the opaque-state
path (the satellite regression), the paged scheduler end-to-end, and the
new observability: gauges, flight edges, the `kv-swap` watchdog stage,
and the /healthz kv lines."""

import time

import numpy as np
import pytest

from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
from tpurpc.obs import flight, watchdog
from tpurpc.serving.kv import (ENTRY_BYTES, FLAG_POISONED, HostKv,
                               KvArenaFull, KvBlockManager)
from tpurpc.serving.scheduler import (SLO_BATCH, SLO_INTERACTIVE,
                                      DecodeScheduler, TokenStream)


@pytest.fixture(autouse=True)
def _fast_streams():
    old = TokenStream.MAX_IDLE_S
    TokenStream.MAX_IDLE_S = 10.0
    yield
    TokenStream.MAX_IDLE_S = old


def _mgr(**kw):
    kw.setdefault("n_blocks", 64)
    kw.setdefault("block_bytes", 64)   # 4 entries per block
    kw.setdefault("kind", "local")
    return KvBlockManager(**kw)


def _poll(pred, timeout=5.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return pred()


# -- the arena / block tables -------------------------------------------------

def test_alloc_free_accounting_roundtrips():
    m = _mgr(n_blocks=8)
    try:
        assert m.free_count() == 8 and m.used_count() == 0
        kv, hit = m.alloc_for_prompt(1, np.arange(9, dtype=np.int32))
        assert hit == 0
        for i in range(9):
            kv.append(i * 3, i)
        assert len(kv.blocks) == 3 and m.used_count() == 3
        m.free_blocks(kv)
        assert m.free_count() == 8 and not kv.blocks
    finally:
        m.close()


def test_entries_survive_block_boundaries():
    m = _mgr()
    try:
        kv, _ = m.alloc_for_prompt(1, np.asarray([1], np.int32))
        for i in range(13):       # crosses 3 block boundaries
            kv.append(i * 1000003, i % 251, i % 2)
        for i in range(13):
            assert kv.entry(i) == (i * 1000003, i % 251, i % 2)
        assert kv.last() == kv.entry(12)
        m.free_blocks(kv)
    finally:
        m.close()


def test_arena_full_raises_after_evicting_prefix_cache():
    m = _mgr(n_blocks=4)
    try:
        # retire a sequence donating a 4-entry (1 block) prefix
        kv, _ = m.alloc_for_prompt(1, np.arange(4, dtype=np.int32))
        for i in range(4):
            kv.append(i, i)
        m.free_blocks(kv, cache_prefix=True)
        assert m.prefix_entries() == 1
        # demand every block: the cache entry is evicted to make room
        kv2, _ = m.alloc_for_prompt(2, np.asarray([9], np.int32))
        kv2.reserve(16)
        assert m.prefix_entries() == 0 and len(kv2.blocks) == 4
        with pytest.raises(KvArenaFull):
            kv3, _ = m.alloc_for_prompt(3, np.asarray([8], np.int32))
            kv3.reserve(4)
        m.free_blocks(kv2)
    finally:
        m.close()


def test_truncate_undoes_partial_appends():
    m = _mgr()
    try:
        kv, _ = m.alloc_for_prompt(1, np.asarray([1], np.int32))
        for i in range(6):
            kv.append(i, i)
        kv.truncate(4)
        assert kv.length == 4
        kv.append(99, 9)
        assert kv.entry(4) == (99, 9, 0)
        m.free_blocks(kv)
    finally:
        m.close()


# -- copy-on-write prefix reuse -----------------------------------------------

def test_prefix_cache_hit_shares_blocks_refcounted():
    m = _mgr()
    try:
        prompt = np.arange(10, dtype=np.int32)   # aligned span = 8
        kv, _ = m.alloc_for_prompt(1, prompt)
        for i in range(10):
            kv.append(i * 7, i)
        shared = list(kv.blocks[:2])
        m.free_blocks(kv, cache_prefix=True)
        kv2, hit = m.alloc_for_prompt(2, prompt)
        assert hit == 8 and kv2.blocks[:2] == shared
        assert kv2.shared_len == 8 and kv2.length == 8
        assert m.block_refs(shared[0]) == 2   # cache + kv2
        # entries readable through the shared span, byte-exact
        assert kv2.entry(7) == (49, 7, 0)
        assert m.prefix_hits == 1
        m.free_blocks(kv2)
        assert m.block_refs(shared[0]) == 1   # cache keeps its ref
    finally:
        m.close()


def test_cow_write_copies_shared_block():
    m = _mgr()
    try:
        prompt = np.arange(8, dtype=np.int32)
        kv, _ = m.alloc_for_prompt(1, prompt)
        for i in range(8):
            kv.append(i, i)
        m.free_blocks(kv, cache_prefix=True)
        kv2, hit = m.alloc_for_prompt(2, prompt)
        assert hit == 8
        orig = kv2.blocks[0]
        fresh = kv2.writable_block(0)
        assert fresh != orig and m.block_refs(fresh) == 1
        # the copy carried the bytes; the CACHED block is untouched by
        # writes through the private copy
        assert kv2.entry(0) == (0, 0, 0)
        m.block_view(fresh)[:4] = b"\xff\xff\xff\xff"
        kv3, hit3 = m.alloc_for_prompt(3, prompt)
        assert hit3 == 8 and kv3.entry(0) == (0, 0, 0)
        m.free_blocks(kv2)
        m.free_blocks(kv3)
    finally:
        m.close()


def test_poisoned_prefix_never_cached():
    m = _mgr()
    try:
        model = ToyDecodeModel(poison_token=666)
        prompt = np.asarray([1, 2, 666, 4, 5, 6, 7, 8], np.int32)
        kv, _ = m.alloc_for_prompt(1, prompt)
        model.prefill_paged([prompt], [kv])
        assert kv.entry(7)[2] & FLAG_POISONED
        m.free_blocks(kv, cache_prefix=True)
        assert m.prefix_entries() == 0
        kv2, hit = m.alloc_for_prompt(2, prompt)
        assert hit == 0
        m.free_blocks(kv2)
    finally:
        m.close()


# -- preempt-to-host swap -----------------------------------------------------

def test_swap_roundtrip_byte_exact_and_gauged():
    m = _mgr()
    try:
        kv, _ = m.alloc_for_prompt(7, np.asarray([1], np.int32))
        for i in range(11):
            kv.append(i * 31, i, 0)
        used0 = m.used_count()
        m.swap_out(kv)
        assert kv.swapped and not kv.blocks
        assert m.used_count() == used0 - 3
        assert m.swapped_count() == 3
        # entries readable FROM the host image (migration ships them)
        assert kv.entry(10) == (310, 10, 0)
        m.swap_in(kv)
        assert not kv.swapped and m.swapped_count() == 0
        for i in range(11):
            assert kv.entry(i) == (i * 31, i, 0)
        m.free_blocks(kv)
    finally:
        m.close()


def test_swap_flight_edges_bracket():
    flight.RECORDER.reset()
    m = _mgr()
    try:
        kv, _ = m.alloc_for_prompt(5, np.asarray([1], np.int32))
        kv.append(1, 1)
        m.swap_out(kv)
        m.swap_in(kv)
        ev = [(e["event"], e["a2"]) for e in flight.snapshot()
              if e["event"].startswith("kv-swap")]
        assert ev == [("kv-swap-begin", 0), ("kv-swap-end", 0),
                      ("kv-swap-begin", 1), ("kv-swap-end", 1)], ev
        m.free_blocks(kv)
    finally:
        m.close()


# -- quarantine ---------------------------------------------------------------

def test_quarantined_blocks_never_return_to_free_list():
    m = _mgr(n_blocks=4)
    try:
        blocks = m.alloc_blocks(1, 2)
        n = m.quarantine(blocks)
        assert n == 2
        assert m.quarantined_count() == 2
        assert m.free_count() == 2
        # the arena can never hand them out again
        got = m.alloc_blocks(2, 2)
        assert not set(got) & set(blocks)
        with pytest.raises(KvArenaFull):
            m.alloc_blocks(3, 1)
        m.free_blocks_raw(got)
    finally:
        m.close()


def test_quarantine_respects_shared_refs():
    m = _mgr()
    try:
        prompt = np.arange(8, dtype=np.int32)
        kv, _ = m.alloc_for_prompt(1, prompt)
        for i in range(8):
            kv.append(i, i)
        m.free_blocks(kv, cache_prefix=True)       # cache holds 2 blocks
        kv2, hit = m.alloc_for_prompt(2, prompt)
        assert hit == 8
        n = m.quarantine(kv2)
        # shared blocks only decref'd (cache still holds them); nothing
        # actually quarantined
        assert n == 0 and m.prefix_entries() == 1
        kv3, hit3 = m.alloc_for_prompt(3, prompt)
        assert hit3 == 8
        m.free_blocks(kv3)
    finally:
        m.close()


# -- explicit-KV model contract: exact equivalence (satellite) ----------------

def test_paged_contract_matches_opaque_path_exactly():
    """The satellite regression: prefill_paged/step_paged emit EXACTLY
    the tokens the opaque prefill/step path (and reference_decode) emit,
    for a spread of prompts and lengths."""
    m = _mgr()
    try:
        for prompt in ([1], [3, 1, 4], list(range(20)), [7] * 5):
            model_a = ToyDecodeModel()
            model_b = ToyDecodeModel()
            p = np.asarray(prompt, np.int32)
            # opaque path
            states, toks = model_a.prefill([p])
            opaque = [int(toks[0])]
            for _ in range(15):
                states, toks = model_a.step(
                    states, np.asarray(toks, np.int32))
                opaque.append(int(toks[0]))
            # paged path
            kv, _ = m.alloc_for_prompt(hash(tuple(prompt)) & 0xFFFF, p)
            paged = [int(model_b.prefill_paged([p], [kv])[0])]
            for _ in range(15):
                paged.append(int(model_b.step_paged(
                    [kv], np.asarray([paged[-1]], np.int32))[0]))
            assert opaque == paged == reference_decode(prompt, 16), prompt
            m.free_blocks(kv)
    finally:
        m.close()


def test_paged_prefill_resumes_from_cached_span_exactly():
    m = _mgr()
    try:
        model = ToyDecodeModel()
        p = np.arange(10, dtype=np.int32)   # span 8 of 10: partial hit
        kv, _ = m.alloc_for_prompt(1, p)
        model.prefill_paged([p], [kv])
        m.free_blocks(kv, cache_prefix=True)
        kv2, hit = m.alloc_for_prompt(2, p)
        assert hit == 8
        first = int(model.prefill_paged([p], [kv2])[0])
        out = [first]
        for _ in range(7):
            out.append(int(model.step_paged(
                [kv2], np.asarray([out[-1]], np.int32))[0]))
        assert out == reference_decode(p, 8)
        m.free_blocks(kv2)
    finally:
        m.close()


def test_hostkv_seeded_base_matches_cold_prefill():
    """The prefill server's shape: a HostKv seeded with the resume hash
    computes the SAME tail entries a cold prefill computes."""
    model = ToyDecodeModel()
    p = np.arange(12, dtype=np.int32)
    cold = HostKv()
    first_cold = int(model.prefill_paged([p], [cold])[0])
    # the decode side's claimed resume point: entry 7's hash
    base_hash = cold.entry(7)[0]
    warm = HostKv(base_pos=8, base_hash=base_hash, base_flags=0)
    first_warm = int(model.prefill_paged([p], [warm])[0])
    assert first_cold == first_warm == reference_decode(p, 1)[0]
    # shipped payloads agree on the overlapping entries
    assert bytes(cold.payload()[8 * ENTRY_BYTES:]) == bytes(warm.payload())


# -- the paged scheduler end-to-end -------------------------------------------

def test_paged_scheduler_streams_reference_tokens():
    m = _mgr(n_blocks=256)
    s = DecodeScheduler(ToyDecodeModel(), kv=m, max_batch=4,
                        idle_wait_s=0.01)
    try:
        handles = {i: s.submit([i, i + 1], max_tokens=24)
                   for i in range(10)}
        for i, h in handles.items():
            assert list(h) == reference_decode([i, i + 1], 24), i
    finally:
        s.close()
        m.close()


def test_paged_scheduler_releases_all_blocks_at_retire():
    m = _mgr(n_blocks=64)
    s = DecodeScheduler(ToyDecodeModel(), kv=m, max_batch=4,
                        idle_wait_s=0.01)
    try:
        for i in range(6):
            list(s.submit([i], max_tokens=10))
        # everything freed (short prompts are below the block-aligned
        # span bar, so nothing is even cached)
        assert _poll(lambda: m.used_count() == 0), m.stats()
    finally:
        s.close()
        m.close()


def test_paged_swap_preemption_resumes_value_exact():
    m = _mgr(n_blocks=128, block_bytes=256)
    s = DecodeScheduler(ToyDecodeModel(step_delay_s=0.002), kv=m,
                        max_batch=1, idle_wait_s=0.005)
    try:
        flight.RECORDER.reset()
        long = s.submit([9], max_tokens=60, slo=SLO_BATCH)
        for _ in range(5):
            long.next(timeout=5)
        quick = s.submit([4], max_tokens=4, slo=SLO_INTERACTIVE)
        assert list(quick) == reference_decode([4], 4)
        rest = list(long)
        assert reference_decode([9], 60)[5:] == rest
        assert s.preempted_total >= 1
        assert m.swaps_out >= 1 and m.swaps_in >= 1
        ev = [e["event"] for e in flight.snapshot()]
        assert "kv-swap-begin" in ev and "kv-swap-end" in ev
    finally:
        s.close()
        m.close()


def test_paged_poisoned_sequence_fails_alone_and_frees():
    m = _mgr(n_blocks=64)
    s = DecodeScheduler(ToyDecodeModel(poison_token=666), kv=m,
                        max_batch=4, idle_wait_s=0.01)
    try:
        good1 = s.submit([3], max_tokens=20)
        bad = s.submit([666], max_tokens=20)
        good2 = s.submit([4], max_tokens=20)
        assert list(good1) == reference_decode([3], 20)
        assert list(good2) == reference_decode([4], 20)
        with pytest.raises(ValueError, match="poison"):
            list(bad)
        assert _poll(lambda: m.used_count() == 0), m.stats()
    finally:
        s.close()
        m.close()


def test_paged_scheduler_requires_contract():
    class NoPaged:
        pass

    m = _mgr()
    try:
        with pytest.raises(ValueError, match="explicit-KV"):
            DecodeScheduler(NoPaged(), kv=m)
    finally:
        m.close()


# -- observability ------------------------------------------------------------

def test_kv_gauges_registered_and_live():
    from tpurpc.obs import metrics

    m = _mgr(n_blocks=16)
    try:
        kv, _ = m.alloc_for_prompt(1, np.asarray([1], np.int32))
        kv.append(1, 1)
        reg = metrics.registry().metrics()
        for name in ("kv_blocks_used", "kv_blocks_free",
                     "kv_blocks_swapped", "kv_blocks_quarantined"):
            assert name in reg, name
        assert reg["kv_blocks_used"].collect()[0] >= 1
        m.free_blocks(kv)
    finally:
        m.close()


def test_healthz_shows_kv_lines():
    from tpurpc.obs import scrape

    m = _mgr(n_blocks=16, name="hz")
    try:
        kv, _ = m.alloc_for_prompt(1, np.asarray([1], np.int32))
        kv.append(1, 1)
        status, _ctype, body = scrape.route_local("/healthz")
        assert status == 200
        text = body.decode()
        assert "kv hz:" in text and "used=1/16" in text, text
        m.free_blocks(kv)
    finally:
        m.close()


def test_watchdog_names_kv_swap_stage():
    """An open kv-swap bracket aged past the stall floor is attributed to
    the new `kv-swap` stage."""
    flight.RECORDER.reset()
    wd = watchdog.StallWatchdog(sweep_s=10, mult=8, min_stall_s=0.2)
    wd.enabled = True
    tag = flight.tag_for("kv:wdtest")
    tok = wd.call_started("/tpurpc.Generate/Generate")
    flight.emit(flight.KV_SWAP_BEGIN, tag, 42, 0)   # no END: wedged
    time.sleep(0.35)
    diags = wd.sweep_once()
    assert diags and diags[0]["stage"] == "kv-swap", diags
    assert "swap" in diags[0]["detail"]
    flight.emit(flight.KV_SWAP_END, tag, 42, 0)
    wd.call_finished(tok)
    wd.reset()


def test_watchdog_names_migration_stage():
    flight.RECORDER.reset()
    wd = watchdog.StallWatchdog(sweep_s=10, mult=8, min_stall_s=0.2)
    wd.enabled = True
    tag = flight.tag_for("disagg:wdtest")
    tok = wd.call_started("/tpurpc.Kv/ResumeSeq")
    flight.emit(flight.MIG_BEGIN, tag, 7, 100)      # no END: wedged
    time.sleep(0.35)
    diags = wd.sweep_once()
    assert diags and diags[0]["stage"] == "migration", diags
    flight.emit(flight.MIG_END, tag, 7, 1)
    wd.call_finished(tok)
    wd.reset()
