"""Wire-compat: STOCK grpcio clients against a tpurpc server (drop-in proof).

The reference is gRPC itself, so its clients work against it by definition;
tpurpc earns the same property here — a grpc.insecure_channel from the
installed grpcio (C-core: full HPACK with huffman + dynamic-table indexing,
real flow control) drives the tpurpc server's h2 path, while tpurpc-native
clients share the same port via protocol sniffing.
"""

import threading
import time

import grpc
import pytest

import tpurpc.rpc as tps  # package re-exports Server + handler factories
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.status import StatusCode


def _echo_server():
    srv = tps.Server(max_workers=8)

    def echo(req, ctx):
        return req

    def tail(req, ctx):
        for i in range(4):
            yield req + str(i).encode()

    def collect(req_iter, ctx):
        return b"|".join(req_iter)

    def chat(req_iter, ctx):
        for req in req_iter:
            yield b"re:" + req

    def boom(req, ctx):
        ctx.set_trailing_metadata((("saw-md", "yes"),))
        ctx.abort(StatusCode.FAILED_PRECONDITION, "nope: not ready")

    def meta(req, ctx):
        md = {k: v for k, v in ctx.invocation_metadata()}
        ctx.set_trailing_metadata((("echoed-key", md.get("x-custom", "?")),
                                   ("bin-bin", md.get("x-blob-bin", b"")),))
        return req

    srv.add_method("/test.Echo/Echo", tps.unary_unary_rpc_method_handler(echo))
    srv.add_method("/test.Echo/Tail", tps.unary_stream_rpc_method_handler(tail))
    srv.add_method("/test.Echo/Collect",
                   tps.stream_unary_rpc_method_handler(collect))
    srv.add_method("/test.Echo/Chat",
                   tps.stream_stream_rpc_method_handler(chat))
    srv.add_method("/test.Echo/Boom", tps.unary_unary_rpc_method_handler(boom))
    srv.add_method("/test.Echo/Meta", tps.unary_unary_rpc_method_handler(meta))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


@pytest.fixture(scope="module")
def compat():
    srv, port = _echo_server()
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield srv, port, ch
    ch.close()
    srv.stop(grace=0)


_ID = lambda x: x  # bytes-in/bytes-out "serializer" for raw interop


def test_grpcio_unary(compat):
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
    assert mc(b"hello tpu", timeout=20) == b"hello tpu"


def test_grpcio_unary_large_flow_controlled(compat):
    """4MiB both ways exercises DATA fragmentation + window updates."""
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
    big = bytes(range(256)) * (4 * 4096)  # 4 MiB
    assert mc(big, timeout=60) == big


def test_grpcio_server_streaming(compat):
    _, _, ch = compat
    mc = ch.unary_stream("/test.Echo/Tail", _ID, _ID)
    assert list(mc(b"x", timeout=20)) == [b"x0", b"x1", b"x2", b"x3"]


def test_grpcio_client_streaming(compat):
    _, _, ch = compat
    mc = ch.stream_unary("/test.Echo/Collect", _ID, _ID)
    assert mc(iter([b"a", b"b", b"c"]), timeout=20) == b"a|b|c"


def test_grpcio_bidi_streaming(compat):
    _, _, ch = compat
    mc = ch.stream_stream("/test.Echo/Chat", _ID, _ID)
    assert list(mc(iter([b"1", b"2"]), timeout=20)) == [b"re:1", b"re:2"]


def test_grpcio_error_status_and_message(compat):
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Boom", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"x", timeout=20)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "nope: not ready" in ei.value.details()
    md = dict(ei.value.trailing_metadata())
    assert md.get("saw-md") == "yes"


def test_grpcio_metadata_roundtrip_incl_binary(compat):
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Meta", _ID, _ID)
    resp, call = mc.with_call(
        b"m", timeout=20,
        metadata=(("x-custom", "v123"), ("x-blob-bin", b"\x00\x01\xfe")))
    assert resp == b"m"
    md = dict(call.trailing_metadata())
    assert md.get("echoed-key") == "v123"
    assert md.get("bin-bin") == b"\x00\x01\xfe"


def test_grpcio_unimplemented(compat):
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Nope", _ID, _ID)
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"x", timeout=20)
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_grpcio_deadline(compat):
    srv, _, ch = compat

    def slow(req, ctx):
        time.sleep(5)
        return req

    srv.add_method("/test.Echo/Slow", tps.unary_unary_rpc_method_handler(slow))
    mc = ch.unary_unary("/test.Echo/Slow", _ID, _ID)
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError) as ei:
        mc(b"x", timeout=0.5)
    assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert time.monotonic() - t0 < 3


def test_grpcio_many_concurrent_calls(compat):
    _, _, ch = compat
    mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
    results = [None] * 16
    def one(i):
        results[i] = mc(f"m{i}".encode(), timeout=30)
    ts = [threading.Thread(target=one, args=(i,)) for i in range(16)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert results == [f"m{i}".encode() for i in range(16)]


def test_native_and_grpcio_share_one_port(compat):
    """Protocol sniffing: tpurpc-native framing and h2 on the same listener."""
    srv, port, ch = compat
    mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
    with Channel(f"127.0.0.1:{port}") as native:
        nmc = native.unary_unary("/test.Echo/Echo")
        assert nmc(b"native", timeout=20) == b"native"
        assert mc(b"h2", timeout=20) == b"h2"


def test_grpcio_gzip_compressed_client(compat):
    """A stock grpcio client with channel-level gzip compression: the tpurpc
    server must decompress requests (and advertise its accept list)."""
    srv, port, _ = compat
    with grpc.insecure_channel(f"127.0.0.1:{port}",
                               compression=grpc.Compression.Gzip) as ch:
        mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
        payload = b"compress-me " * 400  # compressible, > trivial size
        assert mc(payload, timeout=20) == payload
        mcs = ch.stream_unary("/test.Echo/Collect", _ID, _ID)
        assert mcs(iter([b"a" * 100, b"b" * 100]), timeout=20) == \
            b"a" * 100 + b"|" + b"b" * 100


def test_grpcio_deflate_compressed_client(compat):
    """Same as the gzip case but with the deflate codec (raw zlib stream,
    gRPC's second standard compressor) — decode_grpc_message must handle
    both and advertise them in grpc-accept-encoding."""
    srv, port, _ = compat
    with grpc.insecure_channel(f"127.0.0.1:{port}",
                               compression=grpc.Compression.Deflate) as ch:
        mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
        payload = b"deflate-me " * 400
        assert mc(payload, timeout=20) == payload


def test_graceful_stop_with_h2_connection():
    """stop(grace) must survive connections speaking the h2 protocol (they
    have no frame-protocol writer to GOAWAY) and still terminate."""
    import tpurpc.rpc as rpc

    srv = rpc.Server(max_workers=2)
    srv.add_method("/test.Echo/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda b, c: bytes(b)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        mc = ch.unary_unary("/test.Echo/Echo", _ID, _ID)
        assert mc(b"hi", timeout=20) == b"hi"
        ev = srv.stop(grace=1)          # h2 conn live: must not raise
        assert ev.wait(timeout=10)


def test_malformed_settings_rejected_cleanly(compat):
    """A peer advertising RFC-invalid SETTINGS (MAX_FRAME_SIZE=0 — would
    spin the send loop; INITIAL_WINDOW_SIZE>2^31-1 — would blow the flow
    window) gets its connection torn down instead of poisoning the
    server, and the server keeps serving other connections."""
    import socket
    import struct as _s

    _, port, ch = compat
    # raw frame bytes: length(3) type(0x4=SETTINGS) flags(0) sid(0)
    for k, v in ((5, 0), (4, 0xFFFFFFFF)):
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.settimeout(10)
        try:
            s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            payload = _s.pack("!HI", k, v)
            frame = len(payload).to_bytes(3, "big") + b"\x04\x00" + \
                (0).to_bytes(4, "big") + payload
            s.sendall(frame)
            # server must close (possibly after GOAWAY); recv drains to EOF
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    if s.recv(4096) == b"":
                        break
                except socket.timeout:
                    raise AssertionError("server kept the connection open")
        finally:
            s.close()
    # the shared module-scope channel still works: server survived
    assert ch.unary_unary("/test.Echo/Echo", _ID, _ID)(b"alive",
                                                       timeout=15) == b"alive"
