"""Platform.TPU transport: TpuRingEndpoint dispatch, device-ring decode,
lease-gated credit, end-to-end tensor RPC with ledger-proven copy accounting.

The north-star path (BASELINE.json): wire bytes → frame assembly (host) →
device-ring placement → lease-backed jax.Array, with host-memcpy = 0 after
assembly. Reference analogs: creation path ``rdma_bp_posix.cc:706-796``,
receive drain ``ring_buffer.cc:122-191``.
"""

import threading

import numpy as np
import pytest

from tpurpc.jaxshim import TensorClient, add_tensor_method, codec
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.server import Server
from tpurpc.tpu import HbmRing, ledger
from tpurpc.tpu.endpoint import (DeviceMessage, TpuRingEndpoint,
                                 decode_tensor_to_ring, decode_tree_to_ring)


def _tpu_server(monkeypatch, fn, kind="unary_unary", device=True,
                platform="TPU"):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    # Re-arm the config singleton AFTER the env change: a straggler thread
    # from the previous test (server teardown, bootstrap) can rebuild the
    # singleton in the window between the autouse fixture's reset and this
    # setenv, silently pinning the whole test to the default TCP platform.
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv = Server(max_workers=4)
    add_tensor_method(srv, "Call", fn, kind=kind, device=device)
    srv.start()
    port = srv.add_insecure_port("127.0.0.1:0")
    return srv, port


# -- decode-to-ring units -----------------------------------------------------

def test_decode_tensor_to_ring_zero_host_copy():
    """The DeserializeToDevice step itself moves no bytes host-side."""
    x = np.arange(2048, dtype=np.float32)
    wire = bytearray(codec.encode_tensor_bytes(x))
    ring = HbmRing(1 << 16)
    with ledger.track() as w:
        lease, end = decode_tensor_to_ring(ring, wire)
    assert w["host_copy"] == 0
    assert w["dma_h2d"] == x.nbytes
    assert w["dma_d2d"] >= x.nbytes  # in-ring landing + view materialization
    assert end == len(wire)
    with lease as arr:
        assert arr.shape == (2048,)
        np.testing.assert_array_equal(np.asarray(arr), x)


def test_decode_tree_to_ring_roundtrip_and_release():
    tree = {"w": np.ones((16, 16), np.float32),
            "b": np.arange(16, dtype=np.int32)}
    wire = codec.encode_tree_bytes(tree)
    ring = HbmRing(1 << 16)
    out, leases = decode_tree_to_ring(ring, wire)
    np.testing.assert_array_equal(np.asarray(out["w"]), tree["w"])
    np.testing.assert_array_equal(np.asarray(out["b"]), tree["b"])
    assert ring.stats()["live_spans"] == 2
    for lease in leases:
        lease.release()
    st = ring.stats()
    assert st["live_spans"] == 0 and st["writable"] == st["capacity"]


def test_ring_credit_blocks_until_lease_release():
    """An unreleased lease back-pressures placement (flow control), and a
    release from another thread unblocks a waiting place()."""
    x = np.zeros(3000, np.uint8)
    wire = bytearray(codec.encode_tensor_bytes(x))
    ring = HbmRing(1 << 12)  # 4 KiB: one message in flight
    lease, _ = decode_tensor_to_ring(ring, wire)
    with pytest.raises(BufferError):
        decode_tensor_to_ring(ring, wire, timeout=0.05)
    t = threading.Timer(0.1, lease.release)
    t.start()
    lease2, _ = decode_tensor_to_ring(ring, wire, timeout=5)  # blocks, then ok
    lease2.release()
    t.join()


def test_oversized_payload_rejected():
    ring = HbmRing(1 << 12)
    wire = bytearray(codec.encode_tensor_bytes(np.zeros(8192, np.uint8)))
    with pytest.raises(BufferError):
        decode_tensor_to_ring(ring, wire, timeout=0.05)


def test_empty_tensors_no_span_collision():
    """Consecutive zero-size leaves must not collide on the (off, 0) span key
    (reviewer finding: shared _live entry corrupted lease counts)."""
    tree = {"a": np.zeros((0,), np.float32), "b": np.zeros((0,), np.float64),
            "c": np.arange(4, dtype=np.int32)}
    ring = HbmRing(1 << 12)
    out, leases = decode_tree_to_ring(ring, codec.encode_tree_bytes(tree))
    assert out["a"].shape == (0,) and out["b"].shape == (0,)
    np.testing.assert_array_equal(np.asarray(out["c"]), tree["c"])
    for lease in leases:
        lease.release()  # must not KeyError
    st = ring.stats()
    assert st["live_spans"] == 0 and st["writable"] == st["capacity"]


def test_corrupt_trailer_releases_leases():
    """A poison trailer must return every taken lease (reviewer finding:
    leaked credit = one-peer DoS on the connection's ring)."""
    tree = {"x": np.ones(64, np.float32)}
    wire = bytearray(codec.encode_tree_bytes(tree))
    wire[-3:] = b"\xff\xff\xff"  # corrupt the JSON treedef trailer
    ring = HbmRing(1 << 12)
    with pytest.raises(Exception):
        decode_tree_to_ring(ring, wire)
    st = ring.stats()
    assert st["live_spans"] == 0 and st["writable"] == st["capacity"]


def test_tree_larger_than_ring_fails_fast():
    """A tree that can never fit must raise immediately, not stall a worker
    the full place timeout waiting on its own leases (reviewer finding)."""
    import time

    tree = {"a": np.zeros(3000, np.uint8), "b": np.zeros(3000, np.uint8)}
    ring = HbmRing(1 << 12)  # 4 KiB < 6 KB total
    t0 = time.monotonic()
    with pytest.raises(BufferError, match="capacity"):
        decode_tree_to_ring(ring, codec.encode_tree_bytes(tree))
    assert time.monotonic() - t0 < 1.0
    assert ring.stats()["live_spans"] == 0


# -- endpoint dispatch --------------------------------------------------------

@pytest.mark.parametrize("spelling", ["TPU", "RDMA_TPU"])
def test_factory_dispatches_tpu_endpoint(monkeypatch, spelling):
    """GRPC_PLATFORM_TYPE=TPU|RDMA_TPU yields TpuRingEndpoint on both sides
    (the import that was a ModuleNotFoundError in round 1)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", spelling)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)  # see _tpu_server: straggler-thread rebuild
    from tpurpc.core.endpoint import EndpointListener, connect_endpoint

    got = []
    ev = threading.Event()

    def on_ep(ep):
        got.append(ep)
        ev.set()

    lst = EndpointListener("127.0.0.1", 0, on_ep)
    try:
        cli = connect_endpoint("127.0.0.1", lst.port)
        assert ev.wait(10)
        assert isinstance(cli, TpuRingEndpoint)
        assert isinstance(got[0], TpuRingEndpoint)
        cli.write(b"ping")
        assert got[0].read(16, timeout=5) == b"ping"
        cli.close()
        got[0].close()
    finally:
        lst.close()


# -- end-to-end tensor RPC on the TPU platform --------------------------------

def test_e2e_device_tensor_rpc(monkeypatch):
    """GRPC_PLATFORM_TYPE=TPU end to end: handler receives ring-backed device
    arrays, decode adds no host copies beyond frame assembly."""
    import jax

    seen = {}

    def fn(tree):
        seen["type"] = type(tree["x"])
        return {"y": tree["x"] * 2}

    srv, port = _tpu_server(monkeypatch, fn)
    try:
        x = np.arange(1024, dtype=np.float32).reshape(32, 32)
        with Channel(f"127.0.0.1:{port}") as ch:
            out = TensorClient(ch).call("Call", {"x": x}, timeout=30)
        np.testing.assert_array_equal(np.asarray(out["y"]), x * 2)
        assert issubclass(seen["type"], jax.Array)
    finally:
        srv.stop(grace=0)


def test_e2e_rpc_ledger_shows_zero_copy_views(monkeypatch):
    """VERDICT r4 next #3 done-criterion: an end-to-end RPC on the emulated
    TPU platform whose ledger shows zero_copy > 0 and NO view-side d2d for
    eligible (aligned, unwrapped) leaves — the only d2d ops in the window
    are the per-leaf landing writes, so every request view was an alias."""
    import jax

    seen = {}

    def fn(tree):
        seen["arrays"] = [tree["a"], tree["b"]]
        return {"y": tree["a"] + 1}

    srv, port = _tpu_server(monkeypatch, fn)
    try:
        # 4 KiB float32 leaves: span offsets 0 and 4096 on a fresh ring —
        # aligned, unwrapped, dlpack-eligible
        a = np.arange(1024, dtype=np.float32)
        b = np.ones(1024, np.float32)
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            with ledger.track() as w:
                out = cli.call("Call", {"a": a, "b": b}, timeout=30)
        np.testing.assert_array_equal(np.asarray(out["y"]), a + 1)
        assert issubclass(type(seen["arrays"][0]), jax.Array)
        # both request leaves were viewed as ALIASES (zero_copy, no
        # materialization) and the whole tree landed as ONE batched
        # placement (place_many: one h2d + one donated update per tree,
        # not per leaf): view-side d2d == 0, so exactly one d2d op total
        assert w["zero_copy"] >= a.nbytes + b.nbytes, w.delta
        assert w["dma_d2d_ops"] == 1, w.delta  # the batch landing write ONLY
        assert w["dma_h2d_ops"] == 1, w.delta  # one packed h2d per tree
    finally:
        srv.stop(grace=0)


def test_e2e_concurrent_passthrough_echo_no_alias_corruption(monkeypatch):
    """Round-5 serialize-then-release ordering: a device handler returning
    an ALIASED request leaf verbatim must serialize it before the lease
    releases — otherwise a concurrent RPC's in-place placement could
    overwrite the span mid-serialization and corrupt the reply silently
    (reviewer finding, round 5). Hammer two concurrent echo streams with
    distinct payloads and verify every reply byte-exactly."""
    def fn(tree):
        return {"y": tree["x"]}  # passthrough: the alias itself

    srv, port = _tpu_server(monkeypatch, fn)
    errors = []
    try:
        # ONE channel: both workers' RPCs multiplex one connection and so
        # share one receive ring — the only topology where a concurrent
        # placement can reuse a just-released span under a late serializer
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def worker(seed):
                try:
                    rng = np.random.default_rng(seed)
                    for _ in range(30):
                        x = rng.standard_normal(1024).astype(np.float32)
                        out = cli.call("Call", {"x": x}, timeout=30)
                        np.testing.assert_array_equal(np.asarray(out["y"]), x)
                except Exception as exc:
                    errors.append(exc)

            ts = [threading.Thread(target=worker, args=(s,)) for s in (1, 2)]
            [t.start() for t in ts]
            [t.join(timeout=120) for t in ts]
            assert not errors, errors
    finally:
        srv.stop(grace=0)


def test_e2e_client_device_response(monkeypatch):
    """call_device: the RESPONSE lands in the client connection's device ring
    and comes back as a lease-holding DeviceMessage."""
    def fn(tree):
        return {"y": np.asarray(tree["x"]) + 1}

    srv, port = _tpu_server(monkeypatch, fn)
    try:
        x = np.arange(256, dtype=np.float32)
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            msg = cli.call_device("Call", {"x": x}, timeout=30)
            assert isinstance(msg, DeviceMessage)
            ring = ch.device_ring()
            assert ring is not None and ring.stats()["live_spans"] == 1
            with msg as tree:
                np.testing.assert_array_equal(np.asarray(tree["y"]), x + 1)
            assert ring.stats()["live_spans"] == 0  # credit returned
    finally:
        srv.stop(grace=0)


def test_e2e_streaming_rolling_credit(monkeypatch):
    """A device-mode stream longer than the ring holds only one message's
    leases at a time (rolling release as the handler advances)."""
    monkeypatch.setenv("TPURPC_HBM_RING_SIZE_KB", "64")  # 64 KiB device ring

    def consume(trees):
        total = 0
        for t in trees:
            total += int(np.asarray(t["x"]).sum())
        yield {"total": np.int64(total)}

    srv, port = _tpu_server(monkeypatch, consume, kind="stream_stream")
    try:
        x = np.ones(4096, np.float32)  # 16 KiB per message, 8 messages
        with Channel(f"127.0.0.1:{port}") as ch:
            replies = list(TensorClient(ch).duplex(
                "Call", iter([{"x": x}] * 8), timeout=60))
        assert int(np.asarray(replies[0]["total"]).ravel()[0]) == 8 * 4096
    finally:
        srv.stop(grace=0)


def test_device_method_falls_back_off_platform(monkeypatch):
    """device=True on a TCP transport degrades to the host decode."""
    def fn(tree):
        return {"y": np.asarray(tree["x"]) * 3}

    srv, port = _tpu_server(monkeypatch, fn, platform="TCP")
    try:
        x = np.arange(64, dtype=np.float32)
        with Channel(f"127.0.0.1:{port}") as ch:
            out = TensorClient(ch).call("Call", {"x": x}, timeout=30)
            np.testing.assert_array_equal(np.asarray(out["y"]), x * 3)
            assert ch.device_ring() is None
    finally:
        srv.stop(grace=0)


def test_e2e_wrapped_spans_take_pallas_consume(monkeypatch):
    """A long device-mode stream through a SMALL ring forces spans across
    the wrap point; every wrapped view must go through the fused Pallas
    consume kernel (counted) and every payload must decode exactly —
    the kernel exercised by the full transport→ring→lease path."""
    monkeypatch.setenv("TPURPC_HBM_RING_SIZE_KB", "32")  # tiny: wrap often

    import tpurpc.ops as ops_pkg
    from tpurpc.ops.ring_window import ring_window as real_ring_window

    calls = {"n": 0}

    def counting(*a, **kw):
        calls["n"] += 1
        return real_ring_window(*a, **kw)

    monkeypatch.setattr(ops_pkg, "ring_window", counting)

    rng = np.random.default_rng(11)
    payloads = [rng.standard_normal(1500).astype(np.float32)
                for _ in range(12)]  # 6 KiB each through a 32 KiB ring

    def consume(trees):
        acc = 0.0
        for t in trees:
            acc += float(np.asarray(t["x"]).sum())
        yield {"total": np.float64(acc)}

    srv, port = _tpu_server(monkeypatch, consume, kind="stream_stream")
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            replies = list(TensorClient(ch).duplex(
                "Call", iter([{"x": p} for p in payloads]), timeout=60))
        want = sum(float(p.sum()) for p in payloads)
        got = float(np.asarray(replies[0]["total"]).ravel()[0])
        assert abs(got - want) < 1e-3 * max(1.0, abs(want))
        assert calls["n"] >= 1, "stream never crossed the wrap point"
    finally:
        srv.stop(grace=0)
