"""Verbs (RDMA NIC) domain skeleton: availability contract + the full
one-sided call sequence proven against the in-process mock fabric.

The environment has no IB hardware, so the REAL branch of
``native/src/verbs_domain.cc`` is compiled here against
``tests/mock_verbs/infiniband/verbs.h`` — a registry-backed verbs subset
whose RDMA WRITE is a bounds/rkey-checked memcpy and whose QP transitions
are order-checked (RESET→INIT→RTR→RTS). That proves the skeleton's call
sequence and the Python domain's Region/Window wiring end-to-end; the
default build's stubs prove the honest-unavailability contract.
Reference analogs: ``ibverbs/pair.cc`` bring-up + postWrite,
``buffer.h``/``memory_region.h``.
"""

import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MOCK_LIB = os.path.join(ROOT, "native", "build", "libtpurpc_verbs_mock.so")


def _build_mock_lib():
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ toolchain")
    src = os.path.join(ROOT, "native", "src", "verbs_domain.cc")
    mock_inc = os.path.join(ROOT, "tests", "mock_verbs")
    deps = [src, os.path.join(mock_inc, "infiniband", "verbs.h")]
    if (os.path.exists(MOCK_LIB)
            and all(os.path.getmtime(MOCK_LIB) > os.path.getmtime(d)
                    for d in deps)):
        return
    os.makedirs(os.path.dirname(MOCK_LIB), exist_ok=True)
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-shared", "-fPIC",
         "-DTPR_TEST_MOCK_VERBS", f"-I{mock_inc}", src, "-o", MOCK_LIB],
        check=True, timeout=180, capture_output=True)


def _fresh_domain_module(monkeypatch, lib_path=None):
    """verbs.py caches its ctypes lib process-wide; point it somewhere
    specific and reset the cache for this test."""
    import tpurpc.core.verbs as verbs

    if lib_path is not None:
        monkeypatch.setenv("TPURPC_VERBS_LIB", lib_path)
    else:
        monkeypatch.delenv("TPURPC_VERBS_LIB", raising=False)
    monkeypatch.setattr(verbs, "_LIB", None)
    return verbs


def test_stub_build_reports_unavailable_cleanly(monkeypatch):
    """Default libtpurpc.so (no <infiniband/verbs.h> in this image): the
    domain must raise a RuntimeError NAMING the missing capability, never
    fake placement."""
    lib = os.path.join(ROOT, "native", "build", "libtpurpc.so")
    if not os.path.exists(lib):
        pytest.skip("native lib not built")
    verbs = _fresh_domain_module(monkeypatch, lib)
    with pytest.raises(RuntimeError, match="libibverbs|RDMA NIC"):
        verbs.VerbsDomain()
    # the make_domain("verbs") spelling surfaces the same error
    from tpurpc.core.pair import make_domain

    with pytest.raises(RuntimeError, match="libibverbs|RDMA NIC"):
        make_domain("verbs")


def test_one_sided_write_through_mock_fabric(monkeypatch):
    """alloc → reg_mr + QP; open_window → QP bring-up (order-checked by
    the mock) + RDMA WRITE; bytes LAND in the registered region with zero
    receiver involvement — the skeleton's whole reason to exist."""
    _build_mock_lib()
    verbs = _fresh_domain_module(monkeypatch, MOCK_LIB)
    dom = verbs.VerbsDomain()
    region = dom.alloc(4096)
    try:
        assert region.handle.startswith("verbs:")
        win = dom.open_window(region.handle, 4096)
        try:
            win.write(0, b"nic-placed")
            win.write(1000, b"\x01\x02\x03\x04")
            assert bytes(region.buf[:10]) == b"nic-placed"
            assert bytes(region.buf[1000:1004]) == b"\x01\x02\x03\x04"
            # bounds violations are NAK'd (mock: IBV_WC_REM_ACCESS_ERR),
            # surfaced as an error — never a silent wild write
            with pytest.raises((IndexError, OSError)):
                win.write(4090, b"overruns-the-region")
            # the writer exposes its attrs for the reverse RC leg; the
            # region owner installs them (real hardware requires this
            # before the first WRITE; the mock just order-checks it)
            qpn, lid, gid, psn = win.writer_attrs
            dom.accept_writer(region.handle, qpn, lid, gid, psn)
        finally:
            win.close()
    finally:
        region.close()
    # region closed: its handle is gone
    with pytest.raises(KeyError):
        dom.accept_writer(region.handle, 0, 0, b"\x00" * 16, 0)
    dom.close()  # regions first, then the device context (teardown order)
    dom.close()  # idempotent


def test_write_posts_from_registered_bounce_source(monkeypatch):
    """The registered-source post path (ISSUE 3 satellite, closes the
    round-5 skeleton TODO): every WRITE's local SGE must come from an
    ibv_reg_mr'd staging buffer with that MR's real lkey — real RC
    hardware faults on unregistered sources, so the window registers a
    bounce MR at open and stages through it. Proven here by observing the
    bounce registration itself: opening a window adds a second MR (the
    region's + the bounce), closing the window parks it in the domain's
    MR cache (ISSUE 16: registrations are recycled, not deregistered — a
    second same-class window reuses it), domain close deregisters
    everything, and writes still land — including from a read-only bytes
    source (the old from_buffer_copy path is gone; staging handles
    readonly views)."""
    import ctypes

    _build_mock_lib()
    verbs = _fresh_domain_module(monkeypatch, MOCK_LIB)
    lib = ctypes.CDLL(MOCK_LIB)
    lib.tpr_mock_mr_count.restype = ctypes.c_int
    dom = verbs.VerbsDomain()
    region = dom.alloc(256)
    try:
        before = lib.tpr_mock_mr_count()
        win = dom.open_window(region.handle, 256)
        try:
            assert lib.tpr_mock_mr_count() == before + 1  # the bounce MR
            win.write(8, b"readonly-bytes-source")  # readonly view: stages
            assert bytes(region.buf[8:29]) == b"readonly-bytes-source"
            win.write(8, memoryview(bytearray(b"writable-view-source!")))
            assert bytes(region.buf[8:29]) == b"writable-view-source!"
        finally:
            win.close()
        # close PARKS the bounce registration (no dereg); reopening the
        # same size class reuses it instead of registering a fresh MR
        assert lib.tpr_mock_mr_count() == before + 1
        assert dom.mr_cache.stats()["free_entries"] == 1
        win2 = dom.open_window(region.handle, 256)
        try:
            assert lib.tpr_mock_mr_count() == before + 1  # cache hit
            assert dom.mr_cache.stats()["hits"] >= 1
            win2.write(0, b"after-recycle")
            assert bytes(region.buf[0:13]) == b"after-recycle"
        finally:
            win2.close()
    finally:
        region.close()
        dom.close()
    assert lib.tpr_mock_mr_count() == 0  # domain close drains the cache


def test_window_rejects_foreign_and_oversized_handles(monkeypatch):
    _build_mock_lib()
    verbs = _fresh_domain_module(monkeypatch, MOCK_LIB)
    dom = verbs.VerbsDomain()
    with pytest.raises(ValueError):
        dom.open_window("shm:abcdef", 64)
    region = dom.alloc(1024)
    try:
        with pytest.raises(ValueError):
            dom.open_window(region.handle, 4096)  # window > region
        # the nbytes arg is ENFORCED per write, not open-time decoration:
        # a 64-byte window on a 1KB region must reject writes past 64
        win = dom.open_window(region.handle, 64)
        try:
            win.write(0, b"ok")
            with pytest.raises(IndexError):
                win.write(60, b"spills-past-the-window")
        finally:
            win.close()
    finally:
        region.close()
        dom.close()


def test_domain_close_with_live_region_is_safe(monkeypatch):
    """close() tears down leftover regions FIRST (a PD with live MRs can't
    dealloc on real hardware); the region's own later close() must then
    be a no-op, not a double free."""
    _build_mock_lib()
    verbs = _fresh_domain_module(monkeypatch, MOCK_LIB)
    dom = verbs.VerbsDomain()
    region = dom.alloc(512)
    dom.close()      # region still open: domain reaps it
    region.close()   # no-op now (registry pop already happened)
    dom.close()      # idempotent
