"""Interceptors (server + client chains) and the fault-injection filter."""

import random

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.interceptors import (ClientInterceptor, FaultConfig,
                                     FaultInjector, ServerInterceptor,
                                     intercept_channel)
from tpurpc.rpc.server import RpcMethodHandler


def _server(interceptors=()):
    srv = rpc.Server(max_workers=4, interceptors=interceptors)
    srv.add_method("/t.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda req, ctx: req))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, f"127.0.0.1:{port}"


class _Tagger(ServerInterceptor):
    """Wraps the handler to append a tag; records seen methods + metadata."""

    def __init__(self, tag: bytes):
        self.tag = tag
        self.seen = []

    def intercept_service(self, continuation, details):
        self.seen.append((details.method,
                          dict(details.invocation_metadata or [])))
        handler = continuation(details)
        if handler is None:
            return None
        inner = handler.behavior
        return RpcMethodHandler(handler.kind,
                                lambda req, ctx: inner(req, ctx) + self.tag,
                                handler.request_deserializer,
                                handler.response_serializer)


def test_server_interceptor_chain_order():
    a, b = _Tagger(b"-a"), _Tagger(b"-b")
    srv, target = _server([a, b])
    try:
        with rpc.Channel(target) as ch:
            out = ch.unary_unary("/t.S/Echo")(b"x", timeout=10,
                                              metadata=[("k", "v")])
        # first interceptor outermost → its tag applied last
        assert out == b"x-b-a"
        assert a.seen[0][0] == "/t.S/Echo"
        assert a.seen[0][1].get("k") == "v"
    finally:
        srv.stop(grace=0)


def test_client_interceptor_rewrites_details():
    srv, target = _server()

    class AddMd(ClientInterceptor):
        def intercept_call(self, continuation, details, request):
            details.metadata = list(details.metadata or []) + [("seen", "1")]
            return continuation(details, request)

    observed = {}

    class Probe(ServerInterceptor):
        def intercept_service(self, continuation, details):
            observed.update(dict(details.invocation_metadata or []))
            return continuation(details)

    srv.interceptors.append(Probe())
    try:
        with rpc.Channel(target) as raw:
            ch = intercept_channel(raw, AddMd())
            assert ch.unary_unary("/t.S/Echo")(b"q", timeout=10) == b"q"
        assert observed.get("seen") == "1"
    finally:
        srv.stop(grace=0)


def test_fault_injector_aborts_with_configured_code():
    fi = FaultInjector({"/t.S/Echo": FaultConfig(
        abort_code=rpc.StatusCode.RESOURCE_EXHAUSTED,
        abort_message="injected overload", abort_fraction=1.0)},
        rng=random.Random(7))
    srv, target = _server([fi])
    try:
        with rpc.Channel(target) as ch:
            with pytest.raises(rpc.RpcError) as ei:
                ch.unary_unary("/t.S/Echo")(b"x", timeout=10)
        assert ei.value.code() is rpc.StatusCode.RESOURCE_EXHAUSTED
        assert "injected overload" in ei.value.details()
    finally:
        srv.stop(grace=0)


def test_fault_injector_fractional():
    fi = FaultInjector({"*": FaultConfig(
        abort_code=rpc.StatusCode.UNAVAILABLE, abort_fraction=0.5)},
        rng=random.Random(3))
    srv, target = _server([fi])
    try:
        ok = fail = 0
        with rpc.Channel(target) as ch:
            mc = ch.unary_unary("/t.S/Echo")
            for _ in range(30):
                try:
                    mc(b"x", timeout=10)
                    ok += 1
                except rpc.RpcError:
                    fail += 1
        assert ok > 3 and fail > 3  # both outcomes occur
    finally:
        srv.stop(grace=0)


def test_fault_injector_on_h2_path():
    """Stock grpcio client also sees injected faults (shared interceptors)."""
    import grpc

    fi = FaultInjector({"/t.S/Echo": FaultConfig(
        abort_code=rpc.StatusCode.FAILED_PRECONDITION,
        abort_message="h2 injected", abort_fraction=1.0)})
    srv, target = _server([fi])
    try:
        with grpc.insecure_channel(target) as ch:
            mc = ch.unary_unary("/t.S/Echo", lambda x: x, lambda x: x)
            with pytest.raises(grpc.RpcError) as ei:
                mc(b"x", timeout=10)
            assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    finally:
        srv.stop(grace=0)
