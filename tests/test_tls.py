"""TLS: credentials surface + encrypted transport on every platform.

The reference's security stack exists so creds work UNCHANGED over the
swapped byte pipe (SURVEY §2.4, ``lib/security`` + ``tsi``; ``h2_ssl.cc``
fixture). Proven here four ways: tpurpc↔tpurpc over TLS on the TCP *and*
ring platforms (ring bootstrap + notify ride the TLS socket), a STOCK
grpcio TLS client against a tpurpc secure port, and our H2Channel against
a stock grpcio TLS server.
"""

import datetime

import grpc
import pytest

import tpurpc.rpc as tps


@pytest.fixture(scope="module")
def certs():
    """Self-signed CA + server cert for localhost (cryptography lib)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    def make_key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    now = datetime.datetime.now(datetime.timezone.utc)

    ca_key = make_key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "tpurpc-test-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=1))
               .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    def issue(cn):
        key = make_key()
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
                .issuer_name(ca_name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(days=1))
                .add_extension(x509.SubjectAlternativeName(
                    [x509.DNSName("localhost"),
                     x509.IPAddress(__import__("ipaddress")
                                    .ip_address("127.0.0.1"))]),
                    critical=False)
                .sign(ca_key, hashes.SHA256()))
        key_pem = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption())
        return key_pem, cert.public_bytes(serialization.Encoding.PEM)

    ca_pem = ca_cert.public_bytes(serialization.Encoding.PEM)
    srv_key, srv_cert = issue("localhost")
    cli_key, cli_cert = issue("tpurpc-test-client")
    return {"ca": ca_pem, "srv_key": srv_key, "srv_cert": srv_cert,
            "cli_key": cli_key, "cli_cert": cli_cert}


def _tls_server(certs, require_client_auth=False):
    srv = tps.Server(max_workers=4)
    srv.add_method("/t.S/Echo",
                   tps.unary_unary_rpc_method_handler(lambda req, ctx: req))
    creds = tps.ssl_server_credentials(
        [(certs["srv_key"], certs["srv_cert"])],
        root_certificates=certs["ca"] if require_client_auth else None,
        require_client_auth=require_client_auth)
    port = srv.add_secure_port("127.0.0.1:0", creds)
    srv.start()
    return srv, port


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_tls_e2e_native(monkeypatch, platform, certs):
    """tpurpc↔tpurpc over TLS; ring platforms bootstrap over the TLS socket
    and keep it as the encrypted notify channel."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _tls_server(certs)
    try:
        creds = tps.ssl_channel_credentials(root_certificates=certs["ca"])
        with tps.secure_channel(f"localhost:{port}", creds) as ch:
            mc = ch.unary_unary("/t.S/Echo")
            assert bytes(mc(b"secure", timeout=20)) == b"secure"
            big = bytes(256) * 4096  # 1 MiB through the encrypted pipe
            assert bytes(mc(big, timeout=30)) == big
    finally:
        srv.stop(grace=0)


def test_tls_rejects_untrusted_server(monkeypatch, certs):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    srv, port = _tls_server(certs)
    try:
        # a trust anchor that did NOT sign the server cert
        creds = tps.ssl_channel_credentials(
            root_certificates=certs["cli_cert"])
        with pytest.raises(Exception):
            with tps.secure_channel(f"localhost:{port}", creds) as ch:
                ch.unary_unary("/t.S/Echo")(b"x", timeout=5)
    finally:
        srv.stop(grace=0)


def test_tls_plaintext_client_rejected(monkeypatch, certs):
    """A plaintext client hitting a secure port dies at handshake, cleanly."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    srv, port = _tls_server(certs)
    try:
        with pytest.raises(tps.RpcError):
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                ch.unary_unary("/t.S/Echo")(b"x", timeout=5)
    finally:
        srv.stop(grace=0)


def test_mtls_requires_client_cert(monkeypatch, certs):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    srv, port = _tls_server(certs, require_client_auth=True)
    try:
        # without a client cert: rejected
        bare = tps.ssl_channel_credentials(root_certificates=certs["ca"])
        with pytest.raises(Exception):
            with tps.secure_channel(f"localhost:{port}", bare) as ch:
                ch.unary_unary("/t.S/Echo")(b"x", timeout=5)
        # with one: accepted
        mutual = tps.ssl_channel_credentials(
            root_certificates=certs["ca"],
            private_key=certs["cli_key"],
            certificate_chain=certs["cli_cert"])
        with tps.secure_channel(f"localhost:{port}", mutual) as ch:
            assert bytes(ch.unary_unary("/t.S/Echo")(b"m", timeout=20)) == b"m"
    finally:
        srv.stop(grace=0)


def test_stock_grpcio_tls_client_against_tpurpc(monkeypatch, certs):
    """grpc.secure_channel (C-core TLS + ALPN h2) → tpurpc secure port."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    srv, port = _tls_server(certs)
    try:
        creds = grpc.ssl_channel_credentials(root_certificates=certs["ca"])
        with grpc.secure_channel(f"localhost:{port}", creds) as ch:
            mc = ch.unary_unary("/t.S/Echo", lambda x: x, lambda x: x)
            assert mc(b"grpcio-tls", timeout=20) == b"grpcio-tls"
    finally:
        srv.stop(grace=0)


def test_h2channel_tls_against_stock_grpcio(certs):
    """Our h2 client over TLS → stock grpcio TLS server."""
    from concurrent import futures

    gsrv = grpc.server(futures.ThreadPoolExecutor(max_workers=4))

    class H(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method.endswith("Echo"):
                return grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx: req,
                    request_deserializer=lambda x: x,
                    response_serializer=lambda x: x)
            return None

    gsrv.add_generic_rpc_handlers((H(),))
    gcreds = grpc.ssl_server_credentials(
        [(certs["srv_key"], certs["srv_cert"])])
    port = gsrv.add_secure_port("127.0.0.1:0", gcreds)
    gsrv.start()
    try:
        creds = tps.ssl_channel_credentials(root_certificates=certs["ca"])
        with tps.H2Channel(f"localhost:{port}", credentials=creds) as ch:
            mc = ch.unary_unary("/t.S/Echo")
            assert mc(b"h2-tls", timeout=20) == b"h2-tls"
    finally:
        gsrv.stop(grace=0)


@pytest.mark.parametrize("platform", ["RDMA_BPEV", "RDMA_TPU"])
def test_ring_platform_port_serves_stock_grpcio_tls(monkeypatch, platform,
                                                    certs):
    """Ring-platform listeners dispatch MIXED clients: a stock grpcio TLS
    client (h2 preface) lands on the TCP path while ring peers bootstrap —
    beyond the reference, whose RDMA ports cannot speak to vanilla gRPC."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _tls_server(certs)
    try:
        # ring-native client over TLS
        creds = tps.ssl_channel_credentials(root_certificates=certs["ca"])
        with tps.secure_channel(f"localhost:{port}", creds) as ch:
            assert bytes(ch.unary_unary("/t.S/Echo")(b"ring", timeout=30)) == b"ring"
        # stock grpcio TLS client on the SAME port
        gc = grpc.ssl_channel_credentials(root_certificates=certs["ca"])
        with grpc.secure_channel(f"localhost:{port}", gc) as gch:
            mc = gch.unary_unary("/t.S/Echo", lambda x: x, lambda x: x)
            assert mc(b"h2-on-ring-port", timeout=20) == b"h2-on-ring-port"
    finally:
        srv.stop(grace=0)


def test_tls_e2e_over_tcp_window_domain(monkeypatch, certs):
    """TLS + the cross-host ring domain: bootstrap/notify ride the
    encrypted socket; the one-sided record stream is a separate plaintext
    connection (documented boundary, core/tcpw.py docstring — the
    reference's RDMA payloads bypass TLS on the NIC the same way)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    monkeypatch.setenv("TPURPC_RING_DOMAIN", "tcp_window")
    monkeypatch.setenv("TPURPC_RING_BUFFER_SIZE_KB", "256")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _tls_server(certs)
    try:
        creds = tps.ssl_channel_credentials(root_certificates=certs["ca"])
        with tps.secure_channel(f"localhost:{port}", creds) as ch:
            mc = ch.unary_unary("/t.S/Echo")
            assert bytes(mc(b"secure-tcpw", timeout=20)) == b"secure-tcpw"
            big = bytes(range(256)) * 4096  # 1 MiB: wraps + credits
            assert bytes(mc(big, timeout=60)) == big
    finally:
        srv.stop(grace=0)


def test_auth_context_exposes_mtls_identity(monkeypatch, certs):
    """grpcio's ServerContext.auth_context/peer_identities: an mTLS
    handler sees the client certificate's names; plaintext sees {}."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    seen = {}
    srv = tps.Server(max_workers=2)

    def who(req, ctx):
        seen["ac"] = ctx.auth_context()
        seen["ids"] = ctx.peer_identities()
        seen["key"] = ctx.peer_identity_key()
        return b"ok"

    srv.add_method("/t.S/Who", tps.unary_unary_rpc_method_handler(who))
    creds = tps.ssl_server_credentials(
        [(certs["srv_key"], certs["srv_cert"])],
        root_certificates=certs["ca"], require_client_auth=True)
    port = srv.add_secure_port("127.0.0.1:0", creds)
    srv.start()
    try:
        mutual = tps.ssl_channel_credentials(
            root_certificates=certs["ca"],
            private_key=certs["cli_key"],
            certificate_chain=certs["cli_cert"])
        with tps.secure_channel(f"localhost:{port}", mutual) as ch:
            assert ch.unary_unary("/t.S/Who")(b"", timeout=20) == b"ok"
        assert seen["ac"]["transport_security_type"] == [b"ssl"]
        # identity = SANs when present (gRPC's rule; this client cert's CN
        # carries the distinctive name, its SANs the generic host names)
        assert seen["key"] == "x509_subject_alternative_name"
        assert seen["ids"] == seen["ac"]["x509_subject_alternative_name"]
        assert seen["ac"]["x509_common_name"] == [b"tpurpc-test-client"]
    finally:
        srv.stop(grace=0)

    # plaintext: empty auth context, no identities
    srv2 = tps.Server(max_workers=2)
    srv2.add_method("/t.S/Who", tps.unary_unary_rpc_method_handler(who))
    p2 = srv2.add_insecure_port("127.0.0.1:0")
    srv2.start()
    try:
        with tps.Channel(f"127.0.0.1:{p2}") as ch:
            assert ch.unary_unary("/t.S/Who")(b"", timeout=20) == b"ok"
        assert seen["ac"] == {}
        assert seen["ids"] is None and seen["key"] is None
    finally:
        srv2.stop(grace=0)

    # ring platform: the TLS socket lives on as the pair's notify channel
    # — the identity must still surface through the Endpoint seam
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    config_mod.set_config(None)
    try:
        srv3 = tps.Server(max_workers=2)
        srv3.add_method("/t.S/Who", tps.unary_unary_rpc_method_handler(who))
        creds3 = tps.ssl_server_credentials(
            [(certs["srv_key"], certs["srv_cert"])],
            root_certificates=certs["ca"], require_client_auth=True)
        p3 = srv3.add_secure_port("127.0.0.1:0", creds3)
        srv3.start()
        try:
            mutual = tps.ssl_channel_credentials(
                root_certificates=certs["ca"],
                private_key=certs["cli_key"],
                certificate_chain=certs["cli_cert"])
            with tps.secure_channel(f"localhost:{p3}", mutual) as ch:
                assert ch.unary_unary("/t.S/Who")(b"", timeout=30) == b"ok"
            assert seen["ac"]["x509_common_name"] == [b"tpurpc-test-client"]
            assert seen["ids"]  # SANs surfaced over the ring transport too
        finally:
            srv3.stop(grace=0)
    finally:
        monkeypatch.setenv("GRPC_PLATFORM_TYPE", "TCP")
        config_mod.set_config(None)
