"""Pallas device kernels (tpurpc/ops): the fused ring-window gather.

Validated in interpret mode (CPU test mesh) against a numpy oracle across
every wrap phase, plus the HbmRing integration (wrapped view() spans take
the kernel path on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpurpc.ops.ring_window import ring_window, ring_window_reference


@pytest.mark.parametrize("head,n", [
    (0, 64), (4, 60), (100, 4096), (16128, 1024),   # no wrap
    (15872, 4096), (16380, 8), (8192, 16384), (16380, 16384),  # wrap
])
def test_ring_window_matches_oracle(head, n):
    rng = np.random.default_rng(7)
    cap = 1 << 14
    host = rng.integers(0, 256, cap).astype(np.uint8)
    out = np.asarray(ring_window(jnp.asarray(host), head, n, interpret=True))
    np.testing.assert_array_equal(out, ring_window_reference(host, head, n))


def test_ring_window_rejects_misalignment():
    buf = jnp.zeros(1 << 10, jnp.uint8)
    with pytest.raises(ValueError):
        ring_window(buf, 3, 8, interpret=True)
    with pytest.raises(ValueError):
        ring_window(buf, 0, 6, interpret=True)
    with pytest.raises(ValueError):
        ring_window(buf, 0, 1 << 11, interpret=True)


def test_hbm_ring_wrapped_view_takes_kernel_path(monkeypatch):
    """A span crossing the ring's wrap point must read back exactly AND the
    pallas kernel must actually be the path taken (the silent fallback
    would otherwise let a broken kernel pass unnoticed)."""
    import tpurpc.ops as ops_pkg
    from tpurpc.ops.ring_window import ring_window as real_ring_window
    from tpurpc.tpu.hbm_ring import HbmRing

    calls = {"n": 0}

    def counting_ring_window(*a, **kw):
        calls["n"] += 1
        return real_ring_window(*a, **kw)

    monkeypatch.setattr(ops_pkg, "ring_window", counting_ring_window)

    ring = HbmRing(capacity=1 << 13, device=jax.devices("cpu")[0])
    rng = np.random.default_rng(3)
    wrapped = 0
    # 2800 % 4 == 0: spans stay 4-aligned so the kernel path is eligible
    for i in range(5):
        payload = rng.integers(0, 256, 2800).astype(np.uint8)
        off, n = ring.place(payload.tobytes())
        if (off & (ring.capacity - 1)) + n > ring.capacity:
            wrapped += 1
        lease = ring.view(off, n)
        np.testing.assert_array_equal(np.asarray(lease.array), payload)
        lease.release()
    assert wrapped >= 1, "test never crossed the wrap point"
    assert calls["n"] == wrapped   # every wrapped view used the kernel
