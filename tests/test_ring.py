"""Ring framing/flow-control tests.

The invariants tested here are the ones the reference enforces with asserts in
``src/core/lib/ibverbs/ring_buffer.cc`` (footer checks :144-145,179; power-of-two :22;
``check_empty`` ``ring_buffer.h:215-219``) plus stream-integrity fuzzing the reference
never had (SURVEY.md §4 notes it ships no RDMA unit tests — we do better).

tpurpc's framing diverges from the reference on completion detection: messages
are sequence-stamped (header ``[u32 len|u32 seq32]``, footer ``seq64^SALT``)
instead of relying on a zeroed consumed region, eliminating the reference's
memset of every consumed byte (``ring_buffer.cc:122-191``). The staleness
tests below pin down that replacement invariant.
"""

import random

import pytest

from tpurpc.core import ring as R


def make_pipe(capacity=1024):
    """A writer wired straight into a reader's ring memory (one-sided-write emulation)."""
    buf = bytearray(capacity)
    reader = R.RingReader(buf)
    writer = R.RingWriter(capacity, lambda off, data: buf.__setitem__(
        slice(off, off + len(data)), bytes(data)))
    return reader, writer


def pump_credits(reader, writer, force=False):
    """Emulate the credit write-back (pair.cc:276-284 half-ring rule; force=True models
    the receiver's final publish on drain)."""
    if force or reader.should_publish_head():
        writer.update_remote_head(reader.take_publish())


def test_layout_rejects_non_pow2():
    with pytest.raises(ValueError):
        R.RingLayout(1000)
    with pytest.raises(ValueError):
        R.RingLayout(32)  # < 64


def test_message_span_alignment():
    assert R.message_span(1) == 8 + 8 + 8
    assert R.message_span(8) == 8 + 8 + 8
    assert R.message_span(9) == 8 + 16 + 8
    assert R.align_up(0) == 0 and R.align_up(1) == 8 and R.align_up(8) == 8


def test_segments_split_at_wrap():
    lay = R.RingLayout(256)
    assert lay.segments(0, 100) == [(0, 100)]
    assert lay.segments(200, 100) == [(200, 56), (0, 44)]
    assert lay.segments(256, 10) == [(0, 10)]  # exact wrap
    assert lay.segments(250, 6) == [(250, 6)]  # ends exactly at boundary
    assert lay.segments(5, 0) == []


def test_single_message_roundtrip():
    reader, writer = make_pipe()
    msg = b"hello tpu world"
    writer.write(msg)
    assert reader.has_message()
    assert reader.readable() == len(msg)
    assert reader.read(1024) == msg
    assert not reader.has_message()
    assert reader.readable() == 0


def test_incomplete_message_not_visible():
    # Simulate in-flight one-sided write: payload+footer landed but header not yet.
    reader, writer = make_pipe()
    buf = reader.buf
    payload = b"x" * 16
    # footer at 8+16 (stamped for seq 0), header withheld
    buf[8:24] = payload
    buf[24:32] = R.footer_stamp(0).to_bytes(8, "little")
    assert not reader.has_message()
    assert reader.read(100) == b""
    # header arrives last → message becomes visible atomically
    buf[0:8] = R.header_stamp(16, 0).to_bytes(8, "little")
    assert reader.has_message()
    assert reader.read(100) == payload


def test_stale_bytes_never_look_like_messages():
    """The seq-framing replacement for the reference's zero-on-consume
    invariant: after a message is consumed its bytes REMAIN in the ring, and
    the reader must not re-parse them as a new message (the old protocol
    guaranteed this by memsetting the span; ours by the sequence stamp)."""
    reader, writer = make_pipe(256)
    writer.write(b"a" * 100)
    assert reader.read(100) == b"a" * 100
    # consumed span is NOT zeroed (that's the point — no extra memory pass)...
    assert bytes(reader.buf) != b"\x00" * 256
    # ...but nothing at head parses as a message
    assert not reader.has_message()
    assert reader.readable() == 0
    assert reader.read(100) == b""
    # and a genuine next message is still recognized
    writer.write(b"b" * 10)
    assert reader.read(100) == b"b" * 10


def test_forged_stale_header_rejected_across_wrap():
    """A payload that embeds a byte pattern identical to a valid OLD header/
    footer must not fool the reader after the ring wraps over it."""
    reader, writer = make_pipe(256)
    # Message whose payload IS a forged copy of a seq-0 header+footer pair.
    forged = (R.header_stamp(8, 2).to_bytes(8, "little") + b"E" * 8 +
              R.footer_stamp(2).to_bytes(8, "little"))
    writer.write(forged)
    assert reader.read(100) == forged
    # Ring now holds stale bytes that literally spell a stamped message for
    # seq 2; the reader expects seq 1 next, at a different offset — nothing
    # should surface without a genuine write.
    assert not reader.has_message()
    assert reader.read(100) == b""
    writer.write(b"ok")
    assert reader.read(100) == b"ok"


def test_partial_read_resumption():
    reader, writer = make_pipe()
    msg = bytes(range(256))
    writer.write(msg)
    out = b""
    # Drain in ragged chunks (reference remain_/moving_head_ path).
    for chunk in (1, 7, 64, 100, 1000):
        out += reader.read(chunk)
    assert out == msg


def test_multiple_messages_and_readable():
    reader, writer = make_pipe(4096)
    msgs = [b"a" * 10, b"b" * 100, b"c" * 1000]
    for m in msgs:
        writer.write(m)
    assert reader.readable() == 1110
    assert reader.read(5000) == b"".join(msgs)


def test_writev_gather_is_one_message():
    reader, writer = make_pipe()
    writer.writev([b"head", b"", b"body", bytearray(b"tail")])
    assert reader.readable() == 12
    assert reader.read(100) == b"headbodytail"


def test_ring_full_and_credit_resume():
    reader, writer = make_pipe(256)
    cap = writer.writable_payload()
    assert cap == 256 - R.RESERVED_BYTES
    writer.write(b"x" * cap)  # fill it completely
    assert writer.writable_payload() == 0
    with pytest.raises(R.RingFull):
        writer.write(b"y")
    # Reader drains; consuming the whole ring crosses the half-ring credit rule.
    assert reader.read(cap) == b"x" * cap
    assert reader.should_publish_head()
    pump_credits(reader, writer)
    assert writer.writable_payload() == cap
    writer.write(b"y" * 10)
    assert reader.read(10) == b"y" * 10


def test_credit_not_published_below_half_ring():
    reader, writer = make_pipe(1024)
    writer.write(b"x" * 100)
    reader.read(100)
    assert not reader.should_publish_head()  # 100+16 < 512


def test_implausible_header_treated_as_stale():
    """A seq-matching header with an impossible length is a stale lookalike
    (possible after the 32-bit stamp laps), not a parsed message and not a
    connection-killing corruption."""
    reader, writer = make_pipe(256)
    reader.buf[0:8] = R.header_stamp(10**6, 0).to_bytes(8, "little")
    assert not reader.has_message()
    assert reader.read(100) == b""
    # the genuine message overwrites the lookalike and parses normally
    writer.write(b"real")
    assert reader.read(100) == b"real"


def test_credit_regression_detected():
    _, writer = make_pipe(256)
    writer.write(b"x" * 50)
    writer.update_remote_head(writer.tail)
    with pytest.raises(R.RingCorruption):
        writer.update_remote_head(10)  # going backwards
    with pytest.raises(R.RingCorruption):
        writer.update_remote_head(writer.tail + 8)  # beyond tail


def test_wrap_heavy_stream_fuzz():
    """The main property test: arbitrary message sizes + ragged reads over a small ring
    with credit-gated writes must reproduce the exact byte stream."""
    rng = random.Random(0xC0FFEE)
    reader, writer = make_pipe(512)
    sent = bytearray()
    received = bytearray()
    pending = bytearray()  # bytes queued but not yet accepted by the ring
    for step in range(5000):
        if rng.random() < 0.5:
            pending += bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 200)))
        # try to flush pending honoring flow control (pair-layer chunking emulation)
        while pending:
            chunk = min(len(pending), writer.writable_payload())
            if chunk == 0:
                break
            writer.write(pending[:chunk])
            sent += pending[:chunk]
            del pending[:chunk]
        if rng.random() < 0.7:
            received += reader.read(rng.randint(1, 300))
        pump_credits(reader, writer)
    # drain everything left
    while pending:
        pump_credits(reader, writer, force=True)
        chunk = min(len(pending), writer.writable_payload())
        if chunk:
            writer.write(pending[:chunk])
            sent += pending[:chunk]
            del pending[:chunk]
        received += reader.read(1 << 20)
    received += reader.read(1 << 20)
    assert bytes(received) == bytes(sent)
    assert reader.readable() == 0
    # stale bytes remain (no zeroing pass) yet nothing parses as a message
    assert reader.check_empty_region()


def test_max_payload_message_exact_fit():
    reader, writer = make_pipe(128)
    maxp = R.RingLayout(128).max_payload()
    writer.write(b"z" * maxp)
    assert reader.read(1 << 10) == b"z" * maxp


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_various_capacities(seed):
    rng = random.Random(seed)
    cap = rng.choice([64, 128, 2048, 8192])
    reader, writer = make_pipe(cap)
    sent = bytearray()
    received = bytearray()
    for _ in range(800):
        w = writer.writable_payload()
        if w and rng.random() < 0.6:
            n = rng.randint(1, w)
            data = bytes(rng.getrandbits(8) for _ in range(n))
            writer.write(data)
            sent += data
        received += reader.read(rng.randint(1, cap))
        pump_credits(reader, writer)
    received += reader.read(1 << 20)
    assert bytes(received) == bytes(sent)
