"""Parallelism primitives: ring attention, MoE all_to_all, pipeline, mesh.

Gold standard: every sharded program must match its dense single-device
equivalent on the same inputs (capacity chosen so MoE drops no tokens —
then routing is a pure permutation and exact agreement is required).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpurpc.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from tpurpc.parallel import mesh as meshlib
from tpurpc.parallel.moe import MoEParams, init_moe, moe_block
from tpurpc.parallel.pipeline import microbatch, pipeline_apply, unmicrobatch
from tpurpc.parallel.ring_attention import ring_attention


# -- mesh --------------------------------------------------------------------

def test_factor_mesh_products():
    for n in (1, 2, 4, 6, 8):
        sizes = meshlib.factor_mesh(n)
        assert np.prod(list(sizes.values())) == n


def test_build_mesh_axes():
    m = meshlib.build_mesh(8)
    assert m.axis_names == meshlib.AXES
    assert m.devices.size == 8


def test_build_mesh_explicit_sizes():
    m = meshlib.build_mesh(8, sizes={"dp": 2, "sp": 2, "tp": 2})
    assert meshlib.axis_size(m, "dp") == 2
    assert meshlib.axis_size(m, "pp") == 1


# -- ring attention ----------------------------------------------------------

def _dense_attention(q, k, v, causal):
    scores = jnp.einsum("bhqd,bhkd->bhqk",
                        q.astype(jnp.float32) * q.shape[-1] ** -0.5,
                        k.astype(jnp.float32))
    if causal:
        S = q.shape[2]
        mask = np.triu(np.ones((S, S), bool), 1)
        scores = jnp.where(mask, -jnp.inf, scores)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_matches_dense(causal, sp):
    m = meshlib.build_mesh(sp, sizes={"sp": sp})
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 3, 32, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
               for _ in range(3))
    out = ring_attention(q, k, v, m, causal=causal)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_flow():
    m = meshlib.build_mesh(4, sizes={"sp": 4})
    rng = np.random.default_rng(1)
    B, H, S, D = 1, 2, 16, 4
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
               for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, m, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


# -- MoE ---------------------------------------------------------------------

def _dense_moe(params: MoEParams, x, cap):
    """Reference switch MoE, no sharding, same capacity semantics."""
    logits = x.astype(jnp.float32) @ params.router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, -1)
    gate = jnp.max(probs, -1)
    E = params.router.shape[1]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    pos = jnp.cumsum(onehot, axis=0) - 1.0
    keep = (pos < cap).astype(jnp.float32) * onehot
    y = jnp.zeros_like(x, shape=x.shape).astype(jnp.float32)
    for e in range(E):
        h = jax.nn.gelu(x.astype(jnp.float32) @ params.w_in[e].astype(jnp.float32))
        o = h @ params.w_out[e].astype(jnp.float32)
        y = y + o * (keep[:, e] * gate)[:, None]
    return y


@pytest.mark.parametrize("ep", [2, 4])
def test_moe_matches_dense_when_no_drops(ep):
    m = meshlib.build_mesh(ep, sizes={"ep": ep})
    rng = np.random.default_rng(2)
    T, d, f, E = 16, 8, 16, ep  # one expert per shard
    params = init_moe(jax.random.PRNGKey(0), d, f, E)
    x_all = jnp.asarray(rng.standard_normal((ep * T, d)), jnp.float32)

    # generous capacity: cap = 4*T/E >= T → no token ever dropped
    out = shard_map(
        lambda p, xx: moe_block(
            MoEParams(p.router, p.w_in, p.w_out), xx,
            capacity_factor=float(E))[0],
        mesh=m,
        in_specs=(MoEParams(P(None, None), P("ep", None, None),
                            P("ep", None, None)), P("ep", None)),
        out_specs=P("ep", None), check_rep=False)(params, x_all)

    cap = ep * T  # dense sees all tokens at once; no-drop needs global cap
    ref = _dense_moe(params, x_all, cap)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_respects_capacity():
    """All tokens to one expert + tiny capacity → overflow dropped (residual
    passthrough is the block caller's job; here dropped rows are zero)."""
    m = meshlib.build_mesh(2, sizes={"ep": 2})
    d, f = 4, 8
    params = init_moe(jax.random.PRNGKey(1), d, f, 2)
    # router biased hard to expert 0
    params = params._replace(
        router=jnp.asarray(np.array([[9.0, -9.0]] * d, np.float32)))
    x = jnp.ones((8, d), jnp.float32)
    out = shard_map(
        lambda p, xx: moe_block(
            MoEParams(p.router, p.w_in, p.w_out), xx,
            capacity_factor=0.5)[0],
        mesh=m,
        in_specs=(MoEParams(P(None, None), P("ep", None, None),
                            P("ep", None, None)), P("ep", None)),
        out_specs=P("ep", None), check_rep=False)(params, x)
    out = np.asarray(out)
    # cap = 0.5 * 4 / 2 = 1 token per expert per shard → 1 nonzero row per
    # shard of 4 rows
    nonzero_rows = (np.abs(out).sum(-1) > 1e-9).sum()
    assert nonzero_rows == 2


# -- pipeline ----------------------------------------------------------------

@pytest.mark.parametrize("pp,n_micro", [(2, 2), (2, 4), (4, 4)])
def test_pipeline_matches_sequential(pp, n_micro):
    m = meshlib.build_mesh(pp, sizes={"pp": pp})
    rng = np.random.default_rng(3)
    L, B, d = pp * 2, 8, 6  # 2 layers per stage
    Ws = jnp.asarray(rng.standard_normal((L, d, d)) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.standard_normal((L, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((B, d)), jnp.float32)

    def stage_fn(sp_params, h):
        W, b = sp_params
        def layer(carry, wb):
            w, bb = wb
            return jnp.tanh(carry @ w + bb), None
        out, _ = jax.lax.scan(layer, h, (W, b))
        return out

    out = shard_map(
        lambda W, b, xm: pipeline_apply(stage_fn, (W, b), xm),
        mesh=m,
        in_specs=(P("pp", None, None), P("pp", None), P(None, None, None)),
        out_specs=P(None, None, None), check_rep=False,
    )(Ws, bs, microbatch(x, n_micro))
    got = unmicrobatch(out)

    ref = x
    for l in range(L):
        ref = jnp.tanh(ref @ Ws[l] + bs[l])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential():
    pp = 2
    m = meshlib.build_mesh(pp, sizes={"pp": pp})
    rng = np.random.default_rng(4)
    L, B, d = 2, 4, 4
    Ws = jnp.asarray(rng.standard_normal((L, d, d)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((B, d)), jnp.float32)

    def stage_fn(W, h):
        def layer(carry, w):
            return jnp.tanh(carry @ w), None
        out, _ = jax.lax.scan(layer, h, W)
        return out

    piped = shard_map(
        lambda W, xm: pipeline_apply(stage_fn, W, xm),
        mesh=m, in_specs=(P("pp", None, None), P(None, None, None)),
        out_specs=P(None, None, None), check_rep=False)

    def loss_p(W):
        return jnp.sum(piped(W, microbatch(x, 2)) ** 2)

    def loss_s(W):
        h = x
        for l in range(L):
            h = jnp.tanh(h @ W[l])
        return jnp.sum(h ** 2)

    gp = jax.grad(loss_p)(Ws)
    gs = jax.grad(loss_s)(Ws)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                               rtol=1e-4, atol=1e-4)


def test_distributed_single_process_bringup():
    """initialize_cluster is a no-op single-process; global_mesh factors
    the full (virtual 8-device) cluster with dp outermost so DCN-crossing
    axes are the tolerant ones. The same entry points a multi-host launch
    uses (TPURPC_COORDINATOR et al.) — exercised in the 1-process limit."""
    import numpy as np

    import jax

    from tpurpc.parallel.distributed import (global_mesh, initialize_cluster,
                                             process_count)

    assert initialize_cluster() == 0
    assert initialize_cluster() == 0          # idempotent
    assert process_count() == 1
    mesh, sizes = global_mesh()
    assert int(np.prod(list(sizes.values()))) == len(jax.devices())
    assert tuple(mesh.axis_names) == ("dp", "pp", "sp", "tp", "ep")
    # the mesh is usable: a psum over it compiles and runs
    from jax.sharding import PartitionSpec as P

    from tpurpc.parallel.mesh import shard_map

    def allsum(x):
        import jax.numpy as jnp
        s = x
        for ax in ("dp", "pp", "sp", "tp", "ep"):
            s = jax.lax.psum(s, ax)
        return s

    f = shard_map(allsum, mesh=mesh, in_specs=(P(("dp", "ep")),),
                  out_specs=P(("dp", "ep")))
    x = np.ones((8, 4), np.float32)
    out = np.asarray(jax.jit(f)(x))
    assert np.allclose(out, len(jax.devices()) * np.ones_like(out) / 1)
