"""Look-aside LB (grpclb capability) + Channel.update_addresses.

Ref ``lb_policy/grpclb/grpclb.cc``: balancer streams server lists, the
channel redirects live, falls back to resolver addresses when the
balancer dies."""

import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.lookaside import (LoadBalancerServicer, enable_lookaside)
from tpurpc.rpc.status import RpcError


def _named_server(name: str):
    srv = rpc.Server(max_workers=4)
    srv.add_method("/l.S/Who",
                   rpc.unary_unary_rpc_method_handler(
                       lambda r, c, n=name: n.encode()))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _await(fn, timeout=30, every=0.05):
    """Poll until fn() is truthy; a call racing a membership swap may land
    on a just-closed backend once (documented transient) — treat RpcError
    as not-ready, like any retrying client would."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except RpcError:
            pass
        time.sleep(every)
    return False


# -- update_addresses (the mechanism) ----------------------------------------

def test_update_addresses_moves_traffic_and_keeps_live_subchannels():
    s1, p1 = _named_server("one")
    s2, p2 = _named_server("two")
    try:
        with rpc.Channel(f"127.0.0.1:{p1}") as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"one"
            conn_before = ch._subchannels[0]._conn
            # keep p1, add p2, round-robin over both
            ch._lb_spec = "round_robin"
            ch.update_addresses([("127.0.0.1", p1), ("127.0.0.1", p2)])
            assert ch._subchannels[0]._conn is conn_before  # reused, live
            got = {bytes(who(b"", timeout=10)) for _ in range(6)}
            assert got == {b"one", b"two"}
            # drop p1 entirely
            ch.update_addresses([f"127.0.0.1:{p2}"])
            for _ in range(4):
                assert who(b"", timeout=10) == b"two"
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_update_addresses_guards():
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    a, _b = passthru_endpoint_pair()
    ch = Channel(endpoint_factory=lambda: a)
    with pytest.raises(RuntimeError):
        ch.update_addresses(["127.0.0.1:1"])
    ch.close()
    s1, p1 = _named_server("x")
    try:
        ch = rpc.Channel(f"127.0.0.1:{p1}")
        with pytest.raises(ValueError):
            ch.update_addresses([])
        ch.close()
        with pytest.raises(RpcError):
            ch.update_addresses([f"127.0.0.1:{p1}"])  # closed channel
    finally:
        s1.stop(grace=0)


# -- the balancer protocol ----------------------------------------------------

def test_lookaside_balancer_directs_and_rebalances():
    s1, p1 = _named_server("backend1")
    s2, p2 = _named_server("backend2")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("demo", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p2}") as ch:  # fallback = backend2
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "demo")
            who = ch.unary_unary("/l.S/Who")
            # balancer list (backend1) takes over
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend1")
            # rebalance to backend2
            balancer.set_servers("demo", [f"127.0.0.1:{p2}"])
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend2")
            watcher.stop()
    finally:
        bal_srv.stop(grace=0)
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_lookaside_falls_back_when_balancer_dies():
    s1, p1 = _named_server("lbpick")
    s2, p2 = _named_server("fallback")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("d", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p2}") as ch:
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "d")
            who = ch.unary_unary("/l.S/Who")
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"lbpick")
            bal_srv.stop(grace=0)  # balancer gone
            # grpclb fallback rule: revert to the resolver-provided list
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"fallback")
            watcher.stop()
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_lookaside_rejects_factory_channel():
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    a, _b = passthru_endpoint_pair()
    ch = Channel(endpoint_factory=lambda: a)
    with pytest.raises(ValueError):
        enable_lookaside(ch, "127.0.0.1:1", "x")
    ch.close()


def test_update_addresses_hostname_normalizes_to_resolved():
    """'localhost:p' must match the constructor's resolved keys — a no-op
    update keeps the live connection instead of redialing."""
    s1, p1 = _named_server("same")
    try:
        with rpc.Channel(f"localhost:{p1}") as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"same"
            live = [sc._conn for sc in ch._subchannels if sc._conn is not None]
            assert live
            ch.update_addresses([f"localhost:{p1}"])
            kept = [sc._conn for sc in ch._subchannels if sc._conn is not None]
            assert any(c in live for c in kept)  # the connection survived
            assert who(b"", timeout=10) == b"same"
    finally:
        s1.stop(grace=0)


def test_update_addresses_with_composite_spec_degrades_to_round_robin():
    s1, p1 = _named_server("a")
    s2, p2 = _named_server("b")
    try:
        spec = {"priority": [{"policy": "pick_first", "indices": [0]}]}
        with rpc.Channel(f"127.0.0.1:{p1}", lb_policy=spec) as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"a"
            # membership change: dict spec can't remap -> round_robin set
            ch.update_addresses([f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"])
            got = {bytes(who(b"", timeout=10)) for _ in range(6)}
            assert got == {b"a", b"b"}
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)
