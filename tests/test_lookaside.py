"""Look-aside LB (grpclb capability) + Channel.update_addresses.

Ref ``lb_policy/grpclb/grpclb.cc``: balancer streams server lists, the
channel redirects live, falls back to resolver addresses when the
balancer dies."""

import time

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.lookaside import (LoadBalancerServicer, enable_lookaside)
from tpurpc.rpc.status import RpcError


def _named_server(name: str):
    srv = rpc.Server(max_workers=4)
    srv.add_method("/l.S/Who",
                   rpc.unary_unary_rpc_method_handler(
                       lambda r, c, n=name: n.encode()))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def _await(fn, timeout=30, every=0.05):
    """Poll until fn() is truthy; a call racing a membership swap may land
    on a just-closed backend once (documented transient) — treat RpcError
    as not-ready, like any retrying client would."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except RpcError:
            pass
        time.sleep(every)
    return False


# -- update_addresses (the mechanism) ----------------------------------------

def test_update_addresses_moves_traffic_and_keeps_live_subchannels():
    s1, p1 = _named_server("one")
    s2, p2 = _named_server("two")
    try:
        with rpc.Channel(f"127.0.0.1:{p1}") as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"one"
            conn_before = ch._subchannels[0]._conn
            # keep p1, add p2, round-robin over both
            ch._lb_spec = "round_robin"
            ch.update_addresses([("127.0.0.1", p1), ("127.0.0.1", p2)])
            assert ch._subchannels[0]._conn is conn_before  # reused, live
            got = {bytes(who(b"", timeout=10)) for _ in range(6)}
            assert got == {b"one", b"two"}
            # drop p1 entirely
            ch.update_addresses([f"127.0.0.1:{p2}"])
            for _ in range(4):
                assert who(b"", timeout=10) == b"two"
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_update_addresses_guards():
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    a, _b = passthru_endpoint_pair()
    ch = Channel(endpoint_factory=lambda: a)
    with pytest.raises(RuntimeError):
        ch.update_addresses(["127.0.0.1:1"])
    ch.close()
    s1, p1 = _named_server("x")
    try:
        ch = rpc.Channel(f"127.0.0.1:{p1}")
        with pytest.raises(ValueError):
            ch.update_addresses([])
        ch.close()
        with pytest.raises(RpcError):
            ch.update_addresses([f"127.0.0.1:{p1}"])  # closed channel
    finally:
        s1.stop(grace=0)


# -- the balancer protocol ----------------------------------------------------

def test_lookaside_balancer_directs_and_rebalances():
    s1, p1 = _named_server("backend1")
    s2, p2 = _named_server("backend2")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("demo", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p2}") as ch:  # fallback = backend2
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "demo")
            who = ch.unary_unary("/l.S/Who")
            # balancer list (backend1) takes over
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend1")
            # rebalance to backend2
            balancer.set_servers("demo", [f"127.0.0.1:{p2}"])
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend2")
            watcher.stop()
    finally:
        bal_srv.stop(grace=0)
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_lookaside_falls_back_when_balancer_dies():
    s1, p1 = _named_server("lbpick")
    s2, p2 = _named_server("fallback")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("d", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p2}") as ch:
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "d")
            who = ch.unary_unary("/l.S/Who")
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"lbpick")
            bal_srv.stop(grace=0)  # balancer gone
            # grpclb fallback rule: revert to the resolver-provided list
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"fallback")
            watcher.stop()
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_lookaside_rejects_factory_channel():
    from tpurpc.core.endpoint import passthru_endpoint_pair
    from tpurpc.rpc.channel import Channel

    a, _b = passthru_endpoint_pair()
    ch = Channel(endpoint_factory=lambda: a)
    with pytest.raises(ValueError):
        enable_lookaside(ch, "127.0.0.1:1", "x")
    ch.close()


def test_update_addresses_hostname_normalizes_to_resolved():
    """'localhost:p' must match the constructor's resolved keys — a no-op
    update keeps the live connection instead of redialing."""
    s1, p1 = _named_server("same")
    try:
        with rpc.Channel(f"localhost:{p1}") as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"same"
            live = [sc._conn for sc in ch._subchannels if sc._conn is not None]
            assert live
            ch.update_addresses([f"localhost:{p1}"])
            kept = [sc._conn for sc in ch._subchannels if sc._conn is not None]
            assert any(c in live for c in kept)  # the connection survived
            assert who(b"", timeout=10) == b"same"
    finally:
        s1.stop(grace=0)


def test_update_addresses_with_composite_spec_degrades_to_round_robin():
    s1, p1 = _named_server("a")
    s2, p2 = _named_server("b")
    try:
        spec = {"priority": [{"policy": "pick_first", "indices": [0]}]}
        with rpc.Channel(f"127.0.0.1:{p1}", lb_policy=spec) as ch:
            who = ch.unary_unary("/l.S/Who")
            assert who(b"", timeout=10) == b"a"
            # membership change: dict spec can't remap -> round_robin set
            ch.update_addresses([f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"])
            got = {bytes(who(b"", timeout=10)) for _ in range(6)}
            assert got == {b"a", b"b"}
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


# -- grpc.lb.v1 standard wire (tpurpc.rpc.lb_v1) ------------------------------

LB_PROTO = """
syntax = "proto3";
package grpc.lb.v1;
import "google/protobuf/duration.proto";
message LoadBalanceRequest {
  oneof load_balance_request_type {
    InitialLoadBalanceRequest initial_request = 1;
    ClientStats client_stats = 2;
  }
}
message InitialLoadBalanceRequest { string name = 1; }
message ClientStats {
  int64 num_calls_started = 2;
  int64 num_calls_finished = 3;
  int64 num_calls_finished_known_received = 7;
}
message LoadBalanceResponse {
  oneof load_balance_response_type {
    InitialLoadBalanceResponse initial_response = 1;
    ServerList server_list = 2;
  }
}
message InitialLoadBalanceResponse {
  google.protobuf.Duration client_stats_report_interval = 2;
}
message ServerList { repeated Server servers = 1; }
message Server {
  bytes ip_address = 1;
  int32 port = 2;
  string load_balance_token = 3;
  bool drop = 4;
}
"""


def _compile_lb_proto(tmp_path):
    """Compile the real grpc.lb.v1 message subset with protoc so the
    independent protobuf implementation judges our hand-rolled codec."""
    import importlib.util
    import shutil
    import subprocess

    if shutil.which("protoc") is None:
        pytest.skip("no protoc binary")
    proto = tmp_path / "load_balancer.proto"
    proto.write_text(LB_PROTO)
    r = subprocess.run(
        ["protoc", f"-I{tmp_path}", f"--python_out={tmp_path}", str(proto)],
        capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"protoc failed: {r.stderr[:200]}")
    spec = importlib.util.spec_from_file_location(
        "load_balancer_pb2", tmp_path / "load_balancer_pb2.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lb_v1_codec_against_real_protobuf(tmp_path):
    from tpurpc.rpc import lb_v1

    pb = _compile_lb_proto(tmp_path)
    # our encodes parse with stock protobuf
    req = pb.LoadBalanceRequest.FromString(
        lb_v1.encode_initial_request("svc"))
    assert req.initial_request.name == "svc"
    resp = pb.LoadBalanceResponse.FromString(
        lb_v1.encode_server_list(["127.0.0.1:443", "[::1]:8080",
                                  "not-an-ip:1"]))
    servers = resp.server_list.servers
    assert len(servers) == 2  # hostname skipped: the wire carries IPs
    assert servers[0].ip_address == b"\x7f\x00\x00\x01"
    assert servers[0].port == 443
    # stock protobuf encodes parse with our decoder
    kind, lst = lb_v1.decode_response(resp.SerializeToString())
    assert kind == "server_list" and lst == ["127.0.0.1:443", "[::1]:8080"]
    r2 = pb.LoadBalanceRequest()
    r2.initial_request.name = "other"
    assert lb_v1.decode_request(r2.SerializeToString()) == "other"
    # drop-entries are load-shedding directives, not dial targets
    resp2 = pb.LoadBalanceResponse()
    s = resp2.server_list.servers.add()
    s.ip_address, s.port, s.drop = b"\x7f\x00\x00\x01", 1, True
    kind, lst = lb_v1.decode_response(resp2.SerializeToString())
    assert kind == "server_list" and lst == []


def test_lookaside_over_grpclb_wire():
    """The full control loop on the STANDARD wire: watcher subscribes via
    grpc.lb.v1 protobuf, rebalances on ServerList updates."""
    s1, p1 = _named_server("backend1")
    s2, p2 = _named_server("backend2")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("demo", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p2}") as ch:
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "demo",
                                       wire="grpclb")
            who = ch.unary_unary("/l.S/Who")
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend1")
            balancer.set_servers("demo", [f"127.0.0.1:{p2}"])
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"backend2")
            watcher.stop()
    finally:
        bal_srv.stop(grace=0)
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_stock_grpcio_client_subscribes_to_balancer(tmp_path):
    """A stock grpcio client (real protobuf messages, real grpc channel)
    opens BalanceLoad against a tpurpc balancer and receives
    initial_response + ServerList — the grpclb.cc client's wire POV."""
    import queue

    import grpc

    pb = _compile_lb_proto(tmp_path)
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer()
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("inventory", ["10.1.2.3:50051"])
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{bal_port}")
        stream = ch.stream_stream(
            "/grpc.lb.v1.LoadBalancer/BalanceLoad",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.LoadBalanceResponse.FromString)
        hold = queue.Queue()

        def reqs():
            first = pb.LoadBalanceRequest()
            first.initial_request.name = "inventory"
            yield first
            hold.get()  # keep the stream open until the test is done

        try:
            resp_iter = stream(reqs())
            first = next(resp_iter)
            assert first.WhichOneof("load_balance_response_type") == \
                "initial_response"
            sl = next(resp_iter)
            assert [f"{s.ip_address.hex()}:{s.port}"
                    for s in sl.server_list.servers] == ["0a010203:50051"]
            balancer.set_servers("inventory", ["10.9.9.9:1"])
            sl2 = next(resp_iter)
            assert sl2.server_list.servers[0].port == 1
        finally:
            # always unblock the request iterator + close, or a failed
            # assert leaks a grpcio thread parked in hold.get()
            hold.put(None)
            ch.close()
    finally:
        bal_srv.stop(grace=0)


def test_lb_v1_stats_codec_against_real_protobuf(tmp_path):
    """Duration-carrying initial_response + ClientStats, judged by real
    protobuf (same shared proto as the other lb.v1 tests — registering a
    second file with the same symbols would clash in the global pool)."""
    from tpurpc.rpc import lb_v1

    pb = _compile_lb_proto(tmp_path)
    resp = pb.LoadBalanceResponse.FromString(
        lb_v1.encode_initial_response(2.25))
    dur = resp.initial_response.client_stats_report_interval
    assert dur.seconds == 2 and dur.nanos == 250000000
    kind, interval = lb_v1.decode_response(resp.SerializeToString())
    assert kind == "initial" and interval == 2.25

    req = pb.LoadBalanceRequest.FromString(
        lb_v1.encode_client_stats(10, 8, 7))
    cs = req.client_stats
    assert (cs.num_calls_started, cs.num_calls_finished,
            cs.num_calls_finished_known_received) == (10, 8, 7)
    assert lb_v1.decode_client_stats(req.SerializeToString()) == {
        "started": 10, "finished": 8, "known_received": 7}


def test_lookaside_grpclb_load_reporting():
    """The grpclb load-reporting loop: the balancer requests a ClientStats
    cadence in initial_response; the watcher streams call-count deltas;
    the balancer accumulates them per name."""
    s1, p1 = _named_server("b1")
    bal_srv = rpc.Server(max_workers=4)
    balancer = LoadBalancerServicer(stats_interval_s=0.3)
    balancer.attach(bal_srv)
    bal_port = bal_srv.add_insecure_port("127.0.0.1:0")
    bal_srv.start()
    balancer.set_servers("load", [f"127.0.0.1:{p1}"])
    try:
        with rpc.Channel(f"127.0.0.1:{p1}") as ch:
            watcher = enable_lookaside(ch, f"127.0.0.1:{bal_port}", "load",
                                       wire="grpclb")
            who = ch.unary_unary("/l.S/Who")
            assert _await(lambda: bytes(who(b"", timeout=10)) == b"b1")
            # grpclb stats are STREAM-relative deltas, so calls racing the
            # balancer stream's bring-up are legitimately excluded or
            # half-counted. Wait until reporting is demonstrably live,
            # capture a base, and assert exact deltas for calls made
            # strictly after it.
            assert _await(lambda: balancer.stats("load") != {}, timeout=20)
            base = balancer.stats("load")
            for _ in range(7):
                who(b"", timeout=10)

            def _reported():
                st = balancer.stats("load")
                return all(st.get(k, 0) - base.get(k, 0) >= 7
                           for k in ("started", "finished",
                                     "known_received"))

            assert _await(_reported, timeout=30), (base, balancer.stats("load"))
            watcher.stop()
    finally:
        bal_srv.stop(grace=0)
        s1.stop(grace=0)
