"""asyncio surface: four call shapes, async handler overlap, error mapping.

The grpc.aio analog (SURVEY §2.4, src/python/grpcio/grpc/aio/): async
handlers on one event loop over the threaded transport.
"""

import asyncio
import time

import pytest

from tpurpc.rpc import aio
from tpurpc.rpc.status import AbortError, RpcError, StatusCode


def _run(coro):
    return asyncio.run(coro)


async def _serve():
    srv = aio.Server(max_workers=8)

    async def echo(req, ctx):
        return bytes(req)

    async def tail(req, ctx):
        for i in range(4):
            yield bytes(req) + str(i).encode()

    async def collect(req_aiter, ctx):
        parts = []
        async for item in req_aiter:
            parts.append(bytes(item))
        return b"|".join(parts)

    async def chat(req_aiter, ctx):
        async for item in req_aiter:
            yield b"re:" + bytes(item)

    async def boom(req, ctx):
        raise AbortError(StatusCode.FAILED_PRECONDITION, "async nope")

    async def slow(req, ctx):
        await asyncio.sleep(0.5)  # awaits, does NOT block the loop
        return bytes(req)

    srv.add_method("/a.S/Echo", aio.unary_unary_rpc_method_handler(echo))
    srv.add_method("/a.S/Tail", aio.unary_stream_rpc_method_handler(tail))
    srv.add_method("/a.S/Collect",
                   aio.stream_unary_rpc_method_handler(collect))
    srv.add_method("/a.S/Chat", aio.stream_stream_rpc_method_handler(chat))
    srv.add_method("/a.S/Boom", aio.unary_unary_rpc_method_handler(boom))
    srv.add_method("/a.S/Slow", aio.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    await srv.start()
    return srv, port


def test_aio_unary():
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                call = ch.unary_unary("/a.S/Echo")
                assert await call(b"hello-aio", timeout=20) == b"hello-aio"
        finally:
            await srv.stop()

    _run(main())


def test_aio_server_streaming():
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                got = [bytes(m) async for m in
                       ch.unary_stream("/a.S/Tail")(b"x", timeout=20)]
                assert got == [b"x0", b"x1", b"x2", b"x3"]
        finally:
            await srv.stop()

    _run(main())


def test_aio_client_streaming_with_async_request_iterator():
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                async def reqs():
                    for chunk in (b"a", b"b", b"c"):
                        await asyncio.sleep(0)  # prove async production works
                        yield chunk

                out = await ch.stream_unary("/a.S/Collect")(reqs(),
                                                            timeout=20)
                assert bytes(out) == b"a|b|c"
        finally:
            await srv.stop()

    _run(main())


def test_aio_bidi_streaming():
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                async def reqs():
                    yield b"1"
                    yield b"2"

                got = [bytes(m) async for m in
                       ch.stream_stream("/a.S/Chat")(reqs(), timeout=20)]
                assert got == [b"re:1", b"re:2"]
        finally:
            await srv.stop()

    _run(main())


def test_aio_error_status():
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                with pytest.raises(RpcError) as ei:
                    await ch.unary_unary("/a.S/Boom")(b"x", timeout=20)
                assert ei.value.code() is StatusCode.FAILED_PRECONDITION
                assert "async nope" in ei.value.details()
        finally:
            await srv.stop()

    _run(main())


def test_aio_handlers_overlap_on_one_loop():
    """Eight 0.5s-awaiting handlers complete in ~one await, not eight: the
    awaits interleave on the server loop (the reason this module exists)."""
    async def main():
        srv, port = await _serve()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                call = ch.unary_unary("/a.S/Slow")
                t0 = time.monotonic()
                outs = await asyncio.gather(
                    *[call(f"c{i}".encode(), timeout=30) for i in range(8)])
                dt = time.monotonic() - t0
            assert outs == [f"c{i}".encode() for i in range(8)]
            assert dt < 2.5, f"handlers serialized: {dt:.2f}s for 8x0.5s"
        finally:
            await srv.stop()

    _run(main())


def test_aio_abandoned_stream_does_not_wedge_channel():
    """Breaking out of a response stream mid-way must cancel the RPC and
    leave the channel fully usable (reviewer finding: the abandoned pump
    must not strand a thread or leak the stream's credits)."""
    async def main():
        srv = aio.Server(max_workers=4)

        async def forever(req, ctx):
            i = 0
            while True:
                yield str(i).encode()
                i += 1
                await asyncio.sleep(0)

        srv.add_method("/a.S/Forever",
                       aio.unary_stream_rpc_method_handler(forever))
        port = srv.add_insecure_port("127.0.0.1:0")
        await srv.start()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                stream = ch.unary_stream("/a.S/Forever")(b"go", timeout=30)
                seen = 0
                async for _ in stream:
                    seen += 1
                    if seen == 3:
                        break  # abandon mid-stream, no explicit cancel
                await stream.aclose()
                # channel still fully functional afterwards (several times,
                # to cross the abandoned stream's credit bound if it leaked)
                srv.add_method(
                    "/a.S/Echo2",
                    aio.unary_unary_rpc_method_handler(
                        lambda req, ctx: _echo_coro(req)))
                call = ch.unary_unary("/a.S/Echo2")
                for i in range(4):
                    assert await call(f"p{i}".encode(), timeout=15) == \
                        f"p{i}".encode()
        finally:
            await srv.stop()

    async def _echo_coro(req):
        return bytes(req)

    _run(main())


def test_aio_deadline_exceeded():
    """A stalled handler must surface DEADLINE_EXCEEDED through the asyncio
    surface (not hang the event loop)."""
    import threading as _threading

    from tpurpc.rpc.status import RpcError, StatusCode

    release = _threading.Event()

    async def stall(req, ctx):
        await asyncio.get_event_loop().run_in_executor(
            None, release.wait, 20)
        return b"late"

    srv = aio.Server(max_workers=4)
    srv.add_method("/a.S/Stall", aio.unary_unary_rpc_method_handler(stall))
    port = srv.add_insecure_port("127.0.0.1:0")

    async def main():
        await srv.start()
        try:
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                with pytest.raises(RpcError) as ei:
                    await ch.unary_unary("/a.S/Stall")(b"x", timeout=0.5)
                assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
        finally:
            release.set()
            await srv.stop(grace=0)

    asyncio.run(main())


def test_aio_retry_policy_applies():
    """Channel-level RetryPolicy plumbs through the aio surface."""
    from tpurpc.rpc.channel import RetryPolicy
    from tpurpc.rpc.status import StatusCode

    calls = {"n": 0}

    async def flaky(req, ctx):
        calls["n"] += 1
        if calls["n"] < 3:
            ctx.abort(StatusCode.UNAVAILABLE, "flake")
        return b"ok"

    srv = aio.Server(max_workers=4)
    srv.add_method("/a.S/Flaky", aio.unary_unary_rpc_method_handler(flaky))
    port = srv.add_insecure_port("127.0.0.1:0")

    async def main():
        await srv.start()
        try:
            pol = RetryPolicy(max_attempts=5, initial_backoff=0.01)
            async with aio.insecure_channel(f"127.0.0.1:{port}",
                                            retry_policy=pol) as ch:
                assert await ch.unary_unary("/a.S/Flaky")(b"", timeout=10) \
                    == b"ok"
            assert calls["n"] == 3
        finally:
            await srv.stop(grace=0)

    asyncio.run(main())


def test_aio_native_channel():
    """The async face of the ctypes fast path."""
    import os

    import pytest as _pytest

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(os.path.join(root, "native", "build",
                                       "libtpurpc.so")):
        _pytest.skip("native lib not built")
    import asyncio

    import tpurpc.rpc as rpc
    from tpurpc.rpc import aio

    srv = rpc.Server(max_workers=4)
    srv.add_method("/a.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r) + b"?", inline=True))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()

    async def main():
        async with aio.NativeChannel("127.0.0.1", port) as ch:
            echo = ch.unary_unary("/a.S/Echo")
            # 64 concurrent coroutines = 64 calls genuinely in flight on
            # one connection via the CQ (far beyond any executor width —
            # the old thread-per-call face couldn't express this)
            outs = await asyncio.gather(*[echo(b"m%d" % i, timeout=30)
                                          for i in range(64)])
            assert outs == [b"m%d?" % i for i in range(64)]
            assert await ch.ping() < 5

    asyncio.run(main())
    srv.stop(grace=0)


from tests.conftest import requires_native_lib  # noqa: E402


@requires_native_lib
def test_aio_over_ring_platform_round4_planes(monkeypatch):
    """asyncio surface over the round-4 data planes: a ring-platform aio
    channel's calls run through the sync channel's native fast path (the
    executor hop) against a natively-adopted server — the whole stack a
    drop-in asyncio app would ride."""
    import asyncio

    import tpurpc.rpc as rpc

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv = rpc.Server(max_workers=4)
    srv.add_method("/a.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r), inline=True))

    def dbl(it, c):
        for m in it:
            yield bytes(m) * 2

    srv.add_method("/a.S/Dbl", rpc.stream_stream_rpc_method_handler(dbl))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        assert srv._native_dp is not None  # server adopted

        async def main():
            async with aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                echo = ch.unary_unary("/a.S/Echo")
                out = await asyncio.gather(
                    *[echo(f"m{i}".encode(), timeout=30) for i in range(16)])
                assert out == [f"m{i}".encode() for i in range(16)]

                async def gen():
                    yield b"x"
                    yield b"yy"

                got = []
                async for resp in ch.stream_stream("/a.S/Dbl")(gen(),
                                                               timeout=30):
                    got.append(bytes(resp))
                assert got == [b"xx", b"yyyy"]

        asyncio.run(main())
    finally:
        srv.stop(grace=0)
        config_mod.set_config(None)


def test_aio_channel_honors_resolver_service_config():
    """Round-5 service config reaches the aio surface: the aio channel
    wraps the sync core, so a resolver-delivered retryPolicy retries a
    flaky method transparently from async call sites too."""
    import threading

    from tpurpc.rpc import resolver as resolver_mod
    from tpurpc.rpc.resolver import Resolution, register_resolver

    calls = {"n": 0}
    lock = threading.Lock()

    async def flaky(req, ctx):
        with lock:
            calls["n"] += 1
            n = calls["n"]
        if n <= 2:
            raise AbortError(StatusCode.UNAVAILABLE, "flaky")
        return b"ok-aio"

    cfg = {"methodConfig": [{
        "name": [{"service": "a.S", "method": "Flaky"}],
        "retryPolicy": {"maxAttempts": 4, "initialBackoff": "0.01s",
                        "maxBackoff": "0.05s", "backoffMultiplier": 2,
                        "retryableStatusCodes": ["UNAVAILABLE"]}}]}

    async def main():
        srv = aio.Server(max_workers=4)
        srv.add_method("/a.S/Flaky", aio.unary_unary_rpc_method_handler(flaky))
        port = srv.add_insecure_port("127.0.0.1:0")
        await srv.start()
        register_resolver("aiocfg",
                          lambda rest: Resolution([("127.0.0.1", port)], cfg))
        try:
            async with aio.insecure_channel("aiocfg:///x") as ch:
                out = await ch.unary_unary("/a.S/Flaky")(b"", timeout=20)
                assert out == b"ok-aio"
                assert calls["n"] == 3  # 2 failures + 1 success, all config
        finally:
            resolver_mod._RESOLVERS.pop("aiocfg", None)
            await srv.stop()

    _run(main())
