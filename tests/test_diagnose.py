"""tpurpc-oracle (ISSUE 20): the causal diagnosis engine.

Covers the tentpole's three layers — change-point detection (pinned
math: mean-shift split, reset-aware counter deltas, noise floor), the
declarative rule registry (read-only collect + score over the Planes
interface), and ranked noisy-OR hypothesis combination — plus every
face: the live ``/debug/diagnose`` route through the real scrape
dispatch, the shard and fleet merges, bundle replay parity (the frozen
planes rank the same cause the live engine ranked), and the
``TPURPC_DIAGNOSE=0`` off-switch. Three induced fault classes must come
out rank-1 correct: credit-starvation (held send-lease), device-infer
(slow peer: in-flight client call, quiet transport), and a frozen
native ctrl ring (synthesized planes here; the REAL
TPURPC_TEST_FREEZE_NCTRL freeze runs in tools/diagnose_smoke.py, wired
into check.sh).
"""

import json
import time

import pytest

from tpurpc.obs import bundle as obs_bundle
from tpurpc.obs import diagnose, flight, scrape
from tpurpc.obs import tsdb as obs_tsdb
from tpurpc.obs import watchdog


@pytest.fixture(autouse=True)
def _clean_state():
    flight.RECORDER.reset()
    # a fresh tsdb: earlier tests' series (decode schedulers, benches…)
    # would otherwise feed this diagnosis real-looking onsets
    obs_tsdb.postfork_reset()
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled)
    yield
    obs_bundle.disable()
    wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled = prev
    wd.reset()
    flight.RECORDER.reset()


def _fast_wd():
    wd = watchdog.get()
    wd.enabled = True
    wd.min_stall_s = 0.01
    wd.sweep_s = 0.05
    return wd


def _top(doc):
    hyps = doc.get("hypotheses") or []
    return hyps[0]["cause"] if hyps else None


# ---------------------------------------------------------------------------
# change-point detection: the math is pinned
# ---------------------------------------------------------------------------

def test_onset_step_function_found_at_exact_index():
    pts = [(i * 1000, 0.0) for i in range(16)]
    pts += [(i * 1000, 10.0) for i in range(16, 32)]
    onset = diagnose.detect_onset(pts)
    assert onset is not None
    assert onset["index"] == 16          # FIRST point of the right segment
    assert onset["t_ns"] == 16_000
    assert onset["direction"] == 1
    assert onset["magnitude"] == pytest.approx(10.0)
    assert onset["score"] >= diagnose.MIN_SCORE


def test_onset_falling_step_has_negative_direction():
    pts = [(i, 8.0) for i in range(12)] + [(i, 1.0) for i in range(12, 24)]
    onset = diagnose.detect_onset(pts)
    assert onset["direction"] == -1
    assert onset["magnitude"] == pytest.approx(-7.0)


def test_onset_constant_and_noise_series_return_none():
    assert diagnose.detect_onset([(i, 5.0) for i in range(32)]) is None
    # alternating jitter has no single split beating the noise floor
    jitter = [(i, 5.0 + (0.1 if i % 2 else -0.1)) for i in range(32)]
    assert diagnose.detect_onset(jitter) is None


def test_onset_too_few_points_is_inadmissible():
    pts = [(i, 0.0) for i in range(3)] + [(i, 9.0) for i in range(3, 6)]
    assert diagnose.detect_onset(pts) is None


def test_onset_counter_series_diffed_before_split():
    # raw counter: +1/step for 16 steps then +10/step — the SHIFT is in
    # the rate, invisible to a raw mean split over the ramp
    vals = []
    v = 0.0
    for i in range(32):
        v += 1.0 if i < 16 else 10.0
        vals.append((i * 10, v))
    onset = diagnose.detect_onset(vals, kind="counter")
    assert onset is not None and onset["direction"] == 1
    assert onset["magnitude"] == pytest.approx(9.0, abs=0.5)


def test_onset_counter_reset_cannot_fake_a_cliff():
    # a restart (counter falls back to ~0 and re-climbs at the same
    # rate) must NOT read as an onset: the post-reset value IS the delta
    pts = [(i, float(i)) for i in range(16)]
    pts += [(16 + i, float(i)) for i in range(16)]
    assert diagnose.detect_onset(pts, kind="counter") is None


def test_series_shifts_scans_every_series():
    wins = {
        "flat": [(i, 1.0) for i in range(16)],
        "step": [(i, 0.0) for i in range(12)] + [(i, 6.0)
                                                 for i in range(12, 24)],
    }
    shifts = diagnose.series_shifts(wins, {"flat": "gauge",
                                           "step": "gauge"})
    assert set(shifts) == {"step"}


# ---------------------------------------------------------------------------
# rule registry + combination
# ---------------------------------------------------------------------------

def test_registry_carries_the_six_stock_rules():
    names = [r.name for r in diagnose.rules()]
    for want in ("watchdog-stage", "flight-edges", "tsdb-shift",
                 "lens-hop", "seq-ledger", "native-counters"):
        assert want in names


def test_register_and_symptom_kind_gating():
    ran = []

    def collect(planes, symptom):
        ran.append(symptom["kind"])
        return None

    rule = diagnose.Rule("test-gated", ("query",), collect,
                         lambda f, p, s: [])
    diagnose.register(rule)
    try:
        planes = diagnose.Planes()
        diagnose.diagnose(planes, want="why slow")     # kind=query: runs
        assert ran == ["query"]
    finally:
        diagnose._RULES.remove(rule)


def test_combine_noisy_or_and_evidence_dedup():
    hyps = [
        diagnose.Hypothesis("credit-starvation", 0.6,
                            [("flight", "lease", 1)], rule="a"),
        diagnose.Hypothesis("credit-starvation", 0.5,
                            [("flight", "lease", 1),
                             ("tsdb", "credit@9", -3)], rule="b"),
        diagnose.Hypothesis("other", 0.3, [("x", "y", 0)], rule="a"),
    ]
    out = diagnose._combine(hyps)
    assert out[0]["cause"] == "credit-starvation"
    assert out[0]["confidence"] == pytest.approx(1 - 0.4 * 0.5, abs=1e-3)
    assert out[0]["rules"] == ["a", "b"]
    # (flight, lease) cited twice dedups to one evidence row
    assert out[0]["evidence"] == [["flight", "lease", 1],
                                  ["tsdb", "credit@9", -3]]
    assert out[0]["actionable"]  # every ranked cause ships its hint


def test_combine_confidence_capped_under_certainty():
    hyps = [diagnose.Hypothesis("x", 0.99, rule="a"),
            diagnose.Hypothesis("x", 0.99, rule="b")]
    assert diagnose._combine(hyps)[0]["confidence"] <= 0.99


def test_broken_rule_never_breaks_the_report():
    rule = diagnose.Rule(
        "test-broken", (),
        lambda p, s: (_ for _ in ()).throw(RuntimeError("boom")),
        lambda f, p, s: [])
    diagnose.register(rule)
    try:
        wd = _fast_wd()
        tok = wd.call_started("/t/M")
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        doc = diagnose.diagnose(diagnose.LivePlanes())
        assert doc["hypotheses"]          # the other rules still ran
        wd.call_finished(tok)
    finally:
        diagnose._RULES.remove(rule)


# ---------------------------------------------------------------------------
# induced faults: rank-1 correct
# ---------------------------------------------------------------------------

def test_fault_credit_starvation_ranks_first():
    wd = _fast_wd()
    tag = flight.tag_for("pair:diagtest")
    flight.emit(flight.LEASE_RESERVE, tag, 4096)
    tok = wd.call_started("/diag/Wedged")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        doc = diagnose.diagnose(diagnose.LivePlanes())
        assert doc["symptom"]["stage"] == "credit-starvation"
        assert _top(doc) == "credit-starvation"
        top = doc["hypotheses"][0]
        # independent planes corroborate: watchdog stage + flight edge
        assert {"watchdog-stage", "flight-edges"} <= set(top["rules"])
        assert top["confidence"] > 0.9
        assert any(p == "flight" for p, _r, _v in top["evidence"])
        assert "ring" in top["actionable"].lower() \
            or "shed" in top["actionable"].lower()
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 4096)


def test_fault_device_infer_ranks_first():
    wd = _fast_wd()
    tok = wd.call_started("/diag/SlowPeer", kind="client")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        doc = diagnose.diagnose(diagnose.LivePlanes())
        assert doc["symptom"]["stage"] == "device-infer"
        assert _top(doc) == "device-infer"
        assert "fleet" in doc["hypotheses"][0]["actionable"]
    finally:
        wd.call_finished(tok)


class _FrozenNctrlPlanes(diagnose.Planes):
    """The native-ctrl-frozen fault as frozen planes: an active watchdog
    diagnosis plus an aged native-lane ctrl-stall bracket — exactly what
    the live planes show under a real TPURPC_TEST_FREEZE_NCTRL freeze
    (tools/diagnose_smoke.py induces the real one)."""

    NOW = 200_000_000_000

    def now_ns(self):
        return self.NOW

    def watchdog(self):
        return {"active": [{
            "stage": "native-ctrl-frozen", "method": "/m/Bulk",
            "kind": "client", "age_s": 4.2, "since_ns": self.NOW - int(4.2e9),
            "cause": {"stage": "native-ctrl-frozen", "entity": "conn-7",
                      "evidence": [["flight", "nctrl-ring-full:conn-7", 4.1]]},
        }], "history": []}

    def flight_events(self):
        return [{"t_ns": self.NOW - 4_000_000_000,
                 "code": flight.CTRL_STALL_BEGIN, "event": "ctrl-stall",
                 "tag": 1, "entity": "conn-7", "tid": 1, "a1": 8, "a2": 0,
                 "lane": "native"}]


def test_fault_frozen_native_ctrl_ranks_first():
    doc = diagnose.diagnose(_FrozenNctrlPlanes())
    assert doc["symptom"]["stage"] == "native-ctrl-frozen"
    assert _top(doc) == "native-ctrl-frozen"
    top = doc["hypotheses"][0]
    assert {"watchdog-stage", "flight-edges"} <= set(top["rules"])
    assert "restart" in top["actionable"]


def test_fresh_flight_edges_are_traffic_not_wedges():
    """A bracket open for <1s is in-flight traffic; only AGED edges are
    evidence (otherwise every healthy bulk send diagnoses as a wedge)."""
    class Fresh(_FrozenNctrlPlanes):
        def watchdog(self):
            return {}

        def flight_events(self):
            return [{"t_ns": self.NOW - 100_000_000,   # 0.1s old
                     "code": flight.CTRL_STALL_BEGIN, "event": "ctrl-stall",
                     "tag": 1, "entity": "conn-7", "tid": 1, "a1": 8,
                     "a2": 0, "lane": "native"}]

    doc = diagnose.diagnose(Fresh(), want="anything wrong?")
    assert all(h["cause"] != "native-ctrl-frozen"
               for h in doc["hypotheses"])


# ---------------------------------------------------------------------------
# symptom resolution
# ---------------------------------------------------------------------------

def test_symptom_precedence_active_watchdog_beats_history():
    class P(diagnose.Planes):
        def watchdog(self):
            return {"active": [{"stage": "kv-swap", "method": "/a"}],
                    "history": [{"stage": "migration", "method": "/b"}]}

    sym = diagnose.find_symptom(P())
    assert sym["stage"] == "kv-swap" and sym["state"] == "active"


def test_symptom_history_serves_the_bundle_replay_case():
    class P(diagnose.Planes):
        def watchdog(self):
            return {"active": [],
                    "history": [{"stage": "rendezvous", "method": "/b"}]}

    sym = diagnose.find_symptom(P())
    assert sym["stage"] == "rendezvous" and sym["state"] == "history"


def test_symptom_operator_query_is_a_first_class_kind():
    sym = diagnose.find_symptom(diagnose.Planes(), want="why is p99 up")
    assert sym == {"kind": "query", "detail": "why is p99 up",
                   "t_ns": None}


def test_no_symptom_no_hypotheses():
    doc = diagnose.diagnose(diagnose.Planes())
    assert doc["symptom"] is None and doc["hypotheses"] == []


# ---------------------------------------------------------------------------
# faces: live route, off-switch, bundle replay, shard + fleet merge
# ---------------------------------------------------------------------------

def test_debug_diagnose_route_json_and_text():
    wd = _fast_wd()
    tag = flight.tag_for("pair:routetest")
    flight.emit(flight.LEASE_RESERVE, tag, 64)
    tok = wd.call_started("/diag/Route")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        status, ctype, body = scrape._route("/debug/diagnose")
        assert status == 200 and "json" in ctype
        doc = json.loads(body)
        assert doc["enabled"] and _top(doc) == "credit-starvation"
        status, ctype, body = scrape._route("/debug/diagnose?text=1")
        assert status == 200 and ctype.startswith("text/plain")
        text = body.decode()
        assert "credit-starvation" in text and "#1" in text
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 64)


def test_off_switch_disables_engine_and_bundle_dump(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("TPURPC_DIAGNOSE", "0")
    doc = diagnose.diagnose_doc({})
    assert doc == {"enabled": False, "reason": "TPURPC_DIAGNOSE=0"}
    assert "disabled" in diagnose.render_text(doc)
    w = obs_bundle.enable(str(tmp_path), min_interval_s=0.0)
    w.capture("manual", detail="off-switch")
    names = obs_bundle.list_bundles(str(tmp_path))
    assert names
    assert not (tmp_path / names[-1] / "diagnosis.json").exists()


def test_bundle_replay_parity_with_live(tmp_path):
    """The acceptance core: the bundle frozen at trip time replays to
    the same rank-1 cause the live engine reports."""
    wd = _fast_wd()
    tag = flight.tag_for("pair:paritytest")
    flight.emit(flight.LEASE_RESERVE, tag, 128)
    tok = wd.call_started("/diag/Parity")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        live = diagnose.diagnose(diagnose.LivePlanes())
        w = obs_bundle.enable(str(tmp_path), min_interval_s=0.0)
        w.capture("manual", detail="parity")
        names = obs_bundle.list_bundles(str(tmp_path))
        path = str(tmp_path / names[-1])
        shipped = json.loads(
            (tmp_path / names[-1] / "diagnosis.json").read_text())
        replayed = diagnose.diagnose_bundle(path)
        assert (_top(live) == _top(shipped) == _top(replayed)
                == "credit-starvation")
        assert replayed["bundle"] == names[-1]
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 128)


def test_offline_cli_renders_bundle(tmp_path, capsys):
    from tpurpc.tools import diagnose as diagnose_cli

    wd = _fast_wd()
    tag = flight.tag_for("pair:clitest")
    flight.emit(flight.LEASE_RESERVE, tag, 64)
    tok = wd.call_started("/diag/Cli")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        w = obs_bundle.enable(str(tmp_path), min_interval_s=0.0)
        w.capture("manual", detail="cli")
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 64)
    # pointed at the ROOT it resolves the newest bundle
    assert diagnose_cli.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "credit-starvation" in out and "bundle:" in out
    assert diagnose_cli.main([str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert _top(doc) == "credit-starvation"


def _doc(cause, conf, stage=None, state="active"):
    sym = None
    if stage:
        sym = {"kind": "watchdog", "state": state, "stage": stage,
               "method": "/m", "detail": None, "t_ns": 1}
    return {"enabled": True, "symptom": sym,
            "hypotheses": [{"cause": cause, "confidence": conf,
                            "evidence": [["flight", "e", 1]],
                            "rules": ["watchdog-stage"],
                            "actionable": "act"}],
            "onsets": {}, "rules_run": []}


def test_merge_diagnose_docs_corroboration_and_ranking():
    docs = {"0": _doc("credit-starvation", 0.6, stage="credit-starvation"),
            "1": _doc("credit-starvation", 0.5),
            "2": _doc("kv-swap", 0.9, stage="kv-swap", state="history")}
    out = diagnose.merge_diagnose_docs(docs, label="shard")
    assert out["enabled"] and out["sources"] == ["0", "1", "2"]
    by = {h["cause"]: h for h in out["hypotheses"]}
    # two shards citing the same cause compound past either alone
    assert by["credit-starvation"]["confidence"] == pytest.approx(
        1 - 0.4 * 0.5, abs=1e-3)
    assert by["credit-starvation"]["sources"] == ["0", "1"]
    assert out["corroboration"] == {"credit-starvation": ["0", "1"]}
    # evidence rows are source-tagged
    assert by["kv-swap"]["evidence"] == [["flight", "shard=2:e", 1]]
    # the ACTIVE symptom outranks the history one
    assert out["symptom"]["stage"] == "credit-starvation"


def test_merge_diagnose_docs_skips_disabled_members():
    docs = {"a": {"enabled": False},
            "b": _doc("migration", 0.7, stage="migration")}
    out = diagnose.merge_diagnose_docs(docs)
    assert out["enabled"] and [h["cause"] for h in out["hypotheses"]] \
        == ["migration"]
    empty = diagnose.merge_diagnose_docs({"a": {"enabled": False}})
    assert not empty["enabled"] and empty["hypotheses"] == []


def test_collector_fleet_diagnose_merge():
    from tpurpc.obs.collector import FleetCollector

    col = FleetCollector(["h1:1", "h2:2", "h3:3"], poll_s=0.1)
    for t, doc in (("h1:1", _doc("rendezvous", 0.6, stage="rendezvous")),
                   ("h2:2", _doc("rendezvous", 0.5)),
                   ("h3:3", None)):
        m = col._members[t]
        m.metrics_text = "tpurpc_x 1\n"
        m.diagnose = doc
        m.misses = 0
        m.polls += 1
        m.last_ok_mono = time.monotonic()
    out = col.merged_diagnose()
    assert out["enabled"]
    assert _top(out) == "rendezvous"
    assert out["corroboration"] == {"rendezvous": ["h1:1", "h2:2"]}
    assert out["members"] == {"h1:1": "up", "h2:2": "up", "h3:3": "up"}
    assert out["degraded"] == ["h1:1"]   # only h1 reports a symptom
    # evidence carries the member tag
    by = {h["cause"]: h for h in out["hypotheses"]}
    assert by["rendezvous"]["evidence"][0][1].startswith("member=h1:1:")


def test_render_text_cites_evidence_and_action():
    doc = _doc("credit-starvation", 0.8, stage="credit-starvation")
    text = diagnose.render_text(doc)
    assert "symptom [watchdog] credit-starvation" in text
    assert "#1 credit-starvation" in text
    assert "[flight] e = 1" in text
    assert "-> act" in text


# ---------------------------------------------------------------------------
# watchdog structured causes (satellite a): objects under the same prose
# ---------------------------------------------------------------------------

def test_watchdog_diag_carries_structured_cause():
    wd = _fast_wd()
    tag = flight.tag_for("pair:structtest")
    flight.emit(flight.LEASE_RESERVE, tag, 77)
    tok = wd.call_started("/diag/Struct")
    try:
        time.sleep(3 * wd.min_stall_s)
        diags = wd.sweep_once()
        d = next(x for x in diags if x["method"] == "/diag/Struct")
        cause = d["cause"]
        assert cause["stage"] == d["stage"] == "credit-starvation"
        assert cause["evidence"], "structured cause cites no evidence"
        plane, ref, _v = cause["evidence"][0]
        assert plane == "flight" and "lease-reserve-open" in ref
        # the prose face is still the prose face
        assert "send-lease held" in d["detail"]
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 77)


def test_watchdog_retrips_once_per_distinct_stage():
    """A stall that SHARPENS (rendezvous -> native-ctrl-frozen as the C
    evidence lands) must re-trip so the trip-time bundle carries the
    sharper diagnosis — but the same stage never trips twice."""
    wd = _fast_wd()
    trips = []
    hook = lambda diag: trips.append(diag["stage"])  # noqa: E731
    watchdog.add_trip_hook(hook)
    tag = flight.tag_for("pair:retrip")
    tok = wd.call_started("/diag/Retrip")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        wd.sweep_once()                      # same stage: no second trip
        assert trips == ["device-infer"]
        flight.emit(flight.LEASE_RESERVE, tag, 9)   # evidence sharpens
        wd.sweep_once()
        assert trips == ["device-infer", "credit-starvation"]
        wd.sweep_once()
        assert trips == ["device-infer", "credit-starvation"]
    finally:
        watchdog.remove_trip_hook(hook)
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 9)
