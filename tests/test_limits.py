"""Bounded memory: max message size + per-stream backpressure + pool bounds.

VERDICT r1 #8 / reference analogs: resource_quota.cc, chttp2
flow_control.{h,cc} — a fast sender must not balloon server memory, and an
over-limit message must be rejected cleanly (framing intact, stream gets
RESOURCE_EXHAUSTED, connection survives).
"""

import threading
import time

import pytest

import tpurpc.rpc as tps
from tpurpc.rpc.status import RpcError, StatusCode


def _server(**kw):
    srv = tps.Server(max_workers=4, **kw)
    srv.add_method("/t.S/Echo",
                   tps.unary_unary_rpc_method_handler(lambda req, ctx: req))

    def count(req_iter, ctx):
        n = 0
        for _ in req_iter:
            n += 1
        return str(n).encode()

    srv.add_method("/t.S/Count",
                   tps.stream_unary_rpc_method_handler(count))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def test_oversized_unary_rejected_cleanly():
    """Over-limit request → RESOURCE_EXHAUSTED; the connection (and the
    next, legal call on it) survives — the reject is per-stream."""
    srv, port = _server(max_receive_message_length=64 << 10)  # 64 KiB
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/t.S/Echo")
            with pytest.raises(RpcError) as ei:
                mc(b"x" * (1 << 20), timeout=20)  # 1 MiB >> 64 KiB
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
            assert "larger than max" in ei.value.details()
            # framing stayed in sync: a small call on the SAME channel works
            assert bytes(mc(b"small", timeout=20)) == b"small"
    finally:
        srv.stop(grace=0)


def test_oversized_mid_stream_aborts_stream_only():
    srv, port = _server(max_receive_message_length=64 << 10)
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_unary("/t.S/Count")
            msgs = [b"ok1", b"y" * (1 << 20), b"ok2"]
            with pytest.raises(RpcError) as ei:
                mc(iter(msgs), timeout=20)
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
            # connection still serves
            assert bytes(ch.unary_unary("/t.S/Echo")(b"z", timeout=20)) == b"z"
    finally:
        srv.stop(grace=0)


def test_oversized_response_rejected_client_side():
    """The CLIENT enforces its receive bound too."""
    srv, port = _server()  # server side unlimited-ish default
    try:
        with tps.Channel(f"127.0.0.1:{port}",
                         max_receive_message_length=32 << 10) as ch:
            mc = ch.unary_unary("/t.S/Echo")
            with pytest.raises(RpcError) as ei:
                mc(b"q" * (256 << 10), timeout=20)  # reply exceeds 32 KiB
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
    finally:
        srv.stop(grace=0)


def test_env_knob_applies(monkeypatch):
    monkeypatch.setenv("TPURPC_MAX_RECV_MESSAGE_LENGTH", str(16 << 10))
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _server()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/t.S/Echo")(b"e" * (64 << 10), timeout=20)
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
    finally:
        srv.stop(grace=0)


def test_slow_reader_backpressures_fast_sender(monkeypatch):
    """A handler that reads slowly must bound buffered messages: the reader
    stops draining at stream_queue_depth and the transport's flow control
    stalls the sender — memory stays bounded end to end."""
    monkeypatch.setenv("TPURPC_STREAM_QUEUE_DEPTH", "4")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)

    consumed = []
    release = threading.Event()

    def slow_count(req_iter, ctx):
        for item in req_iter:
            consumed.append(len(bytes(item)))
            if len(consumed) == 1:
                release.wait(timeout=30)  # park after the first message
        return str(len(consumed)).encode()

    srv = tps.Server(max_workers=4)
    srv.add_method("/t.S/Slow",
                   tps.stream_unary_rpc_method_handler(slow_count))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        sent = [0]
        result = [None]
        n_msgs, msg = 64, b"b" * (1 << 20)  # 64 x 1 MiB

        def gen():
            for _ in range(n_msgs):
                sent[0] += 1
                yield msg

        def call():
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                result[0] = bytes(
                    ch.stream_unary("/t.S/Slow")(gen(), timeout=60))

        t = threading.Thread(target=call)
        t.start()
        time.sleep(2.0)  # sender runs against a parked handler
        # Backpressure: the generator must NOT have pushed everything while
        # the handler sits on message 1. In-flight budget = queue depth (4
        # messages) + ring capacity + kernel socket buffers << 64 MiB;
        # without the bound the reader drains all 64 immediately.
        assert sent[0] < n_msgs, f"no backpressure: all {sent[0]} sent"
        release.set()
        t.join(timeout=60)
        assert result[0] == str(n_msgs).encode()
    finally:
        srv.stop(grace=0)


def test_pair_pool_per_key_bound_below_total():
    from tpurpc.core.poller import PairPool

    pool = PairPool(max_idle_total=128)
    assert pool.max_idle_total == 128
    assert pool.max_idle_per_key == 32  # one hot key can't evict-starve all
    pool.drain()
    # an explicit per-key bound is honored as given
    pool = PairPool(max_idle_total=128, max_idle_per_key=8)
    assert pool.max_idle_per_key == 8
    pool.drain()
