"""Property-based differential tests: the ring protocol and both Pallas
kernels vs simple oracles, under randomized operation sequences.

SURVEY §7 stage 4 prescribes porting the ring *math* as a formally-tested
state machine — these are the law: a FIFO byte-queue model for the pair
protocol (any divergence is a framing/credit bug), and numpy oracles for
the kernels across randomized wrap geometries.
"""

import numpy as np
import pytest
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from tpurpc.core.pair import LocalDomain, create_loopback_pair

_SETTINGS = dict(max_examples=40, deadline=None,
                 suppress_health_check=[HealthCheck.too_slow])


@settings(**_SETTINGS)
@given(st.lists(st.integers(min_value=0, max_value=3000), min_size=1,
                max_size=30),
       st.randoms(use_true_random=False))
def test_pair_fifo_differential(sizes, rnd):
    """Random message sizes pumped through a 4KB ring == a FIFO byte queue:
    same bytes, same order, regardless of wraps/partials/credit timing."""
    a, b = create_loopback_pair(ring_size=4096, domain=LocalDomain())
    try:
        sent = bytearray()
        got = bytearray()
        payloads = [bytes([i % 256]) * n for i, n in enumerate(sizes)]
        total = sum(len(p) for p in payloads)
        pi, off = 0, 0
        stall = 0
        while len(got) < total and stall < 10000:
            # writer side: push as much of the current payload as accepted
            if pi < len(payloads):
                p = payloads[pi]
                if off < len(p) or len(p) == 0:
                    n = a.send([p], off)
                    off += n
                if off >= len(p):
                    sent.extend(p)
                    pi += 1
                    off = 0
            # reader side: sometimes drain, sometimes not (credit jitter)
            if rnd.random() < 0.7:
                chunk = b.recv(max_bytes=rnd.randrange(1, 5000))
                got.extend(chunk)
                if not chunk:
                    stall += 1
                else:
                    stall = 0
            else:
                stall += 1
        # final drain
        deadline = 10000
        while len(got) < total and deadline:
            got.extend(b.recv())
            deadline -= 1
        assert bytes(got) == bytes(b"".join(payloads))
    finally:
        a.destroy()
        b.destroy()


jax = pytest.importorskip("jax")


def _words(rnd, lo, hi):
    return 4 * rnd.randrange(lo // 4, hi // 4 + 1)


@settings(**_SETTINGS)
@given(st.randoms(use_true_random=False))
def test_ring_window_oracle_randomized(rnd):
    from tpurpc.ops.ring_window import ring_window, ring_window_reference

    import jax.numpy as jnp

    cap = 1 << rnd.randrange(13, 16)  # 8KB..32KB
    buf = np.random.default_rng(rnd.randrange(1 << 30)).integers(
        0, 256, cap, dtype=np.uint8)
    head = _words(rnd, 0, cap - 4)
    n = _words(rnd, 4, cap)
    want = ring_window_reference(buf, head, n)
    got = np.asarray(ring_window(jnp.asarray(buf), head, n, interpret=True))
    np.testing.assert_array_equal(got, want)


@settings(**_SETTINGS)
@given(st.randoms(use_true_random=False))
def test_ring_scatter_oracle_randomized(rnd):
    from tpurpc.ops.ring_scatter import (ring_scatter,
                                         ring_scatter_reference)

    import jax.numpy as jnp

    cap = 1 << rnd.randrange(14, 16)  # 16KB..32KB (>= two RMW windows)
    rng = np.random.default_rng(rnd.randrange(1 << 30))
    ring0 = rng.integers(0, 256, cap, dtype=np.uint8)
    start = _words(rnd, 0, cap - 4)
    n = _words(rnd, 4, cap)
    pay = rng.integers(0, 256, n, dtype=np.uint8)
    want = ring_scatter_reference(ring0, pay, start)
    got = np.asarray(ring_scatter(jnp.asarray(ring0), jnp.asarray(pay),
                                  start, interpret=True))
    np.testing.assert_array_equal(got, want)
