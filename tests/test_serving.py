"""tpurpc-cadence (ISSUE 10): the continuous-batching decode scheduler.

The acceptance claim — batching is demonstrably CONTINUOUS — plus the
scheduler's edge cases: join-during-step, leave-mid-stream, idle→wake,
poison isolation, drain-during-decode, SLO priority + preemption, and
class-aware shedding; then the transport face (per-token streaming over
RPC, shed → UNAVAILABLE + pushback, /healthz state lines) and the
AdmissionGate's new step-time latency hook."""

import gc
import threading
import time

import numpy as np
import pytest

from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
from tpurpc.obs import flight, watchdog
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.server import PUSHBACK_KEY, AdmissionGate
from tpurpc.rpc.status import RpcError, StatusCode
from tpurpc.serving import (SLO_BATCH, SLO_INTERACTIVE, DecodeScheduler,
                            DrainingError, GenerationClient, ShedError,
                            serve_generation)
from tpurpc.serving.scheduler import TokenStream


@pytest.fixture(autouse=True)
def _fast_streams():
    """A broken scheduler must fail the test, not hang the suite."""
    old = TokenStream.MAX_IDLE_S
    TokenStream.MAX_IDLE_S = 10.0
    yield
    TokenStream.MAX_IDLE_S = old


def _sched(model=None, **kw):
    kw.setdefault("idle_wait_s", 0.01)
    return DecodeScheduler(model or ToyDecodeModel(), **kw)


def _poll(pred, timeout=5.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return pred()


# -- the model contract -------------------------------------------------------

def test_toy_model_matches_reference():
    m = ToyDecodeModel()
    states, toks = m.prefill([np.asarray([3, 1, 4], np.int32)])
    out = [int(toks[0])]
    for _ in range(7):
        states, toks = m.step(states, np.asarray(toks, np.int32))
        out.append(int(toks[0]))
    assert out == reference_decode([3, 1, 4], 8)


def test_toy_model_rows_independent():
    """Batched step == per-row steps: the property the scheduler's
    re-batching (join/leave/preempt) and isolation retry rely on."""
    m = ToyDecodeModel()
    sa, ta = m.prefill([np.asarray([1], np.int32)])
    sb, tb = m.prefill([np.asarray([2], np.int32)])
    both, tboth = m.prefill([np.asarray([1], np.int32),
                             np.asarray([2], np.int32)])
    assert int(tboth[0]) == int(ta[0]) and int(tboth[1]) == int(tb[0])
    s2, t2 = m.step(both, tboth)
    sa2, ta2 = m.step(sa, ta)
    assert int(t2[0]) == int(ta2[0])


# -- basic streaming ----------------------------------------------------------

def test_single_sequence_streams_reference_tokens():
    s = _sched()
    try:
        assert list(s.submit([1, 2, 3], max_tokens=16)) == \
            reference_decode([1, 2, 3], 16)
    finally:
        s.close()


def test_eos_stops_early():
    # pick an eos that actually occurs in the stream
    full = reference_decode([7], 64)
    eos = full[5]
    s = _sched(ToyDecodeModel(eos=eos))
    try:
        got = list(s.submit([7], max_tokens=64))
        assert got == full[:full.index(eos) + 1]
    finally:
        s.close()


def test_many_concurrent_sequences_no_crosstalk():
    s = _sched(max_batch=4)
    try:
        handles = {i: s.submit([i, i + 1], max_tokens=24)
                   for i in range(10)}
        for i, h in handles.items():
            assert list(h) == reference_decode([i, i + 1], 24), i
        assert s.steps > 0 and s.tokens_out >= 10 * 24
    finally:
        s.close()


# -- ACCEPTANCE: continuous batching is continuous ----------------------------

def test_join_mid_decode_streams_first_token_before_batch_drains():
    """A request admitted mid-decode joins the running device batch
    within one step boundary — flight shows its `gen-join` BETWEEN two
    step events — and streams its first token while the earlier request
    is still generating (no waiting for the batch to drain)."""
    flight.RECORDER.reset()
    s = _sched(ToyDecodeModel(step_delay_s=0.003), max_batch=4,
               idle_wait_s=0.005)
    try:
        a = s.submit([1], max_tokens=400)
        for _ in range(10):            # A is mid-decode, far from done
            a.next(timeout=5)
        steps_at_submit = s.steps
        b = s.submit([2], max_tokens=4)
        first_b = b.next(timeout=5)
        steps_at_first = s.steps
        assert first_b == reference_decode([2], 1)[0]
        # B's first token did NOT wait for A's 400-token stream
        assert a.emitted < 400
        # join landed within one step boundary of the submit (one step
        # may already be in flight when submit lands, plus the boundary
        # that admits B and the step that follows it)
        assert steps_at_first - steps_at_submit <= 3, \
            (steps_at_submit, steps_at_first)
        ev = flight.snapshot()
        joins = [e for e in ev
                 if e["event"] == "gen-join" and e["a1"] == b.sid]
        assert joins, "no gen-join for the mid-decode request"
        t_join = joins[0]["t_ns"]
        steps = [e for e in ev
                 if e["event"] in ("gen-step-begin", "gen-step-end")]
        assert any(e["t_ns"] < t_join for e in steps), \
            "no step events before the join: batch was not running"
        assert any(e["t_ns"] > t_join for e in steps), \
            "no step events after the join: batch drained instead"
        # and A kept streaming correct values across the membership change
        rest = [a.next(timeout=5) for _ in range(10)]
        assert [a_tok for a_tok in rest] == \
            reference_decode([1], 400)[10:20]
        a.cancel()
        list(b)
    finally:
        s.close()


def test_join_during_step_lands_next_boundary():
    """Submit while a step is EXECUTING: the join must not corrupt the
    in-flight step and lands at the next boundary."""
    gate = threading.Event()
    release = threading.Event()

    class GateModel(ToyDecodeModel):
        def step(self, states, tokens):
            gate.set()                 # the test knows a step is running
            release.wait(5)
            return super().step(states, tokens)

    s = _sched(GateModel(), max_batch=4, idle_wait_s=0.005)
    try:
        a = s.submit([1], max_tokens=6)
        assert gate.wait(5)            # step 1 in flight
        b = s.submit([2], max_tokens=6)   # joins while stepping
        release.set()
        assert list(a) == reference_decode([1], 6)
        assert list(b) == reference_decode([2], 6)
    finally:
        release.set()
        s.close()


# -- leave / idle / poison / drain -------------------------------------------

def test_leave_mid_stream_retires_at_boundary_without_stalling_siblings():
    flight.RECORDER.reset()
    s = _sched(max_batch=4)
    try:
        a = s.submit([1], max_tokens=5000)
        b = s.submit([2], max_tokens=40)
        for _ in range(5):
            a.next(timeout=5)
        a.cancel()
        # the sibling's stream is unaffected, values exact
        assert list(b) == reference_decode([2], 40)
        ev = _poll(lambda: [e for e in flight.snapshot()
                            if e["event"] == "gen-leave"
                            and e["a1"] == a.sid])
        assert ev, "no gen-leave for the cancelled sequence"
        # the scheduler dropped it from the running batch
        assert _poll(lambda: s.running_depth() == 0)
    finally:
        s.close()


def test_idle_scheduler_wakes_on_submit():
    s = _sched(idle_wait_s=0.5)   # long idle slice: the wake must be the
    try:                          # kick, not the timeout
        list(s.submit([1], max_tokens=2))
        time.sleep(0.05)
        n0 = s.steps
        time.sleep(0.2)
        assert s.steps == n0, "idle scheduler kept stepping"
        t0 = time.monotonic()
        h = s.submit([2], max_tokens=3)
        first = h.next(timeout=5)
        assert time.monotonic() - t0 < 0.4, "wake waited out the idle slice"
        assert first == reference_decode([2], 1)[0]
        list(h)
    finally:
        s.close()


def test_poisoned_sequence_fails_alone():
    """A poisoned row fails the BATCHED step; the scheduler's row-by-row
    retry fails only the poisoned sequence — siblings' streams complete
    with exact values (PR 3/7 poison discipline, decode edition)."""
    s = _sched(ToyDecodeModel(poison_token=666), max_batch=4)
    try:
        good1 = s.submit([3], max_tokens=20)
        bad = s.submit([666], max_tokens=20)
        good2 = s.submit([4], max_tokens=20)
        assert list(good1) == reference_decode([3], 20)
        assert list(good2) == reference_decode([4], 20)
        with pytest.raises(ValueError, match="poison"):
            list(bad)
    finally:
        s.close()


def test_drain_finishes_inflight_and_refuses_new():
    s = _sched(ToyDecodeModel(step_delay_s=0.002), max_batch=4)
    try:
        a = s.submit([1], max_tokens=60)
        for _ in range(3):
            a.next(timeout=5)
        s.drain()
        with pytest.raises(DrainingError):
            s.submit([2], max_tokens=5)
        # in-flight sequence runs to completion
        rest = list(a)
        assert [*reference_decode([1], 60)][3:] == rest
        assert s.state_str() == "draining"
    finally:
        s.close()


def test_drain_refuses_already_queued_prefills():
    """Sequences still WAITING (never prefillled) when the drain lands
    are refused, not stranded."""
    gate = threading.Event()

    class SlowPrefill(ToyDecodeModel):
        def step(self, states, tokens):
            gate.wait(2)
            return super().step(states, tokens)

    s = _sched(SlowPrefill(), max_batch=1, idle_wait_s=0.005)
    try:
        a = s.submit([1], max_tokens=50)   # occupies the whole batch
        a.next(timeout=5)
        b = s.submit([2], max_tokens=5)    # parked waiting
        s.drain()
        gate.set()
        with pytest.raises(DrainingError):
            list(b)
        a.cancel()
    finally:
        gate.set()
        s.close()


# -- SLO classes: priority, preemption, shedding ------------------------------

def test_interactive_admitted_before_earlier_batch_submit():
    gate = threading.Event()

    class Gated(ToyDecodeModel):
        def step(self, states, tokens):
            gate.wait(2)
            return super().step(states, tokens)

    flight.RECORDER.reset()
    s = _sched(Gated(), max_batch=1, idle_wait_s=0.005)
    try:
        a = s.submit([1], max_tokens=2)
        _poll(lambda: s.running_depth() == 1)
        b_batch = s.submit([2], max_tokens=2, slo=SLO_BATCH)
        c_inter = s.submit([3], max_tokens=2, slo=SLO_INTERACTIVE)
        gate.set()
        list(a), list(b_batch), list(c_inter)
        ev = flight.snapshot()
        joins = [e["a1"] for e in ev if e["event"] == "gen-join"]
        # interactive (later submit) joined before the batch-class one
        assert joins.index(c_inter.sid) < joins.index(b_batch.sid), joins
    finally:
        gate.set()
        s.close()


def test_preemption_makes_room_and_preempted_resumes_exact():
    flight.RECORDER.reset()
    s = _sched(ToyDecodeModel(step_delay_s=0.002), max_batch=2,
               idle_wait_s=0.005)
    try:
        b1 = s.submit([1], max_tokens=300, slo=SLO_BATCH)
        b2 = s.submit([2], max_tokens=300, slo=SLO_BATCH)
        for _ in range(4):
            b1.next(timeout=5)
        inter = s.submit([3], max_tokens=6, slo=SLO_INTERACTIVE)
        got = list(inter)
        assert got == reference_decode([3], 6)
        ev = flight.snapshot()
        pre = [e for e in ev if e["event"] == "gen-preempt"]
        assert pre, "interactive never preempted the full batch-class batch"
        assert pre[0]["a2"] == 1  # the preempted row was batch-class
        assert s.preempted_total >= 1
        # the preempted sequence RESUMES (no re-prefill) and its stream
        # stays value-exact across preempt/resume
        b1.cancel()
        b2.cancel()
    finally:
        s.close()


def test_preempted_stream_values_survive_resume():
    s = _sched(ToyDecodeModel(step_delay_s=0.001), max_batch=1,
               idle_wait_s=0.005)
    try:
        long = s.submit([9], max_tokens=50, slo=SLO_BATCH)
        for _ in range(5):
            long.next(timeout=5)
        quick = s.submit([4], max_tokens=4, slo=SLO_INTERACTIVE)
        assert list(quick) == reference_decode([4], 4)
        # the preempted batch stream finishes with the exact remainder
        rest = list(long)
        assert [*reference_decode([9], 50)][5:] == rest
    finally:
        s.close()


def test_shed_batch_first_interactive_still_admitted():
    flight.RECORDER.reset()
    gate = threading.Event()

    class Gated(ToyDecodeModel):
        def step(self, states, tokens):
            gate.wait(2)
            return super().step(states, tokens)

    s = _sched(Gated(), max_batch=1, max_waiting=6, batch_shed_depth=2,
               idle_wait_s=0.005)
    try:
        running = s.submit([1], max_tokens=50)
        _poll(lambda: s.running_depth() == 1)
        w1 = s.submit([2], max_tokens=2)
        w2 = s.submit([3], max_tokens=2)
        # batch class sheds at its bar (2 waiting)...
        with pytest.raises(ShedError) as ei:
            s.submit([4], max_tokens=2, slo=SLO_BATCH)
        assert ei.value.pushback_ms > 0 and ei.value.slo == SLO_BATCH
        # ...while interactive is still admitted at the same depth
        w3 = s.submit([5], max_tokens=2, slo=SLO_INTERACTIVE)
        assert s.shed_total == 1
        assert any(e["event"] == "gen-shed" and e["a1"] == 1
                   for e in flight.snapshot())
        assert s.state_str() == "shedding"
        # interactive sheds only at the full bar
        for i in range(6, 20):
            try:
                s.submit([i], max_tokens=2)
            except ShedError as exc:
                assert exc.slo == SLO_INTERACTIVE
                break
        else:
            pytest.fail("interactive never shed at the full bar")
        running.cancel()
        gate.set()
        list(w1), list(w2), list(w3)
    finally:
        gate.set()
        s.close()


def test_step_time_slo_sheds_batch_class():
    s = _sched(ToyDecodeModel(step_delay_s=0.02), max_batch=1,
               max_waiting=64, batch_shed_depth=64, step_slo_ms=1.0,
               idle_wait_s=0.005)
    try:
        a = s.submit([1], max_tokens=100)
        _poll(lambda: s.steps >= 3)      # EWMA has seen slow steps
        s.submit([2], max_tokens=2)      # one waiter (depth > 0)
        with pytest.raises(ShedError, match="step time over SLO"):
            s.submit([3], max_tokens=2, slo=SLO_BATCH)
        a.cancel()
    finally:
        s.close()


# -- prefill token budget -----------------------------------------------------

def test_prefill_budget_staggers_joins_but_all_complete():
    flight.RECORDER.reset()
    s = _sched(ToyDecodeModel(step_delay_s=0.001), max_batch=8,
               prefill_budget=8, idle_wait_s=0.005)
    try:
        # 4 prompts of 6 tokens each: at most one fits the per-step
        # budget (6 <= 8 but 12 > 8), so joins spread across boundaries
        handles = [s.submit([i] * 6, max_tokens=10) for i in range(4)]
        for i, h in enumerate(handles):
            assert list(h) == reference_decode([i] * 6, 10)
        ev = [e for e in flight.snapshot() if e["event"] == "gen-join"]
        assert len(ev) == 4
    finally:
        s.close()


def test_oversized_prompt_still_admitted_alone():
    s = _sched(prefill_budget=4)
    try:
        assert list(s.submit([1] * 64, max_tokens=5)) == \
            reference_decode([1] * 64, 5)
    finally:
        s.close()


# -- watchdog: the decode-step stage ------------------------------------------

def test_watchdog_names_decode_step_for_wedged_step():
    flight.RECORDER.reset()
    wedge = threading.Event()

    class Wedged(ToyDecodeModel):
        def step(self, states, tokens):
            wedge.wait(3)
            return super().step(states, tokens)

    wd = watchdog.StallWatchdog(sweep_s=10, mult=8, min_stall_s=0.2)
    wd.enabled = True
    s = _sched(Wedged(), idle_wait_s=0.005)
    try:
        tok = wd.call_started("/tpurpc.Generate/Generate")
        h = s.submit([1], max_tokens=5)
        _poll(lambda: [e for e in flight.snapshot()
                       if e["event"] == "gen-step-begin"])
        time.sleep(0.35)                 # past the stall bar, step open
        diags = wd.sweep_once()
        assert diags and diags[0]["stage"] == "decode-step", diags
        assert "wedged" in diags[0]["detail"]
        wedge.set()
        list(h)
        wd.call_finished(tok)
    finally:
        wedge.set()
        s.close()


def test_watchdog_decode_step_when_loop_starved():
    """The other decode failure shape: sequences WAITING but the loop
    never completes a step inside the stall window."""
    gc.collect()
    flight.RECORDER.reset()
    hold = threading.Event()

    class WedgedPrefill(ToyDecodeModel):
        def prefill(self, prompts):
            hold.wait(3)
            return super().prefill(prompts)

    wd = watchdog.StallWatchdog(sweep_s=10, mult=8, min_stall_s=0.2)
    wd.enabled = True
    s = _sched(WedgedPrefill(), max_batch=1, idle_wait_s=0.005)
    try:
        tok = wd.call_started("/tpurpc.Generate/Generate")
        a = s.submit([1], max_tokens=3)     # loop wedges in its prefill
        b = s.submit([2], max_tokens=3)     # parked waiting
        time.sleep(0.35)
        diags = wd.sweep_once()
        assert diags and diags[0]["stage"] == "decode-step", diags
        assert "waiting" in diags[0]["detail"]
        hold.set()
        list(a), list(b)
        wd.call_finished(tok)
    finally:
        hold.set()
        s.close()


# -- AdmissionGate: the step-time latency hook --------------------------------

def test_admission_gate_latency_fn_overrides_watchdog_signal():
    sig = [0.5]
    gate = AdmissionGate(8, soft_limit=2, latency_slo_ms=10.0,
                         latency_ms_fn=lambda: sig[0])
    assert gate.try_admit() is None
    assert gate.try_admit() is None
    assert gate.try_admit() is None      # between limits, signal healthy
    sig[0] = 50.0                        # step time over SLO
    pb = gate.try_admit()
    assert isinstance(pb, int) and pb > 0
    sig[0] = 0.5
    assert gate.try_admit() is None


def test_admission_gate_latency_fn_failure_never_blocks():
    def broken():
        raise RuntimeError("probe died")

    gate = AdmissionGate(4, soft_limit=1, latency_slo_ms=1.0,
                         latency_ms_fn=broken)
    assert gate.try_admit() is None
    assert gate.try_admit() is None      # broken probe degrades to depth


# -- the transport face -------------------------------------------------------

def test_rpc_stream_tokens_in_order_and_exact():
    srv, port, sched = serve_generation(ToyDecodeModel(), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            pairs = list(gen.generate_with_meta([1, 2], max_tokens=12,
                                                timeout=15))
            assert [i for i, _ in pairs] == list(range(12))
            assert [t for _, t in pairs] == reference_decode([1, 2], 12)
    finally:
        srv.stop(grace=0)
        sched.close()


def test_rpc_concurrent_streams_interleave_without_crosstalk():
    srv, port, sched = serve_generation(
        ToyDecodeModel(step_delay_s=0.001), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            out = {}

            def run(i):
                out[i] = list(gen.generate([i], max_tokens=16, timeout=20))

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i in range(6):
                assert out[i] == reference_decode([i], 16), i
        # the device saw merged batches, not 6 serial streams
        assert sched.steps < 6 * 16
    finally:
        srv.stop(grace=0)
        sched.close()


def test_rpc_client_cancel_is_a_leave():
    flight.RECORDER.reset()
    srv, port, sched = serve_generation(
        ToyDecodeModel(step_delay_s=0.002), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            call = gen.call([1], max_tokens=10000, timeout=60)
            it = iter(call)
            next(it)
            call.cancel()
        ev = _poll(lambda: [e for e in flight.snapshot()
                            if e["event"] == "gen-leave"])
        assert ev, "client cancel never became a scheduler leave"
        assert _poll(lambda: sched.running_depth() == 0)
    finally:
        srv.stop(grace=0)
        sched.close()


def test_rpc_shed_maps_to_unavailable_with_pushback():
    gate = threading.Event()

    class Gated(ToyDecodeModel):
        def step(self, states, tokens):
            gate.wait(3)
            return super().step(states, tokens)

    srv, port, sched = serve_generation(Gated(), max_batch=1,
                                        max_waiting=4, batch_shed_depth=1)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            calls = [gen.call([i], max_tokens=5, timeout=30)
                     for i in range(3)]
            iters = [iter(c) for c in calls]
            _poll(lambda: sched.running_depth() + sched.queue_depth() >= 2)
            with pytest.raises(RpcError) as ei:
                list(gen.generate([9], max_tokens=5, slo=SLO_BATCH,
                                  timeout=10))
            assert ei.value.code() is StatusCode.UNAVAILABLE
            md = dict(ei.value.trailing_metadata() or ())
            assert PUSHBACK_KEY in md and int(md[PUSHBACK_KEY]) > 0
            gate.set()
            for c in calls:
                c.cancel()
    finally:
        gate.set()
        srv.stop(grace=0)
        sched.close()


def test_rpc_poisoned_stream_fails_alone():
    srv, port, sched = serve_generation(
        ToyDecodeModel(poison_token=666), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            good_out = {}

            def good():
                good_out["v"] = list(gen.generate([5], max_tokens=12,
                                                  timeout=20))

            t = threading.Thread(target=good)
            t.start()
            with pytest.raises(RpcError) as ei:
                list(gen.generate([666], max_tokens=12, timeout=20))
            assert ei.value.code() is StatusCode.INTERNAL
            t.join()
            assert good_out["v"] == reference_decode([5], 12)
    finally:
        srv.stop(grace=0)
        sched.close()


def test_rpc_drain_finishes_streams_refuses_new():
    srv, port, sched = serve_generation(
        ToyDecodeModel(step_delay_s=0.005), max_batch=4)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)
            call = gen.call([1], max_tokens=60, timeout=60)
            it = iter(call)
            next(it)
            drained = []
            t = threading.Thread(
                target=lambda: drained.append(srv.drain(linger=15.0)))
            t.start()
            _poll(lambda: srv.draining)
            with pytest.raises(RpcError) as ei:
                with Channel(f"127.0.0.1:{port}") as ch2:
                    list(GenerationClient(ch2).generate([2], max_tokens=3,
                                                        timeout=10))
            assert ei.value.code() is StatusCode.UNAVAILABLE
            # the in-flight stream finishes every token
            rest = sum(1 for _ in it)
            assert 1 + rest == 60
            t.join(timeout=20)
            assert drained == [True]
    finally:
        srv.stop(grace=0)
        sched.close()


def test_healthz_shows_gen_state():
    from tpurpc.obs import scrape

    srv, port, sched = serve_generation(ToyDecodeModel(), max_batch=2,
                                        max_waiting=4, batch_shed_depth=1)
    try:
        status, _ctype, body = scrape.route_local("/healthz")
        assert status == 200
        text = body.decode()
        assert f"gen Generate:" in text, text
        assert "state=ok" in text
        # shed flips the visible state
        gate = threading.Event()
        sched.model.step_delay_s = 0.05
        h = sched.submit([1], max_tokens=100)
        sched.submit([2], max_tokens=2)
        with pytest.raises(ShedError):
            sched.submit([3], max_tokens=2, slo=SLO_BATCH)
        status, _ctype, body = scrape.route_local("/healthz")
        assert b"state=shedding" in body, body
        h.cancel()
    finally:
        srv.stop(grace=0)
        sched.close()


def test_load_provider_reports_waiting_plus_swapped():
    """ISSUE 11 satellite fix: the fleet load report must include
    preempted/swapped rows — queue_depth alone made a server holding
    swapped work look idle to least_loaded picking."""
    srv, port, sched = serve_generation(ToyDecodeModel(), max_batch=2)
    try:
        assert srv._load_extra == sched.load_depth  # bound-method equality
        # a swapped sequence counts toward the load signal even though it
        # is in no queue
        sched._swapped.append(object())
        assert sched.load_depth() == sched.queue_depth() + 1
        sched._swapped.clear()
    finally:
        srv.stop(grace=0)
        sched.close()


def test_load_depth_counts_preempted_swapped_rows():
    """End-to-end: preempt a batch-class sequence on a paged scheduler —
    while its KV sits swapped on host, load_depth reports the debt that
    queue_depth omits."""
    from tpurpc.serving.kv import KvBlockManager

    mgr = KvBlockManager(n_blocks=64, block_bytes=256, kind="local",
                         name="loadsig")
    s = _sched(ToyDecodeModel(step_delay_s=0.002), kv=mgr, max_batch=1)
    try:
        long = s.submit([9], max_tokens=4000, slo=SLO_BATCH)
        for _ in range(3):
            long.next(timeout=5)
        quick = s.submit([4], max_tokens=50, slo=SLO_INTERACTIVE)
        # the batch row is preempted to host while interactive runs
        assert _poll(lambda: s.swapped_depth() == 1), s.swapped_depth()
        assert s.load_depth() >= 1
        assert s.queue_depth() == 0  # the omission the fix closes
        quick.cancel()
        long.cancel()
    finally:
        s.close()
        mgr.close()
