"""Chaos: the stack under concurrent churn, garbage peers, and server death.

The reference's robustness properties (SURVEY §4/§5: peer_exit handling,
reconnect-on-UNAVAILABLE, bounded bootstrap, misconfiguration surfacing as
clear errors) exercised adversarially rather than one case at a time.
"""

import os
import random
import socket
import threading
import time

import pytest

import tpurpc.rpc as tps
from tpurpc.rpc.status import RpcError, StatusCode


def _echo_server(platform=None, **kw):
    srv = tps.Server(max_workers=8, **kw)
    srv.add_method("/c.S/Echo",
                   tps.unary_unary_rpc_method_handler(lambda req, ctx: req))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_garbage_and_churn_peers_dont_break_service(monkeypatch, platform):
    """While real clients run traffic, hostile peers connect and send
    garbage / connect and vanish / open-close rapidly. Service must stay
    correct throughout and afterwards."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _echo_server()
    stop = threading.Event()
    errors: list = []

    def good_client(idx: int):
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                mc = ch.unary_unary("/c.S/Echo")
                i = 0
                while not stop.is_set():
                    payload = f"{idx}-{i}".encode()
                    assert bytes(mc(payload, timeout=30)) == payload
                    i += 1
                assert i > 0, "client made no progress"
        except Exception as exc:
            errors.append(exc)

    def garbage_peer():
        rng = random.Random(1234)
        while not stop.is_set():
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=5)
                mode = rng.randrange(3)
                if mode == 0:
                    s.sendall(rng.randbytes(rng.randrange(1, 256)))
                elif mode == 1:
                    pass  # connect-and-vanish (silent peer)
                # mode 2: immediate close
                s.close()
            except OSError:
                pass
            time.sleep(0.02)

    clients = [threading.Thread(target=good_client, args=(i,))
               for i in range(3)]
    chaos = threading.Thread(target=garbage_peer, daemon=True)
    try:
        [t.start() for t in clients]
        chaos.start()
        time.sleep(4.0)
    finally:
        stop.set()
        [t.join(timeout=60) for t in clients]
    assert not errors, errors
    # the server is still healthy after the storm
    with tps.Channel(f"127.0.0.1:{port}") as ch:
        assert bytes(ch.unary_unary("/c.S/Echo")(b"after", timeout=20)) == b"after"
    srv.stop(grace=0)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_server_death_reconnect_flight_sequence(monkeypatch, platform):
    """tpurpc-blackbox (ISSUE 5): the flight recorder must replay the
    server-death/reconnect story IN ORDER — connection death, the
    subchannel's re-dial, and the first successful call on the fresh
    connection — on both the TCP and ring (RDMA_BPEV) platforms. This is
    the postmortem the recorder exists for: after the incident, the event
    ring alone reconstructs what happened and when."""
    from tpurpc.obs import flight

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()

    srv, port = _echo_server()
    with tps.Channel(f"127.0.0.1:{port}") as ch:
        # pin to the Python transport: the lifecycle events under test
        # (conn-dead / reconnect / call-first-ok) are its subchannel
        # machinery; the native fast path has its own (slower) death
        # detection that would only add timing noise here
        mc = ch.unary_unary("/c.S/Echo", tpurpc_native=False)
        assert bytes(mc(b"warm", timeout=30)) == b"warm"
        t_kill = time.monotonic_ns()
        srv.stop(grace=0)
        # the in-flight-less death may surface on the next call attempt
        with pytest.raises(RpcError):
            for _ in range(20):
                mc(b"probe", timeout=5)
                time.sleep(0.05)
        # revive a server on the SAME port; the channel's backoff redials
        deadline = time.monotonic() + 20
        srv2 = None
        while srv2 is None and time.monotonic() < deadline:
            try:
                srv2 = tps.Server(max_workers=4)
                srv2.add_method("/c.S/Echo", tps.unary_unary_rpc_method_handler(
                    lambda req, ctx: req))
                srv2.add_insecure_port(f"127.0.0.1:{port}")
                srv2.start()
            except OSError:
                srv2 = None
                time.sleep(0.2)
        assert srv2 is not None, "could not rebind the port"
        try:
            deadline = time.monotonic() + 20
            while True:
                try:
                    assert bytes(mc(b"back", timeout=5)) == b"back"
                    break
                except RpcError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.1)
            # the ordered postmortem: death -> re-dial -> first OK call,
            # all after the kill stamp (warmup events precede it) — the
            # cross-entity order via the ONE protocol helper, the
            # per-entity legality via the declared machines (ISSUE 12)
            from tpurpc.analysis import protocol

            snap = flight.snapshot()
            protocol.assert_ordered(
                snap, ["conn-dead", "reconnect", "call-first-ok"],
                since_ns=t_kill)
            assert protocol.check_events(snap, strict=False) == []
        finally:
            srv2.stop(grace=0)


def test_server_death_mid_streams_fails_calls_cleanly():
    """Kill the server while many streaming calls are in flight: every call
    must terminate with a status (UNAVAILABLE/CANCELLED), never hang."""
    srv = tps.Server(max_workers=8)

    hold = threading.Event()

    def trickle(req, ctx):
        for i in range(10_000):
            if not ctx.is_active():
                return
            yield str(i).encode()
            hold.wait(timeout=0.01)

    srv.add_method("/c.S/Trickle", tps.unary_stream_rpc_method_handler(trickle))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()

    outcomes: list = []

    def consumer():
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                got = 0
                for _ in ch.unary_stream("/c.S/Trickle")(b"", timeout=60):
                    got += 1
                outcomes.append(("finished", got))
        except RpcError as exc:
            outcomes.append(("status", exc.code()))
        except Exception as exc:
            outcomes.append(("error", exc))

    threads = [threading.Thread(target=consumer) for _ in range(4)]
    [t.start() for t in threads]
    time.sleep(1.0)           # streams established and flowing
    srv.stop(grace=0)         # yank the server
    [t.join(timeout=30) for t in threads]
    assert len(outcomes) == 4, outcomes
    for kind, detail in outcomes:
        assert kind == "status", (kind, detail)
        assert detail in (StatusCode.UNAVAILABLE, StatusCode.CANCELLED), detail


def test_channel_churn_during_traffic(monkeypatch):
    """Rapid open/close of channels (pool take/putback churn on the ring
    platform) while a steady client runs: no cross-talk, no corruption."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    srv, port = _echo_server()
    stop = threading.Event()
    errors: list = []

    def steady():
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                mc = ch.unary_unary("/c.S/Echo")
                i = 0
                while not stop.is_set():
                    payload = os.urandom(1024)
                    assert bytes(mc(payload, timeout=30)) == payload
                    i += 1
                assert i > 3
        except Exception as exc:
            errors.append(exc)

    def churner():
        try:
            while not stop.is_set():
                with tps.Channel(f"127.0.0.1:{port}") as ch:
                    assert bytes(ch.unary_unary("/c.S/Echo")(
                        b"x", timeout=30)) == b"x"
        except Exception as exc:
            errors.append(exc)

    ts = [threading.Thread(target=steady),
          threading.Thread(target=churner), threading.Thread(target=churner)]
    try:
        [t.start() for t in ts]
        time.sleep(4.0)
    finally:
        stop.set()
        [t.join(timeout=60) for t in ts]
    assert not errors, errors
    srv.stop(grace=0)


def test_churn_with_full_connection_management(monkeypatch):
    """All connection-management machinery at once, under churn: keepalive
    both sides + client_idle + max_age, aggressive windows, ring platform.
    Every call must succeed (GOAWAY/idle races retry transparently); the
    machinery must neither kill live calls nor leak dead connections."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIME_MS", "200")
    monkeypatch.setenv("GRPC_ARG_KEEPALIVE_TIMEOUT_MS", "400")
    monkeypatch.setenv("GRPC_ARG_CLIENT_IDLE_TIMEOUT_MS", "300")
    monkeypatch.setenv("GRPC_ARG_MAX_CONNECTION_AGE_MS", "500")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)

    srv = tps.Server(max_workers=8)

    def echo(req, ctx):
        time.sleep(random.uniform(0, 0.02))
        return bytes(req)

    srv.add_method("/cm.S/Echo", tps.unary_unary_rpc_method_handler(echo))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    stop = threading.Event()
    errors = []
    done = [0] * 3

    def worker(idx):
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                mc = ch.unary_unary("/cm.S/Echo")
                while not stop.is_set():
                    payload = os.urandom(256)
                    assert bytes(mc(payload, timeout=30)) == payload
                    done[idx] += 1
                    if done[idx] % 7 == 0:
                        time.sleep(random.uniform(0, 0.4))  # idle gaps
        except Exception as exc:
            errors.append(exc)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    try:
        [t.start() for t in ts]
        time.sleep(6.0)
    finally:
        stop.set()
        [t.join(timeout=60) for t in ts]
    assert not errors, errors
    assert all(n > 5 for n in done), done
    srv.stop(grace=0)


def test_connection_churn_soak_no_leak(monkeypatch):
    """Steady-state resource flatness under connection churn: after a
    warm-up phase, hundreds more churned connections must not grow
    threads or RSS (the 4-minute manual soak showed flat 195-206MB over
    9.4K connections; this is its bounded CI regression)."""
    import gc
    import os
    import threading

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    import tpurpc.rpc as rpc
    from tpurpc.rpc.channel import Channel

    def rss_kb():
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1])

    srv = rpc.Server(max_workers=8)
    srv.add_method("/soak.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        def churn(n, calls=20):
            for _ in range(n):
                with Channel(f"127.0.0.1:{port}") as ch:
                    e = ch.unary_unary("/soak.S/Echo")
                    for _ in range(calls):
                        e(b"x" * 512, timeout=30)

        def settled_threads(timeout=5.0):
            # per-connection sniff/reader threads die asynchronously after
            # a churn burst; sample the SETTLED count, not the in-flight
            # transient — otherwise the assert races thread teardown
            import time as _t

            end = _t.monotonic() + timeout
            low = threading.active_count()
            while _t.monotonic() < end:
                _t.sleep(0.1)
                low = min(low, threading.active_count())
            return low

        churn(60)  # warm: pools, pairs, worker threads reach steady state
        gc.collect()
        base_threads, base_rss = settled_threads(), rss_kb()
        churn(240)
        gc.collect()
        dt_threads = settled_threads() - base_threads
        dt_rss = rss_kb() - base_rss
        # Shared pools (handler executor, blocking-ops, timer wheel) grow
        # lazily toward their caps — observed +4-5 across the measured
        # phase. The guard is against PER-CONNECTION leakage: 240 churned
        # connections leaking even one thread each would be +240.
        assert dt_threads <= 12, f"thread growth {dt_threads}"
        # generous for allocator jitter on a loaded CI host; a real
        # per-connection leak at even 2KB would show ~0.5MB here on top
        # of noise that measured +-10MB — this guards order-of-magnitude
        # regressions (forgotten pairs/rings/threads), not bytes
        assert dt_rss < 60_000, f"RSS grew {dt_rss}KB over 240 connections"
    finally:
        srv.stop(grace=0)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_fleet_drain_zero_failed_rpcs(monkeypatch, platform):
    """tpurpc-fleet (ISSUE 6) acceptance: a 3-server fleet under steady
    pipelined traffic, one server drained mid-flight — ZERO failed RPCs,
    the drain completes within its linger budget, migration is visible
    (the drained server receives no calls afterwards), and the flight
    ring replays drain-begin → drain-end in order."""
    from tpurpc.obs import flight

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()

    rigs = []
    for i in range(3):
        # native_dataplane=False: the drain machinery under test (GOAWAY +
        # refused-stream migration) is the Python plane's
        srv = tps.Server(max_workers=8, native_dataplane=False)
        calls = []

        def handler(req, ctx, _c=calls):
            _c.append(1)
            time.sleep(0.002)
            return req

        srv.add_method("/fd.S/Echo",
                       tps.unary_unary_rpc_method_handler(handler))
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        rigs.append((srv, port, calls))
    addrs = ",".join(f"127.0.0.1:{p}" for _, p, _ in rigs)
    try:
        with tps.Channel(f"ipv4:{addrs}", lb_policy="round_robin") as ch:
            pipe = ch.unary_unary("/fd.S/Echo").pipeline(depth=4)
            futs = []
            drain_result = []

            def drainer():
                drain_result.append(rigs[1][0].drain(linger=10.0))

            t_end = time.monotonic() + 0.6
            while time.monotonic() < t_end:
                futs.append(pipe.call_async(b"x", timeout=30))
                time.sleep(0.002)
            dt = threading.Thread(target=drainer)
            t_drain = time.monotonic_ns()
            dt.start()
            t_end = time.monotonic() + 1.2
            while time.monotonic() < t_end:
                futs.append(pipe.call_async(b"x", timeout=30))
                time.sleep(0.002)
            dt.join(timeout=30)
            # zero failed RPCs: every future resolves OK
            for f in futs:
                assert bytes(f.result(timeout=30)) == b"x"
            assert drain_result == [True], "drain missed its linger budget"
            # migration: the drained server gets NO further traffic
            settled = len(rigs[1][2])
            more = [pipe.call_async(b"y", timeout=30) for _ in range(30)]
            for f in more:
                assert bytes(f.result(timeout=30)) == b"y"
            assert len(rigs[1][2]) == settled, "drained server saw traffic"
            assert len(rigs[0][2]) + len(rigs[2][2]) > 0
            pipe.close()
        from tpurpc.analysis import protocol

        snap = flight.snapshot()
        protocol.assert_ordered(snap, ["drain-begin", "drain-end"],
                                since_ns=t_drain)
        assert protocol.check_events(snap, strict=False) == []
    finally:
        for srv, _, _ in rigs:
            srv.stop(grace=0)
        config_mod.set_config(None)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_partition_peer_stops_reading_names_stage(monkeypatch, platform):
    """Chaos scenario: network partition mid-stream — the peer stays
    connected but stops reading. The server handler wedges in the
    transport write; the watchdog must diagnose it (naming a write-side
    stage on the ring plane, where the flight ring carries the credit
    evidence) and the flight sequence must be ordered."""
    from tpurpc.obs import flight, watchdog

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    # pin the FRAMED plane: 256 KiB chunks are at the rendezvous size bar,
    # and a partition mid-bulk-transfer is the rendezvous plane's own
    # scenario (test_rendezvous_peer_death_releases_claimed_region) with
    # its own watchdog stage — this test exists for the ring-credit
    # evidence path
    monkeypatch.setenv("TPURPC_RENDEZVOUS", "0")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s, wd.mult)
    wd.min_stall_s, wd.sweep_s, wd.mult = 0.3, 0.1, 8.0

    srv = tps.Server(max_workers=4, native_dataplane=False)
    chunk = b"\x5a" * (256 * 1024)

    def firehose(req, ctx):
        for _ in range(100_000):
            if not ctx.is_active():
                return
            yield chunk

    srv.add_method("/pt.S/Hose", tps.unary_stream_rpc_method_handler(firehose))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    t_start = time.monotonic_ns()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            call = ch.unary_stream("/pt.S/Hose", tpurpc_native=False)(
                b"", timeout=60)
            it = iter(call)
            for _ in range(3):
                next(it)  # stream established and flowing
            # ... then the partition: this peer never reads again. The
            # client's per-stream credits fill, its reader stops draining
            # the transport, and the server's writer wedges.
            diag = None
            deadline = time.monotonic() + 15
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.15)
                for d in wd.sweep_once():
                    if d["method"] == "/pt.S/Hose":
                        diag = d
                        break
            assert diag is not None, "watchdog never diagnosed the wedge"
            assert diag["stage"] in watchdog.STAGES
            assert diag["stage"] != "unknown"
            if platform == "RDMA_BPEV":
                # the ring plane carries the credit evidence: the stage
                # must name the write side, and the flight ring must hold
                # the starvation edge that justified it
                assert diag["stage"] in ("credit-starvation",
                                         "peer-not-reading"), diag
                from tpurpc.analysis import protocol

                protocol.assert_ordered(
                    flight.snapshot(),
                    [(("credit-starve-begin", "write-stall-begin"), {})],
                    since_ns=t_start)
            # the trip itself is flight evidence on BOTH planes, ordered
            # after the stream began
            from tpurpc.analysis import protocol

            protocol.assert_ordered(flight.snapshot(), ["watchdog-trip"],
                                    since_ns=t_start)
            call.cancel()
    finally:
        wd.min_stall_s, wd.sweep_s, wd.mult = prev
        wd.reset()
        srv.stop(grace=0)
        config_mod.set_config(None)
        # leave no wedged pair behind: a write-stalled fleet gauge that
        # outlives this test would skew the NEXT test's stage attribution
        from tpurpc.obs import metrics as _metrics

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            gauge = _metrics.registry().metrics().get("pairs_write_stalled")
            if gauge is None or gauge.collect()[0] == 0:
                break
            time.sleep(0.1)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_slow_peer_names_device_infer_stage(monkeypatch, platform):
    """Chaos scenario: slow peer — an artificially delayed handler with a
    quiet transport. The watchdog must attribute the stall to the handler
    (device-infer), NOT to a transport stage, and the flight replay must
    order the trip inside the call's lifetime."""
    from tpurpc.obs import flight, watchdog

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    # settle: a wedged pair from a PRIOR test (the partition scenario) must
    # finish dying first, or its write-stall fleet gauge would skew this
    # test's stage attribution toward the transport
    from tpurpc.obs import metrics as _metrics

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        gauge = _metrics.registry().metrics().get("pairs_write_stalled")
        if gauge is None or gauge.collect()[0] == 0:
            break
        time.sleep(0.1)
    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s, wd.mult)
    wd.min_stall_s, wd.sweep_s, wd.mult = 0.3, 0.1, 8.0

    srv = tps.Server(max_workers=4, native_dataplane=False)

    def slow(req, ctx):
        time.sleep(1.2)
        return req

    srv.add_method("/sp.S/Slow", tps.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    t_start = time.monotonic_ns()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/sp.S/Slow", tpurpc_native=False)
            result = []
            t = threading.Thread(
                target=lambda: result.append(bytes(mc(b"z", timeout=30))))
            t.start()
            diag = None
            deadline = time.monotonic() + 10
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.1)
                for d in wd.sweep_once():
                    if d["method"] == "/sp.S/Slow" and d["kind"] == "server":
                        diag = d
                        break
            assert diag is not None, "watchdog never diagnosed the slow peer"
            assert diag["stage"] == "device-infer", diag
            t.join(timeout=30)
            assert result == [b"z"]  # the call itself completes fine
            t_done = time.monotonic_ns()
            from tpurpc.analysis import protocol

            (trip,) = protocol.assert_ordered(
                flight.snapshot(), ["watchdog-trip"], since_ns=t_start)
            assert trip["t_ns"] <= t_done
    finally:
        wd.min_stall_s, wd.sweep_s, wd.mult = prev
        wd.reset()
        srv.stop(grace=0)
        config_mod.set_config(None)


def test_connection_churn_soak_tcpw_domain(monkeypatch):
    """The same churn-flatness guard over the CROSS-HOST tcp_window
    domain: every connection bootstraps a socket-carried one-sided ring,
    so leaked appliers/regions would show up as thread or RSS growth."""
    import gc
    import os
    import threading

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BP")
    monkeypatch.setenv("TPURPC_RING_DOMAIN", "tcp_window")
    monkeypatch.setenv("GRPC_RDMA_RING_BUFFER_SIZE_KB", "256")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    import tpurpc.rpc as rpc
    from tpurpc.rpc.channel import Channel

    def rss_kb():
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1])

    srv = rpc.Server(max_workers=8)
    srv.add_method("/soakw.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        def churn(n, calls=10):
            for _ in range(n):
                with Channel(f"127.0.0.1:{port}") as ch:
                    e = ch.unary_unary("/soakw.S/Echo")
                    for _ in range(calls):
                        e(b"w" * 512, timeout=30)

        def settled_threads(timeout=5.0):
            import time as _t

            end = _t.monotonic() + timeout
            low = threading.active_count()
            while _t.monotonic() < end:
                _t.sleep(0.1)
                low = min(low, threading.active_count())
            return low

        churn(30)
        gc.collect()
        base_threads, base_rss = settled_threads(), rss_kb()
        churn(120)
        gc.collect()
        dt_threads = settled_threads() - base_threads
        dt_rss = rss_kb() - base_rss
        assert dt_threads <= 12, f"thread growth {dt_threads}"
        assert dt_rss < 60_000, f"RSS grew {dt_rss}KB over 120 connections"
    finally:
        srv.stop(grace=0)
        config_mod.set_config(None)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_kill_one_shard_under_pipelined_traffic(monkeypatch, platform):
    """tpurpc-manycore (ISSUE 7): SIGKILL one of two shard workers while
    pipelined depth-4 traffic runs. Contract: in-flight calls on the dead
    shard fail with a STATUS (UNAVAILABLE — never a hang), clients re-dial
    onto the survivor and keep making progress, the supervisor's flight
    ring records shard-death, and the aggregated /metrics drops the dead
    shard's series — on both the TCP and ring (RDMA_BPEV) platforms."""
    import json as _json
    import socket as _socket

    from tpurpc.obs import flight
    from tpurpc.rpc.shard import ShardedServer

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()

    def build(shard_id):
        srv = tps.Server(max_workers=8)
        srv.add_method("/c.S/Who", tps.unary_unary_rpc_method_handler(
            lambda req, ctx: str(shard_id).encode()))
        return srv

    sup = ShardedServer(build, workers=2, listener="reuseport").start()
    stop = threading.Event()
    t_kill = [0]
    progress_after_kill = [0] * 3
    bad_codes: list = []
    hung: list = []

    def client(idx: int):
        while not stop.is_set():
            try:
                with tps.Channel(f"127.0.0.1:{sup.port}") as ch:
                    pl = ch.unary_unary("/c.S/Who",
                                        tpurpc_native=False).pipeline(4)
                    while not stop.is_set():
                        futs = [pl.call_async(b"x", timeout=20)
                                for _ in range(4)]
                        for f in futs:
                            who = bytes(f.result(timeout=25))
                            assert who in (b"0", b"1")
                        if t_kill[0]:
                            progress_after_kill[idx] += 1
            except RpcError as exc:
                if exc.code() not in (StatusCode.UNAVAILABLE,
                                      StatusCode.CANCELLED,
                                      StatusCode.DEADLINE_EXCEEDED):
                    bad_codes.append(exc.code())
                time.sleep(0.05)  # redial
            except (TimeoutError, OSError):
                hung.append(idx)
                return

    threads = [threading.Thread(target=client, args=(i,)) for i in range(3)]
    try:
        [t.start() for t in threads]
        time.sleep(1.5)  # steady traffic on both shards
        victim = sup.alive_workers()[0]
        assert sup.kill_worker(victim)
        t_kill[0] = time.monotonic_ns()
        time.sleep(2.5)  # survivors absorb the re-dials
    finally:
        stop.set()
        [t.join(timeout=60) for t in threads]
    try:
        assert not any(t.is_alive() for t in threads), "client thread hung"
        assert not hung, f"clients timed out instead of failing fast: {hung}"
        assert not bad_codes, f"non-UNAVAILABLE failures: {bad_codes}"
        assert all(n > 0 for n in progress_after_kill), (
            f"a client made no progress after the kill: "
            f"{progress_after_kill}")
        # supervisor postmortem: the death is in the flight ring
        deaths = [e for e in flight.snapshot()
                  if e["event"] == "shard-death"]
        assert [e["a1"] for e in deaths] == [victim], deaths
        # aggregated scrape: the dead shard's series are GONE
        survivor = 1 - victim
        deadline = time.monotonic() + 10
        text = ""
        while time.monotonic() < deadline:
            try:
                with _socket.create_connection(
                        ("127.0.0.1", sup.port), timeout=5) as s:
                    s.settimeout(5)
                    s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
                    buf = bytearray()
                    while True:
                        chunk = s.recv(65536)
                        if not chunk:
                            break
                        buf += chunk
                text = bytes(buf).partition(b"\r\n\r\n")[2].decode()
                if (f'tpurpc_shard_up{{shard="{victim}"}}' not in text
                        and f'tpurpc_shard_up{{shard="{survivor}"}} 1'
                        in text):
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert f'tpurpc_shard_up{{shard="{victim}"}}' not in text
        assert f'tpurpc_shard_up{{shard="{survivor}"}} 1' in text
        # and the merged flight view still answers, single-shard
        with _socket.create_connection(("127.0.0.1", sup.port),
                                       timeout=5) as s:
            s.settimeout(5)
            s.sendall(b"GET /debug/flight HTTP/1.0\r\n\r\n")
            buf = bytearray()
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        doc = _json.loads(bytes(buf).partition(b"\r\n\r\n")[2])
        assert doc["shards"] == [survivor]
    finally:
        sup.stop()
        config_mod.set_config(None)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_peer_death_mid_rendezvous_releases_region(monkeypatch, platform):
    """tpurpc-express (ISSUE 9): kill the peer MID-RENDEZVOUS — after the
    receiver claimed a landing region but before the sender completed. The
    claimed region must be released (the ringcheck model's peer-death
    invariant, here proven against the implementation), the call must fail
    with a status (never hang), and the flight recorder must replay the
    ordered offer → claim → death → release story."""
    import tpurpc.core.rendezvous as rdv
    from tpurpc.obs import flight

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()

    srv = tps.Server(max_workers=4, native_dataplane=False)
    big = b"\x6b" * (1 << 20)
    srv.add_method("/rdvx.S/Big", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: big))
    srv.add_method("/rdvx.S/Warm", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: b"ok"))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    wedge = threading.Event()  # never set: the sender wedges after claim
    outcome: list = []
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/rdvx.S/Big", tpurpc_native=False)
            # a SMALL warm call settles the capability hello without
            # creating standing grants for the big size class — the wedged
            # transfer below is then SOLICITED (observable offer/claim)
            warm = ch.unary_unary("/rdvx.S/Warm", tpurpc_native=False)
            assert bytes(warm(b"w", timeout=30)) == b"ok"
            rdv.TEST_HOOKS["wedge_after_claim"] = wedge

            def call():
                try:
                    mc(b"x", timeout=60)
                    outcome.append(("ok",))
                except RpcError as exc:
                    outcome.append(("status", exc.code()))

            t = threading.Thread(target=call)
            t.start()
            # wait until the CLIENT (the receiver of the big response) has
            # claimed a landing region for the wedged transfer
            t_armed = time.monotonic_ns()
            deadline = time.monotonic() + 15
            claimed = None
            while claimed is None and time.monotonic() < deadline:
                time.sleep(0.05)
                for e in flight.snapshot(since_ns=t_armed):
                    if e["event"] == "rdv-claim" and e["a1"] != 0:
                        claimed = e
                        break
            assert claimed is not None, "claim never observed"
            t_kill = time.monotonic_ns()
            srv.stop(grace=0)  # ... and the peer dies mid-rendezvous
            t.join(timeout=30)
            assert not t.is_alive(), "call hung after peer death"
            assert outcome and outcome[0][0] == "status", outcome
            assert outcome[0][1] in (StatusCode.UNAVAILABLE,
                                     StatusCode.CANCELLED,
                                     StatusCode.DEADLINE_EXCEEDED), outcome
            # ordered postmortem on the CLAIMING side: offer -> claim ->
            # death -> release, all for the same link+lease — the
            # machines prove the lease lifecycle, assert_ordered the
            # cross-entity death placement (ISSUE 12)
            from tpurpc.analysis import protocol

            events = flight.snapshot()
            tag, lease = claimed["tag"], claimed["a2"]
            protocol.assert_ordered(
                events,
                [("rdv-offer", {"tag": tag}),
                 ("rdv-claim", {"tag": tag, "a2": lease}),
                 (("conn-dead", "peer-death"), {}),
                 ("rdv-release", {"tag": tag, "a1": lease})],
                since_ns=t_armed)
            assert protocol.check_events(events, strict=False) == []
    finally:
        rdv.TEST_HOOKS.pop("wedge_after_claim", None)
        wedge.set()  # free any straggling sender thread
        srv.stop(grace=0)
        config_mod.set_config(None)


# -- reconnect storm (tpurpc-hive, ISSUE 16) ---------------------------------


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))]


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_reconnect_storm_sheds_and_survivors_recover(monkeypatch, platform):
    """tpurpc-hive (ISSUE 16): kill the server under live clients, revive
    it at hard admission saturation, then hit the port with a mass
    re-dial storm of 2000 dial attempts. The accept gate must SHED each
    one (cheap close + ACCEPT_SHED flight event) BEFORE any handshake
    work, no client thread may hang, the surviving clients' post-recovery
    p99 must be bounded, and the whole episode's flight ring must replay
    protocol-conformant.

    The 2k-client storm is expressed as 2000 dial attempts from a bounded
    thread pool so tier-1 stays inside its fd/time budget; the shed path
    exercised is identical — ``EndpointListener._dispatch`` consulting
    ``AdmissionGate.connection_pushback_ms`` per accepted socket."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    monkeypatch.setenv("TPURPC_ACCEPT_BURST", "4")  # handshake cap -> 64
    from tpurpc.obs import flight, metrics
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    flight.RECORDER.reset()
    shed_before = metrics.registry().counter("accept_shed").snapshot()

    srv, port = _echo_server()
    stop = threading.Event()
    server_down = threading.Event()
    recovered = threading.Event()
    lat_before: list = []
    lat_after: list = []
    errors: list = []
    recovered_at = [float("inf")]
    payload = b"storm-survivor"

    def _past_grace() -> bool:
        # calls caught mid-shed surface UNAVAILABLE a beat after the gate
        # un-wedges; recovery claims start once the re-dials had a chance
        return (recovered.is_set()
                and time.monotonic() - recovered_at[0] > 2.0)

    def survivor(idx: int):
        try:
            with tps.Channel(f"127.0.0.1:{port}") as ch:
                mc = ch.unary_unary("/c.S/Echo", tpurpc_native=False)
                while not stop.is_set():
                    t0 = time.monotonic()
                    try:
                        assert bytes(mc(payload, timeout=30)) == payload
                    except RpcError:
                        # the down window (and the shed storm after it) is
                        # allowed to fail calls; afterwards it is not
                        if not _past_grace():
                            time.sleep(0.05)
                            continue
                        raise
                    dt = time.monotonic() - t0
                    if _past_grace():
                        lat_after.append(dt)
                    elif not server_down.is_set():
                        lat_before.append(dt)
        except Exception as exc:  # noqa: BLE001 — surfaced via `errors`
            errors.append((idx, exc))

    survivors = [threading.Thread(target=survivor, args=(i,))
                 for i in range(16)]
    wedged = 0
    gate = None
    srv2 = None
    try:
        [t.start() for t in survivors]
        deadline = time.monotonic() + 20
        while len(lat_before) < 64 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert lat_before, "no baseline traffic before the kill"

        server_down.set()
        srv.stop(grace=0)

        # revive on the SAME port, then storm it before the survivors'
        # backoff re-dials have drained
        deadline = time.monotonic() + 20
        from tpurpc.rpc.server import AdmissionGate

        while srv2 is None and time.monotonic() < deadline:
            try:
                srv2 = tps.Server(max_workers=8,
                                  admission=AdmissionGate(max_inflight=32))
                srv2.add_method("/c.S/Echo",
                                tps.unary_unary_rpc_method_handler(
                                    lambda req, ctx: req))
                srv2.add_insecure_port(f"127.0.0.1:{port}")
                srv2.start()
            except OSError:
                srv2 = None
                time.sleep(0.2)
        assert srv2 is not None, "could not rebind the port"

        # wedge the admission gate at hard saturation — the storm of
        # reconnecting peers below lands on a server whose RPC plane is
        # already full, the exact condition the accept-path shed exists
        # for (each slot owes a release; the finally pays the debt)
        gate = srv2.admission
        while gate.try_admit() is None:
            wedged += 1
        assert gate.connection_pushback_ms() is not None

        def storm(n: int):
            for _ in range(n):
                try:
                    s = socket.create_connection(("127.0.0.1", port),
                                                 timeout=5)
                    s.close()
                except OSError:
                    pass

        stormers = [threading.Thread(target=storm, args=(250,))
                    for _ in range(8)]  # 2000 dials total
        [t.start() for t in stormers]
        [t.join(timeout=60) for t in stormers]
        assert not any(t.is_alive() for t in stormers), "storm dialers hung"

        # storm over: un-wedge the gate and let survivors re-dial
        for _ in range(wedged):
            gate.release()
        wedged = 0
        recovered_at[0] = time.monotonic()
        recovered.set()
        deadline = time.monotonic() + 30
        while len(lat_after) < 64 and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        stop.set()
        [t.join(timeout=60) for t in survivors]
        if gate is not None:
            for _ in range(wedged):
                gate.release()
        if srv2 is not None:
            srv2.stop(grace=0)
        srv.stop(grace=0)
        config_mod.set_config(None)

    assert not any(t.is_alive() for t in survivors), "survivor thread hung"
    assert not errors, errors
    assert len(lat_after) >= 64, \
        f"survivors made no progress after the storm ({len(lat_after)} calls)"
    shed = metrics.registry().counter("accept_shed").snapshot() - shed_before
    assert shed > 0, "storm never hit the accept-shed path"
    bound = max(1.5, 20 * _p99(lat_before))
    p99 = _p99(lat_after)
    assert p99 <= bound, \
        f"post-storm p99 {p99 * 1e3:.1f}ms blew the bound {bound * 1e3:.1f}ms"
    events = flight.snapshot()
    assert any(e["event"] == "accept-shed" for e in events), \
        "no ACCEPT_SHED flight event"
    from tpurpc.analysis import protocol

    assert protocol.check_events(events, strict=False) == []


# -- native-plane peer death (tpurpc-ironclad) -------------------------------


def _native_counters():
    from tpurpc.rpc import native_client

    return native_client.rdv_counters()


def _bulk_recovery_roundtrip(platform):
    """After a native-plane death, a fresh server+channel must move bulk
    byte-exact again — the discard-quarantine left the landing pool sane."""
    srv = tps.Server(max_workers=4)
    srv.add_method("/natchaos.S/Echo", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/natchaos.S/Echo")
            assert bytes(mc(b"warm", timeout=30)) == b"warm"
            big = bytes(range(256)) * 4096
            assert bytes(mc(big, timeout=60)) == big
    finally:
        srv.stop(grace=1)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_native_peer_death_mid_rendezvous_no_hang(monkeypatch, platform):
    """Kill the server while the NATIVE client plane is mid-bulk-stream
    (claims and one-sided writes in flight). The call must fail with a
    status — never hang — and the landing pool must come back clean for
    the next connection (the C Link's discard-quarantine)."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    if platform == "TCP":
        # plain-TCP channels keep the Python transport unless forced
        monkeypatch.setenv("TPURPC_NATIVE_FAST_UNARY", "1")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    if _native_counters() is None:
        pytest.skip("native data plane unavailable")

    srv = tps.Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/natchaos.S/Total",
                   tps.stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = b"\x42" * (1 << 20)
    in_flight = threading.Event()
    outcome: list = []
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/natchaos.S/Total")
            list(mc(iter([payload] * 2), timeout=60))  # warmup: negotiate

            def gen():
                for i in range(64):
                    if i == 2:
                        in_flight.set()  # ladder is hot mid-stream
                    yield payload

            def call():
                try:
                    list(mc(gen(), timeout=60))
                    outcome.append(("ok",))
                except RpcError as exc:
                    outcome.append(("status", exc.code()))

            t = threading.Thread(target=call)
            t.start()
            assert in_flight.wait(timeout=30), "stream never got hot"
            srv.stop(grace=0)  # peer dies mid-rendezvous
            t.join(timeout=30)
            assert not t.is_alive(), "native bulk stream hung on peer death"
            assert outcome and outcome[0][0] == "status", outcome
            assert outcome[0][1] in (StatusCode.UNAVAILABLE,
                                     StatusCode.CANCELLED,
                                     StatusCode.INTERNAL,
                                     StatusCode.DEADLINE_EXCEEDED), outcome
    finally:
        srv.stop(grace=0)
    _bulk_recovery_roundtrip(platform)
    config_mod.set_config(None)


def test_native_peer_death_mid_ctrl_drain_no_hang(monkeypatch):
    """Freeze the native ctrl-ring consumers (TPURPC_TEST_FREEZE_NCTRL —
    descriptor records age in the rings, claims stall), then kill the
    peer during the stall. The claim waiter must be woken by link death
    and the call must fail with a status, never hang."""
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    if _native_counters() is None:
        pytest.skip("native data plane unavailable")

    srv = tps.Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/natchaos.S/Total2",
                   tps.stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = b"\x77" * (1 << 20)
    outcome: list = []
    t0 = [0.0]
    try:
        with tps.Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/natchaos.S/Total2")
            list(mc(iter([payload] * 2), timeout=60))  # warmup: rings hot
            # NOW freeze every in-process C consumer: the next OFFER's
            # CLAIM strands in the ring — a stall mid-ctrl-drain
            monkeypatch.setenv("TPURPC_TEST_FREEZE_NCTRL", "1")

            def call():
                t0[0] = time.monotonic()
                try:
                    list(mc(iter([payload] * 4), timeout=60))
                    outcome.append(("ok",))
                except RpcError as exc:
                    outcome.append(("status", exc.code()))

            t = threading.Thread(target=call)
            t.start()
            time.sleep(1.0)  # inside the claim stall window
            srv.stop(grace=0)  # peer dies mid-drain
            t.join(timeout=30)
            assert not t.is_alive(), "claim waiter hung on peer death"
            # either the death surfaced as a status, or the stack managed
            # to finish framed before the kill landed — both are correct;
            # a HANG is the only failure
            assert outcome, outcome
            if outcome[0][0] == "status":
                assert outcome[0][1] in (StatusCode.UNAVAILABLE,
                                         StatusCode.CANCELLED,
                                         StatusCode.INTERNAL,
                                         StatusCode.DEADLINE_EXCEEDED), outcome
    finally:
        monkeypatch.delenv("TPURPC_TEST_FREEZE_NCTRL", raising=False)
        srv.stop(grace=0)
    _bulk_recovery_roundtrip("RDMA_BPEV")
    config_mod.set_config(None)
