"""tpurpc-manycore (ISSUE 7): shard lifecycle, handoff, merge, observability.

The sharding unit is a worker PROCESS (fork-based, see
tpurpc/rpc/shard.py), so these tests exercise real crash semantics: a
killed shard's in-flight calls must fail UNAVAILABLE (never hang), its
connections re-accept onto survivors, and its telemetry must VANISH from
the aggregated scrape instead of freezing.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

import tpurpc.rpc as tps
from tpurpc.rpc.shard import ShardedServer
from tpurpc.rpc.status import RpcError, StatusCode


def _build_who(shard_id):
    """Worker build fn: /Who answers the serving shard's id; /Slow parks."""
    srv = tps.Server(max_workers=8)
    srv.add_method("/t.S/Who", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: str(shard_id).encode()))

    def slow(req, ctx):
        time.sleep(float(req.decode()))
        return str(shard_id).encode()

    srv.add_method("/t.S/Slow", tps.unary_unary_rpc_method_handler(slow))
    return srv


def _who(port, timeout=20):
    with tps.Channel(f"127.0.0.1:{port}") as ch:
        return bytes(ch.unary_unary("/t.S/Who")(b"x", timeout=timeout)).decode()


def _http_get(port, path, timeout=5.0):
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        buf = bytearray()
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = bytes(buf).partition(b"\r\n\r\n")
    return int(head.split(None, 2)[1]), body


# ---------------------------------------------------------------------------
# listener sharding
# ---------------------------------------------------------------------------

def test_reuseport_accept_spread():
    """SO_REUSEPORT: with enough distinct connections the kernel's spread
    must land work on EVERY shard (P[all-on-one] ≈ 2^-31 at 32 conns)."""
    sup = ShardedServer(_build_who, workers=2, listener="reuseport").start()
    try:
        seen = {}
        for _ in range(32):
            who = _who(sup.port)
            seen[who] = seen.get(who, 0) + 1
        assert set(seen) == {"0", "1"}, seen
        assert sum(seen.values()) == 32
    finally:
        sup.stop()


def test_handoff_round_robin_distribution():
    """Supervisor fd handoff: round-robin is deterministic per connection —
    an even split, every fd delivered over SCM_RIGHTS and served."""
    sup = ShardedServer(_build_who, workers=2, listener="handoff").start()
    try:
        seen = {}
        for _ in range(12):
            who = _who(sup.port)
            seen[who] = seen.get(who, 0) + 1
        assert seen == {"0": 6, "1": 6}, seen
        from tpurpc.obs import flight

        handoffs = [e for e in flight.snapshot()
                    if e["event"] == "conn-handoff"]
        assert len(handoffs) >= 12
    finally:
        sup.stop()


def test_handoff_least_loaded_avoids_busy_shard():
    """least_loaded: with shard 0 pinned by slow calls (streamed load
    reports > 0), new connections route to the idle shard."""
    sup = ShardedServer(_build_who, workers=2, listener="handoff",
                        handoff_policy="least_loaded").start()
    try:
        # occupy ONE shard with parked calls; learn which one it was
        ch = tps.Channel(f"127.0.0.1:{sup.port}")
        busy = bytes(ch.unary_unary("/t.S/Who")(b"x", timeout=20)).decode()
        slow_mc = ch.unary_unary("/t.S/Slow")
        threads = [threading.Thread(
            target=lambda: slow_mc(b"3", timeout=30)) for _ in range(4)]
        [t.start() for t in threads]
        time.sleep(0.5)  # load report interval is 50ms; let it propagate
        other = {"0": "1", "1": "0"}[busy]
        placed = [_who(sup.port) for _ in range(6)]
        assert placed.count(other) >= 5, (busy, placed)
        [t.join(timeout=40) for t in threads]
        ch.close()
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# crash semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("listener", ["reuseport", "handoff"])
def test_worker_crash_inflight_unavailable_and_reaccept(listener):
    """Kill the shard serving an in-flight call: the call must fail with
    UNAVAILABLE (not hang), and a redial must land on a survivor."""
    sup = ShardedServer(_build_who, workers=2, listener=listener).start()
    try:
        ch = tps.Channel(f"127.0.0.1:{sup.port}")
        victim = int(bytes(
            ch.unary_unary("/t.S/Who")(b"x", timeout=20)).decode())
        outcome = {}

        def call():
            try:
                ch.unary_unary("/t.S/Slow")(b"30", timeout=45)
                outcome["ok"] = True
            except RpcError as exc:
                outcome["code"] = exc.code()

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.5)
        assert sup.kill_worker(victim)
        t.join(timeout=20)
        assert not t.is_alive(), "in-flight call hung after shard death"
        assert outcome.get("code") is StatusCode.UNAVAILABLE, outcome
        ch.close()
        # connections re-accept on the survivor
        deadline = time.monotonic() + 10
        served = None
        while time.monotonic() < deadline:
            try:
                served = _who(sup.port, timeout=5)
                break
            except (RpcError, OSError):
                time.sleep(0.1)
        assert served == str(1 - victim)
        assert sup.alive_workers() == [1 - victim]
    finally:
        sup.stop()


def test_dead_shard_drops_out_of_aggregated_metrics():
    """The PR 4 weakref-death contract across the process boundary: a dead
    worker's series VANISH from /metrics (no frozen last values), and
    tpurpc_shard_up enumerates only the living."""
    sup = ShardedServer(_build_who, workers=2, listener="reuseport").start()
    try:
        for _ in range(8):
            _who(sup.port)
        status, body = _http_get(sup.port, "/metrics")
        text = body.decode()
        assert status == 200
        assert 'tpurpc_shard_up{shard="0"} 1' in text
        assert 'tpurpc_shard_up{shard="1"} 1' in text
        assert 'shard="0"' in text and 'shard="1"' in text
        sup.kill_worker(0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                status, body = _http_get(sup.port, "/metrics")
                text = body.decode()
                if 'tpurpc_shard_up{shard="0"}' not in text:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert 'tpurpc_shard_up{shard="0"}' not in text, text[:2000]
        assert 'tpurpc_shard_up{shard="1"} 1' in text
        # no shard-0 series linger anywhere (frozen values are the bug)
        assert 'shard="0"' not in text
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# shard-tagged observability
# ---------------------------------------------------------------------------

def test_aggregated_flight_and_stalls_carry_shard_tags():
    sup = ShardedServer(_build_who, workers=2, listener="reuseport").start()
    try:
        for _ in range(16):
            _who(sup.port)
        status, body = _http_get(sup.port, "/debug/flight")
        assert status == 200
        doc = json.loads(body)
        assert sorted(doc["shards"]) == [0, 1]
        starts = {(e["a1"], e.get("shard")) for e in doc["events"]
                  if e["event"] == "shard-start"}
        assert starts == {(0, 0), (1, 1)}, starts
        # every merged event names its shard
        assert all("shard" in e for e in doc["events"])
        status, body = _http_get(sup.port, "/debug/stalls")
        assert status == 200
        stalls = json.loads(body)
        assert sorted(stalls["shards"]) == ["0", "1"]
        assert all(s.get("shard") in (0, 1)
                   for s in stalls["shards"].values())
        status, body = _http_get(sup.port, "/healthz")
        assert status == 200 and body.strip() == b"ok"
        # ?local=1 escape hatch: one worker's own view, no shard fan-out
        ports = sup.scrape_ports()
        status, body = _http_get(ports[0], "/metrics?local=1")
        assert status == 200 and b"tpurpc_shard_up" not in body
    finally:
        sup.stop()


def test_worker_fleet_gauges_visible_in_aggregate():
    """FleetGauge satellite: gauges registered INSIDE a worker (its poller,
    its streams) must surface in the aggregated scrape, shard-tagged."""
    sup = ShardedServer(_build_who, workers=2, listener="reuseport").start()
    try:
        for _ in range(8):
            _who(sup.port)
        _status, body = _http_get(sup.port, "/metrics")
        text = body.decode()
        # the fleet gauges exist per worker (weakref'd live objects were
        # cleared at fork and re-registered by the worker's own transport)
        assert "tpurpc_srv_call_us" in text
        for k in ("0", "1"):
            assert f'tpurpc_srv_calls{{shard="{k}"' in text, text[:2000]
    finally:
        sup.stop()


def test_graceful_drain_broadcast():
    """drain() reaches every worker: /healthz flips to draining while the
    servers bleed (PR 6 drain semantics, per shard)."""
    sup = ShardedServer(_build_who, workers=2, listener="reuseport").start()
    try:
        for _ in range(4):
            _who(sup.port)
        sup.drain(linger=1.0)
        deadline = time.monotonic() + 10
        seen = b""
        while time.monotonic() < deadline:
            _status, seen = _http_get(sup.port, "/healthz")
            if seen.strip() == b"draining":
                break
            time.sleep(0.1)
        assert seen.strip() == b"draining", seen
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# device-boundary merge (in-process: DeviceMerger / ShardedFanIn)
# ---------------------------------------------------------------------------

def test_device_merger_gathers_concurrent_subbatches():
    from tpurpc.jaxshim.service import DeviceMerger

    calls = []
    gate = threading.Event()
    first_in = threading.Event()

    def fn(tree):
        calls.append(np.asarray(tree["a"]).shape)
        if len(calls) == 1:
            first_in.set()
            gate.wait(10)
        return {"y": np.asarray(tree["a"]) * 2}

    merger = DeviceMerger(fn)
    try:
        results = {}

        def sub(name, rows, val):
            results[name] = merger.entry()(
                {"a": np.full((rows, 2), val, np.float32)})

        t1 = threading.Thread(target=sub, args=("A", 2, 1.0))
        t1.start()
        assert first_in.wait(10)  # merger busy inside A's dispatch
        t2 = threading.Thread(target=sub, args=("B", 3, 2.0))
        t3 = threading.Thread(target=sub, args=("C", 1, 3.0))
        t2.start()
        t3.start()
        time.sleep(0.3)  # B and C commit into the handoff ring
        gate.set()
        for t in (t1, t2, t3):
            t.join(10)
        # B+C merged into ONE 4-row dispatch; every caller's rows correct
        assert calls == [(2, 2), (4, 2)], calls
        assert list(results["A"]["y"][:, 0]) == [2.0, 2.0]
        assert list(results["B"]["y"][:, 0]) == [4.0, 4.0, 4.0]
        assert list(results["C"]["y"][:, 0]) == [6.0]
        assert merger.subs_merged == 2
    finally:
        merger.close()


def test_device_merger_misshaped_subbatch_dispatches_alone():
    """Incompatible signatures never co-dispatch: each shape group gets its
    own device call, both succeed."""
    from tpurpc.jaxshim.service import DeviceMerger

    shapes = []
    gate = threading.Event()
    first_in = threading.Event()

    def fn(tree):
        a = np.asarray(tree["a"])
        shapes.append(a.shape)
        if len(shapes) == 1:
            first_in.set()
            gate.wait(10)
        return {"y": a.sum(axis=tuple(range(1, a.ndim)))}

    merger = DeviceMerger(fn)
    try:
        out = {}

        def sub(name, shape, val):
            out[name] = merger.entry()(
                {"a": np.full(shape, val, np.float32)})

        t1 = threading.Thread(target=sub, args=("warm", (1, 2), 0.0))
        t1.start()
        assert first_in.wait(10)
        t2 = threading.Thread(target=sub, args=("wide", (2, 4), 1.0))
        t3 = threading.Thread(target=sub, args=("narrow", (2, 2), 1.0))
        t2.start()
        t3.start()
        time.sleep(0.3)
        gate.set()
        for t in (t1, t2, t3):
            t.join(10)
        assert sorted(shapes[1:]) == [(2, 2), (2, 4)], shapes
        assert list(out["wide"]["y"]) == [4.0, 4.0]
        assert list(out["narrow"]["y"]) == [2.0, 2.0]
    finally:
        merger.close()


def test_device_merger_poison_subbatch_fails_alone():
    """PR 3's poison-isolation contract across the merge boundary: a merged
    dispatch that fails is retried per sub-batch, so only the poisoned
    shard's callers see the error."""
    from tpurpc.jaxshim.service import DeviceMerger

    gate = threading.Event()
    first_in = threading.Event()
    ncalls = [0]

    def fn(tree):
        a = np.asarray(tree["a"])
        ncalls[0] += 1
        if ncalls[0] == 1:
            first_in.set()
            gate.wait(10)
        if (a == 666.0).any():
            raise ValueError("poison row")
        return {"y": a + 1}

    merger = DeviceMerger(fn)
    try:
        out = {}

        def sub(name, val):
            try:
                out[name] = ("ok",
                             merger.entry()(
                                 {"a": np.full((2, 2), val, np.float32)}))
            except Exception as exc:
                out[name] = ("err", str(exc))

        t1 = threading.Thread(target=sub, args=("warm", 0.0))
        t1.start()
        assert first_in.wait(10)
        t2 = threading.Thread(target=sub, args=("good", 5.0))
        t3 = threading.Thread(target=sub, args=("poison", 666.0))
        t2.start()
        t3.start()
        time.sleep(0.3)
        gate.set()
        for t in (t1, t2, t3):
            t.join(10)
        assert out["warm"][0] == "ok"
        assert out["good"][0] == "ok", out
        assert list(out["good"][1]["y"][:, 0]) == [6.0, 6.0]
        assert out["poison"][0] == "err" and "poison" in out["poison"][1]
    finally:
        merger.close()


def test_sharded_fanin_end_to_end():
    from tpurpc.jaxshim.service import ShardedFanIn

    fan = ShardedFanIn(lambda t: {"y": np.asarray(t["a"]) * 10.0},
                       n_shards=2, max_batch=4, max_delay_s=0.001)
    try:
        outs = [None] * 12

        def caller(i):
            outs[i] = fan({"a": np.full((1, 3), float(i), np.float32)})

        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(12)]
        [t.start() for t in threads]
        [t.join(15) for t in threads]
        for i in range(12):
            assert outs[i] is not None, f"caller {i} stranded"
            assert float(outs[i]["y"][0, 0]) == i * 10.0
        assert fan.batches_run >= 1
        assert fan.queue_depth() == 0
    finally:
        fan.close()


def test_sharded_fanin_close_fails_pending_cleanly():
    from tpurpc.jaxshim.service import ShardedFanIn

    hold = threading.Event()

    def fn(t):
        hold.wait(5)
        return {"y": np.asarray(t["a"])}

    fan = ShardedFanIn(fn, n_shards=2, max_batch=2, max_delay_s=0.001)
    outs = []

    def caller():
        try:
            outs.append(("ok", fan({"a": np.zeros((1, 2), np.float32)})))
        except Exception as exc:
            outs.append(("err", exc))

    threads = [threading.Thread(target=caller) for _ in range(4)]
    [t.start() for t in threads]
    time.sleep(0.2)
    hold.set()
    fan.close()
    [t.join(15) for t in threads]
    assert len(outs) == 4  # nobody stranded on a closed merge boundary


# ---------------------------------------------------------------------------
# the handoff ring itself
# ---------------------------------------------------------------------------

def test_handoff_ring_mpmc_order_and_completeness():
    from tpurpc.core.handoff import HandoffRing

    ring = HandoffRing(capacity=4)
    n_producers, per = 4, 50
    done = threading.Event()
    got = []

    def producer(pid):
        for k in range(per):
            assert ring.publish((pid, k), timeout=10)

    def consumer():
        while len(got) < n_producers * per:
            item = ring.take(timeout=10)
            if item is None:
                break
            got.append(item)
        done.set()

    threads = [threading.Thread(target=producer, args=(p,))
               for p in range(n_producers)]
    tc = threading.Thread(target=consumer)
    tc.start()
    [t.start() for t in threads]
    [t.join(20) for t in threads]
    assert done.wait(20)
    ring.close()
    assert len(got) == n_producers * per
    assert len(set(got)) == len(got), "duplicate delivery"
    for p in range(n_producers):  # per-producer FIFO survives the MPMC merge
        ks = [k for pid, k in got if pid == p]
        assert ks == list(range(per))


def test_handoff_ring_close_unblocks_producer():
    from tpurpc.core.handoff import HandoffRing

    ring = HandoffRing(capacity=2)
    assert ring.publish("a") and ring.publish("b")
    result = []
    t = threading.Thread(target=lambda: result.append(ring.publish("c")))
    t.start()
    time.sleep(0.1)
    ring.close()
    t.join(5)
    assert result == [False]
