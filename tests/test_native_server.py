"""Python-server native adoption (tpurpc/rpc/native_server.py).

The round-4 grpcio-architecture seam: a Python ``Server`` on a ring
platform hands accepted ring connections to libtpurpc's shared-poller
loop (``tpr_server_adopt_fd``) with Python handlers trampolined back.
These tests pin the trampoline's SEMANTIC surface — all four shapes,
metadata both directions, abort, dynamic (generic-handler) dispatch —
and the eligibility gates that keep feature-carrying servers on the
Python plane.
"""

import threading

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc.channel import Channel

from tests.conftest import requires_native_lib  # noqa: E402

pytestmark = requires_native_lib


@pytest.fixture(params=["RDMA_BP"])
def ring_platform(request, monkeypatch):
    monkeypatch.setenv("GRPC_PLATFORM_TYPE", request.param)
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)
    yield
    config_mod.set_config(None)


def _four_shape_server():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/n.S/Echo", rpc.unary_unary_rpc_method_handler(
        lambda r, c: bytes(r), inline=True))
    srv.add_method("/n.S/Split", rpc.unary_stream_rpc_method_handler(
        lambda r, c: iter([bytes(r)] * 3)))
    srv.add_method("/n.S/Join", rpc.stream_unary_rpc_method_handler(
        lambda it, c: b"".join(bytes(m) for m in it)))

    def dbl(req_iter, ctx):
        for m in req_iter:
            yield bytes(m) * 2

    srv.add_method("/n.S/Dbl", rpc.stream_stream_rpc_method_handler(dbl))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


@pytest.mark.parametrize("ring_platform",
                         ["RDMA_BP", "RDMA_EVENT", "RDMA_BPEV"],
                         indirect=True)
def test_adoption_serves_all_four_shapes(ring_platform):
    """All three wakeup disciplines ride the round-4 planes: the adopted
    server's poller epolls the notify fd regardless of discipline, and
    the client fast path's inline-read pump is discipline-independent."""
    srv, port = _four_shape_server()
    try:
        assert srv._native_dp is not None, "adoption did not engage"
        with Channel(f"127.0.0.1:{port}") as ch:
            assert ch.unary_unary("/n.S/Echo")(b"u", timeout=20) == b"u"
            assert list(ch.unary_stream("/n.S/Split")(
                b"s", timeout=20)) == [b"s"] * 3
            assert ch.stream_unary("/n.S/Join")(
                iter([b"a", b"b"]), timeout=20) == b"ab"
            assert list(ch.stream_stream("/n.S/Dbl")(
                iter([b"x", b"yy"]), timeout=20)) == [b"xx", b"yyyy"]
            big = bytes(range(256)) * 8192  # 2 MiB: frame fragmentation
            assert ch.unary_unary("/n.S/Echo")(big, timeout=60) == big
    finally:
        srv.stop(grace=0)


def test_adoption_metadata_abort_and_generic_dispatch(ring_platform):
    srv = rpc.Server(max_workers=4)

    def meta(req, ctx):
        md = dict(ctx.invocation_metadata())
        ctx.send_initial_metadata((("x-init", "i1"),))
        ctx.set_trailing_metadata((("x-tr", "t1"),))
        return md.get("x-key", "?").encode()

    srv.add_method("/n.S/Meta", rpc.unary_unary_rpc_method_handler(meta))

    def fail(req, ctx):
        ctx.abort(rpc.StatusCode.FAILED_PRECONDITION, "nope")

    srv.add_method("/n.S/Fail", rpc.unary_unary_rpc_method_handler(fail))

    class GH:  # grpcio generic handler (the codegen registration shape)
        def service(self, hcd):
            if hcd.method == "/g.S/Up":
                return rpc.unary_unary_rpc_method_handler(
                    lambda r, c: bytes(r).upper())
            return None

    srv.add_generic_rpc_handlers((GH(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        assert srv._native_dp is not None
        with Channel(f"127.0.0.1:{port}") as ch:
            # metadata calls skip the CLIENT fast path but still land on
            # the natively-adopted server; trailing metadata comes back
            mc = ch.unary_unary("/n.S/Meta")
            resp, call = mc.with_call(b"", timeout=20,
                                      metadata=(("x-key", "v1"),))
            assert resp == b"v1"
            assert ("x-init", "i1") in [tuple(x) for x in
                                        call.initial_metadata() or []]
            assert ("x-tr", "t1") in [tuple(x) for x in
                                      call.trailing_metadata() or []]
            with pytest.raises(rpc.RpcError) as ei:
                ch.unary_unary("/n.S/Fail")(b"", timeout=20)
            assert ei.value.code() is rpc.StatusCode.FAILED_PRECONDITION
            assert "nope" in ei.value.details()
            # dynamic dispatch through the native DEFAULT handler
            assert ch.unary_unary("/g.S/Up")(b"abc", timeout=20) == b"ABC"
            with pytest.raises(rpc.RpcError) as ei:
                ch.unary_unary("/none/None")(b"", timeout=20)
            assert ei.value.code() is rpc.StatusCode.UNIMPLEMENTED
    finally:
        srv.stop(grace=0)


def test_adoption_eligibility_gates(ring_platform, monkeypatch):
    # interceptors keep the server on the Python plane
    class NoopInterceptor:
        def intercept_service(self, continuation, details):
            return continuation(details)

    srv = rpc.Server(max_workers=2, interceptors=(NoopInterceptor(),))
    srv.add_method("/n.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        assert srv._native_dp is None
        with Channel(f"127.0.0.1:{port}") as ch:  # and it still serves
            assert ch.unary_unary("/n.S/Echo")(b"i", timeout=20) == b"i"
    finally:
        srv.stop(grace=0)

    # the explicit opt-outs
    srv2 = rpc.Server(max_workers=2, native_dataplane=False)
    srv2.add_method("/n.S/Echo",
                    rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    srv2.add_insecure_port("127.0.0.1:0")
    srv2.start()
    try:
        assert srv2._native_dp is None
    finally:
        srv2.stop(grace=0)

    monkeypatch.setenv("TPURPC_NATIVE_SERVER", "0")
    srv3 = rpc.Server(max_workers=2)
    srv3.add_method("/n.S/Echo",
                    rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    srv3.add_insecure_port("127.0.0.1:0")
    srv3.start()
    try:
        assert srv3._native_dp is None
    finally:
        srv3.stop(grace=0)


def test_adoption_concurrent_multiplexed_calls(ring_platform):
    """Many threads, one adopted connection each + multiplexed calls —
    the poller demux and trampoline GIL handoffs under pressure."""
    srv, port = _four_shape_server()
    try:
        errs = []

        def worker(i):
            try:
                with Channel(f"127.0.0.1:{port}") as ch:
                    echo = ch.unary_unary("/n.S/Echo")
                    for j in range(20):
                        body = f"w{i}-{j}".encode() + b"p" * (i * 53)
                        assert echo(body, timeout=30) == body
            except Exception as exc:
                errs.append(exc)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        [t.start() for t in ts]
        [t.join(120) for t in ts]
        # liveness, not just error-freeness: a deadlocked worker must FAIL
        # this test, not time out of join() into a vacuous pass
        assert not any(t.is_alive() for t in ts), "worker deadlocked"
        assert not errs, errs[:3]
    finally:
        srv.stop(grace=0)


def test_bulk_stream_no_token_stealing_stall(ring_platform, monkeypatch):
    """Round-5 regression (ring_transport.h wait_event): a reader and a
    credit-blocked bulk writer share one notify fd, and before the
    one-poller-others-park rewrite the reader could STEAL the writer's
    credit token — bulk senders then moved exactly one ring per 100ms
    poll slice. A deliberately small ring makes that pathology blow this
    generous deadline by ~10x (32MB through a 256KB ring: ~13s broken,
    well under a second fixed), while byte integrity proves the fast
    path is still correct."""
    monkeypatch.setenv("GRPC_RDMA_RING_BUFFER_SIZE_KB", "256")
    from tpurpc.utils import config as config_mod

    config_mod.set_config(None)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv = rpc.Server(max_workers=4)
    srv.add_method("/n.S/Total", rpc.stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = b"\x5a" * (1024 * 1024)
    msgs = 32
    try:
        import time as _time

        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/n.S/Total")
            t0 = _time.monotonic()
            out = list(mc(iter([payload] * msgs), timeout=60))
            dt = _time.monotonic() - t0
        assert out == [str(msgs * len(payload)).encode()]
        # stolen-wakeup regime: >= (total/ring) * 100ms ≈ 12.8s. The bound
        # leaves 10x headroom over the fixed path for shared-core weather.
        assert dt < 8.0, f"bulk stream took {dt:.1f}s — token stealing?"
    finally:
        srv.stop(grace=0)
