"""Wire-compat, roles swapped: the tpurpc H2Channel against a STOCK grpcio
server (the other half of the drop-in proof — ``test_grpc_compat.py`` covers
stock clients hitting tpurpc servers).

The grpcio server here is the real C-core: full HPACK (huffman + dynamic
table), real flow control, trailers-only errors — everything a compliant
client must survive. Mirrors the reference's property that its client stack
IS gRPC (chttp2_connector, SURVEY.md §3.2).
"""

import threading
import time
from concurrent import futures

import grpc
import pytest

from tpurpc.rpc.status import RpcError, StatusCode
from tpurpc.wire.h2_client import H2Channel

_ID = lambda x: x


class _Handlers(grpc.GenericRpcHandler):
    """Raw-bytes service on a stock grpcio server."""

    def service(self, details):
        name = details.method.rsplit("/", 1)[-1]
        if name == "Echo":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: req,
                request_deserializer=_ID, response_serializer=_ID)
        if name == "Tail":
            def tail(req, ctx):
                for i in range(4):
                    yield req + str(i).encode()
            return grpc.unary_stream_rpc_method_handler(
                tail, request_deserializer=_ID, response_serializer=_ID)
        if name == "Collect":
            def collect(req_iter, ctx):
                return b"|".join(req_iter)
            return grpc.stream_unary_rpc_method_handler(
                collect, request_deserializer=_ID, response_serializer=_ID)
        if name == "Chat":
            def chat(req_iter, ctx):
                for req in req_iter:
                    yield b"re:" + req
            return grpc.stream_stream_rpc_method_handler(
                chat, request_deserializer=_ID, response_serializer=_ID)
        if name == "Boom":
            def boom(req, ctx):
                ctx.set_trailing_metadata((("saw-md", "yes"),))
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, "nope: not ready")
            return grpc.unary_unary_rpc_method_handler(
                boom, request_deserializer=_ID, response_serializer=_ID)
        if name == "Meta":
            def meta(req, ctx):
                md = {k: v for k, v in ctx.invocation_metadata()}
                ctx.set_trailing_metadata(
                    (("echoed-key", md.get("x-custom", "?")),
                     ("bin-bin", md.get("x-blob-bin", b"")),))
                return req
            return grpc.unary_unary_rpc_method_handler(
                meta, request_deserializer=_ID, response_serializer=_ID)
        if name == "Slow":
            def slow(req, ctx):
                time.sleep(5)
                return req
            return grpc.unary_unary_rpc_method_handler(
                slow, request_deserializer=_ID, response_serializer=_ID)
        return None  # UNIMPLEMENTED


@pytest.fixture(scope="module")
def rig():
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    srv.add_generic_rpc_handlers((_Handlers(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    ch = H2Channel(f"127.0.0.1:{port}")
    yield srv, port, ch
    ch.close()
    srv.stop(grace=0)


def test_unary_roundtrip(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Echo")
    assert mc(b"hello from tpurpc", timeout=20) == b"hello from tpurpc"


def test_unary_large_flow_controlled(rig):
    """4 MiB both directions: DATA chunking under the peer's max-frame and
    conn+stream windows, window replenishment on receive."""
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Echo")
    big = bytes(range(256)) * (4 * 4096)  # 4 MiB
    assert mc(big, timeout=60) == big


def test_server_streaming(rig):
    _, _, ch = rig
    mc = ch.unary_stream("/test.Echo/Tail")
    assert list(mc(b"x", timeout=20)) == [b"x0", b"x1", b"x2", b"x3"]


def test_client_streaming(rig):
    _, _, ch = rig
    mc = ch.stream_unary("/test.Echo/Collect")
    assert mc(iter([b"a", b"b", b"c"]), timeout=20) == b"a|b|c"


def test_bidi_streaming(rig):
    _, _, ch = rig
    mc = ch.stream_stream("/test.Echo/Chat")
    assert list(mc(iter([b"1", b"2"]), timeout=20)) == [b"re:1", b"re:2"]


def test_error_status_message_and_trailing_metadata(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Boom")
    with pytest.raises(RpcError) as ei:
        mc(b"x", timeout=20)
    assert ei.value.code() is StatusCode.FAILED_PRECONDITION
    assert "nope: not ready" in ei.value.details()
    md = dict(ei.value.trailing_metadata() or [])
    assert md.get("saw-md") == "yes"


def test_metadata_roundtrip_incl_binary(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Meta")
    # metadata travels out; echoed values come back in trailers, but a
    # successful call doesn't raise — use Boom-style check via a failing
    # variant is not available, so assert via the error-free path + a second
    # call carrying different metadata (dynamic-table exercise).
    assert mc(b"m", timeout=20,
              metadata=(("x-custom", "v123"),
                        ("x-blob-bin", b"\x00\x01\xfe"))) == b"m"
    assert mc(b"m2", timeout=20,
              metadata=(("x-custom", "v456"),
                        ("x-blob-bin", b"\xff\x00"))) == b"m2"


def test_unimplemented_maps_to_status(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Nope")
    with pytest.raises(RpcError) as ei:
        mc(b"x", timeout=20)
    assert ei.value.code() is StatusCode.UNIMPLEMENTED


def test_deadline_expires_fast(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Slow")
    t0 = time.monotonic()
    with pytest.raises(RpcError) as ei:
        mc(b"x", timeout=0.5)
    assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
    assert time.monotonic() - t0 < 3


def test_many_sequential_calls_exercise_dynamic_table(rig):
    """Repeated calls with repeating headers: the dynamic-table encoder path
    must stay in sync with grpcio's decoder across many HEADERS blocks."""
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Echo")
    for i in range(20):
        payload = f"msg-{i}".encode()
        assert mc(payload, timeout=20,
                  metadata=(("x-repeat", "const"),)) == payload


def test_many_concurrent_calls(rig):
    _, _, ch = rig
    mc = ch.unary_unary("/test.Echo/Echo")
    results = [None] * 16

    def one(i):
        results[i] = mc(f"m{i}".encode(), timeout=30)

    ts = [threading.Thread(target=one, args=(i,)) for i in range(16)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert results == [f"m{i}".encode() for i in range(16)]


def test_h2channel_against_tpurpc_server():
    """Full circle: our h2 client against our own server's sniffed h2 path."""
    import tpurpc.rpc as tps

    srv = tps.Server(max_workers=4)
    srv.add_method("/test.Echo/Echo",
                   tps.unary_unary_rpc_method_handler(lambda req, ctx: req))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with H2Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/test.Echo/Echo")
            assert mc(b"self-interop", timeout=20) == b"self-interop"
    finally:
        srv.stop(grace=0)


def test_h2channel_against_gzip_compressing_server():
    """A grpcio server configured for gzip compresses RESPONSES; H2Channel
    must advertise gzip and decompress them."""
    gsrv = grpc.server(futures.ThreadPoolExecutor(max_workers=4),
                       compression=grpc.Compression.Gzip)
    gsrv.add_generic_rpc_handlers((_Handlers(),))
    port = gsrv.add_insecure_port("127.0.0.1:0")
    gsrv.start()
    try:
        with H2Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/test.Echo/Echo")
            payload = b"squeeze " * 500
            assert mc(payload, timeout=20) == payload
    finally:
        gsrv.stop(grace=0)
