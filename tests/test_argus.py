"""tpurpc-argus (ISSUE 14): tsdb history, SLO burn-rate alerting, fleet
collector, and automatic evidence capture.

Covers the tentpole's four pieces — the two-tier ring tsdb (bounds,
decimation, rate/quantile queries, reset-aware differentiation), the SLO
evaluator (burn math, pending→firing→resolved, shed-vs-error budgets,
watchdog bridge), the fleet collector (member labels, staleness,
counter-reset clamping, merged SLO view), and the bundle writer (content,
rate limiting, caps, protocol-checkable flight dump) — plus the
satellites: the structured ``/healthz?json=1`` ``degraded_reasons`` body
(each subsystem's reason appears and clears), the shard-merge counter
reset hardening, and the end-to-end detect→capture acceptance proof.
"""

import json
import os
import threading
import time

import pytest

from tpurpc.obs import bundle as obs_bundle
from tpurpc.obs import flight, metrics, scrape
from tpurpc.obs import slo as obs_slo
from tpurpc.obs import tsdb as obs_tsdb
from tpurpc.obs import watchdog
from tpurpc.obs.tsdb import ResetClamp, Tsdb


@pytest.fixture(autouse=True)
def _clean_argus_state():
    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled)
    yield
    obs_slo.reset()
    obs_bundle.disable()
    wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled = prev
    wd.reset()
    flight.RECORDER.reset()


def _private_db(**kw) -> Tsdb:
    reg = metrics.Registry()
    kw.setdefault("fine_s", 1.0)
    kw.setdefault("fine_window_s", 16.0)
    kw.setdefault("coarse_s", 4.0)
    kw.setdefault("coarse_window_s", 64.0)
    return Tsdb(registry=reg, **kw)


S = int(1e9)  # one second of synthetic monotonic nanoseconds


# ---------------------------------------------------------------------------
# ResetClamp
# ---------------------------------------------------------------------------

def test_reset_clamp_monotone_across_restarts():
    c = ResetClamp()
    assert c.clamp("k", 10) == 10
    assert c.clamp("k", 25) == 25
    # restart: raw drops to 3 -> continue from last-known (25) + 3
    assert c.clamp("k", 3) == 28
    assert c.resets == 1
    assert c.clamp("k", 7) == 32
    # second restart accumulates
    assert c.clamp("k", 1) == 33
    assert c.resets == 2


def test_reset_clamp_forget_by_prefix():
    c = ResetClamp()
    c.clamp(("m1", "x"), 10)
    c.clamp(("m1", "x"), 2)           # reset recorded
    c.clamp(("m2", "y"), 5)
    assert c.clamp(("m1", "x"), 4) == 14
    c.forget("m1")
    assert c.clamp(("m1", "x"), 4) == 4   # state dropped
    assert c.clamp(("m2", "y"), 6) == 6   # untouched


# ---------------------------------------------------------------------------
# tsdb: rings, tiers, queries
# ---------------------------------------------------------------------------

def test_tsdb_window_and_ring_bound():
    db = _private_db()
    ctr = db._registry.counter("reqs")
    for i in range(40):  # 40 samples > 16 fine slots: the ring must wrap
        ctr.inc(5)
        db.sample_once(now_ns=(i + 1) * S)
    pts = db.window("reqs", 100.0, now_ns=40 * S)
    # coarse tier covers 100s; fine would have been chosen under 16s
    fine_pts = db.window("reqs", 10.0, now_ns=40 * S)
    assert len(fine_pts) <= db._fine.slots
    assert fine_pts[-1][1] == 200.0
    assert pts[0][0] < fine_pts[0][0]  # coarse reaches further back
    assert db._fine.n == 40


def test_tsdb_rate_and_counter_reset():
    db = _private_db()
    ctr = db._registry.counter("reqs")
    for i in range(10):
        ctr.inc(10)  # +10/s
        db.sample_once(now_ns=(i + 1) * S)
    assert db.rate("reqs", 9.0, now_ns=10 * S) == pytest.approx(10.0)
    # counter reset mid-window: the restarted process re-counts from zero
    ctr.reset()
    ctr.inc(3)
    db.sample_once(now_ns=11 * S)
    r = db.rate("reqs", 10.0, now_ns=11 * S)
    assert r > 0  # never a negative rate off a reset
    # window {t=9: 90, t=10: 100, t=11: 3}: +10, then the reset -> +3
    assert db.delta("reqs", 2.0, now_ns=11 * S) == pytest.approx(13.0)


def test_tsdb_two_tier_decimation():
    db = _private_db()  # fine 1s, coarse 4s -> decimation 4
    g = db._registry.gauge("load")
    for i in range(12):
        g.set(i)
        db.sample_once(now_ns=(i + 1) * S)
    assert db._coarse.n == 3  # every 4th fine tick
    coarse = db._coarse.points("load", 0)
    assert [v for _t, v in coarse] == [0.0, 4.0, 8.0]


def test_tsdb_quantile_and_threshold_fraction():
    db = _private_db()
    g = db._registry.gauge("p99_us")
    vals = [10, 10, 10, 10, 10, 10, 10, 10, 90, 90]
    for i, v in enumerate(vals):
        g.set(v)
        db.sample_once(now_ns=(i + 1) * S)
    assert db.quantile_over_time("p99_us", 0.5, 12.0,
                                 now_ns=10 * S) == 10.0
    frac = db.over_threshold_fraction("p99_us", 50.0, 12.0, now_ns=10 * S)
    assert frac == pytest.approx(0.2)
    assert db.over_threshold_fraction("nope", 1.0, 12.0,
                                      now_ns=10 * S) is None


def test_tsdb_histogram_and_labeled_series():
    db = _private_db()
    h = db._registry.histogram("lat_us", kind="latency")
    fam = db._registry.labeled_counter("calls", ("method", "code"))
    h.record(1000)
    h.record(2000)
    fam.labels("/m/A", "0").inc(5)
    fam.labels("/m/A", "14").inc(1)
    db.sample_once(now_ns=S)
    kinds = db.series()
    assert kinds["lat_us:p99"] == "quantile"
    assert kinds["lat_us:count"] == "counter"
    assert kinds["calls{/m/A,0}"] == "counter"
    assert db.window("calls{/m/A,14}", 5.0, now_ns=S)[-1][1] == 1.0


def test_tsdb_series_cap_bounds_memory(monkeypatch):
    monkeypatch.setattr(obs_tsdb, "MAX_SERIES", 4)
    db = _private_db()
    for i in range(10):
        db._registry.counter(f"c{i}")
    db.sample_once(now_ns=S)
    assert len(db.series()) == 4
    before = db.resident_bytes()
    for i in range(10, 20):
        db._registry.counter(f"c{i}")
    db.sample_once(now_ns=2 * S)
    assert db.resident_bytes() == before  # capped: no growth


def test_tsdb_doc_and_resident_bytes():
    db = _private_db()
    db._registry.counter("reqs").inc(7)
    db.sample_once()  # real clock: doc() windows against now
    doc = db.doc()
    assert "reqs" in doc["series"]
    assert doc["resident_bytes"] > 0
    one = db.doc(series="reqs", window_s=10.0)
    assert one["points"][-1][1] == 7.0
    assert one["kind"] == "counter"


def test_tsdb_postfork_reset_gives_fresh_instance():
    a = obs_tsdb.get()
    obs_tsdb.postfork_reset()
    b = obs_tsdb.get()
    assert a is not b


def test_debug_history_route():
    status, ctype, body = scrape._route("/debug/history?local=1")
    assert status == 200
    doc = json.loads(body)
    assert doc["enabled"] is True
    assert "fine" in doc and "coarse" in doc


# ---------------------------------------------------------------------------
# slo: burn math + the alert state machine (private tsdb, synthetic clock)
# ---------------------------------------------------------------------------

def _latency_rig(threshold_ms=5.0, windows=((4.0, 8.0, 2.0),)):
    """A private tsdb + evaluator around one latency objective bound to a
    gauge series the test drives directly."""
    db = _private_db(fine_s=1.0, fine_window_s=32.0,
                     coarse_s=8.0, coarse_window_s=64.0)
    g = db._registry.gauge("p99g")
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    obj = ev.declare(obs_slo.SloObjective(
        "lat", latency_ms=threshold_ms, latency_target_pct=50.0,
        series="p99g", windows=[tuple(w) for w in windows]))
    return db, g, ev, obj


def test_slo_pending_firing_resolved_with_flight_events():
    db, g, ev, obj = _latency_rig()
    st = obj.tracks["latency"]
    # healthy: p99 1ms for 10s
    for i in range(10):
        g.set(1000.0)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert st.state == "ok"
    # degrade: p99 50ms — fast window (4s) saturates before slow (8s)
    t = 10
    while st.state == "ok" and t < 30:
        t += 1
        g.set(50_000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "pending"
    while st.state == "pending" and t < 40:
        t += 1
        g.set(50_000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "firing"
    fired_at = t
    # recover: p99 back to 1ms — the alert must resolve
    while st.state == "firing" and t < fired_at + 30:
        t += 1
        g.set(1000.0)
        db.sample_once(now_ns=t * S)
        ev.evaluate_once(now_ns=t * S)
    assert st.state == "ok"
    transitions = [(h["from"], h["to"]) for h in ev.doc()["history"]
                   if h["objective"] == "lat"]
    assert ("ok", "pending") in transitions
    assert ("pending", "firing") in transitions
    assert ("firing", "ok") in transitions
    # flight: firing strictly before resolved, tagged with the objective
    names = [e["event"] for e in flight.snapshot()
             if e["entity"] == "slo:lat"]
    assert names.index("slo-firing") < names.index("slo-resolved")
    # ... and the bracket satisfies the declared protocol machine
    from tpurpc.analysis import protocol

    assert protocol.check_events(flight.snapshot(), strict=False) == []


def test_slo_blip_does_not_fire():
    db, g, ev, obj = _latency_rig()
    st = obj.tracks["latency"]
    for i in range(20):
        # one bad sample in ten: fast window burns briefly, slow never
        g.set(50_000.0 if i % 10 == 0 else 1000.0)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
        assert st.state != "firing"
    assert st.fired == 0


def test_slo_availability_errors_and_sheds_burn_separate_budgets():
    db = _private_db(fine_s=1.0, fine_window_s=32.0)
    fam = db._registry.labeled_counter("srv_calls", ("method", "code"))
    shed = db._registry.counter("srv_admission_rejected")
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    obj = ev.declare(obs_slo.SloObjective(
        "avail", method="/m/A", target_pct=99.0, shed_target_pct=80.0,
        windows=[(4.0, 8.0, 2.0)]))
    ok = fam.labels("/m/A", "0")
    bad = fam.labels("/m/A", "14")
    # heavy shedding, zero handler errors: the shed budget burns, the
    # error budget must NOT (pushback is the system working)
    for i in range(12):
        ok.inc(10)
        shed.inc(10)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert obj.tracks["errors"].state == "ok"
    assert obj.tracks["sheds"].state == "firing"
    # now handler errors with no sheds: the error budget burns
    obj2 = ev.declare(obs_slo.SloObjective(
        "avail2", method="/m/A", target_pct=99.0,
        windows=[(4.0, 8.0, 2.0)]))
    for i in range(12, 26):
        ok.inc(9)
        bad.inc(1)  # 10% errors vs a 1% budget: burn 10x > 2.0
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert obj2.tracks["errors"].state == "firing"


def test_slo_method_scoping():
    db = _private_db(fine_s=1.0, fine_window_s=32.0)
    fam = db._registry.labeled_counter("srv_calls", ("method", "code"))
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    obj = ev.declare(obs_slo.SloObjective(
        "a-only", method="/m/A", target_pct=99.0,
        windows=[(4.0, 8.0, 2.0)]))
    # /m/B fails hard; /m/A is clean — the scoped objective must not burn
    for i in range(12):
        fam.labels("/m/A", "0").inc(10)
        fam.labels("/m/B", "14").inc(10)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert obj.tracks["errors"].state == "ok"


def test_slo_firing_bridges_watchdog_and_healthz(monkeypatch):
    # GLOBAL plumbing: a firing alert must land in /debug/stalls history
    # (stage slo), flip /healthz to 503, and clear back out
    wd = watchdog.get()
    db = _private_db(fine_s=1.0, fine_window_s=32.0)
    g = db._registry.gauge("p99g")
    ev = obs_slo.SloEvaluator(eval_s=1.0, tsdb=db)
    monkeypatch.setattr(obs_slo, "_instance", ev)
    obj = ev.declare(obs_slo.SloObjective(
        "page-me", latency_ms=5.0, latency_target_pct=50.0,
        series="p99g", windows=[(2.0, 4.0, 2.0)]))
    for i in range(10):
        g.set(50_000.0)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert obj.tracks["latency"].state == "firing"
    assert any(h.get("stage") == "slo" and h.get("method") == "page-me"
               for h in wd.snapshot()["history"])
    status, _ctype, body = scrape._route("/healthz")
    assert status == 503 and b"slo" in body.lower()
    status, _ctype, body = scrape._route("/healthz?json=1")
    doc = json.loads(body)
    assert doc["status"] == "degraded"
    assert "slo-firing" in [r["reason"] for r in doc["degraded_reasons"]]
    # /debug/slo reports it too
    status, _ctype, body = scrape._route("/debug/slo?local=1")
    sdoc = json.loads(body)
    assert sdoc["firing"] and sdoc["firing"][0]["objective"] == "page-me"
    # recovery clears healthz
    for i in range(10, 25):
        g.set(100.0)
        db.sample_once(now_ns=(i + 1) * S)
        ev.evaluate_once(now_ns=(i + 1) * S)
    assert obj.tracks["latency"].state == "ok"
    status, _ctype, body = scrape._route("/healthz?json=1")
    doc = json.loads(body)
    assert doc["status"] == "ok" and doc["degraded_reasons"] == []


# ---------------------------------------------------------------------------
# /healthz?json=1: every subsystem's reason appears and clears
# ---------------------------------------------------------------------------

def _health_reasons():
    status, _ctype, body = scrape._route("/healthz?json=1")
    doc = json.loads(body)
    return status, [r["reason"] for r in doc["degraded_reasons"]], doc


def test_healthz_json_watchdog_reason_appears_and_clears():
    wd = watchdog.get()
    wd.enabled = True
    wd.min_stall_s = 0.01
    tok = wd.call_started("/argus/Wedge")
    time.sleep(0.05)
    wd.sweep_once()
    status, reasons, doc = _health_reasons()
    assert status == 503 and "watchdog-stall" in reasons
    # legacy text body preserved byte-for-byte
    status, _ctype, body = scrape._route("/healthz")
    worst = wd.active()[0]
    expect = (f"degraded: {len(wd.active())} stalled call(s); "
              f"{worst['method']} blocked on {worst['stage']} "
              f"for {worst['age_s']}s\n").encode()
    assert status == 503 and body == expect
    wd.call_finished(tok)
    wd.sweep_once()
    status, reasons, _doc = _health_reasons()
    assert status == 200 and "watchdog-stall" not in reasons


def test_healthz_json_draining_reason_appears_and_clears():
    from tpurpc.rpc.server import Server

    srv = Server(max_workers=2)
    srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        _status, reasons, _doc = _health_reasons()
        assert "draining" not in reasons
        t = threading.Thread(target=srv.drain, args=(0.5,), daemon=True)
        t.start()
        deadline = time.monotonic() + 2.0
        seen = False
        while time.monotonic() < deadline:
            status, reasons, doc = _health_reasons()
            if "draining" in reasons:
                seen = True
                assert status == 200 and doc["status"] == "draining"
                break
        assert seen, "draining reason never appeared"
        t.join(timeout=5)
    finally:
        srv.stop(grace=0)
    _status, reasons, _doc = _health_reasons()
    assert "draining" not in reasons  # a stopped server is not draining


def test_healthz_json_shedding_and_kv_reasons(monkeypatch):
    import sys

    sched_mod = pytest.importorskip("tpurpc.serving.scheduler")
    kv_mod = pytest.importorskip("tpurpc.serving.kv")

    class _FakeSched:
        name = "gen0"
        _closed = False
        steps = 1
        shed_total = 2
        preempted_total = 0

        def state_str(self):
            return "shedding"

        def running_depth(self):
            return 1

        def queue_depth(self):
            return 9

        def swapped_depth(self):
            return 0

    class _FakeKv:
        name = "arena0"

        def stats(self):
            return {"used": 1, "blocks": 4, "free": 3,
                    "swapped_blocks": 2, "quarantined": 1,
                    "prefix_hits": 0}

    fake_s, fake_k = _FakeSched(), _FakeKv()
    sched_mod._LIVE.add(fake_s)
    kv_mod._LIVE.add(fake_k)
    try:
        status, reasons, doc = _health_reasons()
        assert "shedding" in reasons and "kv-pressure" in reasons
        assert status == 200  # shedding/pressure inform, they do not page
        assert any(ln.startswith("gen gen0:") for ln in doc["lines"])
        assert any(ln.startswith("kv arena0:") for ln in doc["lines"])
    finally:
        sched_mod._LIVE.discard(fake_s)
        kv_mod._LIVE.discard(fake_k)
    _status, reasons, _doc = _health_reasons()
    assert "shedding" not in reasons and "kv-pressure" not in reasons
    assert sys.modules.get("tpurpc.serving.kv") is kv_mod


# ---------------------------------------------------------------------------
# bundle: content, protocol conformance, rate limit, caps
# ---------------------------------------------------------------------------

def test_bundle_contents_and_protocol_conformance(tmp_path):
    from tpurpc.analysis import protocol

    # a realistic flight history: an rdv exchange + an slo bracket
    tag = flight.tag_for("pair:test")
    flight.emit(flight.RDV_OFFER, tag, 7, 4096)
    flight.emit(flight.RDV_CLAIM, tag, 7, 99)
    flight.emit(flight.RDV_COMPLETE, tag, 99, 4096)
    w = obs_bundle.BundleWriter(str(tmp_path), min_interval_s=0.0)
    path = w.capture("manual", detail="unit test")
    assert path is not None and os.path.isdir(path)
    names = sorted(os.listdir(path))
    pid = os.getpid()
    assert f"flight-{pid}.json" in names
    assert {"meta.json", "traces.json", "history.json",
            "slo.json", "stalls.json"} <= set(names)
    with open(os.path.join(path, f"flight-{pid}.json")) as f:
        events = json.load(f)
    assert isinstance(events, list) and len(events) >= 3
    # the acceptance contract: the bundle dir IS a --flight argument
    total, violations = protocol.check_dump(path)
    assert violations == [] and total >= 3
    # a bundle-written flight event landed (pure-int, interned tag)
    assert any(e["event"] == "bundle-written" for e in flight.snapshot())


def test_bundle_rate_limit_one_per_interval(tmp_path):
    w = obs_bundle.BundleWriter(str(tmp_path), min_interval_s=60.0)
    assert w.capture("slo", key="slo:lat") is not None
    # the flap: same alert again inside the interval
    assert w.capture("slo", key="slo:lat") is None
    # a DIFFERENT alert shortly after is also held by the global floor
    assert w.capture("watchdog", key="wd:other") is None
    assert len(obs_bundle.list_bundles(str(tmp_path))) == 1


def test_bundle_caps_delete_oldest(tmp_path):
    w = obs_bundle.BundleWriter(str(tmp_path), max_bundles=2,
                                min_interval_s=0.0)
    paths = [w.capture("manual", key=f"k{i}") for i in range(4)]
    assert all(p is not None for p in paths)
    left = obs_bundle.list_bundles(str(tmp_path))
    assert len(left) == 2
    assert os.path.basename(paths[-1]) in left  # newest survives


def test_bundle_armed_by_watchdog_trip(tmp_path):
    obs_bundle.enable(str(tmp_path), min_interval_s=0.0)
    wd = watchdog.get()
    wd.enabled = True
    wd.external_trip("slo", "lat-objective", "unit-test page")
    bundles = obs_bundle.list_bundles(str(tmp_path))
    assert len(bundles) == 1 and "-slo-" in bundles[0]
    wd.external_trip("rendezvous", "other", "different stage")
    # different key but the global floor holds inside min_interval/2=0
    assert len(obs_bundle.list_bundles(str(tmp_path))) == 2


def test_bundle_renderer_cli(tmp_path, capsys):
    from tpurpc.tools import bundle as bundle_cli

    w = obs_bundle.BundleWriter(str(tmp_path), min_interval_s=0.0)
    flight.emit(flight.PAIR_CONNECT, 0, 1)
    w.capture("manual", detail="render me")
    assert bundle_cli.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "render me" in out and "flight" in out


# ---------------------------------------------------------------------------
# collector: labels, staleness, reset clamp, merged slo, HTTP face
# ---------------------------------------------------------------------------

def _fake_member(col, target, text, slo=None):
    m = col._members[target]
    m.metrics_text = text
    m.slo = slo
    m.misses = 0
    m.polls += 1
    m.last_ok_mono = time.monotonic()
    return m


def test_collector_member_labels_and_census():
    from tpurpc.obs.collector import FleetCollector

    col = FleetCollector(["h1:1", "h2:2"], poll_s=0.1)
    _fake_member(col, "h1:1",
                 "# TYPE tpurpc_x counter\ntpurpc_x 5\n")
    _fake_member(col, "h2:2",
                 "# TYPE tpurpc_x counter\ntpurpc_x{a=\"b\"} 7\n")
    text = col.merged_metrics()
    assert 'tpurpc_x{member="h1:1"} 5' in text
    assert 'tpurpc_x{member="h2:2",a="b"} 7' in text
    assert 'tpurpc_member_up{member="h1:1"} 1' in text


def test_collector_stale_member_series_vanish():
    from tpurpc.obs.collector import FleetCollector

    col = FleetCollector(["up:1", "dead:2"], poll_s=0.1, stale_after=2)
    _fake_member(col, "up:1", "# TYPE tpurpc_x counter\ntpurpc_x 5\n")
    m = _fake_member(col, "dead:2",
                     "# TYPE tpurpc_x counter\ntpurpc_x 9\n")
    text = col.merged_metrics()
    assert 'tpurpc_x{member="dead:2"} 9' in text
    m.misses = 3  # the member died: polls failed past the staleness bar
    text = col.merged_metrics()
    assert 'member="dead:2"} 9' not in text          # series VANISH
    assert 'tpurpc_member_up{member="dead:2"} 0' in text     # marked
    assert 'tpurpc_member_stale{member="dead:2"} 1' in text
    census = {c["member"]: c["state"] for c in col.census()}
    assert census == {"up:1": "up", "dead:2": "stale"}


def test_collector_counter_reset_clamped():
    from tpurpc.obs.collector import FleetCollector

    col = FleetCollector(["m:1"], poll_s=0.1)
    _fake_member(col, "m:1", "# TYPE tpurpc_c counter\ntpurpc_c 100\n")
    t1 = col.merged_metrics()
    assert 'tpurpc_c{member="m:1"} 100' in t1
    # the member restarted: raw counter re-counts from 4
    _fake_member(col, "m:1", "# TYPE tpurpc_c counter\ntpurpc_c 4\n")
    t2 = col.merged_metrics()
    assert 'tpurpc_c{member="m:1"} 104' in t2    # last-known + delta
    assert "tpurpc_collector_counter_resets 1" in t2
    # gauges pass through unclamped
    _fake_member(col, "m:1", "# TYPE tpurpc_g gauge\ntpurpc_g 2\n")
    assert 'tpurpc_g{member="m:1"} 2' in col.merged_metrics()


def test_collector_merged_slo_alerts_carry_member():
    from tpurpc.obs.collector import FleetCollector

    col = FleetCollector(["a:1", "b:2"], poll_s=0.1)
    _fake_member(col, "a:1", "", slo={
        "firing": [{"objective": "lat", "track": "latency",
                    "burn_fast": 3.0}],
        "objectives": []})
    _fake_member(col, "b:2", "", slo={"firing": [], "objectives": []})
    doc = col.merged_slo()
    assert doc["firing"] == 1
    assert doc["alerts"][0]["member"] == "a:1"
    assert doc["members"]["b:2"]["state"] == "up"


def test_collector_live_http_end_to_end():
    import urllib.request

    from tpurpc.obs.collector import FleetCollector
    from tpurpc.rpc.server import Server

    srv = Server(max_workers=2)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    col = FleetCollector([f"127.0.0.1:{port}"], poll_s=0.2)
    try:
        col.poll_once()
        assert col.census()[0]["state"] == "up"
        text = col.merged_metrics()
        assert f'member="127.0.0.1:{port}"' in text
        cport = col.serve(port=0)
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{cport}/fleet/metrics", timeout=5).read()
        assert b"tpurpc_member_up" in raw
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{cport}/fleet/slo", timeout=5).read()
        assert b"members" in raw
        # the member dies: its series must vanish, not freeze
        srv.stop(grace=0)
        for _ in range(col.stale_after + 1):
            col.poll_once()
        text = col.merged_metrics()
        assert f'tpurpc_member_up{{member="127.0.0.1:{port}"}} 0' in text
        assert f'tpurpc_ring_msgs_read{{member="127.0.0.1:{port}"' \
            not in text
    finally:
        col.stop()
        srv.stop(grace=0)


# ---------------------------------------------------------------------------
# shard merge: counter-reset hardening (satellite)
# ---------------------------------------------------------------------------

def test_shard_merge_clamps_restarted_worker(monkeypatch):
    from tpurpc.obs import shard as obs_shard

    monkeypatch.setattr(obs_shard, "_CLAMP", None)  # fresh clamp

    bodies = {"scrape": 0}

    def fake_each(path):
        # shard 0 healthy both scrapes; shard 1 restarted in between
        # (killed-and-respawned worker: counters re-count from zero)
        if path.startswith("/metrics"):
            v1 = "120" if bodies["scrape"] == 0 else "3"
            yield 0, 200, b"# TYPE tpurpc_c counter\ntpurpc_c 50\n"
            yield 1, 200, (f"# TYPE tpurpc_c counter\ntpurpc_c {v1}\n"
                           ).encode()
        else:
            wf1 = {"hops": [{"hop": "wire",
                             "bytes": 1000 if bodies["scrape"] == 0 else 40,
                             "busy_ms": 1.0, "copy_bytes": 0,
                             "what": "w"}]}
            yield 0, 200, json.dumps(
                {"hops": [{"hop": "wire", "bytes": 500, "busy_ms": 1.0,
                           "copy_bytes": 0, "what": "w"}]}).encode()
            yield 1, 200, json.dumps(wf1).encode()

    monkeypatch.setattr(obs_shard, "_each_shard", fake_each)
    text1 = obs_shard.aggregate_metrics()
    assert 'tpurpc_c{shard="1"} 120' in text1
    wf_before = obs_shard.aggregate_waterfall()
    assert wf_before["hops"][0]["bytes"] == 1500
    bodies["scrape"] = 1  # shard 1 has restarted
    text2 = obs_shard.aggregate_metrics()
    assert 'tpurpc_c{shard="1"} 123' in text2  # 120 + 3, never backwards
    wf_after = obs_shard.aggregate_waterfall()
    assert wf_after["hops"][0]["bytes"] >= wf_before["hops"][0]["bytes"]


# ---------------------------------------------------------------------------
# end-to-end: detect -> localize -> capture (the acceptance proof)
# ---------------------------------------------------------------------------

@pytest.mark.timeout(60)
def test_argus_detect_to_capture_end_to_end(tmp_path, monkeypatch):
    """With windows scaled down: an induced p99 degradation fires a
    burn-rate alert (pending→firing observed, flight ordered), trips
    /healthz degraded, and produces exactly ONE rate-limited bundle whose
    flight dump passes protocol conformance."""
    from tpurpc.analysis import protocol
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    # fresh global tsdb on a fast grain (the env knob the smoke uses too)
    monkeypatch.setenv("TPURPC_TSDB_FINE_S", "0.05")
    obs_tsdb.postfork_reset()
    obs_slo.reset()
    db = obs_tsdb.get()

    slow = threading.Event()

    def handler(req, ctx):
        if slow.is_set():
            time.sleep(0.05)
        return b"ok"

    srv = Server(max_workers=4)
    srv.add_method("/argus/Probe", unary_unary_rpc_method_handler(handler))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()  # starts the tsdb sampler; arms nothing else yet
    obs_bundle.enable(str(tmp_path), min_interval_s=30.0)
    ev = obs_slo.get()
    ev.eval_s = 0.1
    obj = obs_slo.declare(
        "probe-p99", method="/argus/Probe", latency_ms=10.0,
        latency_target_pct=50.0, windows=[(0.8, 1.6, 1.2)])
    st = obj.tracks["latency"]
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            call = ch.unary_unary("/argus/Probe")
            for _ in range(16):  # build the healthy rolling-p99 history
                call(b"x", timeout=5)
            slow.set()           # induce the p99 degradation
            t0 = time.monotonic()
            states = set()
            deadline = t0 + 2 * 0.8 + 8.0  # 2 fast windows + rig slack
            while time.monotonic() < deadline:
                call(b"x", timeout=5)
                states.add(st.state)
                if st.state == "firing":
                    break
            assert st.state == "firing", (st.state, states)
            assert "pending" in states  # observed BEFORE firing
            # healthz degraded with the structured reason
            status, _ctype, body = scrape._route("/healthz?json=1")
            doc = json.loads(body)
            assert status == 503
            assert "slo-firing" in [r["reason"]
                                    for r in doc["degraded_reasons"]]
            # the page landed in /debug/stalls
            assert any(h.get("stage") == "slo"
                       for h in watchdog.get().snapshot()["history"])
            # exactly ONE bundle despite continued firing evaluations
            time.sleep(0.5)
            bundles = obs_bundle.list_bundles(str(tmp_path))
            assert len(bundles) == 1, bundles
            bpath = os.path.join(str(tmp_path), bundles[-1])
            total, violations = protocol.check_dump(bpath)
            assert violations == [] and total > 0
            # the bundle's flight dump shows the firing edge
            with open(os.path.join(
                    bpath, f"flight-{os.getpid()}.json")) as f:
                events = json.load(f)
            assert any(e["event"] == "slo-firing" for e in events)
            # the tsdb window in the bundle brackets the degradation
            with open(os.path.join(bpath, "history.json")) as f:
                hist = json.load(f)
            assert "watchdog_p99_us{/argus/Probe}" in hist["series"]
    finally:
        ev.stop()
        srv.stop(grace=0)
        db.stop()
        obs_tsdb.postfork_reset()  # next get() rebuilds on default grain
    # flight order end-to-end: firing recorded, bundle written after
    names = [e["event"] for e in flight.snapshot()]
    assert names.index("slo-firing") < names.index("bundle-written")
