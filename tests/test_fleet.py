"""tpurpc-fleet (ISSUE 6): hedged retries, load-aware picking, graceful
drain, and overload admission control — the fleet front door.

The gRFC A6 hedging state machine, the ORCA-style load-report loop
(server piggyback → client strip → least_loaded EWMA), the admission
gate's shed-with-pushback contract, and their flight-recorder evidence.
The multi-server chaos scenarios live in test_chaos.py; this file is the
per-mechanism contract."""

import threading
import time

import pytest

import tpurpc.rpc as tps
from tpurpc.obs import flight
from tpurpc.rpc import health
from tpurpc.rpc.channel import (Channel, HedgingPolicy, RetryPolicy,
                                _LOAD_KEY, _PUSHBACK_KEY)
from tpurpc.rpc.resolver import LeastLoaded, make_policy
from tpurpc.rpc.server import (LOAD_KEY, PUSHBACK_KEY, AdmissionGate,
                               Server)
from tpurpc.rpc.service_config import ServiceConfig
from tpurpc.rpc.status import RpcError, StatusCode


def _poll_until(pred, timeout: float = 5.0, interval: float = 0.02):
    """Condition-polling replacement for fixed sleeps (PR 9 noted the
    fixed-sleep flakes on 1-core containers: a loaded host can need far
    longer than any constant, and an idle one shouldn't pay it)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return pred()


def _settles_at(fn, expect, settle_s: float = 0.4, interval: float = 0.02):
    """Negative-assertion helper: ``fn()`` must equal ``expect`` for the
    whole settle window (e.g. "no further attempt ever lands"). Returns
    False the moment it diverges instead of sleeping blind."""
    deadline = time.monotonic() + settle_s
    while time.monotonic() < deadline:
        if fn() != expect:
            return False
        time.sleep(interval)
    return fn() == expect


def test_metadata_keys_agree_across_modules():
    # channel.py carries its own literals to avoid a server import in the
    # client module; they MUST stay in lockstep with the server's
    assert _LOAD_KEY == LOAD_KEY
    assert _PUSHBACK_KEY == PUSHBACK_KEY


def _server(name: str, delay: float = 0.0, max_workers: int = 8, **kw):
    srv = Server(max_workers=max_workers, **kw)
    calls = []

    def who(req, ctx):
        calls.append(bytes(req))
        if delay:
            time.sleep(delay)
        return name.encode()

    srv.add_method("/fleet.S/Who", tps.unary_unary_rpc_method_handler(who))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port, calls


# -- hedging ------------------------------------------------------------------

def test_hedging_policy_validation():
    with pytest.raises(ValueError):
        HedgingPolicy(max_attempts=1)
    with pytest.raises(ValueError):
        HedgingPolicy(hedging_delay=-0.1)


def test_service_config_parses_hedging_policy():
    sc = ServiceConfig.from_json({"methodConfig": [{
        "name": [{"service": "fleet.S"}],
        "hedgingPolicy": {"maxAttempts": 7, "hedgingDelay": "0.02s",
                          "nonFatalStatusCodes": ["UNAVAILABLE",
                                                  "ABORTED"]}}]})
    hp = sc.for_method("/fleet.S/Who").hedging_policy
    assert hp.max_attempts == 5  # capped like retryPolicy
    assert hp.hedging_delay == pytest.approx(0.02)
    assert StatusCode.ABORTED in hp.non_fatal_codes


def test_service_config_rejects_retry_plus_hedging():
    with pytest.raises(ValueError):
        ServiceConfig.from_json({"methodConfig": [{
            "name": [{}],
            "retryPolicy": {"maxAttempts": 2,
                            "retryableStatusCodes": ["UNAVAILABLE"]},
            "hedgingPolicy": {"maxAttempts": 2}}]})


def test_hedge_beats_slow_replica_and_cancels_loser():
    """One slow replica; the hedge fires after the delay, wins on the fast
    one, and the flight ring shows fired → won → cancelled."""
    s1, p1, calls1 = _server("slow", delay=1.0)
    s2, p2, _ = _server("fast")
    flight.RECORDER.reset()
    try:
        with Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                     lb_policy="pick_first",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.02)) as ch:
            mc = ch.unary_unary("/fleet.S/Who")
            t0 = time.monotonic()
            assert bytes(mc(b"x", timeout=5)) == b"fast"
            # did not wait out the slow replica. The window is WIDE on
            # purpose (1-core flake, PR 9): the claim is "well under the
            # 1.0s handler", not a scheduling-latency bound.
            assert time.monotonic() - t0 < 0.8
        events = [e["event"] for e in flight.snapshot()]
        assert "hedge-fired" in events
        assert "hedge-won" in events
        assert "hedge-cancelled" in events
        fired = [e for e in flight.snapshot() if e["event"] == "hedge-fired"]
        won = [e for e in flight.snapshot() if e["event"] == "hedge-won"]
        assert fired[0]["t_ns"] <= won[0]["t_ns"]
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_hedge_attempts_prefer_distinct_subchannels():
    """With every replica slow, max_attempts hedges land on DISTINCT
    backends (the used-subchannel exclusion), not the same one thrice."""
    rigs = [_server(f"s{i}", delay=0.3) for i in range(3)]
    addrs = ",".join(f"127.0.0.1:{p}" for _, p, _ in rigs)
    try:
        with Channel(f"ipv4:{addrs}", lb_policy="pick_first",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.01)) as ch:
            mc = ch.unary_unary("/fleet.S/Who")
            mc(b"x", timeout=5)
        # cancelled losers' handlers finish appending asynchronously
        assert _poll_until(
            lambda: sum(1 for _, _, calls in rigs if calls) == 3,
            timeout=5.0), [len(c) for _, _, c in rigs]
    finally:
        for s, _, _ in rigs:
            s.stop(grace=0)


def test_hedging_no_delay_on_healthy_fleet():
    """A fast first response means NO hedge fires — hedging must cost a
    healthy fleet nothing."""
    s1, p1, calls1 = _server("a")
    flight.RECORDER.reset()
    try:
        with Channel(f"ipv4:127.0.0.1:{p1}",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.25)) as ch:
            mc = ch.unary_unary("/fleet.S/Who")
            for _ in range(5):
                assert bytes(mc(b"x", timeout=5)) == b"a"
        assert len(calls1) == 5  # no duplicate attempts
        events = [e["event"] for e in flight.snapshot()]
        assert "hedge-fired" not in events
    finally:
        s1.stop(grace=0)


def test_hedging_gated_by_retry_throttle():
    """A drained retry-throttle bucket suppresses hedges — the gRFC A6
    no-retry-storm rule applies to hedging too."""
    s1, p1, calls1 = _server("only", delay=0.15)
    try:
        with Channel(f"ipv4:127.0.0.1:{p1}",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.01)) as ch:
            ch.update_service_config(
                {"retryThrottling": {"maxTokens": 10, "tokenRatio": 0.1}})
            ch._service_config.retry_throttle._tokens = 0.0  # drained
            mc = ch.unary_unary("/fleet.S/Who")
            assert bytes(mc(b"x", timeout=5)) == b"only"
        # no hedge was allowed to fire — and none trickles in late
        assert _settles_at(lambda: len(calls1), 1), calls1
    finally:
        s1.stop(grace=0)


def test_hedging_fatal_status_resolves_immediately():
    """A non-retryable failure (here INVALID_ARGUMENT) must surface at
    once instead of waiting out other hedges."""
    srv = Server(max_workers=4)

    def bad(req, ctx):
        ctx.abort(StatusCode.INVALID_ARGUMENT, "nope")

    srv.add_method("/fleet.S/Who", tps.unary_unary_rpc_method_handler(bad))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"ipv4:127.0.0.1:{port}",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=1.0)) as ch:
            t0 = time.monotonic()
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/fleet.S/Who")(b"x", timeout=10)
            assert ei.value.code() is StatusCode.INVALID_ARGUMENT
            assert time.monotonic() - t0 < 0.9  # not a hedging_delay wait
    finally:
        srv.stop(grace=0)


# -- load reports + least_loaded ----------------------------------------------

def test_load_report_stripped_from_app_metadata():
    """The piggyback is transport-internal: trailing metadata surfaced to
    the application must NOT contain the load key."""
    s1, p1, _ = _server("a")
    try:
        with Channel(f"127.0.0.1:{p1}") as ch:
            mc = ch.unary_unary("/fleet.S/Who", tpurpc_native=False)
            _resp, call = mc.with_call(b"x", timeout=5)
            keys = [k for k, _v in (call.trailing_metadata() or ())]
            assert _LOAD_KEY not in keys
    finally:
        s1.stop(grace=0)


def test_least_loaded_policy_feeds_from_responses():
    """End-to-end loop: server piggyback → channel strip → policy EWMA."""
    s1, p1, _ = _server("a")
    s2, p2, _ = _server("b")
    try:
        with Channel(f"ipv4:127.0.0.1:{p1},127.0.0.1:{p2}",
                     lb_policy="least_loaded") as ch:
            mc = ch.unary_unary("/fleet.S/Who")
            for _ in range(6):
                mc(b"x", timeout=5)
            snap = ch._policy.snapshot()
            assert any(snap["reported"]), snap
    finally:
        s1.stop(grace=0)
        s2.stop(grace=0)


def test_least_loaded_orders_by_reported_load():
    pol = make_policy("least_loaded", 3)
    pol.load_report(0, b"9,4,0.0")   # util 13
    pol.load_report(1, b"1,0,0.0")   # util 1
    pol.load_report(2, b"4,1,0.0")   # util 5
    order = list(pol.order())
    assert order == [1, 2, 0]
    # reports keep steering after EWMA updates
    for _ in range(8):
        pol.load_report(1, b"50,0,0.0")
    assert list(pol.order())[0] != 1


def test_least_loaded_parse_tolerates_junk():
    assert LeastLoaded.parse_report(b"3,5,12.5") == (8.0, 12.5)
    assert LeastLoaded.parse_report(b"3") == (3.0, 0.0)
    assert LeastLoaded.parse_report(b"junk") is None
    assert LeastLoaded.parse_report("") is None
    pol = LeastLoaded(2)
    pol.load_report(7, b"1,1,1")  # out-of-range index: ignored
    pol.load_report(0, b"not,numbers")
    assert pol.snapshot()["reported"] == [False, False]


def test_least_loaded_ejects_erroring_and_reinstates():
    flight.RECORDER.reset()
    pol = LeastLoaded(3, ejection_failures=2, ejection_s=0.2)
    for _ in range(2):
        pol.failed(1)
    snap = pol.snapshot()
    assert snap["ejected"] == [False, True, False]
    assert list(pol.order())[-1] == 1  # ejected sorts last, never dropped
    events = [e for e in flight.snapshot() if e["event"] == "subch-ejected"]
    assert events and events[0]["a1"] == 1 and events[0]["a2"] == 0
    # expiry is observed on a pick AFTER ejection_s has elapsed — poll
    # picks instead of trusting one fixed sleep to out-wait the clock
    assert _poll_until(
        lambda: (pol.order(), pol.snapshot()["ejected"])[1]
        == [False, False, False], timeout=3.0)
    assert any(e["event"] == "subch-reinstated" and e["a1"] == 1
               for e in flight.snapshot())


def test_least_loaded_ejects_slow_outlier():
    flight.RECORDER.reset()
    pol = LeastLoaded(3, slow_mult=3.0)
    for _ in range(4):
        pol.load_report(0, b"1,0,5.0")
        pol.load_report(1, b"1,0,5.0")
        pol.load_report(2, b"1,0,500.0")  # GC-hell replica: modest load,
    snap = pol.snapshot()                  # garbage latency
    assert snap["ejected"] == [False, False, True]
    events = [e for e in flight.snapshot() if e["event"] == "subch-ejected"]
    assert events and events[-1]["a1"] == 2 and events[-1]["a2"] == 1


# -- admission control --------------------------------------------------------

def test_admission_gate_validation_and_env():
    with pytest.raises(ValueError):
        AdmissionGate(0)
    with pytest.raises(ValueError):
        AdmissionGate(4, soft_limit=9)
    assert AdmissionGate.from_env() is None  # unset: opt-in


def test_admission_gate_soft_hard_and_release():
    gate = AdmissionGate(3, soft_limit=2)
    assert gate.try_admit() is None
    assert gate.try_admit() is None
    # between soft and hard with no SLO configured: admitted
    assert gate.try_admit() is None
    pb = gate.try_admit()  # at the hard limit: shed, pushback grows
    assert isinstance(pb, int) and pb >= gate.base_pushback_ms
    assert gate.rejected == 1
    gate.release()
    assert gate.try_admit() is None


def test_admission_shed_carries_pushback_and_recovers():
    srv = Server(max_workers=8, admission=AdmissionGate(2, soft_limit=2))
    gate_open = threading.Event()

    def slow(req, ctx):
        gate_open.wait(5)
        return b"ok"

    srv.add_method("/fleet.S/Slow", tps.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    flight.RECORDER.reset()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/fleet.S/Slow", tpurpc_native=False)
            futs = [mc.future(b"", timeout=10) for _ in range(2)]
            deadline = time.monotonic() + 5
            shed = None
            while shed is None and time.monotonic() < deadline:
                try:
                    mc(b"", timeout=2)
                except RpcError as exc:
                    if exc.code() is StatusCode.UNAVAILABLE:
                        shed = exc
                time.sleep(0.02)
            assert shed is not None, "gate never shed"
            md = dict(shed.trailing_metadata() or ())
            assert _PUSHBACK_KEY in md and int(md[_PUSHBACK_KEY]) > 0
            assert "overloaded" in shed.details()
            assert any(e["event"] == "admit-reject"
                       for e in flight.snapshot())
            gate_open.set()
            for f in futs:
                f.result(timeout=10)
            # capacity released: admitted again
            assert bytes(mc(b"", timeout=5)) == b"ok"
    finally:
        gate_open.set()
        srv.stop(grace=0)


def test_admission_exempts_health_probes():
    srv = Server(max_workers=8, admission=AdmissionGate(1, soft_limit=1))
    servicer = health.add_health_servicer(srv)
    hold = threading.Event()

    def slow(req, ctx):
        hold.wait(5)
        return b"ok"

    srv.add_method("/fleet.S/Slow", tps.unary_unary_rpc_method_handler(slow))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/fleet.S/Slow", tpurpc_native=False)
            fut = mc.future(b"", timeout=10)  # occupies the whole gate
            assert _poll_until(lambda: srv.admission.inflight() >= 1,
                               timeout=5.0), "gate never saw the call"
            check = ch.unary_unary(f"/{health.SERVICE_NAME}/Check",
                                   tpurpc_native=False)
            # the probe is admitted even though the gate is full
            assert health.decode_response(
                check(health.encode_request(""), timeout=5)) \
                is health.ServingStatus.SERVING
            hold.set()
            fut.result(timeout=10)
    finally:
        hold.set()
        srv.stop(grace=0)
        _ = servicer


def test_retry_policy_honors_pushback_floor(monkeypatch):
    """RetryPolicy sleeps at least the server-named pushback before the
    next attempt (the shed is not immediately re-hammered)."""
    attempts = []

    def attempt():
        attempts.append(time.monotonic())
        if len(attempts) == 1:
            raise RpcError(StatusCode.UNAVAILABLE, "shed",
                           [(_PUSHBACK_KEY, "200")])
        return "ok"

    policy = RetryPolicy(max_attempts=3, initial_backoff=0.001,
                         max_backoff=0.002)
    assert policy.run(None, attempt) == "ok"
    assert attempts[1] - attempts[0] >= 0.2 * 0.95


def test_pushback_stops_hedging():
    """An admission-shedding fleet must not receive further hedges: the
    pushback resolves the hedged call with the shed failure once the
    original attempt is done, without launching more attempts."""
    srv = Server(max_workers=4)
    seen = []

    def shed(req, ctx):
        seen.append(1)
        ctx.set_trailing_metadata([(_PUSHBACK_KEY, "100")])
        ctx.abort(StatusCode.UNAVAILABLE, "synthetic shed")

    srv.add_method("/fleet.S/Who", tps.unary_unary_rpc_method_handler(shed))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"ipv4:127.0.0.1:{port}",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.01)) as ch:
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/fleet.S/Who")(b"x", timeout=5)
            assert ei.value.code() is StatusCode.UNAVAILABLE
        # pushback stopped attempts 2..N — and none trickles in late
        assert _settles_at(lambda: len(seen), 1), seen
    finally:
        srv.stop(grace=0)


# -- drain --------------------------------------------------------------------

def test_drain_sets_health_and_draining_flag():
    srv = Server(max_workers=4)
    servicer = health.add_health_servicer(srv)
    servicer.set("fleet.S", health.ServingStatus.SERVING)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        assert srv.draining is False
        assert srv.drain(linger=1.0) is True  # no streams: clean
        assert srv.draining is True
        with Channel(f"127.0.0.1:{port}") as ch:
            check = ch.unary_unary(f"/{health.SERVICE_NAME}/Check",
                                   tpurpc_native=False)
            # overall AND named services answer NOT_SERVING (set_all)
            for svc in ("", "fleet.S"):
                st = health.decode_response(
                    check(health.encode_request(svc), timeout=5))
                assert st is health.ServingStatus.NOT_SERVING, svc
    finally:
        srv.stop(grace=0)


def test_drain_is_idempotent_and_flight_ordered():
    flight.RECORDER.reset()
    srv, port, _ = _server("d")
    try:
        assert srv.drain(linger=1.0) is True
        assert srv.drain(linger=0.1) is True  # second call: re-wait only
        begins = [e for e in flight.snapshot()
                  if e["event"] == "drain-begin"]
        ends = [e for e in flight.snapshot() if e["event"] == "drain-end"]
        assert len(begins) == 1 and len(ends) == 1  # one drain, one pair
        assert begins[0]["t_ns"] <= ends[0]["t_ns"]
        assert ends[0]["a1"] == 0  # clean: nothing left at budget expiry
    finally:
        srv.stop(grace=0)
