"""Message compression on the tpurpc framing (FLAG_COMPRESSED).

The h2 wire negotiates grpc-encoding with stock peers
(test_grpc_compat/test_h2_client); this file covers the native framing's
per-message gzip: channel-level opt-in, server-side mirror on responses,
fragmentation of compressed payloads, and corrupt-payload handling."""

import gzip

import pytest

import tpurpc.rpc as rpc
from tpurpc.rpc import frame as fr
from tpurpc.rpc.status import RpcError, StatusCode


def _echo_server():
    srv = rpc.Server(max_workers=4)
    srv.add_method("/c.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))

    def dbl(req_iter, ctx):
        for m in req_iter:
            yield bytes(m) * 2

    srv.add_method("/c.S/Dbl", rpc.stream_stream_rpc_method_handler(dbl))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def test_writer_compresses_flagged_messages():
    """Unit: FLAG_COMPRESSED input → gzip payload on the wire, flag kept."""
    wrote = []

    class Ep:
        def write(self, bufs):
            wrote.append(b"".join(bytes(b) for b in bufs))

    w = fr.FrameWriter(Ep())
    body = b"A" * 4096  # compressible
    w.send(fr.MESSAGE, fr.FLAG_COMPRESSED | fr.FLAG_END_STREAM, 1, body)
    frame = wrote[0]
    ftype, flags, sid, ln = fr.HEADER_FMT.unpack(frame[:fr.HEADER_FMT.size])
    assert flags & fr.FLAG_COMPRESSED
    payload = frame[fr.HEADER_FMT.size:]
    assert len(payload) == ln < len(body)  # actually smaller on the wire
    assert gzip.decompress(payload) == body
    # control frames and unflagged messages are untouched
    wrote.clear()
    w.send(fr.MESSAGE, 0, 1, body)
    assert wrote[0][fr.HEADER_FMT.size:] == body


@pytest.mark.parametrize("spelling", ["gzip", 2])
def test_compressed_unary_and_streaming_round_trip(spelling, monkeypatch):
    """e2e with compression on: payloads survive, and the server MIRRORS
    the encoding on responses (observed via the client-side decompress)."""
    decompressions = []
    real = fr.decompress_message
    monkeypatch.setattr(
        fr, "decompress_message",
        lambda data, limit=None: decompressions.append(1) or real(data,
                                                                  limit))
    srv, port = _echo_server()
    try:
        with rpc.Channel(f"127.0.0.1:{port}", compression=spelling) as ch:
            body = b"compressible " * 1000
            assert ch.unary_unary("/c.S/Echo")(body, timeout=15) == body
            assert decompressions, "response was not mirrored compressed"
            out = list(ch.stream_stream("/c.S/Dbl")(
                iter([b"a" * 100, b"b" * 100]), timeout=15))
            assert out == [b"a" * 200, b"b" * 200]
    finally:
        srv.stop(grace=0)


def test_compressed_large_message_fragments():
    """A >1MiB compressed-but-still-large message crosses the frame bound:
    compression happens before fragmentation, reassembly before gunzip."""
    import os

    srv, port = _echo_server()
    try:
        with rpc.Channel(f"127.0.0.1:{port}", compression="gzip") as ch:
            body = os.urandom(3 << 20)  # incompressible: stays ~3MiB
            assert ch.unary_unary("/c.S/Echo")(body, timeout=60) == body
    finally:
        srv.stop(grace=0)


def test_corrupt_compressed_request_aborts_cleanly():
    """A flagged message that does not gunzip fails THAT call with a clear
    status; the connection survives for the next call."""
    srv, port = _echo_server()
    try:
        with rpc.Channel(f"127.0.0.1:{port}") as ch:
            conn = ch._connection()
            st = conn.open_stream()
            conn.writer.send(fr.HEADERS, 0, st.stream_id,
                             fr.headers_payload("/c.S/Echo", (), None))
            # forge FLAG_COMPRESSED garbage at the endpoint, bypassing the
            # writer's gzip step
            payload = b"\x00garbage-not-gzip\xff"
            conn.writer._ep.write([fr.HEADER_FMT.pack(
                fr.MESSAGE, fr.FLAG_END_STREAM | fr.FLAG_COMPRESSED,
                st.stream_id, len(payload)), payload])
            while True:  # that CALL fails with a decompression status...
                ev = st.events.get(timeout=15)
                if ev[0] == "trailers":
                    assert ev[1] is StatusCode.INTERNAL
                    assert "decompress" in ev[2]
                    break
            # ...and the CONNECTION survives for the next clean call
            assert ch.unary_unary("/c.S/Echo")(b"ok", timeout=15) == b"ok"
    finally:
        srv.stop(grace=0)


def test_deflate_accepted_as_compression():
    """grpcio accepts Compression.Deflate (1); a drop-in call site passing
    it must construct (the framing honors the intent with its one codec)."""
    ch = rpc.Channel("127.0.0.1:1", compression="deflate")
    assert ch._compress_flag == fr.FLAG_COMPRESSED
    ch.close()
    ch = rpc.Channel("127.0.0.1:1", compression=1)  # Compression.Deflate
    assert ch._compress_flag == fr.FLAG_COMPRESSED
    ch.close()


def test_unknown_compression_degrades_with_warning():
    """Unknown compression values degrade to identity (warning), keeping
    constructor drop-in compatibility instead of raising."""
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ch = rpc.Channel("127.0.0.1:1", compression="snappy")
    assert ch._compress_flag == 0
    assert any("snappy" in str(w.message) for w in caught)
    ch.close()


def test_channel_options_compression():
    """grpcio's grpc.default_compression_algorithm channel arg (2 = gzip)
    turns framing compression on."""
    srv, port = _echo_server()
    try:
        ch = rpc.insecure_channel(
            f"127.0.0.1:{port}",
            options=[("grpc.default_compression_algorithm", 2)])
        assert ch._compress_flag == fr.FLAG_COMPRESSED
        assert ch.unary_unary("/c.S/Echo")(b"z" * 512, timeout=15) == b"z" * 512
        ch.close()
    finally:
        srv.stop(grace=0)


def test_gzip_bomb_guard():
    """The receive limit binds the POST-decompression size: a tiny gzip
    of a huge message passes the wire-size check but must be rejected
    RESOURCE_EXHAUSTED instead of inflating into memory."""
    srv = rpc.Server(max_workers=2, max_receive_message_length=4096)
    srv.add_method("/c.S/Echo",
                   rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with rpc.Channel(f"127.0.0.1:{port}", compression="gzip") as ch:
            bomb = b"\x00" * (32 << 20)  # 32 MiB of zeros -> ~32 KiB gzip
            with pytest.raises(RpcError) as ei:
                ch.unary_unary("/c.S/Echo")(bomb, timeout=30)
            assert ei.value.code() is StatusCode.RESOURCE_EXHAUSTED
            # connection survives for a clean call
            assert ch.unary_unary("/c.S/Echo")(b"ok", timeout=15) == b"ok"
    finally:
        srv.stop(grace=0)


def test_incompressible_payload_clears_flag(monkeypatch):
    """Random bytes gzip LARGER: the writer sends them uncompressed with
    the bit cleared (gRPC's compressed-flag rule), so the receiver never
    decompresses."""
    import os as _os

    calls = []
    real = fr.decompress_message
    monkeypatch.setattr(fr, "decompress_message",
                        lambda d, lim=None: calls.append(1) or real(d, lim))
    srv, port = _echo_server()
    try:
        with rpc.Channel(f"127.0.0.1:{port}", compression="gzip") as ch:
            body = _os.urandom(4096)
            assert ch.unary_unary("/c.S/Echo")(body, timeout=15) == body
        assert not calls  # nothing on either side actually decompressed
    finally:
        srv.stop(grace=0)
