"""Bench rig: histogram math, micro closed-loop, qps localhost scenario."""

import io
import re

import pytest

from tpurpc.bench import micro, qps
from tpurpc.bench.histogram import LatencyHistogram


def test_histogram_percentiles_accurate():
    h = LatencyHistogram()
    for v in range(1, 10001):  # 1..10000 ns uniform
        h.record(v)
    assert h.total == 10000
    assert h.percentile(50) == pytest.approx(5000, rel=0.03)
    assert h.percentile(99) == pytest.approx(9900, rel=0.03)
    assert h.mean_ns == pytest.approx(5000.5, rel=0.001)


def test_histogram_merge_matches_union():
    a, b, u = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    for v in (10, 200, 3000, 45000):
        a.record(v)
        u.record(v)
    for v in (7, 800, 90000):
        b.record(v)
        u.record(v)
    a.merge(b)
    assert a.total == u.total and a.sum_ns == u.sum_ns
    assert a.percentile(50) == u.percentile(50)


def test_histogram_serialization_roundtrip():
    h = LatencyHistogram()
    for v in (5, 77, 1234, 987654):
        h.record(v)
    h2 = LatencyHistogram.from_dict(h.to_dict())
    assert h2.percentile(99) == h.percentile(99)
    assert h2.total == h.total


def test_micro_closed_loop_unary_report_format():
    srv = micro.run_server(0)
    try:
        out = io.StringIO()
        result = micro.run_client(f"127.0.0.1:{srv.bench_port}", req_size=64,
                                  duration=1.5, report_every=0.5, out=out)
        text = out.getvalue()
        # reference-compatible log lines (SURVEY.md §6 format)
        assert re.search(r"Rate \d+ RPCs/s, TX Bandwidth [\d.]+ Mb/s, "
                         r"RTT \(us\) mean [\d.]+ P50 [\d.]+", text)
        assert "Aggregated" in text
        assert result["rpcs"] > 10
        assert result["rtt_us"]["p50"] > 0
    finally:
        srv.stop(grace=0)


def test_micro_streaming_ping_pong():
    srv = micro.run_server(0)
    try:
        result = micro.run_client(f"127.0.0.1:{srv.bench_port}", req_size=32,
                                  streaming=True, duration=1.5,
                                  report_every=0.5, out=io.StringIO())
        assert result["rpcs"] > 10
    finally:
        srv.stop(grace=0)


def test_qps_localhost_scenario_two_clients():
    agg = qps.run_localhost(n_clients=2, req_size=64, duration=1.5,
                            concurrency=1)
    assert agg["n_clients"] == 2
    assert agg["rpcs"] > 20
    assert agg["rate_rps"] > 0
    assert agg["rtt_us"]["p50"] > 0
    # achieved-concurrency provenance (ISSUE 3 satellite): workers can fall
    # behind --concurrency; a healthy localhost run must achieve all of it,
    # summed across the 2 client workers
    assert agg["concurrency_requested"] == 2
    assert agg["concurrency_achieved"] == 2


def test_micro_records_achieved_concurrency():
    srv = micro.run_server(0)
    try:
        result = micro.run_client(f"127.0.0.1:{srv.bench_port}", req_size=32,
                                  duration=1.0, concurrency=3,
                                  report_every=0.5, out=io.StringIO())
        assert result["concurrency_requested"] == 3
        assert result["concurrency_achieved"] == 3  # nobody fell behind
    finally:
        srv.stop(grace=0)


def test_micro_achieved_concurrency_drops_when_workers_die():
    """A worker that dies mid-run (server torn down under it while others
    already stopped... simulated directly: bogus target for some workers)
    must NOT be counted as achieved load."""
    srv = micro.run_server(0)
    port = srv.bench_port
    srv.stop(grace=0)  # nothing listens: every worker errors out mid-run
    result = micro.run_client(f"127.0.0.1:{port}", req_size=32,
                              duration=1.0, concurrency=2,
                              report_every=0.5, out=io.StringIO())
    assert result["concurrency_requested"] == 2
    assert result["concurrency_achieved"] < 2
    assert result["rpcs"] == 0


def _cpus() -> int:
    import os

    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


@pytest.mark.skipif(_cpus() < 4, reason=(
    "ring-beats-TCP is a property of the spinning data plane: with <4 cores "
    "the hybrid discipline degrades to event (poller.py) and the measurement "
    "compares scheduler wakeup latencies, not transports. The bench host "
    "(multi-core TPU VM) runs this; single-hart CI skips."))
def test_ring_beats_tcp_small_unary(monkeypatch):
    """The reference's defining property (README.md:1-8): the ring path must
    beat the TCP fallback on the same host. 64B closed-loop unary."""
    import io as _io

    import tpurpc.utils.config as config_mod

    results = {}
    for platform in ("TCP", "RDMA_BPEV"):
        monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
        config_mod.set_config(None)
        srv = micro.run_server(0)
        try:
            r = micro.run_client(f"127.0.0.1:{srv.bench_port}", req_size=64,
                                 duration=2.0, report_every=10,
                                 out=_io.StringIO())
        finally:
            srv.stop(grace=0)
        results[platform] = r
    assert (results["RDMA_BPEV"]["rtt_us"]["p50"]
            < results["TCP"]["rtt_us"]["p50"]), results


@pytest.mark.skipif(_cpus() < 4, reason="see test_ring_beats_tcp_small_unary")
def test_ring_beats_tcp_streaming_bandwidth(monkeypatch):
    """1MiB streaming ping-pong bandwidth: ring >= TCP on a spinning host."""
    import io as _io

    import tpurpc.utils.config as config_mod

    rates = {}
    for platform in ("TCP", "RDMA_BPEV"):
        monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
        config_mod.set_config(None)
        srv = micro.run_server(0)
        try:
            r = micro.run_client(f"127.0.0.1:{srv.bench_port}",
                                 req_size=1 << 20, streaming=True,
                                 duration=2.0, report_every=10,
                                 out=_io.StringIO())
        finally:
            srv.stop(grace=0)
        rates[platform] = r["rpcs"]
    assert rates["RDMA_BPEV"] >= rates["TCP"], rates


def test_raw_bench_modes():
    """Raw (no-RPC) transport bench — the rdma_microbenchmark analog —
    produces sane JSON for both workloads on every wait discipline."""
    import json as _json

    from tpurpc.bench import raw as rawbench

    out = rawbench.run_bw(size=1 << 16, msgs=32, ring_size=1 << 20,
                          discipline="event")
    assert out["gbps"] > 0 and out["msgs_per_s"] > 0

    out = rawbench.run_lat(iters=50, ring_size=1 << 20, discipline="hybrid")
    assert out["p50_us"] > 0 and out["p99_us"] >= out["p50_us"]

    # CLI shape: one JSON line
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rawbench.main(["bw", "--size", "65536", "--msgs", "16",
                       "--ring-kb", "1024"])
    parsed = _json.loads(buf.getvalue())
    assert parsed["metric"] == "raw_ring_bandwidth"


def test_sweep_cell_runs():
    """One sweep cell end to end: fresh-process server under the cell's
    platform, JSON result with the reference-comparable fields."""
    from tpurpc.bench.sweep import run_cell

    cell = run_cell("TCP", 64, duration=1.0, concurrency=1, streaming=False)
    assert cell["rpcs"] > 0
    assert cell["rate_rps"] > 0
    assert {"p50", "p95", "p99"} <= set(cell["rtt_us"])
    assert cell["platform"] == "TCP" and cell["size"] == 64


def test_wire_sweep_cell_runs():
    """One cell of the gRPC-wire-path sweep (tpurpc/bench/wire.py): a
    stock grpcio client against the tpurpc h2 server produces a sane
    measurement record — the rig behind bench/results/wire_1core.log."""
    from tpurpc.bench.wire import run_cell

    cell = run_cell("tpurpc", 64, duration=0.5, streaming=False)
    assert cell["server"] == "tpurpc" and cell["size"] == 64
    assert cell["rpcs"] > 10
    assert cell["rtt_us"]["p50"] > 0
