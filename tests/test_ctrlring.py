"""tpurpc-pulse (ISSUE 13): shared-memory descriptor rings for the
rendezvous control plane.

Covers the ring protocol itself (post/drain ordering, seq stamping, the
frame_seq ordering gate, ring-full fallback, the parked/kick handshake,
nonce verification), the hello-blob negotiation ladder (un-negotiated
peers and garbage blobs stay framed), the end-to-end zero-control-frames
steady state, peer death with ring control in flight on both platforms,
the stale-ring (late write lands in dead memory) rule, the exhaustive
ringcheck model + its seeded mutants, the watchdog's ``ctrl-ring`` stage,
the lens ``ctrl`` hop's slowest-hop exclusion, and the coalesced framed
path (FrameWriter.batch + the migrate burst)."""

import threading
import time

import numpy as np
import pytest

import tpurpc.core.ctrlring as ctrlring
import tpurpc.core.rendezvous as rdv
import tpurpc.rpc as tps
from tpurpc.rpc.channel import Channel
from tpurpc.rpc.status import RpcError, StatusCode


@pytest.fixture
def fresh_config(monkeypatch):
    from tpurpc.utils import config as config_mod

    yield monkeypatch
    config_mod.set_config(None)


def _reset_platform(monkeypatch, platform):
    from tpurpc.utils import config as config_mod

    monkeypatch.setenv("GRPC_PLATFORM_TYPE", platform)
    config_mod.set_config(None)


def _pair_rings():
    """An (rx, tx) pair the unit tests drive directly: the consumer-owned
    ring plus a producer window opened from its descriptor."""
    rx = ctrlring.CtrlRing(kind="shm", nslots=8)
    desc = rx.descriptor()
    (nslots, slot_bytes, nbytes, nonce,
     klen) = ctrlring._DESC.unpack_from(desc)
    pos = ctrlring._DESC.size
    kind = desc[pos:pos + klen].decode()
    handle = desc[pos + klen:].decode()
    tx = ctrlring.CtrlPeer(kind, handle, nslots, slot_bytes, nbytes, nonce)
    return rx, tx


# ---------------------------------------------------------------------------
# the ring protocol
# ---------------------------------------------------------------------------

def test_post_drain_roundtrip_in_order():
    rx, tx = _pair_rings()
    try:
        for i in range(5):
            assert tx.post(3, 100 + i, bytes([i]) * (i + 1), 0) in (1, 2)
        got = []
        n = rx.drain(lambda op, sid, pl: got.append((op, sid, bytes(pl))),
                     lambda: 0)
        assert n == 5
        assert got == [(3, 100 + i, bytes([i]) * (i + 1))
                       for i in range(5)]
        assert tx.backlog() == 0  # one cons_head publish per batch
    finally:
        tx.close()
        rx.close()


def test_ring_full_refuses_then_recovers():
    rx, tx = _pair_rings()
    try:
        for i in range(rx.nslots):
            assert tx.post(1, i, b"x", 0)
        assert tx.post(1, 99, b"x", 0) == 0  # full: framed fallback
        assert tx.backlog() == rx.nslots
        got = []
        rx.drain(lambda *a: got.append(a), lambda: 0)
        assert len(got) == rx.nslots
        assert tx.post(1, 99, b"x", 0)  # space returned
    finally:
        tx.close()
        rx.close()


def test_oversized_payload_refused():
    rx, tx = _pair_rings()
    try:
        assert tx.post(1, 1, b"y" * (ctrlring.MAX_CTRL_PAYLOAD + 1), 0) == 0
        assert tx.post(1, 1, b"y" * ctrlring.MAX_CTRL_PAYLOAD, 0)
    finally:
        tx.close()
        rx.close()


def test_frame_seq_gate_defers_until_frames_dispatch():
    """A record stamped with frame_seq N is invisible until the consumer
    has dispatched N frames — the ordering seam between the ring and the
    framed path."""
    rx, tx = _pair_rings()
    try:
        assert tx.post(3, 1, b"a", 2)
        assert tx.post(3, 2, b"b", 4)
        got = []
        sink = lambda op, sid, pl: got.append(sid)  # noqa: E731
        assert rx.drain(sink, lambda: 0) == 0     # both gated
        assert rx.drain(sink, lambda: 2) == 1     # first passes
        assert got == [1]
        assert rx.drain(sink, lambda: 3) == 0     # head-of-line gates
        assert rx.drain(sink, lambda: 4) == 1
        assert got == [1, 2]
    finally:
        tx.close()
        rx.close()


def test_parked_flag_requests_kick():
    rx, tx = _pair_rings()
    try:
        rx.set_parked(False)
        assert tx.post(1, 1, b"a", 0) == 1   # consumer polling: no kick
        rx.set_parked(True)
        assert tx.post(1, 2, b"b", 0) == 2   # parked: caller must kick
    finally:
        tx.close()
        rx.close()


def test_peer_open_rejects_wrong_nonce():
    rx = ctrlring.CtrlRing(kind="shm", nslots=8)
    try:
        desc = rx.descriptor()
        (nslots, slot_bytes, nbytes, _nonce,
         klen) = ctrlring._DESC.unpack_from(desc)
        pos = ctrlring._DESC.size
        kind = desc[pos:pos + klen].decode()
        handle = desc[pos + klen:].decode()
        with pytest.raises(OSError):
            ctrlring.CtrlPeer(kind, handle, nslots, slot_bytes, nbytes,
                              b"\x00" * 16)
    finally:
        rx.close()


def test_stale_ring_write_lands_in_dead_memory():
    """The satellite claim: a late ring-slot write AFTER link death lands
    in orphaned memory — never in a ring a new link reads.  The consumer
    closes (region released on its side); the straggling producer's post
    hits its still-mapped window without error, and a FRESH ring never
    observes it."""
    rx, tx = _pair_rings()
    rx.close()                      # link death: consumer side gone
    assert tx.post(3, 7, b"late", 0) in (0, 1, 2)  # no crash either way
    # a new link allocates a NEW ring (never pooled): the straggler's
    # bytes are unobservable there
    rx2, tx2 = _pair_rings()
    try:
        got = []
        assert rx2.drain(lambda *a: got.append(a), lambda: 0) == 0
        assert got == []
        assert rx2.drain(lambda *a: got.append(a), lambda: 0) == 0
    finally:
        tx2.close()
        rx2.close()
        tx.close()
    # the dead ring's drain is inert too
    assert rx.drain(lambda *a: None, lambda: 0) == 0


def test_plane_negotiation_ladder():
    """Empty blob (peer predates rings / non-shm), garbage blob, and a
    valid blob: only the last arms; the rest stay framed."""
    a = ctrlring.CtrlPlane("test-a")
    b = ctrlring.CtrlPlane("test-b")
    try:
        assert not a.on_hello(b"")          # un-negotiated peer
        assert not a.armed
        assert not a.on_hello(b"\x07garbage")
        assert not a.armed
        assert a.on_hello(b.hello_blob())   # real descriptor: adopt
        assert a.armed
        sent = []
        assert a.post(3, 1, b"p", 0, kick=lambda: sent.append("kick"))
        got = []
        assert b.drain(lambda op, sid, pl: got.append((op, sid)),
                       lambda: 0) == 1
        assert got == [(3, 1)]
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# end to end
# ---------------------------------------------------------------------------

def _sink_server():
    from tpurpc.jaxshim import add_tensor_method

    srv = tps.Server(max_workers=4, native_dataplane=False)

    def consume(req_iter):
        total = 0
        for tree in req_iter:
            total += np.asarray(tree["x"]).nbytes
        yield {"bytes": np.int64(total)}

    add_tensor_method(srv, "Sink", consume, kind="stream_stream")
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_steady_state_stream_zero_control_frames(fresh_config, platform):
    """The tentpole claim end to end: after warmup, a stream of standing
    transfers does one one-sided write + one ring slot per message —
    ``rdv_ctrl_frames`` stays flat and every control op rides the ring."""
    _reset_platform(fresh_config, platform)
    from tpurpc.jaxshim import TensorClient
    from tpurpc.obs import flight, metrics

    reg = metrics.registry().metrics()
    srv, port = _sink_server()
    payload = np.ones((512, 512), np.float32)  # 1 MiB
    t0 = time.monotonic_ns()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            list(cli.duplex("Sink", gen(2), native=False, timeout=60))
            frames0 = reg["rdv_ctrl_frames"].snapshot()
            posts0 = reg["ctrl_ring_posts"].snapshot()
            sent0 = reg["rdv_transfers_sent"].snapshot()
            replies = list(cli.duplex("Sink", gen(8), native=False,
                                      timeout=120))
            total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
            assert total == 8 * payload.nbytes
            assert reg["rdv_transfers_sent"].snapshot() - sent0 == 8
            assert reg["rdv_ctrl_frames"].snapshot() - frames0 == 0
            assert reg["ctrl_ring_posts"].snapshot() - posts0 >= 8
        evs = [e["event"] for e in flight.snapshot(since_ns=t0)]
        assert "ctrl-adopt" in evs
        # the declared ctrl machines hold over everything this emitted
        from tpurpc.analysis import protocol

        assert protocol.check_events(flight.snapshot(since_ns=t0),
                                     strict=False) == []
    finally:
        srv.stop(grace=1)


def test_disabled_env_keeps_framed_control(fresh_config):
    """TPURPC_CTRL_RING=0: the PR 9 framed control path exactly as it
    was — transfers still rendezvous, control ops frame."""
    _reset_platform(fresh_config, "RDMA_BPEV")
    fresh_config.setenv("TPURPC_CTRL_RING", "0")
    from tpurpc.jaxshim import TensorClient
    from tpurpc.obs import metrics

    reg = metrics.registry().metrics()
    srv, port = _sink_server()
    payload = np.ones((512, 512), np.float32)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            frames0 = reg["rdv_ctrl_frames"].snapshot()
            sent0 = reg["rdv_transfers_sent"].snapshot()
            list(cli.duplex("Sink", gen(4), native=False, timeout=60))
            assert reg["rdv_transfers_sent"].snapshot() > sent0
            assert reg["rdv_ctrl_frames"].snapshot() > frames0
    finally:
        srv.stop(grace=1)


@pytest.mark.parametrize("platform", ["TCP", "RDMA_BPEV"])
def test_peer_death_with_ring_control_in_flight(fresh_config, platform):
    """The chaos satellite: kill the peer while descriptor-ring control is
    mid-transfer (claim observed, COMPLETE never sent).  The victim gets a
    status (never hangs), the claimed region releases/quarantines, and the
    protocol checker holds over the dump — ctrl machines included."""
    from tpurpc.obs import flight

    _reset_platform(fresh_config, platform)
    flight.RECORDER.reset()
    srv = tps.Server(max_workers=4, native_dataplane=False)
    big = b"\x6b" * (1 << 20)
    srv.add_method("/pulse.S/Big", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: big))
    srv.add_method("/pulse.S/Warm", tps.unary_unary_rpc_method_handler(
        lambda req, ctx: b"ok"))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    wedge = threading.Event()  # never set: the sender wedges after claim
    outcome: list = []
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/pulse.S/Big", tpurpc_native=False)
            warm = ch.unary_unary("/pulse.S/Warm", tpurpc_native=False)
            assert bytes(warm(b"w", timeout=30)) == b"ok"
            t_armed = time.monotonic_ns()
            # ring control must actually be in flight for this scenario
            deadline = time.monotonic() + 10
            adopted = False
            while not adopted and time.monotonic() < deadline:
                adopted = any(e["event"] == "ctrl-adopt"
                              for e in flight.snapshot())
                time.sleep(0.02)
            assert adopted, "descriptor ring never adopted"
            rdv.TEST_HOOKS["wedge_after_claim"] = wedge

            def call():
                try:
                    mc(b"x", timeout=60)
                    outcome.append(("ok",))
                except RpcError as exc:
                    outcome.append(("status", exc.code()))

            t = threading.Thread(target=call)
            t.start()
            claimed = None
            deadline = time.monotonic() + 15
            while claimed is None and time.monotonic() < deadline:
                time.sleep(0.05)
                for e in flight.snapshot(since_ns=t_armed):
                    if e["event"] == "rdv-claim" and e["a1"] != 0:
                        claimed = e
                        break
            assert claimed is not None, "claim never observed"
            srv.stop(grace=0)  # peer dies with ring control in flight
            t.join(timeout=30)
            assert not t.is_alive(), "call hung after peer death"
            assert outcome and outcome[0][0] == "status", outcome
            assert outcome[0][1] in (StatusCode.UNAVAILABLE,
                                     StatusCode.CANCELLED,
                                     StatusCode.DEADLINE_EXCEEDED), outcome
            from tpurpc.analysis import protocol

            events = flight.snapshot()
            tag, lease = claimed["tag"], claimed["a2"]
            protocol.assert_ordered(
                events,
                [("rdv-claim", {"tag": tag, "a2": lease}),
                 (("conn-dead", "peer-death"), {}),
                 ("rdv-release", {"tag": tag, "a1": lease})],
                since_ns=t_armed)
            assert protocol.check_events(events, strict=False) == []
    finally:
        rdv.TEST_HOOKS.pop("wedge_after_claim", None)
        wedge.set()
        srv.stop(grace=0)


def test_async_domain_complete_stays_framed():
    """Regression (caught live by the tcpw cross-process test): a COMPLETE
    whose payload rode an ASYNC landing domain (no host-addressable view —
    tcp_window records, verbs WRs) must ride the framed path, which the
    shared record stream sequences after the payload; a ring-posted
    COMPLETE would overtake the bytes and deliver a torn region."""
    from tpurpc.core import pair as pair_mod

    framed = []
    ring = []
    link = rdv.RdvLink("t", lambda op, sid, pl: framed.append(op),
                       lambda sid, fl, body: None)
    link.ctrl_post = lambda op, sid, pl: ring.append(op) or True

    def mk_claim(view):
        c = rdv._Claim(7, "k", "h", 0, 1 << 20, b"", standing=False)
        link._windows[("k", "h")] = pair_mod.Window(
            write=lambda off, data: None, view=view)
        return c

    # async domain: no view -> framed COMPLETE
    link.rdv_complete(mk_claim(None), 1, 0, 64)
    assert framed == [rdv.OP_COMPLETE] and ring == []
    # sync (view-backed) domain: ring COMPLETE
    framed.clear()
    link.rdv_complete(mk_claim(memoryview(bytearray(8))), 1, 0, 64)
    assert ring == [rdv.OP_COMPLETE] and framed == []


# ---------------------------------------------------------------------------
# the model, the watchdog stage, the lens hop
# ---------------------------------------------------------------------------

def test_ringcheck_ctrl_model_clean():
    from tpurpc.analysis import ringcheck

    for res in ringcheck.ctrl_default_suite():
        assert res.ok, res


def test_ringcheck_ctrl_mutants_all_killed():
    from tpurpc.analysis import ringcheck

    kills = ringcheck.ctrl_mutant_kill_suite()
    assert set(kills) == set(ringcheck.CTRL_MUTANTS)
    assert all(kills.values()), kills


def test_watchdog_names_ctrl_ring_stage():
    """An aged ring-full stall bracket (or backlog behind an aged
    rendezvous edge) attributes to `ctrl-ring`, outranking the generic
    rendezvous story."""
    from tpurpc.obs import watchdog as wdmod

    wd = wdmod.StallWatchdog(sweep_s=10, min_stall_s=0.2)
    now = time.monotonic_ns()
    ev = {
        "now_ns": now, "open_lease": 0, "open_edges": {},
        "open_rdv": {(7, "o", 1): now - int(2e9)},
        "open_ctrl": {7: now - int(2e9)},
        "ctrl_ring_backlog": 3,
        "open_swap": {}, "open_mig": {}, "open_step": {},
        "last_step_end_ns": 0, "last_step_batch": 0, "last_h2_ns": 0,
        "pairs_write_stalled": 0, "batcher_queue_depth": 0,
        "pairs_msg_waiting": 0, "decode_waiting": 0, "decode_running": 0,
    }
    stage, detail = wd._attribute(ev, "client", int(2e9))
    assert stage == "ctrl-ring", (stage, detail)
    # without ring evidence the rendezvous story is untouched
    ev2 = dict(ev, open_ctrl={}, ctrl_ring_backlog=0)
    stage2, _ = wd._attribute(ev2, "client", int(2e9))
    assert stage2 == "rendezvous"
    assert "ctrl-ring" in wdmod.STAGES


def test_lens_ctrl_hop_declared_and_excluded_from_slowest():
    """The `ctrl` hop exists, and the <1%-of-bulk-bytes rule keeps a
    control-only hop out of the slowest-hop argmin."""
    from tpurpc.obs import lens

    assert "ctrl" in lens.HOP_NAMES
    rows = [
        {"hop": "rendezvous", "bytes": 1 << 30, "busy_ms": 500.0,
         "gbps": 2.0, "copy_bytes": 0, "what": ""},
        {"hop": "ctrl", "bytes": 4096, "busy_ms": 400.0,
         "gbps": 0.00001, "copy_bytes": 0, "what": ""},
    ]
    assert lens.slowest_hop(rows) == "rendezvous"


# ---------------------------------------------------------------------------
# the coalesced framed path (satellite: one writev per burst)
# ---------------------------------------------------------------------------

class _FakeEndpoint:
    def __init__(self):
        self.writes = []

    def write(self, segs):
        if isinstance(segs, (bytes, bytearray, memoryview)):
            segs = [segs]
        self.writes.append(b"".join(bytes(s) for s in segs))


def test_framewriter_batch_one_writev():
    from tpurpc.rpc import frame as fr

    ep = _FakeEndpoint()
    w = fr.FrameWriter(ep)
    with w.batch():
        for sid in (1, 3, 5):
            w.send_many([(fr.HEADERS, 0, sid, b"h" * 8),
                         (fr.MESSAGE, fr.FLAG_END_STREAM, sid, b"m" * 16)])
    assert len(ep.writes) == 1  # six frames, ONE gathered writev
    assert w.frames_sent == 6
    # order inside the batch is issue order
    r = fr.FrameReader(_ReplayEndpoint(ep.writes[0]))
    seen = []
    while True:
        f = r.read_frame()
        if f is None:
            break
        seen.append((f.type, f.stream_id))
    assert seen == [(fr.HEADERS, 1), (fr.MESSAGE, 1), (fr.HEADERS, 3),
                    (fr.MESSAGE, 3), (fr.HEADERS, 5), (fr.MESSAGE, 5)]


class _ReplayEndpoint:
    def __init__(self, blob):
        self._blob = memoryview(bytes(blob))
        self._pos = 0

    def read_into(self, dst, timeout=None):
        n = min(len(dst), len(self._blob) - self._pos)
        dst[:n] = self._blob[self._pos:self._pos + n]
        self._pos += n
        return n


def test_ctrl_frame_coalescer_self_clocking():
    """Ops arriving while a flush is in flight drain in ONE multi-op
    send — PR 3's self-clocking writev discipline on the control path."""
    sent_single = []
    sent_multi = []
    gate = threading.Event()
    release = threading.Event()

    def send_op(op, sid, payload):
        sent_single.append((op, sid))
        gate.set()
        release.wait(5)

    def send_ops(ops):
        sent_multi.append([o[:2] for o in ops])

    co = rdv._CtrlFrameCoalescer(send_op, send_ops)
    t = threading.Thread(target=lambda: co.send(3, 1, b"a"))
    t.start()
    assert gate.wait(5)  # first op mid-flush
    co.send(3, 2, b"b")  # queue while in flight
    co.send(3, 3, b"c")
    release.set()
    t.join(5)
    assert sent_single == [(3, 1)]
    assert sent_multi == [[(3, 2), (3, 3)]]  # one flush for the burst


def test_migrate_burst_one_writev(fresh_config):
    """The disagg satellite end to end: migrating several sequences
    flushes the OfferKv burst (and the CompleteKv burst) as coalesced
    writevs — the ctrl_call_batch histogram records multi-frame batches —
    and every sequence resumes exactly at the peer."""
    _reset_platform(fresh_config, "TCP")
    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.serving.disagg import DisaggClient, migrate, serve_decode
    from tpurpc.utils import stats as _st

    model_a = ToyDecodeModel(step_delay_s=0.004)
    model_b = ToyDecodeModel(step_delay_s=0.004)
    srv_a, port_a, sched_a, state_a = serve_decode(
        model_a, kv_blocks=256, name="pulse-src")
    srv_b, port_b, sched_b, state_b = serve_decode(
        model_b, kv_blocks=256, name="pulse-dst")
    ch_b = Channel(f"127.0.0.1:{port_b}")
    try:
        prompts = [[3, 1, 4, 1], [2, 7, 1, 8], [1, 6, 1, 8]]
        streams = [sched_a.submit(np.array(p, np.int32), max_tokens=200)
                   for p in prompts]
        for s in streams:  # a few tokens so KV exists
            for _ in range(3):
                s.next(timeout=5)
        _st.reset_batch_stats()
        moved, failed = migrate(state_a, ch_b, f"127.0.0.1:{port_b}")
        assert moved == 3 and failed == 0, (moved, failed)
        hist = _st.batch_snapshot().get("ctrl_call_batch") or {}
        assert hist.get("count", 0) >= 1
        assert hist.get("p99", 0) >= 3, hist  # 3 offers in one writev
    finally:
        ch_b.close()
        for srv, sched, state in ((srv_a, sched_a, state_a),
                                  (srv_b, sched_b, state_b)):
            srv.stop(grace=0)
            sched.close()       # deregister from /healthz (test isolation)
            state.close()
            state.mgr.close()


# ---------------------------------------------------------------------------
# native planes (tpurpc-ironclad): the C consumer's drain discipline
# ---------------------------------------------------------------------------

def _native_counters():
    from tpurpc.rpc import native_client

    return native_client.rdv_counters()


@pytest.mark.parametrize("platform", ["RDMA_BP", "RDMA_BPEV"])
def test_native_steady_state_zero_control_frames(fresh_config, platform):
    """The acceptance bar on the C planes: after warmup, native bulk moves
    with ZERO framed control ops — every OFFER/CLAIM/COMPLETE rides the
    128 B descriptor ring — and (near-)zero CTRL_KICK fd wakeups (parking
    transitions at stream edges are the only legitimate kicks)."""
    _reset_platform(fresh_config, platform)
    if _native_counters() is None:
        pytest.skip("native data plane unavailable")
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    srv = Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/ctrlnat.S/Total",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = b"\xa5" * (1 << 20)
    n = 8
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/ctrlnat.S/Total")
            list(mc(iter([payload] * 2), timeout=60))  # warmup: hello+heat
            c0 = _native_counters()
            out = list(mc(iter([payload] * n), timeout=120))
            c1 = _native_counters()
        assert out[-1] == str(n * len(payload)).encode()
        assert c1["rdv_sent"] - c0["rdv_sent"] >= n
        # ZERO control ops fell back to frames...
        assert c1["ctrl_frames"] == c0["ctrl_frames"]
        # ...the ring carried them — steady state on a standing grant is
        # ONE COMPLETE descriptor per message (no OFFER/CLAIM at all)...
        assert c1["ctrl_posts"] - c0["ctrl_posts"] >= n
        assert c1["ctrl_records"] - c0["ctrl_records"] >= n
        # ...and fd kicks happened at most at the stream's cold edges,
        # never once per message (the wakeup the ring exists to delete)
        assert c1["ctrl_kicks"] - c0["ctrl_kicks"] <= n // 2
    finally:
        srv.stop(grace=1)


def test_native_ctrl_disabled_still_rendezvous(fresh_config):
    """TPURPC_CTRL_RING=0 on the native planes: transfers still ride the
    rendezvous ladder, control ops go framed — correct, just chattier."""
    _reset_platform(fresh_config, "RDMA_BP")
    if _native_counters() is None:
        pytest.skip("native data plane unavailable")
    fresh_config.setenv("TPURPC_CTRL_RING", "0")
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    srv = Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/ctrlnat.S/Total2",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = b"\x3c" * (1 << 20)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/ctrlnat.S/Total2")
            list(mc(iter([b"warm"]), timeout=30))
            c0 = _native_counters()
            out = list(mc(iter([payload] * 3), timeout=60))
            c1 = _native_counters()
        assert out[-1] == str(3 * len(payload)).encode()
        assert c1["rdv_sent"] - c0["rdv_sent"] >= 3   # ladder still on
        assert c1["ctrl_posts"] == c0["ctrl_posts"]   # no ring
        assert c1["ctrl_frames"] > c0["ctrl_frames"]  # framed control
    finally:
        srv.stop(grace=1)
