"""tpurpc-blackbox (ISSUE 5): flight recorder, stall watchdog, tail capture.

Covers the tentpole's three pieces — the binary event ring (bounds, wrap,
preallocated-encoder reuse, tag interning), the stall watchdog (stage
attribution from flight tail + fleet gauges, trip side effects, clearing),
and tail-based trace capture (promotion on slow/error/flag, drop on
healthy) — plus the satellites: RED counters, the pipelined deadline
counter + flight event, the /debug scrape routes, degraded /healthz, and
the `flight` lint rule.
"""

import json
import struct
import threading
import time

import pytest

from tpurpc.obs import flight, metrics, scrape, tracing, watchdog
from tpurpc.obs.flight import FlightRecorder


@pytest.fixture(autouse=True)
def _clean_blackbox_state():
    flight.RECORDER.reset()
    tracing.reset()
    tracing.force(None)
    tracing.configure(0.0)
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled)
    yield
    wd.min_stall_s, wd.sweep_s, wd.mult, wd.enabled = prev
    wd.reset()
    flight.RECORDER.reset()
    tracing.reset()


# ---------------------------------------------------------------------------
# flight recorder: ring bounds, wrap, encoder reuse, tags
# ---------------------------------------------------------------------------

def test_ring_wrap_keeps_newest_and_stays_bounded():
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.emit(flight.PAIR_CONNECT, 0, i)
    events = rec.snapshot()
    assert len(events) == 8  # exactly the capacity survives a wrap
    assert [e["a1"] for e in events] == list(range(12, 20))  # newest 8
    assert len(rec._buf) == 8 * flight.RECORD_BYTES  # fixed-size, no growth


def test_encoder_reuse_no_reallocation():
    rec = FlightRecorder(capacity=16)
    buf_id = id(rec._buf)
    for i in range(100):
        rec.emit(flight.BATCH_FLUSH, 0, i % 4, i)
    assert id(rec._buf) is not None and id(rec._buf) == buf_id
    assert len(rec._buf) == 16 * flight.RECORD_BYTES
    # disabled recorder emits nothing (the bench's off leg)
    rec.enabled = False
    before = rec.snapshot()
    rec.emit(flight.PAIR_CONNECT, 0, 1)
    assert rec.snapshot() == before


def test_record_fields_roundtrip_and_time_order():
    rec = FlightRecorder(capacity=64)
    rec.emit(flight.LEASE_RESERVE, 3, 12345, -7)
    rec.emit(flight.LEASE_COMMIT, 3, 12345)
    events = rec.snapshot()
    assert [e["event"] for e in events] == ["lease-reserve", "lease-commit"]
    e = events[0]
    assert (e["tag"], e["a1"], e["a2"]) == (3, 12345, -7)
    assert e["tid"] == threading.get_ident() & 0xFFFFFFFF
    assert events[0]["t_ns"] <= events[1]["t_ns"]
    # huge args clamp instead of raising (emit must never throw)
    rec.emit(flight.PAIR_CONNECT, 0, 1 << 80, -(1 << 80))
    got = rec.snapshot()[-1]
    assert got["a1"] == (1 << 63) - 1 and got["a2"] == -(1 << 63)


def test_torn_records_are_skipped():
    rec = FlightRecorder(capacity=8)
    rec.emit(flight.PAIR_CONNECT, 1)
    # simulate a torn slot: plausible timestamp, garbage code
    struct.pack_into("<QHHIqq", rec._buf, flight.RECORD_BYTES,
                     time.monotonic_ns(), 9999, 0, 0, 0, 0)
    events = rec.snapshot()
    assert [e["event"] for e in events] == ["pair-connect"]


def test_tag_interning_is_stable_and_bounded():
    t1 = flight.tag_for("pair:abc")
    t2 = flight.tag_for("pair:abc")
    t3 = flight.tag_for("pair:def")
    assert t1 == t2 != t3
    assert flight.tag_name(t1) == "pair:abc"
    assert flight.tag_name(10 ** 6).startswith("#")  # unknown: no KeyError


def test_dump_text_renders_every_event():
    rec = FlightRecorder(capacity=8)
    rec.emit(flight.WRITE_STALL_BEGIN, flight.tag_for("pair:dump"), 42)
    text = rec.dump_text()
    assert "write-stall-begin" in text and "pair:dump" in text


# ---------------------------------------------------------------------------
# transport emission: a real stalled pair leaves the right evidence
# ---------------------------------------------------------------------------

def test_pair_stall_emits_edge_events():
    from tpurpc.core.pair import create_loopback_pair

    a, b = create_loopback_pair(ring_size=4096)
    try:
        sent = a.send([b"z" * 16384])
        assert sent < 16384 and a.want_write
        names = [e["event"] for e in flight.snapshot()]
        assert "write-stall-begin" in names
        assert "credit-starve-begin" in names
        # drain + resume: the end edges land
        b.recv(1 << 20)
        a.send([b"tail"])
        names = [e["event"] for e in flight.snapshot()]
        assert "write-stall-end" in names
        assert "credit-starve-end" in names
    finally:
        a.destroy()
        b.destroy()


# ---------------------------------------------------------------------------
# stall watchdog: attribution, trip side effects, clearing
# ---------------------------------------------------------------------------

def _fast_wd():
    wd = watchdog.get()
    wd.enabled = True
    wd.min_stall_s = 0.01
    wd.sweep_s = 0.05
    return wd


def test_watchdog_attributes_held_lease_as_credit_starvation():
    wd = _fast_wd()
    tag = flight.tag_for("nclease")
    flight.emit(flight.LEASE_RESERVE, tag, 4096)  # reserve, never commit
    tok = wd.call_started("/t/Lease")
    time.sleep(0.02)
    diags = wd.sweep_once()
    assert diags and diags[0]["stage"] == "credit-starvation"
    assert "send-lease held" in diags[0]["detail"]
    wd.call_finished(tok)
    assert wd.sweep_once() == []


def test_watchdog_attributes_h2_flow_control():
    wd = _fast_wd()
    flight.emit(flight.H2_WINDOW_EXHAUSTED, flight.tag_for("h2srv:t"), 7)
    tok = wd.call_started("/t/H2")
    time.sleep(0.02)
    diags = wd.sweep_once()
    assert diags and diags[0]["stage"] == "h2-flow-control"
    wd.call_finished(tok)


def test_watchdog_quiet_transport_names_device_infer():
    wd = _fast_wd()
    tok = wd.call_started("/t/Infer")
    time.sleep(0.02)
    diags = wd.sweep_once()
    assert diags and diags[0]["stage"] == "device-infer"
    wd.call_finished(tok)
    assert wd.sweep_once() == []


def test_watchdog_trip_side_effects():
    wd = _fast_wd()
    trips0 = metrics.counter("watchdog_trips").snapshot()
    tctx = tracing.maybe_sample()  # provisional (sample rate 0, tail on)
    assert tctx is not None and tctx.provisional
    with tracing.use(tctx):
        with tracing.span("stuck-phase"):
            pass
    assert tracing.spans(tctx.trace_id) == []  # still buffered
    tok = wd.call_started("/t/Trip", tctx.trace_id)
    time.sleep(0.02)
    diags = wd.sweep_once()
    assert diags
    # trip: counter bumped, flight event emitted, trace promoted LIVE
    assert metrics.counter("watchdog_trips").snapshot() == trips0 + 1
    assert any(e["event"] == "watchdog-trip" for e in flight.snapshot())
    assert [s["name"] for s in tracing.spans(tctx.trace_id)] == \
        ["stuck-phase"]
    # second sweep does NOT re-trip (one trip per stalled call)
    wd.sweep_once()
    assert metrics.counter("watchdog_trips").snapshot() == trips0 + 1
    labeled = metrics.labeled_counter("watchdog_stalls", ("stage",))
    assert sum(labeled.snapshot().values()) >= 1
    wd.call_finished(tok)


def test_watchdog_respects_rolling_p99_bar():
    wd = _fast_wd()
    wd.min_stall_s = 0.05
    wd.mult = 100.0
    # history: ~1ms calls → bar = max(min_stall, 100 * ~1ms) ≈ 0.1s+
    for _ in range(16):
        t = wd.call_started("/t/Fast")
        time.sleep(0.001)
        wd.call_finished(t)
    assert wd.slow_threshold_ns("/t/Fast") is not None
    tok = wd.call_started("/t/Fast")
    time.sleep(0.06)  # over min_stall but under the p99 multiple
    assert wd.sweep_once() == []
    wd.call_finished(tok)


# ---------------------------------------------------------------------------
# tail capture: promotion rules
# ---------------------------------------------------------------------------

def test_tail_slow_call_promotes_fast_call_drops():
    ctx_fast = tracing.maybe_sample()
    ctx_slow = tracing.maybe_sample()
    for ctx in (ctx_fast, ctx_slow):
        with tracing.use(ctx):
            with tracing.span("work"):
                pass
    assert not tracing.tail_decide(ctx_fast, 1_000_000, method="/t/M")
    assert tracing.tail_decide(ctx_slow, 10 ** 12, method="/t/M")
    assert tracing.spans(ctx_fast.trace_id) == []
    assert [s["name"] for s in tracing.spans(ctx_slow.trace_id)] == ["work"]
    # post-commit spans land directly in the main ring
    tracing.record("late", ctx_slow, 1, 2)
    assert len(tracing.spans(ctx_slow.trace_id)) == 2


def test_tail_error_promotes():
    ctx = tracing.maybe_sample()
    with tracing.use(ctx):
        with tracing.span("failing"):
            pass
    assert tracing.tail_decide(ctx, 1_000, error=True)
    assert [s["name"] for s in tracing.spans(ctx.trace_id)] == ["failing"]


def test_tail_p99_multiple_tightens_static_bar():
    wd = _fast_wd()
    wd.mult = 2.0
    for _ in range(16):
        t = wd.call_started("/t/Tight")
        wd.call_finished(t)  # ~0 duration history
    ctx = tracing.maybe_sample()
    with tracing.use(ctx):
        with tracing.span("outlier"):
            pass
    # 5ms is far under the 250ms static bar but far over 2 x p99(~µs)
    assert tracing.tail_decide(ctx, 5_000_000, method="/t/Tight")


def test_tail_pending_is_bounded():
    first = tracing.maybe_sample()
    for _ in range(tracing._PENDING_TRACES + 10):
        ctx = tracing.maybe_sample()
        with tracing.use(ctx):
            tracing.record("s", ctx, 1, 1)
    assert tracing.tail_pending() <= tracing._PENDING_TRACES
    # the oldest trace was evicted; committing it now yields nothing
    tracing.tail_commit(first.trace_id)
    assert tracing.spans(first.trace_id) == []


def test_wire_context_adopt_registers_provisional():
    ctx = tracing.TraceContext(0xABC, 1, provisional=True)
    assert ctx.encode().endswith("-2")
    got = tracing.adopt(ctx.encode())
    assert got is not None and got.provisional and got.sampled
    with tracing.use(got):
        with tracing.span("server-side"):
            pass
    assert tracing.spans(0xABC) == []  # buffered under the SAME trace id
    tracing.tail_commit(0xABC)
    assert [s["name"] for s in tracing.spans(0xABC)] == ["server-side"]
    # non-provisional wire flags stay committed-style
    assert not tracing.adopt(
        tracing.TraceContext(1, 2, True).encode()).provisional


# ---------------------------------------------------------------------------
# end-to-end: RED counters, deadline satellite, scrape routes
# ---------------------------------------------------------------------------

def _echo_server(hold=None):
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
    from tpurpc.rpc.status import StatusCode

    srv = Server(max_workers=4)

    def echo(req, ctx):
        if hold is not None:
            hold.wait(5)
        return bytes(req)

    def boom(req, ctx):
        ctx.abort(StatusCode.INVALID_ARGUMENT, "nope")

    srv.add_method("/f.S/Echo", unary_unary_rpc_method_handler(echo))
    srv.add_method("/f.S/Boom", unary_unary_rpc_method_handler(boom))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def test_red_counters_per_method_per_code():
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.status import RpcError

    srv, port = _echo_server()
    try:
        fam = metrics.labeled_counter("srv_calls", ("method", "code"))
        before_ok = fam.snapshot().get(("/f.S/Echo", "0"), 0)
        before_bad = fam.snapshot().get(("/f.S/Boom", "3"), 0)
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/f.S/Echo", tpurpc_native=False)
            for _ in range(3):
                assert mc(b"ok", timeout=20) == b"ok"
            with pytest.raises(RpcError):
                ch.unary_unary("/f.S/Boom", tpurpc_native=False)(
                    b"x", timeout=20)
        # the RED bump lands in the server handler's finally, which can
        # trail the client-visible trailer by a beat — poll briefly
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            snap = fam.snapshot()
            if (snap.get(("/f.S/Echo", "0"), 0) >= before_ok + 3
                    and snap.get(("/f.S/Boom", "3"), 0) >= before_bad + 1):
                break
            time.sleep(0.02)
        assert snap.get(("/f.S/Echo", "0"), 0) >= before_ok + 3
        assert snap.get(("/f.S/Boom", "3"), 0) >= before_bad + 1
        # the Prometheus face renders the labels
        text = scrape.render_prometheus()
        assert 'tpurpc_srv_calls{method="/f.S/Echo",code="0"}' in text
    finally:
        srv.stop(grace=0)


def test_pipelined_deadline_expiry_counter_and_flight_event():
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.status import RpcError, StatusCode

    hold = threading.Event()
    srv, port = _echo_server(hold=hold)
    try:
        fam = metrics.labeled_counter("deadline_exceeded", ("method",))
        before = fam.snapshot().get(("/f.S/Echo",), 0)
        with Channel(f"127.0.0.1:{port}") as ch:
            pl = ch.unary_unary("/f.S/Echo").pipeline(depth=2)
            fut = pl.call_async(b"wedge", timeout=0.2)
            with pytest.raises(RpcError) as ei:
                fut.result(20)
            assert ei.value.code() is StatusCode.DEADLINE_EXCEEDED
        hold.set()
        assert fam.snapshot().get(("/f.S/Echo",), 0) >= before + 1
        assert any(e["event"] == "deadline-expired"
                   for e in flight.snapshot())
    finally:
        hold.set()
        srv.stop(grace=0)


def test_debug_routes_and_healthz_degradation():
    flight.emit(flight.PAIR_CONNECT, flight.tag_for("pair:route"), 1)
    status, ctype, body = scrape._route("/debug/flight")
    assert status == 200 and ctype == "application/json"
    events = json.loads(body)["events"]
    assert any(e["event"] == "pair-connect" and e["entity"] == "pair:route"
               for e in events)
    status, _, body = scrape._route("/debug/flight?text=1")
    assert status == 200 and b"pair-connect" in body

    status, ctype, body = scrape._route("/debug/stalls")
    assert status == 200
    snap = json.loads(body)
    assert {"active", "history", "inflight"} <= set(snap)

    # healthz: ok when quiet, degraded (503) while a diagnosis is active
    wd = _fast_wd()
    assert scrape._route("/healthz")[0] == 200
    tok = wd.call_started("/t/Health")
    time.sleep(0.02)
    wd.sweep_once()
    status, _, body = scrape._route("/healthz")
    assert status == 503 and b"degraded" in body and b"/t/Health" in body
    wd.call_finished(tok)
    wd.sweep_once()
    assert scrape._route("/healthz")[0] == 200


def test_tail_capture_end_to_end_sample_zero():
    """TPURPC_TRACE_SAMPLE=0: a slow RPC yields a committed span tree (the
    acceptance property), a fast RPC leaves the main ring untouched."""
    from tpurpc.rpc.channel import Channel

    hold = threading.Event()
    srv, port = _echo_server(hold=hold)
    try:
        assert not tracing.ACTIVE and tracing.LIVE
        hold.set()  # fast path first
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/f.S/Echo", tpurpc_native=False)
            assert mc(b"fast", timeout=20) == b"fast"
            fast_traces = {s["trace_id"] for s in tracing.spans()}
            hold.clear()

            def release():
                time.sleep(0.45)  # > the 250ms static tail bar
                hold.set()

            threading.Thread(target=release, daemon=True).start()
            assert mc(b"slow", timeout=30) == b"slow"
        deadline = time.monotonic() + 2
        names = set()
        while time.monotonic() < deadline:
            by_trace = {}
            for s in tracing.spans():
                if s["trace_id"] in fast_traces:
                    continue
                by_trace.setdefault(s["trace_id"], set()).add(s["name"])
            names = set().union(*by_trace.values()) if by_trace else set()
            if {"client-send", "wire", "dispatch", "respond"} <= names:
                break
            time.sleep(0.05)
        assert {"client-send", "wire", "dispatch", "respond"} <= names, names
    finally:
        hold.set()
        srv.stop(grace=0)


# ---------------------------------------------------------------------------
# the `flight` lint rule
# ---------------------------------------------------------------------------

HOT = "tpurpc/core/pair.py"  # any FLIGHT_HOT_MODULES suffix


def _lint(src):
    from tpurpc.analysis.lint import lint_source

    return [v for v in lint_source(src, HOT) if v.rule == "flight"]


def test_flight_lint_accepts_preallocated_int_plumbing():
    src = (
        "def f(self):\n"
        "    _flight.emit(_flight.PAIR_CONNECT, self._ftag,\n"
        "                 self.writer.tail - self.writer.remote_head)\n")
    assert _lint(src) == []


def test_flight_lint_rejects_dict_fstring_call_and_str():
    bad = [
        "_flight.emit(_flight.PAIR_CONNECT, 0, {'k': 1})\n",
        "_flight.emit(_flight.PAIR_CONNECT, 0, f'{x}')\n",
        "_flight.emit(_flight.PAIR_CONNECT, tag_for(self.tag))\n",
        "_flight.emit(_flight.PAIR_CONNECT, 0, len(views))\n",
        "_flight.emit(_flight.PAIR_CONNECT, 0, 'stringy')\n",
        "flight.RECORDER.emit(_flight.PAIR_CONNECT, str(x))\n",
    ]
    for src in bad:
        assert _lint(src), f"should flag: {src!r}"


def test_flight_lint_suppression_and_cold_modules():
    src = "_flight.emit(C, 0, len(x))  # tpr: allow(flight)\n"
    assert _lint(src) == []
    from tpurpc.analysis.lint import lint_source

    cold = lint_source("_flight.emit(C, 0, len(x))\n", "tpurpc/rpc/aio.py")
    assert [v for v in cold if v.rule == "flight"] == []


def test_repo_tree_is_flight_clean():
    from tpurpc.analysis.lint import lint_tree

    assert [v for v in lint_tree() if v.rule == "flight"] == []
