"""FrameReader sink path: timeout resumability, gather fragmentation, IOV cap."""

import threading

import numpy as np
import pytest

from tpurpc.core.endpoint import Endpoint, ReadTimeout, passthru_endpoint_pair
from tpurpc.rpc import frame as fr


class _CollectSink(fr.MessageSink):
    def __init__(self):
        self.buffers = {}
        self.done = []

    def buffer_for(self, stream_id):
        return self.buffers.setdefault(stream_id, fr.Assembly())

    def commit(self, stream_id, flags):
        if not flags & fr.FLAG_MORE:
            self.done.append(
                (stream_id, bytes(self.buffers.pop(stream_id).take())))


def test_sink_assembles_fragmented_gather_message():
    a, b = passthru_endpoint_pair()
    w = fr.FrameWriter(a)
    r = fr.FrameReader(b)
    sink = _CollectSink()
    r.sink = sink
    payload = np.arange(1 << 19, dtype=np.uint8)  # 512KiB
    segs = [payload[: 100].tobytes(), payload[100:].data]  # gather list
    w.send(fr.MESSAGE, 0, 7, segs)
    w.send(fr.TRAILERS, 0, 7, fr.trailers_payload(0, ""))
    got = r.read_frame(timeout=5)
    assert got is fr.CONSUMED
    assert sink.done == [(7, payload.tobytes())]
    trailers = r.read_frame(timeout=5)
    assert trailers.type == fr.TRAILERS


def test_sink_resumes_after_mid_payload_timeout():
    """A ReadTimeout inside a MESSAGE body must not desync the framing."""
    a, b = passthru_endpoint_pair()
    w = fr.FrameWriter(a)
    r = fr.FrameReader(b)
    sink = _CollectSink()
    r.sink = sink
    big = bytes(range(256)) * 4096  # 1 MiB → one frame, but sent in pieces

    # write the frame header + first half of the payload only
    hdr = fr.HEADER_FMT.pack(fr.MESSAGE, 0, 3, len(big))
    a.write([hdr, big[: len(big) // 2]])

    with pytest.raises(ReadTimeout):
        r.read_frame(timeout=0.2)
    assert sink.done == []  # incomplete: nothing committed

    a.write(big[len(big) // 2:])  # rest arrives later
    got = r.read_frame(timeout=5)
    assert got is fr.CONSUMED
    assert sink.done == [(3, big)]


def test_many_segment_gather_write_survives_iov_max():
    """>1024 gather segments in one frame must not kill the connection
    (Linux sendmsg caps one call at IOV_MAX=1024 iovecs)."""
    import socket

    from tpurpc.core.endpoint import TcpEndpoint

    s1, s2 = socket.socketpair()
    a, b = TcpEndpoint(s1), TcpEndpoint(s2)
    try:
        w = fr.FrameWriter(a)
        r = fr.FrameReader(b)
        sink = _CollectSink()
        r.sink = sink
        segs = [bytes([i % 256]) * 3 for i in range(3000)]
        want = b"".join(segs)

        t = threading.Thread(target=lambda: w.send(fr.MESSAGE, 0, 1, segs))
        t.start()
        assert r.read_frame(timeout=10) is fr.CONSUMED
        t.join(timeout=10)
        assert sink.done == [(1, want)]
    finally:
        a.close()
        b.close()


def test_metadata_and_header_parsers_never_crash_on_garbage():
    """Wire-facing parsers must fail LOUDLY-BUT-TYPED on hostile bytes
    (FrameError — the reader turns it into a connection error), never
    with an unexpected exception class a dispatcher wouldn't catch."""
    import random

    from tpurpc.rpc import frame as fr

    rng = random.Random(11)
    for _ in range(300):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(96)))
        for parse in (fr.decode_metadata, fr.parse_headers,
                      fr.parse_trailers):
            try:
                parse(blob)
            except fr.FrameError:
                pass  # the documented loud-but-typed outcome
