"""tpurpc serving: continuous-batching generation + the paged KV plane.

* :mod:`tpurpc.serving.scheduler` — the :class:`DecodeScheduler` state
  machine: sequences JOIN and LEAVE the device batch between decode steps,
  prefill rides a per-step token budget, SLO classes gate admission and
  preemption, and load shedding trips before collapse. With ``kv=`` it
  runs PAGED: block-table state, prefix-cache prefill skips, and
  preempt-to-host swap (tpurpc-keystone, ISSUE 11).
* :mod:`tpurpc.serving.kv` — the paged KV block manager: block arena over
  a registered region, per-sequence block tables, copy-on-write prefix
  reuse, swap, quarantine.
* :mod:`tpurpc.serving.api` — the transport face: ``serve_generation``
  stands up a streaming Generate method around a step model;
  ``GenerationClient`` consumes per-token streams.
* :mod:`tpurpc.serving.disagg` — disaggregated prefill/decode: KV blocks
  ship over the rendezvous plane's block grants, sequences hand off and
  MIGRATE live between decode servers, clients re-attach transparently.
"""

from tpurpc.serving.api import (GEN_SERVICE, GenerationClient,
                                add_generation_method, serve_generation)
from tpurpc.serving.disagg import (KV_SERVICE, DisaggClient, DisaggDecode,
                                   DisaggPrefill, MigrationFailed,
                                   SeqMigrated, migrate, serve_decode,
                                   serve_prefill)
from tpurpc.serving.kv import HostKv, KvArenaFull, KvBlockManager, SeqKv
from tpurpc.serving.scheduler import (SLO_BATCH, SLO_INTERACTIVE,
                                      DecodeScheduler, DrainingError,
                                      ShedError, TokenStream)

__all__ = [
    "DecodeScheduler", "TokenStream", "ShedError", "DrainingError",
    "SLO_INTERACTIVE", "SLO_BATCH",
    "GEN_SERVICE", "GenerationClient", "add_generation_method",
    "serve_generation",
    "KvBlockManager", "SeqKv", "HostKv", "KvArenaFull",
    "KV_SERVICE", "DisaggClient", "DisaggDecode", "DisaggPrefill",
    "SeqMigrated", "MigrationFailed", "migrate", "serve_decode",
    "serve_prefill",
]
