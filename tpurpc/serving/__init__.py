"""tpurpc-cadence (ISSUE 10): continuous-batching token-streaming serving.

* :mod:`tpurpc.serving.scheduler` — the :class:`DecodeScheduler` state
  machine: sequences JOIN and LEAVE the device batch between decode steps,
  prefill rides a per-step token budget, SLO classes gate admission and
  preemption, and load shedding trips before collapse.
* :mod:`tpurpc.serving.api` — the transport face: ``serve_generation``
  stands up a streaming Generate method around a step model;
  ``GenerationClient`` consumes per-token streams.
"""

from tpurpc.serving.api import (GEN_SERVICE, GenerationClient,
                                add_generation_method, serve_generation)
from tpurpc.serving.scheduler import (SLO_BATCH, SLO_INTERACTIVE,
                                      DecodeScheduler, DrainingError,
                                      ShedError, TokenStream)

__all__ = [
    "DecodeScheduler", "TokenStream", "ShedError", "DrainingError",
    "SLO_INTERACTIVE", "SLO_BATCH",
    "GEN_SERVICE", "GenerationClient", "add_generation_method",
    "serve_generation",
]
