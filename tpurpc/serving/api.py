"""tpurpc-cadence transport face: streaming generation over tpurpc.

``add_generation_method`` registers a server-streaming tensor method whose
handler is a thin bridge: submit to the :class:`~tpurpc.serving.scheduler.
DecodeScheduler`, then forward tokens from the sequence's stream queue to
the RPC stream with BOUNDED waits interleaving client-liveness checks — a
client that cancels (RST) or dies flips ``ctx.is_active()`` and the bridge
cancels the sequence, which the scheduler retires at the next step
boundary (leave-mid-stream never stalls the batch).

Per-token responses are tiny trees (``{"token", "index"}``): exactly the
small-payload regime the serving-loop studies call pathological for
framed RPC — which is why the responses ride the PR 3 coalescing path
(many streams' tokens gather into one writev per flush) instead of one
syscall per token.

Wire shapes (all int32):

* request: ``{"prompt": [L], "max_tokens": scalar, "slo": scalar}``
  (slo: 0 = interactive, 1 = batch);
* response, one per token: ``{"token": scalar, "index": scalar}``.

``serve_generation`` is the one-liner (serve_jax's sibling): scheduler +
server + admission gate (queue-depth via transport inflight, step-time
via the scheduler's rolling p99) + fleet load reports + drain wiring.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from tpurpc.jaxshim import codec
from tpurpc.obs import odyssey as _odyssey
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc.server import (PUSHBACK_KEY, AdmissionGate, Server,
                               unary_stream_rpc_method_handler)
from tpurpc.rpc.status import StatusCode
from tpurpc.serving.scheduler import (SLO_BATCH, SLO_INTERACTIVE,
                                      DecodeScheduler, DrainingError,
                                      ShedError)

__all__ = ["GEN_SERVICE", "add_generation_method", "serve_generation",
           "GenerationClient"]

GEN_SERVICE = "tpurpc.Generate"

_SLO_BY_CODE = {0: SLO_INTERACTIVE, 1: SLO_BATCH}
_CODE_BY_SLO = {v: k for k, v in _SLO_BY_CODE.items()}

#: how often the token bridge re-checks client liveness while no token is
#: ready: the leave-detection latency bound (one step boundary away from
#: the scheduler's own reaction)
_POLL_S = 0.05


def _method_path(name: str) -> str:
    return f"/{GEN_SERVICE}/{name}"


def _scalar(x) -> int:
    """int() of a wire scalar, tolerant of 0-d and shape-(1,) encodings."""
    arr = np.asarray(x)
    return int(arr if arr.ndim == 0 else arr.ravel()[0])


def _account_from(ctx) -> Optional[str]:
    """The ``tpurpc-account`` metadata value, if the caller sent one —
    tpurpc-odyssey's accounting identity (tenant stand-in)."""
    try:
        for key, value in ctx.invocation_metadata():
            if key == _odyssey.ACCOUNT_KEY:
                return _odyssey.sanitize_account(value)
    except Exception:
        pass
    return None


def add_generation_method(server: Server, scheduler: DecodeScheduler,
                          name: str = "Generate") -> None:
    """Register ``/tpurpc.Generate/<name>`` streaming tokens from
    ``scheduler``. Sheds map to UNAVAILABLE with the PR 6 pushback
    trailer; a draining scheduler refuses with UNAVAILABLE "draining"
    (clients replay elsewhere); a failed sequence surfaces INTERNAL with
    the model's reason — all without touching sibling streams."""

    def behavior(req, ctx):
        prompt = np.asarray(req["prompt"], dtype=np.int32).reshape(-1)
        max_tokens = _scalar(req.get("max_tokens", 32))
        slo = _SLO_BY_CODE.get(_scalar(req.get("slo", 0)),
                               SLO_INTERACTIVE)
        try:
            # tpurpc-odyssey: the sequence inherits this RPC's trace
            # context (the server installed it as ambient) and the
            # caller's accounting identity — the journey and the ledger
            # start HERE, at admission
            stream = scheduler.submit(prompt, max_tokens=max_tokens,
                                      slo=slo, trace=_tracing.current(),
                                      account=_account_from(ctx))
        except ShedError as exc:
            ctx.set_trailing_metadata([(PUSHBACK_KEY,
                                        str(exc.pushback_ms))])
            ctx.abort(StatusCode.UNAVAILABLE, f"generation shed: {exc}")
        except DrainingError as exc:
            ctx.abort(StatusCode.UNAVAILABLE, str(exc))
        idx = 0
        try:
            while True:
                if not ctx.is_active():
                    return  # client left: finally cancels the sequence
                try:
                    tok = stream.next(timeout=_POLL_S)
                except StopIteration:
                    return
                except (ShedError, DrainingError) as exc:
                    ctx.abort(StatusCode.UNAVAILABLE, str(exc))
                except Exception as exc:
                    ctx.abort(StatusCode.INTERNAL,
                              f"sequence failed: {exc}")
                if tok is None:
                    continue
                yield {"token": np.int32(tok), "index": np.int32(idx)}
                idx += 1
        finally:
            stream.cancel()

    server.add_method(
        _method_path(name),
        unary_stream_rpc_method_handler(behavior, codec.tree_deserializer,
                                        codec.tree_serializer))


def serve_generation(model, address: str = "127.0.0.1:0", *,
                     name: str = "Generate", max_batch: int = 8,
                     prefill_budget: int = 128, max_waiting: int = 32,
                     batch_shed_depth: Optional[int] = None,
                     step_slo_ms: Optional[float] = None,
                     admission: "bool | AdmissionGate" = True,
                     kv=None, max_workers: int = 32,
                     ) -> Tuple[Server, int, DecodeScheduler]:
    """Stand up a continuous-batching generation server around a step
    model (:mod:`tpurpc.jaxshim.generate` contract). Returns
    ``(server, port, scheduler)``; the caller stops the server and closes
    the scheduler.

    Wiring (the full tpurpc-cadence posture):

    * the scheduler refuses new prefills while ``server.draining`` — a
      drain finishes in-flight sequences, never strands them;
    * ``admission=True`` builds an :class:`AdmissionGate` sized to the
      scheduler (hard limit = batch + queue capacity, with headroom for
      probe/scrape traffic) whose latency signal is the scheduler's
      rolling step-time p99 against ``step_slo_ms`` — the transport-level
      backstop behind the scheduler's own class-aware shedding;
    * the batcher-side queue depth rides the PR 6 load report, so
      ``least_loaded`` clients steer away from a backed-up decode server.
    """
    srv_box = []

    def draining() -> bool:
        return bool(srv_box and srv_box[0].draining)

    sched = DecodeScheduler(
        model, max_batch=max_batch, prefill_budget=prefill_budget,
        max_waiting=max_waiting, batch_shed_depth=batch_shed_depth,
        step_slo_ms=step_slo_ms, draining_fn=draining, kv=kv, name=name)
    gate: Optional[AdmissionGate]
    if admission is True:
        gate = AdmissionGate(
            sched.max_batch + sched.max_waiting + 8,
            soft_limit=sched.max_batch + sched.batch_shed_depth,
            latency_slo_ms=step_slo_ms,
            latency_ms_fn=sched.step_p99_ms)
    elif admission is False:
        gate = None
    else:
        gate = admission
    srv = Server(max_workers=max_workers, admission=gate)
    srv_box.append(srv)
    add_generation_method(srv, sched, name=name)
    # the fleet load report carries waiting AND preempted/swapped work —
    # queue_depth alone made a server holding swapped sequences look idle
    # to least_loaded picking (ISSUE 11 satellite fix)
    srv.set_load_provider(sched.load_depth)
    srv.start()
    port = srv.add_insecure_port(address)
    return srv, port, sched


class GenerationClient:
    """Per-token streaming client for generation methods; wraps a
    :class:`tpurpc.rpc.channel.Channel` (or anything with
    ``unary_stream``). ``account=`` (constructor or per call) attaches
    the ``tpurpc-account`` accounting identity tpurpc-odyssey rolls
    per-sequence cost under."""

    def __init__(self, channel, name: str = "Generate",
                 account: Optional[str] = None):
        self._channel = channel
        self._name = name
        self._account = account

    def call(self, prompt, *, max_tokens: int = 32,
             slo: str = SLO_INTERACTIVE,
             timeout: Optional[float] = None,
             account: Optional[str] = None):
        """The raw streaming call: an iterator of response trees (and a
        grpc Call underneath — ``.cancel()`` it to leave mid-stream)."""
        mc = self._channel.unary_stream(
            _method_path(self._name), codec.tree_serializer,
            codec.tree_deserializer)
        req = {"prompt": np.asarray(prompt, dtype=np.int32).reshape(-1),
               "max_tokens": np.int32(max_tokens),
               "slo": np.int32(_CODE_BY_SLO[slo])}
        acct = account if account is not None else self._account
        md = [(_odyssey.ACCOUNT_KEY, acct)] if acct else None
        return mc(req, timeout=timeout, metadata=md)

    def generate(self, prompt, *, max_tokens: int = 32,
                 slo: str = SLO_INTERACTIVE,
                 timeout: Optional[float] = None,
                 account: Optional[str] = None) -> Iterator[int]:
        """Iterate generated token ids, in order, as they stream."""
        for item in self.call(prompt, max_tokens=max_tokens, slo=slo,
                              timeout=timeout, account=account):
            yield _scalar(item["token"])

    def generate_with_meta(self, prompt, *, max_tokens: int = 32,
                           slo: str = SLO_INTERACTIVE,
                           timeout: Optional[float] = None,
                           account: Optional[str] = None
                           ) -> Iterator[Tuple[int, int]]:
        """Like :meth:`generate` but yields ``(index, token)`` — the
        per-token ordering proof the smoke/bench clients assert."""
        for item in self.call(prompt, max_tokens=max_tokens, slo=slo,
                              timeout=timeout, account=account):
            yield (_scalar(item["index"]), _scalar(item["token"]))
