"""tpurpc-keystone: the paged KV-cache plane.

PR 10's DecodeScheduler treats sequence state as opaque model rows stacked
into a batch array — fine for a toy, wrong for a generation fleet, where
the KV cache IS the resource being scheduled (ROADMAP item 2). This module
makes it explicit:

* :class:`KvBlockManager` — a block arena carved from ONE registered
  region (an HbmRing-style span allocated through the
  :class:`~tpurpc.core.pair.MemoryDomain` seam, so on ``shm`` the same
  bytes are one-sided-writable cross-process — the property
  :mod:`tpurpc.serving.disagg` ships KV over). Fixed block size, free-list
  allocation, per-block refcounts.
* per-sequence **block tables** (:class:`SeqKv`) — a sequence's KV is an
  ordered list of block ids; entries are 16-byte ``<hash u64, token u32,
  flags u32>`` records appended as decode advances. Entry ``p`` depends
  only on the token stream up to ``p`` — the invariant every reuse move
  below leans on.
* **copy-on-write prefix reuse** keyed by prompt-prefix hash: retiring a
  sequence donates its block-aligned prompt span to a prefix cache
  (refcounted, LRU-evicted under arena pressure). A later prompt with the
  same prefix starts with those blocks SHARED — prefill is skipped for the
  shared span (``kv_prefix_hits``), and shared blocks are never written:
  decode only appends into fresh private blocks, and an explicit write
  into a shared span goes through :meth:`SeqKv.writable_block`, which
  copies first (the COW rule; tested directly).
* **preempt-to-host swap** — preemption no longer parks rows in HBM
  (PR 10's keep-in-HBM move): :meth:`swap_out` copies a sequence's blocks
  to a host buffer and returns every block to the arena;
  :meth:`swap_in` re-allocates and restores byte-exactly. The
  ``kv_blocks_swapped`` gauge and the ``kv-swap`` flight edge pair
  (:data:`~tpurpc.obs.flight.KV_SWAP_BEGIN`/``END``) make a stuck swap a
  watchdog-attributable stage.
* **quarantine** — blocks that a dead peer's straggling one-sided write
  might still reach (a migration that died between CLAIM and COMPLETE)
  are quarantined, never returned to the free list: the Pair.init /
  LandingPool stale-write rule, applied at block granularity. The
  ``reuse-before-quarantine`` mutant in ``analysis/ringcheck.py
  check_kv_handoff`` is the modeled version of exactly this bug.

Every alloc / free / swap / quarantine is flight-logged (edges at
sequence-lifetime boundaries, not per token) and gauged
(``kv_blocks_used/free/swapped/quarantined``, ``kv_prefix_hits``).

The lint rule ``kv`` (analysis/lint.py) holds callers to the discipline:
a function that calls ``alloc_blocks``/``alloc_for_prompt`` must reach a
``free_blocks``/``swap_out``/``quarantine`` on an exception path
(except/finally), or carry ``# tpr: allow(kv)`` where ownership provably
transfers.
"""

from __future__ import annotations

import hashlib
import os
import struct
import weakref
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tpurpc.analysis.locks import make_lock
from tpurpc.core import pair as _pair
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler

__all__ = [
    "KvBlockManager", "SeqKv", "HostKv", "KvArenaFull",
    "ENTRY", "ENTRY_BYTES", "FLAG_POISONED", "health_lines",
]

#: tpurpc-lens: swap traffic is the kv plane's CPU story — a preemption
#: storm shows up as kv_swap time, not as unattributed serving work
_LENS_STAGES = {
    "swap_out": "kv_swap",
    "swap_in": "kv_swap",
    "alloc_for_prompt": "decode_step",
    "free_blocks": "decode_step",
}
_profiler.register_stages(__file__, _LENS_STAGES)

#: one KV entry: the model-visible record per token position
ENTRY = struct.Struct("<QII")  # hash u64, token u32, flags u32
ENTRY_BYTES = ENTRY.size       # 16

FLAG_POISONED = 1

_ALIGN = 64
_NONCE_BYTES = 16

# -- gauges / counters (process-wide registry, weakref fleet like PR 10) ------
_USED_G = _metrics.fleet("kv_blocks_used", lambda m: m.used_count())
_FREE_G = _metrics.fleet("kv_blocks_free", lambda m: m.free_count())
_SWAPPED_G = _metrics.fleet("kv_blocks_swapped", lambda m: m.swapped_count())
_QUAR_G = _metrics.fleet("kv_blocks_quarantined",
                         lambda m: m.quarantined_count())
_PREFIX_HITS = _metrics.counter("kv_prefix_hits")
_PREFIX_HIT_TOKENS = _metrics.counter("kv_prefix_hit_tokens")
_SWAPS = _metrics.counter("kv_swaps")
_COW_COPIES = _metrics.counter("kv_cow_copies")

#: live managers for the /healthz "kv" lines (the gen-lines pattern)
_LIVE: "weakref.WeakSet[KvBlockManager]" = weakref.WeakSet()


class KvArenaFull(RuntimeError):
    """No free block in the arena (after prefix-cache eviction). The
    scheduler maps this to a row-alone failure or keeps the sequence
    parked — never a batch-wide error."""


class SeqKv:
    """One sequence's block table over a :class:`KvBlockManager` arena.

    ``length`` counts ENTRIES present (not capacity). The first
    ``shared_len`` entries may live in blocks shared with the prefix
    cache or sibling sequences (refs > 1); those are read-only — appends
    go to private blocks, and :meth:`writable_block` is the COW door.

    A swapped-out table has ``host`` set (the byte image) and an empty
    ``blocks`` list; :meth:`KvBlockManager.swap_in` restores it.
    """

    __slots__ = ("mgr", "key", "blocks", "length", "shared_len",
                 "prefix_key", "prefix_span", "host", "_reserved")

    def __init__(self, mgr: "KvBlockManager", key: int):
        self.mgr = mgr
        self.key = key
        self.blocks: List[int] = []
        self.length = 0          # entries present
        self.shared_len = 0      # entries covered by shared (COW) blocks
        self.prefix_key: Optional[bytes] = None   # cache key of the
        self.prefix_span = 0                      # block-aligned prompt span
        self.host: Optional[bytearray] = None     # swap image when parked
        self._reserved = 0       # entries of pre-allocated capacity

    # -- capacity ------------------------------------------------------------

    @property
    def swapped(self) -> bool:
        return self.host is not None

    def capacity(self) -> int:
        return len(self.blocks) * self.mgr.block_tokens

    def reserve(self, n_entries: int) -> None:
        """Pre-allocate blocks so ``n_entries`` total entries fit (the
        handoff receiver's move: the grant must name every landing block
        up front)."""
        bt = self.mgr.block_tokens
        need = (n_entries + bt - 1) // bt - len(self.blocks)
        if need > 0:
            # ownership transfers to the table in the same statement
            self.blocks.extend(
                self.mgr.alloc_blocks(self.key, need))  # tpr: allow(kv)
        self._reserved = max(self._reserved, n_entries)

    # -- entry access ---------------------------------------------------------

    def _entry_site(self, pos: int) -> Tuple[memoryview, int]:
        bt = self.mgr.block_tokens
        block = self.blocks[pos // bt]
        off = self.mgr.block_offset(block) + (pos % bt) * ENTRY_BYTES
        return self.mgr.region_buf, off

    def entry(self, pos: int) -> Tuple[int, int, int]:
        """``(hash, token, flags)`` at entry position ``pos``."""
        if not 0 <= pos < self.length:
            raise IndexError(f"entry {pos} of {self.length}")
        if self.host is not None:
            return ENTRY.unpack_from(self.host, pos * ENTRY_BYTES)
        buf, off = self._entry_site(pos)
        return ENTRY.unpack_from(buf, off)

    def last(self) -> Tuple[int, int, int]:
        return self.entry(self.length - 1)

    def append(self, h: int, token: int, flags: int = 0) -> None:
        """Write the next entry (decode's per-token move). Allocates a
        fresh PRIVATE block at block boundaries; never touches a shared
        block (appends beyond ``shared_len`` by construction)."""
        if self.host is not None:
            raise RuntimeError("append to a swapped-out table")
        if self.length >= self.capacity():
            # ownership transfers to the table in the same statement
            self.blocks.extend(
                self.mgr.alloc_blocks(self.key, 1))  # tpr: allow(kv)
        buf, off = self._entry_site(self.length)
        ENTRY.pack_into(buf, off, h & 0xFFFFFFFFFFFFFFFF,
                        token & 0xFFFFFFFF, flags & 0xFFFFFFFF)
        self.length += 1

    def truncate(self, n_entries: int) -> None:
        """Forget entries past ``n_entries`` (the row-isolation retry's
        undo: a failed batched call may have appended for some rows).
        Blocks are kept — capacity is not ownership."""
        self.length = min(self.length, max(0, int(n_entries)))

    def set_length(self, n_entries: int) -> None:
        """Declare entries [0, n) present (the handoff receiver's move
        after COMPLETE: the bytes arrived one-sided, not via append)."""
        bt = self.mgr.block_tokens
        if n_entries > len(self.blocks) * bt:
            raise ValueError(f"{n_entries} entries exceed the "
                             f"{len(self.blocks)}-block table")
        self.length = int(n_entries)

    def writable_block(self, idx: int) -> int:
        """The COW door: block ``idx`` of the table, privately owned —
        if it is shared (refs > 1), its bytes are copied into a fresh
        block first and the table repointed. Returns the block id."""
        block = self.blocks[idx]
        if self.mgr.block_refs(block) <= 1:
            return block
        fresh = self.mgr.alloc_blocks(self.key, 1)
        try:
            src = self.mgr.block_view(block)
            self.mgr.block_view(fresh[0])[:] = src
        except BaseException:
            self.mgr.free_blocks_raw(fresh)
            raise
        self.blocks[idx] = fresh[0]
        self.mgr._decref(block)
        bt = self.mgr.block_tokens
        self.shared_len = min(self.shared_len, idx * bt)
        _COW_COPIES.inc()
        return fresh[0]

    # -- bulk views (the ship/swap paths) -------------------------------------

    def chunks(self, start_entry: int, end_entry: int
               ) -> Iterator[Tuple[int, memoryview]]:
        """Per-block byte views covering entries [start, end) — the
        migration/handoff sender's gather list. ``start_entry`` must be
        block-aligned (shared spans are). Yields ``(block_index,
        view)``."""
        bt = self.mgr.block_tokens
        if start_entry % bt:
            raise ValueError(f"start entry {start_entry} not block-aligned")
        for bi in range(start_entry // bt,
                        (max(start_entry, end_entry) + bt - 1) // bt):
            lo = bi * bt
            hi = min(end_entry, lo + bt)
            nb = (hi - lo) * ENTRY_BYTES
            if self.host is not None:
                view = memoryview(self.host)[lo * ENTRY_BYTES:
                                             lo * ENTRY_BYTES + nb]
            else:
                off = self.mgr.block_offset(self.blocks[bi])
                view = self.mgr.region_buf[off:off + nb]
            yield bi, view


class HostKv:
    """A host-memory table implementing the SeqKv entry interface — what a
    PREFILL server computes into before shipping (it has no arena; the
    landing blocks live in the decode server). ``base_pos``/``base_hash``
    seed a table that logically starts mid-sequence: the prefix-cache-hit
    handoff, where the decode side already holds entries [0, base_pos)
    and returned the resume hash in its CLAIM."""

    __slots__ = ("base_pos", "_base_hash", "_base_flags", "buf", "length")

    def __init__(self, base_pos: int = 0, base_hash: int = 0,
                 base_flags: int = 0):
        self.base_pos = int(base_pos)
        self._base_hash = int(base_hash)
        self._base_flags = int(base_flags)
        self.buf = bytearray()
        self.length = self.base_pos  # entries "present" in the logical seq

    def entry(self, pos: int) -> Tuple[int, int, int]:
        if pos == self.base_pos - 1 and self.base_pos:
            return (self._base_hash, 0, self._base_flags)
        local = pos - self.base_pos
        if not 0 <= local < (self.length - self.base_pos):
            raise IndexError(f"entry {pos} (base {self.base_pos}, "
                             f"length {self.length})")
        return ENTRY.unpack_from(self.buf, local * ENTRY_BYTES)

    def last(self) -> Tuple[int, int, int]:
        return self.entry(self.length - 1)

    def append(self, h: int, token: int, flags: int = 0) -> None:
        self.buf += ENTRY.pack(h & 0xFFFFFFFFFFFFFFFF, token & 0xFFFFFFFF,
                               flags & 0xFFFFFFFF)
        self.length += 1

    def truncate(self, n_entries: int) -> None:
        n_entries = max(self.base_pos, int(n_entries))
        del self.buf[(n_entries - self.base_pos) * ENTRY_BYTES:]
        self.length = n_entries

    def payload(self) -> memoryview:
        """The computed entries [base_pos, length) as bytes — what ships."""
        return memoryview(self.buf)


class _PrefixEntry:
    __slots__ = ("blocks", "span", "last_hash", "last_flags")

    def __init__(self, blocks: Tuple[int, ...], span: int, last_hash: int,
                 last_flags: int):
        self.blocks = blocks
        self.span = span
        self.last_hash = last_hash
        self.last_flags = last_flags


class KvBlockManager:
    """The arena + block tables + prefix cache + swap/quarantine machinery
    (module docstring has the full story).

    ``kind`` names the :class:`~tpurpc.core.pair.MemoryDomain` backing the
    arena: ``"local"`` for in-process scheduling, ``"shm"`` when the arena
    must double as a one-sided landing target for the disaggregated
    handoff plane (:func:`grant_blocks`).
    """

    #: lint rule `lock`: every mutable map below is shared between the
    #: scheduler loop thread, disagg RPC handlers, and migration threads
    _GUARDED_BY = {
        "_free": "_lock", "_refs": "_lock", "_owner": "_lock",
        "_quarantined": "_lock", "_prefix": "_lock", "_swapped_blocks":
        "_lock",
    }

    def __init__(self, n_blocks: int = 256, block_bytes: int = 2048,
                 kind: str = "local", name: str = "kv"):
        if block_bytes % ENTRY_BYTES:
            raise ValueError(f"block_bytes {block_bytes} not a multiple of "
                             f"the {ENTRY_BYTES}-byte entry")
        self.name = name
        self.kind = kind
        self.n_blocks = int(n_blocks)
        self.block_bytes = int(block_bytes)
        self.block_tokens = block_bytes // ENTRY_BYTES
        self._domain = _pair.make_domain(kind)
        total = _ALIGN + self.n_blocks * self.block_bytes + _NONCE_BYTES
        self._region = self._domain.alloc(total)
        base = np.frombuffer(self._region.buf, np.uint8)
        self._base_off = int((-base.ctypes.data) % _ALIGN)
        del base
        self.nonce = os.urandom(_NONCE_BYTES)
        self.nonce_off = self._base_off + self.n_blocks * self.block_bytes
        self._region.buf[self.nonce_off:
                         self.nonce_off + _NONCE_BYTES] = self.nonce
        self._lock = make_lock("KvBlockManager._lock")
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._refs: Dict[int, int] = {}
        self._owner: Dict[int, int] = {}      # block -> first owner key
        self._quarantined: List[int] = []
        #: prompt-prefix hash -> _PrefixEntry (LRU: move_to_end on hit)
        self._prefix: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self._swapped_blocks: Dict[int, int] = {}  # seq key -> block count
        self.prefix_hits = 0
        self.swaps_out = 0
        self.swaps_in = 0
        self._tag = _flight.tag_for(f"kv:{name}")
        self._closed = False
        _USED_G.track(self)
        _FREE_G.track(self)
        _SWAPPED_G.track(self)
        _QUAR_G.track(self)
        _LIVE.add(self)

    # -- raw arena geometry (the disagg grant path reads these) ---------------

    @property
    def region_handle(self) -> str:
        return self._region.handle

    @property
    def region_buf(self) -> memoryview:
        return self._region.buf

    @property
    def window_bytes(self) -> int:
        """Bytes a peer window must map to reach every block + the nonce."""
        return self.nonce_off + _NONCE_BYTES

    def block_offset(self, block: int) -> int:
        return self._base_off + block * self.block_bytes

    def block_view(self, block: int) -> memoryview:
        off = self.block_offset(block)
        return self._region.buf[off:off + self.block_bytes]

    def block_refs(self, block: int) -> int:
        with self._lock:
            return self._refs.get(block, 0)

    # -- allocation -----------------------------------------------------------

    def alloc_blocks(self, owner_key: int, n: int) -> List[int]:
        """``n`` fresh private blocks (refs=1) for ``owner_key``. Evicts
        prefix-cache entries LRU-first under pressure; raises
        :class:`KvArenaFull` when even eviction cannot cover it."""
        with self._lock:
            if self._closed:
                raise KvArenaFull("arena closed")
            while len(self._free) < n and self._prefix:
                self._evict_one_locked()
            if len(self._free) < n:
                raise KvArenaFull(
                    f"arena {self.name}: want {n} blocks, "
                    f"{len(self._free)} free (of {self.n_blocks})")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
                self._owner[b] = owner_key
        nb = len(out)
        _flight.emit(_flight.KV_ALLOC, self._tag, owner_key, nb)
        return out

    def alloc_for_prompt(self, seq_key: int, prompt: np.ndarray,
                         reserve_entries: int = 0) -> Tuple[SeqKv, int]:
        """A fresh block table for ``prompt``, prefix-cache consulted:
        returns ``(table, hit_entries)`` where the first ``hit_entries``
        entries are ALREADY PRESENT via shared blocks — prefill skips
        them. ``reserve_entries`` pre-allocates capacity (the handoff
        grant's requirement); 0 defers allocation to append time."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        kv = SeqKv(self, seq_key)
        span = (int(prompt.shape[0]) // self.block_tokens) \
            * self.block_tokens
        key = self._prefix_key(prompt, span) if span else None
        kv.prefix_key = key
        kv.prefix_span = span
        hit = 0
        if key is not None:
            with self._lock:
                ent = self._prefix.get(key)
                if ent is not None:
                    self._prefix.move_to_end(key)
                    for b in ent.blocks:
                        self._refs[b] += 1
                    kv.blocks.extend(ent.blocks)
                    hit = ent.span
                    self.prefix_hits += 1
            if hit:
                kv.length = hit
                kv.shared_len = hit
                _PREFIX_HITS.inc()
                _PREFIX_HIT_TOKENS.inc(hit)
                _flight.emit(_flight.KV_PREFIX_HIT, self._tag, seq_key, hit)
        if reserve_entries:
            try:
                kv.reserve(reserve_entries)
            except KvArenaFull:
                self.free_blocks(kv)
                raise
        return kv, hit

    def _prefix_key(self, prompt: np.ndarray, span: int) -> bytes:
        """Content hash of the block-aligned prompt prefix — the cache
        key. sha1 over the raw int32 bytes: collisions are content-
        equality for any realistic fleet, and the cached entry's span is
        re-checked on hit."""
        return hashlib.sha1(prompt[:span].tobytes()).digest()

    # -- release / prefix donation --------------------------------------------

    def free_blocks(self, kv: SeqKv, cache_prefix: bool = False) -> None:
        """Release a table. With ``cache_prefix=True`` (natural retire /
        clean leave) the block-aligned prompt span is donated to the
        prefix cache first — refcounted, so the data outlives the
        sequence. Poisoned spans are never cached (a latent-poison prefix
        would infect clean prompts sharing it)."""
        if kv.host is not None:
            with self._lock:
                self._swapped_blocks.pop(kv.key, None)
            kv.host = None
        if not kv.blocks:
            kv.length = 0
            return
        donate: Optional[Tuple[bytes, _PrefixEntry]] = None
        if (cache_prefix and kv.prefix_key is not None
                and kv.length >= kv.prefix_span > 0):
            h, _tok, flags = kv.entry(kv.prefix_span - 1)
            if not flags & FLAG_POISONED:
                bt = self.block_tokens
                span_blocks = tuple(kv.blocks[:kv.prefix_span // bt])
                donate = (kv.prefix_key,
                          _PrefixEntry(span_blocks, kv.prefix_span, h,
                                       flags))
        blocks, kv.blocks = kv.blocks, []
        n = len(blocks)
        kv.length = 0
        kv.shared_len = 0
        with self._lock:
            if donate is not None and donate[0] not in self._prefix:
                self._prefix[donate[0]] = donate[1]
                for b in donate[1].blocks:
                    self._refs[b] += 1
            for b in blocks:
                self._decref_locked(b)
        _flight.emit(_flight.KV_FREE, self._tag, kv.key, n)

    def free_blocks_raw(self, blocks: Sequence[int]) -> None:
        """Release raw block ids (the grant/undo paths, where no SeqKv
        owns them yet)."""
        n = len(blocks)
        with self._lock:
            for b in blocks:
                self._decref_locked(b)
        if n:
            _flight.emit(_flight.KV_FREE, self._tag, 0, n)

    def _decref(self, block: int) -> None:
        with self._lock:
            self._decref_locked(block)

    def _decref_locked(self, block: int) -> None:
        # contract: caller holds self._lock (the _locked suffix)
        r = self._refs.get(block, 0) - 1
        if r > 0:
            self._refs[block] = r  # tpr: allow(lock)
            return
        self._refs.pop(block, None)  # tpr: allow(lock)
        self._owner.pop(block, None)  # tpr: allow(lock)
        self._free.append(block)  # tpr: allow(lock)

    def _evict_one_locked(self) -> None:
        # contract: caller holds self._lock (the _locked suffix)
        key, ent = self._prefix.popitem(last=False)  # tpr: allow(lock)
        for b in ent.blocks:
            self._decref_locked(b)

    def lookup_prefix(self, prompt: np.ndarray
                      ) -> Tuple[int, int, int]:
        """``(span, last_hash, last_flags)`` for the cached prefix of
        ``prompt`` (0, 0, 0 on miss) WITHOUT taking references — the
        handoff OFFER's probe (the CLAIM allocates for real)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        span = (int(prompt.shape[0]) // self.block_tokens) \
            * self.block_tokens
        if not span:
            return 0, 0, 0
        with self._lock:
            ent = self._prefix.get(self._prefix_key(prompt, span))
            if ent is None:
                return 0, 0, 0
            return ent.span, ent.last_hash, ent.last_flags

    # -- preempt-to-host swap -------------------------------------------------

    def swap_out(self, kv: SeqKv) -> None:
        """Copy the table's entries to a host image and return every
        block to the arena — the preemption that actually FREES device
        memory. Byte-exact restore via :meth:`swap_in`."""
        if kv.host is not None:
            return
        key = kv.key
        n = len(kv.blocks)
        _flight.emit(_flight.KV_SWAP_BEGIN, self._tag, key, 0)
        host = bytearray(kv.length * ENTRY_BYTES)
        for bi, view in kv.chunks(0, kv.length):
            lo = bi * self.block_bytes
            host[lo:lo + len(view)] = view
        blocks, kv.blocks = kv.blocks, []
        kv.host = host
        kv.shared_len = 0
        with self._lock:
            for b in blocks:
                self._decref_locked(b)
            self._swapped_blocks[key] = n
        self.swaps_out += 1
        _SWAPS.inc()
        _flight.emit(_flight.KV_SWAP_END, self._tag, key, 0)

    def swap_in(self, kv: SeqKv) -> None:
        """Restore a swapped table into fresh arena blocks (all private —
        sharing does not survive a swap; the prefix cache keeps its own
        refs). Raises :class:`KvArenaFull` when the arena cannot take it
        back — the caller keeps the sequence parked and retries."""
        if kv.host is None:
            return
        key = kv.key
        length = kv.length
        bt = self.block_tokens
        need = (length + bt - 1) // bt
        _flight.emit(_flight.KV_SWAP_BEGIN, self._tag, key, 1)
        blocks = self.alloc_blocks(key, need)
        try:
            host = kv.host
            for i, b in enumerate(blocks):
                lo = i * self.block_bytes
                chunk = host[lo:lo + self.block_bytes]
                off = self.block_offset(b)
                self._region.buf[off:off + len(chunk)] = chunk
        except BaseException:
            self.free_blocks_raw(blocks)
            raise
        kv.blocks = blocks
        kv.host = None
        with self._lock:
            self._swapped_blocks.pop(key, None)
        self.swaps_in += 1
        _flight.emit(_flight.KV_SWAP_END, self._tag, key, 1)

    # -- quarantine (the death path) ------------------------------------------

    def quarantine(self, kv_or_blocks) -> int:
        """Remove blocks from circulation FOREVER (until arena close): a
        straggling one-sided writer may still land bytes in them, so they
        must never be re-leased (the modeled ``reuse_before_quarantine``
        mutant is this rule violated). Accepts a SeqKv or a block list;
        returns the count quarantined."""
        if isinstance(kv_or_blocks, SeqKv):
            blocks, kv_or_blocks.blocks = kv_or_blocks.blocks, []
            kv_or_blocks.length = 0
            kv_or_blocks.shared_len = 0
        else:
            blocks = list(kv_or_blocks)
        n = 0
        with self._lock:
            for b in blocks:
                r = self._refs.get(b, 0) - 1
                # shared refs (prefix cache) keep THEIR view; only the
                # final release diverts to quarantine instead of free
                if r > 0:
                    self._refs[b] = r
                    continue
                self._refs.pop(b, None)
                self._owner.pop(b, None)
                self._quarantined.append(b)
                n += 1
        if n:
            _flight.emit(_flight.KV_QUARANTINE, self._tag, 0, n)
        return n

    # -- introspection --------------------------------------------------------

    def used_count(self) -> int:
        with self._lock:
            return self.n_blocks - len(self._free) - len(self._quarantined)

    def free_count(self) -> int:
        return len(self._free)

    def swapped_count(self) -> int:
        with self._lock:
            return sum(self._swapped_blocks.values())

    def quarantined_count(self) -> int:
        return len(self._quarantined)

    def prefix_entries(self) -> int:
        return len(self._prefix)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "blocks": self.n_blocks,
                "free": len(self._free),
                "used": self.n_blocks - len(self._free)
                - len(self._quarantined),
                "swapped_seqs": len(self._swapped_blocks),
                "swapped_blocks": sum(self._swapped_blocks.values()),
                "quarantined": len(self._quarantined),
                "prefix_entries": len(self._prefix),
                "prefix_hits": self.prefix_hits,
            }

    def close(self) -> None:
        self._closed = True
        _LIVE.discard(self)
        try:
            self._region.close()
        except Exception:
            pass


def health_lines() -> List[str]:
    """One ``kv`` line per live arena for /healthz — block occupancy and
    swap pressure at a glance, without the metrics plane."""
    out = []
    for m in list(_LIVE):
        try:
            s = m.stats()
            out.append(
                f"kv {m.name}: used={s['used']}/{s['blocks']} "
                f"free={s['free']} swapped={s['swapped_blocks']} "
                f"quarantined={s['quarantined']} "
                f"prefix_hits={s['prefix_hits']}")
        except Exception:
            continue
    return sorted(out)
